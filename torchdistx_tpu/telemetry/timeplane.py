"""Time plane: tick-phase decomposition, host/device attribution, and
trigger-fired profiler capture.

The ops plane (PR 10) says *how loaded* a tick was (occupancy, budgets,
goodput) and the perf plane (PR 11) says *what compiled and what HBM
costs* — but ``serve.tick_s`` itself stayed one opaque number.  This
module decomposes it and, when the tick loop misbehaves, captures a
real device profile of the misbehaving window:

**Tick phases.**  The engine tick loop marks phase boundaries into a
:class:`TickTimer` (one ``perf_counter`` call per transition — gated
exactly like the ops plane's per-tick attribution, so the disabled path
pays nothing), and :func:`publish_tick` folds the per-phase durations
into per-engine labeled histograms
``serve.tick_phase_s{engine=,phase=}``:

* ``schedule`` — reap/admit/swap-in/lifecycle bookkeeping (host),
* ``audit_pump`` — the shadow auditor's per-tick pump,
* ``prefill_dispatch`` — chunked-prefill dispatches (host side of the
  compiled prefill calls),
* ``decode_dispatch`` — building and dispatching the decode chunk,
* ``device_wait`` — the **dispatch gap**: the host blocked on the
  device materializing the chunk's tokens (``np.asarray`` of the
  donated call's output — the one host sync per chunk),
* ``commit`` — pushing committed tokens to handles and retiring slots.

``serve.host_overhead_frac{engine=}`` is the split the roadmap items
(speculative decode, page migration, autoscaling) need before claiming
any speedup: ``(tick_s - device_wait) / tick_s`` — near 1 means the
tick loop is host-bound and a faster kernel buys nothing.

When anything records (``events_enabled``), each non-idle tick also
emits ONE ``serve.tick`` event carrying its ordered phase segments, so
``scripts/timeline_export.py`` can lay the tick loop out as a Perfetto
track next to the per-request timelines.

**ProfilerTrigger.**  A rate-limited, bounded ``jax.profiler`` capture:
:func:`fire_profile` starts a trace into a fresh artifact directory,
holds it open for a bounded window on a daemon thread, and stops it —
recording an ``ops.profile`` event with the artifact path (and a
cooldown-suppressed second trigger as ``ops.profile_suppressed``).  The
stall watchdog, the SLO burn monitor, the recompile-storm detector, and
the slow-tick outlier check (``tick_s > k × p50``) all route here, so
the flight dump of an incident comes WITH a device profile of the slow
window instead of just the event ring.  On-demand capture goes through
the ops plane's ``/profile?seconds=N`` endpoint.

Environment (read once, at first use; :func:`set_trigger` wins):

* ``TDX_PROFILE_DIR=/path`` — enable trigger-fired capture; artifact
  directories are created under it.  Unset = captures disabled (the
  ``/profile`` endpoint still works, into a temp directory).
* ``TDX_PROFILE_SECONDS`` — capture window (default 2.0).
* ``TDX_PROFILE_COOLDOWN_S`` — minimum spacing between captures
  (default 120).  A trigger inside the cooldown (or while a capture is
  in flight) is suppressed, never queued: profiles are for the FIRST
  incident of a burst, and ``jax.profiler`` is process-global.
* ``TDX_SLOW_TICK_K`` — the slow-tick outlier multiple over the
  engine's own ``serve.tick_s`` p50 (default 8; needs ≥ 64 recorded
  ticks before it can fire, so cold-start compiles never trigger).

Like the rest of telemetry: stdlib-only at import (jax is imported
lazily, inside the capture thread), never fails the instrumented
operation, and free when off.
"""

from __future__ import annotations

import logging
import os
import re
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import _core

_logger = logging.getLogger(__name__)

__all__ = [
    "PHASES",
    "ProfilerTrigger",
    "TickTimer",
    "fire_profile",
    "get_trigger",
    "phase_summaries",
    "prune_engine",
    "publish_tick",
    "set_trigger",
]

# The tick decomposition, in canonical display order (the exporter lays
# segments out in recorded order; this tuple is the label universe the
# per-engine prune walks).
PHASES = (
    "schedule",
    "audit_pump",
    "prefill_dispatch",
    "decode_dispatch",
    "device_wait",
    "commit",
)

_T_PROFILES = _core.counter("ops.profiles")
_T_SUPPRESSED = _core.counter("ops.profiles_suppressed")

# Slots of the tick histogram the slow-tick check needs before a p50 is
# trustworthy — cold-start ticks (first compiles, first admissions) must
# never fire a capture.
_SLOW_TICK_MIN_TICKS = 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


_SLOW_TICK_K = _env_float("TDX_SLOW_TICK_K", 8.0)


# ---------------------------------------------------------------------------
# Tick-phase timing


class TickTimer:
    """Ordered phase segments of one engine tick.

    ``begin(phase)`` closes the current segment and opens the next —
    one ``perf_counter`` call per transition, a handful per tick, no
    allocation beyond the segment tuples.  The engine creates one per
    tick only when the ops plane (or forced tick attribution) is on,
    so the disabled path builds nothing."""

    __slots__ = ("t0", "ts", "segments", "_phase", "_p0")

    def __init__(self, t0: Optional[float] = None):
        self.t0 = time.perf_counter() if t0 is None else t0
        self.ts = time.time()  # wall-clock tick start, for the exporter
        self.segments: List[Tuple[str, float, float]] = []
        self._phase: Optional[str] = None
        self._p0 = self.t0

    def begin(self, phase: str) -> None:
        now = time.perf_counter()
        if self._phase is not None:
            self.segments.append((self._phase, self._p0 - self.t0, now - self._p0))
        self._phase = phase
        self._p0 = now

    def end(self) -> None:
        """Close the open segment (idempotent)."""
        if self._phase is not None:
            now = time.perf_counter()
            self.segments.append((self._phase, self._p0 - self.t0, now - self._p0))
            self._phase = None

    def totals(self) -> Dict[str, float]:
        """Accumulated seconds per phase (phases that never ran absent)."""
        out: Dict[str, float] = {}
        for phase, _, dur in self.segments:
            out[phase] = out.get(phase, 0.0) + dur
        return out


def publish_tick(engine, timer: TickTimer, tick_s: float, idle: bool = False) -> None:
    """Fold one tick's phase segments into the engine's labeled
    histograms, set ``serve.host_overhead_frac``, emit the ``serve.tick``
    timeline event (when anything records), and run the slow-tick
    outlier check.  Fully idle ticks publish nothing (the ops plane's
    idle rule) beyond zeroing the host gauge once at the idle edge."""
    state = engine._tp_state
    if idle:
        if state is not None and not engine._was_idle:
            state["host"].set(0)
        return
    eid = engine.engine_id
    if state is None:
        state = engine._tp_state = {
            "hists": {
                ph: _core.histogram("serve.tick_phase_s", engine=eid, phase=ph)
                for ph in PHASES
            },
            "host": _core.gauge("serve.host_overhead_frac", engine=eid),
        }
    totals = timer.totals()
    for phase, dur in totals.items():
        h = state["hists"].get(phase)
        if h is not None:
            h.observe(dur)
    device_s = totals.get("device_wait", 0.0)
    host_frac = (
        max(0.0, min(1.0, (tick_s - device_s) / tick_s)) if tick_s > 0 else 0.0
    )
    state["host"].set(round(host_frac, 4))
    if _core.events_enabled():
        # dur_s is the SLICE duration for the exporter: the tail
        # segment closes after tick_s was measured (it covers the
        # attribution writes themselves), so the slice must extend to
        # the last segment's end or the Perfetto children would escape
        # their parent.  tick_s is the measured tick, unchanged.
        span_end = max(
            [tick_s] + [off + dur for _, off, dur in timer.segments]
        )
        _core.event(
            "serve.tick",
            engine=eid,
            tick=engine._tick_no,
            t0=round(timer.ts, 6),
            dur_s=round(span_end, 6),
            tick_s=round(tick_s, 6),
            host_overhead_frac=round(host_frac, 4),
            segments=[
                [phase, round(off, 6), round(dur, 6)]
                for phase, off, dur in timer.segments
            ],
        )
    # Slow-tick outlier → profiler capture.  Checked only with a trigger
    # installed (the p50 readback copies the bucket array), against the
    # engine's OWN tick distribution, and only once it has real history.
    # A manual_only trigger (the /profile temp-dir default) is not an
    # opt-in to automatic capture — same gate as fire_profile.
    trigger = get_trigger()
    if trigger is not None and not trigger.manual_only:
        h_tick = getattr(engine, "_h_tick", None)
        if h_tick is not None and h_tick.count >= _SLOW_TICK_MIN_TICKS:
            p50 = h_tick.percentile(50)
            if p50 and tick_s > _SLOW_TICK_K * p50:
                trigger.fire(
                    "slow_tick",
                    engine=eid,
                    tick_s=round(tick_s, 6),
                    p50_s=round(p50, 6),
                    k=_SLOW_TICK_K,
                )


def phase_summaries(engine_id: str) -> Dict[str, Dict[str, Any]]:
    """One engine's tick-phase breakdown, phase → histogram summary
    (``{count, sum, min, max, p50, p95, p99}``; phases never observed
    omitted).  The ONE readback bench/bench_gate consume — callers must
    not hand-parse the rendered ``serve.tick_phase_s{...}`` registry
    names, whose label encoding belongs to ``_core``."""
    out: Dict[str, Dict[str, Any]] = {}
    for ph in PHASES:
        name = _core._labeled(
            "serve.tick_phase_s", {"engine": engine_id, "phase": ph}
        )
        h = _core._state.histograms.get(name)
        if h is not None and h.count:
            out[ph] = h.summary()
    return out


def prune_engine(engine_id: str) -> None:
    """Drop a stopped engine's time-plane rows from the registry — the
    same bounded-cardinality rule as the tenant/stall families: no
    ``serve.tick_phase_s`` row survives ``_finish_drain``."""
    for ph in PHASES:
        _core.remove("serve.tick_phase_s", engine=engine_id, phase=ph)
    _core.remove("serve.host_overhead_frac", engine=engine_id)


# ---------------------------------------------------------------------------
# Trigger-fired profiler capture


class ProfilerTrigger:
    """Rate-limited, bounded ``jax.profiler`` capture windows.

    ``fire(reason)`` creates a fresh artifact directory under
    ``log_dir``, records ``ops.profile`` with its path, and runs the
    capture (start → bounded sleep → stop) on a daemon thread so the
    serving tick loop never blocks on it.  A fire while a capture is in
    flight, or inside ``cooldown_s`` of the last accepted one, is
    SUPPRESSED (``ops.profiles_suppressed`` + an
    ``ops.profile_suppressed`` event) — the profiler is process-global
    and a burst of stalls should yield one profile of the first, not a
    pile-up.  ``_start_profiler``/``_stop_profiler`` are the jax seam
    (tests stub them; a jax-less or profiler-less process still creates
    the artifact directory and records the event — the capture is then
    empty, never an error)."""

    def __init__(
        self,
        log_dir: str,
        seconds: float = 2.0,
        cooldown_s: float = 120.0,
        manual_only: bool = False,
    ):
        if seconds <= 0:
            raise ValueError("seconds must be > 0")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.log_dir = str(log_dir)
        self.seconds = float(seconds)
        self.cooldown_s = float(cooldown_s)
        # manual_only: the /profile endpoint's default temp-dir trigger
        # serves ON-DEMAND captures only — fire_profile (the automatic
        # stall/burn/storm/slow-tick funnel) skips it, so one curl of
        # /profile on a box without TDX_PROFILE_DIR cannot silently arm
        # automatic profiling into directories nobody collects.
        self.manual_only = bool(manual_only)
        self.captures: List[str] = []  # artifact dirs, in fire order
        self.suppressed = 0
        self._lock = threading.Lock()
        self._seq = 0
        self._last_fire: Optional[float] = None
        self._active = False
        self._thread: Optional[threading.Thread] = None

    # -- the jax seam -------------------------------------------------------

    @staticmethod
    def _start_profiler(path: str) -> None:
        from jax import profiler as _jprof

        _jprof.start_trace(path)

    @staticmethod
    def _stop_profiler() -> None:
        from jax import profiler as _jprof

        _jprof.stop_trace()

    # -- firing -------------------------------------------------------------

    def fire(
        self,
        reason: str,
        engine: Optional[str] = None,
        seconds: Optional[float] = None,
        **attrs,
    ) -> Optional[str]:
        """Capture one bounded window; returns the artifact directory,
        or None when suppressed (cooldown / capture in flight) or the
        directory could not be created."""
        now = time.monotonic()
        with self._lock:
            suppressed = self._active or (
                self._last_fire is not None
                and now - self._last_fire < self.cooldown_s
            )
            if suppressed:
                self.suppressed += 1
            else:
                self._seq += 1
                seq = self._seq
                self._active = True
                prev_last_fire = self._last_fire
                self._last_fire = now
        if suppressed:
            # Side effects OUTSIDE the lock (the repo-wide rule — see
            # SLOMonitor/storm detector): _core.event fans out to
            # listeners on this thread, and a listener path re-entering
            # fire() must contend, not deadlock.
            _T_SUPPRESSED.add()
            _core.event(
                "ops.profile_suppressed",
                engine=engine,
                reason=reason,
                **attrs,
            )
            return None
        slug = re.sub(r"[^\w.-]", "_", reason) or "capture"
        path = os.path.join(self.log_dir, f"profile-{seq:04d}-{slug}")
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            # A capture that never happened must not arm the cooldown
            # (the NEXT incident would be suppressed for a window with
            # nothing to show for it) — roll the state back and say so.
            with self._lock:
                self._active = False
                self._last_fire = prev_last_fire
            _logger.warning(
                "timeplane: profiler capture dir %s failed (%s); "
                "capture skipped, cooldown not armed", path, e,
            )
            _core.event(
                "ops.profile_failed", engine=engine, reason=reason,
                path=path, error=str(e),
            )
            return None
        window_s = float(seconds) if seconds is not None else self.seconds
        _T_PROFILES.add()
        _core.event(
            "ops.profile",
            engine=engine,
            reason=reason,
            path=path,
            seconds=window_s,
            **attrs,
        )
        self.captures.append(path)
        t = threading.Thread(
            target=self._capture,
            args=(path, window_s),
            name=f"tdx-profiler-{seq}",
            daemon=True,
        )
        self._thread = t
        t.start()
        return path

    def _capture(self, path: str, window_s: float) -> None:
        started = False
        try:
            self._start_profiler(path)
            started = True
        except Exception:  # noqa: BLE001 — no jax / profiler busy: dir stays
            pass
        try:
            time.sleep(window_s)
        finally:
            if started:
                try:
                    self._stop_profiler()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            with self._lock:
                self._active = False

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the in-flight capture (if any) finishes."""
        t = self._thread
        if t is not None:
            t.join(timeout)


# Module-level trigger: env-seeded lazily, programmatic set_trigger wins.
_TRIGGER: Any = "__unset__"
_TRIGGER_LOCK = threading.Lock()


def _env_trigger() -> Optional[ProfilerTrigger]:
    d = os.environ.get("TDX_PROFILE_DIR", "").strip()
    if not d:
        return None
    return ProfilerTrigger(
        d,
        seconds=max(0.01, _env_float("TDX_PROFILE_SECONDS", 2.0)),
        cooldown_s=max(0.0, _env_float("TDX_PROFILE_COOLDOWN_S", 120.0)),
    )


def get_trigger(create_default: bool = False) -> Optional[ProfilerTrigger]:
    """The installed trigger (env-seeded on first call), or None when
    capture is disabled.  ``create_default=True`` (the ``/profile``
    endpoint) installs a temp-directory trigger when nothing else is
    configured, so on-demand capture always has somewhere to write —
    marked ``manual_only`` so it never arms AUTOMATIC capture."""
    global _TRIGGER
    with _TRIGGER_LOCK:
        if isinstance(_TRIGGER, str):
            _TRIGGER = _env_trigger()
        if _TRIGGER is None and create_default:
            _TRIGGER = ProfilerTrigger(
                tempfile.mkdtemp(prefix="tdx-profile-"), manual_only=True
            )
        return _TRIGGER


def set_trigger(trigger: Any) -> Any:
    """Install (or disable, with None) the process trigger.  Returns
    the previous value for restoration — pass it back verbatim
    (``"__unset__"`` restores the not-yet-env-read state)."""
    global _TRIGGER
    with _TRIGGER_LOCK:
        prev = _TRIGGER
        _TRIGGER = trigger
    return prev


def fire_profile(
    reason: str,
    engine: Optional[str] = None,
    seconds: Optional[float] = None,
    **attrs,
) -> Optional[str]:
    """Fire the installed trigger (no-op None when capture is off) —
    the one funnel the stall watchdog, SLO burn monitor, recompile-storm
    detector, and slow-tick check all call.  A ``manual_only`` trigger
    (the ``/profile`` endpoint's temp-dir default) does not count as
    opting into automatic capture."""
    trigger = get_trigger()
    if trigger is None or trigger.manual_only:
        return None
    return trigger.fire(reason, engine=engine, seconds=seconds, **attrs)


def _reset() -> None:
    # Test isolation: a trigger installed (or env-seeded) by one test
    # must not rate-limit the next; env re-reads on next use.
    global _TRIGGER
    with _TRIGGER_LOCK:
        _TRIGGER = "__unset__"


_core.on_reset(_reset)
