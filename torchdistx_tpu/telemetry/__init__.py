"""Unified telemetry: spans, counters, gauges, and trace export.

The observability layer for the whole stack — the tape recorder
(``_tape.py``), materialization (``materialize.py``), the compilation
cache (``utils/compilation_cache.py``), and the training loop
(``parallel/fit.py``) all report through this module; ``bench.py``
assembles its headline JSON from it.  See ``docs/observability.md`` for
the span/counter catalog and the export formats.

Quick start::

    from torchdistx_tpu import telemetry

    telemetry.configure(collect=True)          # in-memory collector
    # ... materialize / train ...
    telemetry.snapshot()                       # {"counters", "gauges", "spans"}

    # or from the environment, with a JSON-lines trace file:
    #   TDX_TELEMETRY=/tmp/trace.jsonl python train.py

Instrumenting your own code::

    with telemetry.span("my.phase", size=n):
        ...
    telemetry.counter("my.events").add()
    telemetry.gauge("my.rate").set(v)
"""

from ._core import (  # noqa: F401
    Histogram,
    Span,
    add_listener,
    on_reset,
    configure,
    counter,
    counters,
    drain,
    emit_counters,
    enabled,
    event,
    events_enabled,
    flight_dump,
    flight_records,
    gauge,
    gauges,
    histogram,
    histograms,
    registry_view,
    remove,
    remove_listener,
    reset,
    snapshot,
    span,
    start_span,
    tracing,
)

__all__ = [
    "Histogram",
    "Span",
    "add_listener",
    "on_reset",
    "configure",
    "counter",
    "counters",
    "drain",
    "emit_counters",
    "enabled",
    "event",
    "events_enabled",
    "flight_dump",
    "flight_records",
    "gauge",
    "gauges",
    "histogram",
    "histograms",
    "registry_view",
    "remove",
    "remove_listener",
    "reset",
    "snapshot",
    "span",
    "start_span",
    "tracing",
]
