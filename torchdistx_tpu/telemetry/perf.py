"""Perf plane: compile observatory + HBM ledger with OOM forensics.

PR 9/10 made the *request* path transparent; this module opens the
*device* side — the two costs that actually sink a serving engine or a
materialization and that nothing upstream could attribute:

**Compile observatory.**  XLA compile time dominates materialization
cost (the very fact ``utils/compilation_cache.py`` exists for), and the
serving engine's whole performance model rests on ONE compiled decode
chunk — a shape leak that recompiles it per tick shows up only as
mysteriously cratered tok/s.  Three labeled families make both visible:

* ``compile.count{program=}`` — compiles per program label,
* ``compile.time_s{program=}`` — compile-duration histogram,
* ``compile.recompiles{program=}`` — compiles beyond a program's first.

Attribution is two-layered.  :class:`JitProgram` wraps a jitted callable
under a stable label and detects (re)compiles exactly, via the jit
cache-size delta around each call — donation, tracing, and monkeypatched
stand-ins (chaos tests swap the decode chunk for a flaky double) all
pass through untouched.  Where the running JAX exposes
``jax.monitoring`` duration events (:func:`install_monitoring`, hooked
by ``ensure_compilation_cache``), the listener supplies the precise
backend-compile duration and catches every compile *outside* a wrapped
call too (attributed to the ambient :func:`program` scope, else
``other``); without it, call wall time is the fallback.  Every event
lands exactly once: a scope in which the listener already counted
suppresses the fallback.

The **recompile-storm detector** rides the recompile counter: the same
program recompiled ``TDX_RECOMPILE_STORM_N`` times (default 3) inside
``TDX_RECOMPILE_STORM_WINDOW_S`` (default 30 s) latches
``serve.recompile_storm{engine=}``, dumps the flight recorder with
``reason="recompile_storm"``, and marks the owning engine OVERLOADED
(the stall-watchdog convention: a fleet router routes around it; the
latch clears once the program goes a full window without recompiling).
A shape leak in the decode chunk is caught live, not in next week's
bench.

**HBM ledger.**  Device memory is spent by four subsystems — weights,
the paged KV pool, swap staging, prefix-cache-held pages — and a
``RESOURCE_EXHAUSTED`` names none of them.  :data:`ledger` attributes
bytes per component as ``mem.hbm_bytes{component=}`` gauges
(``register``/``unregister``; multiple owners of one component sum, and
shared ownership — N engines over one params pytree — dedupes by owner
key).  :func:`oom_dump` snapshots the ledger into the flight record
(``reason="device_oom"`` / ``"pool_exhausted"``) so an OOM post-mortem
reads *what held the memory*, not just that it ran out; :func:`is_oom`
classifies the error strings XLA actually raises.

Like the rest of telemetry: dependency-light (jax imported lazily, only
by the monitoring hookup), never fails the instrumented operation, and
free when nothing records — the non-compile fast path of a wrapped call
is two ints and a perf_counter.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from . import _core
from . import timeplane as _timeplane

__all__ = [
    "JitProgram",
    "Ledger",
    "install_monitoring",
    "is_oom",
    "ledger",
    "monitoring_installed",
    "oom_dump",
    "program",
    "pytree_nbytes",
    "record_compile",
    "storm_config",
]

_T_OOMS = _core.counter("mem.ooms")
_T_STORMS = _core.counter("serve.recompile_storms")

# Substrings of the errors XLA actually raises when device memory runs
# out (XlaRuntimeError carries the grpc-style status name).
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "RESOURCE EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OutOfMemory",
)


# ---------------------------------------------------------------------------
# Program attribution scopes + the jax.monitoring hookup

_tls = threading.local()

# The jax.monitoring duration-event names that mean "XLA compiled a
# program" across the jax versions this stack supports.
_COMPILE_EVENTS = (
    "/jax/core/compile/backend_compile_duration",
    "/jax/core/compile/backend_compile_duration_sec",
)

_install_lock = threading.Lock()
_monitoring = False  # listener registered successfully


class _Scope:
    """One thread's ambient program label for compile attribution.

    ``counted`` flips when the monitoring listener lands an event inside
    the scope, so the scope owner's wall-time fallback
    (:meth:`ensure_counted`) never double-counts a compile the listener
    already recorded precisely.  ``track`` marks labels that denote ONE
    program identity (the :class:`JitProgram` scopes): only those feed
    the recompile counter and the storm detector — a broad label like
    ``materialize`` or ``other`` covers many distinct programs, whose
    second compile is not a recompile of anything."""

    __slots__ = ("label", "owner", "counted", "track")

    def __init__(self, label: str, owner: Any = None, track: bool = False):
        self.label = label
        self.owner = owner
        self.counted = 0
        self.track = track

    def ensure_counted(self, fallback_duration_s: float) -> None:
        """Guarantee exactly one compile record for this scope: a no-op
        when the listener already attributed one, else the fallback
        (call wall time — an upper bound that includes the first
        execute, honest enough for the histogram's ~33% buckets)."""
        if not self.counted:
            record_compile(
                self.label, fallback_duration_s, owner=self.owner,
                track=self.track,
            )


class program:
    """Context manager: attribute XLA compiles in this thread to
    ``label`` (``with perf.program("materialize"): ...``).  Nests —
    the innermost scope wins.  Yields the scope object."""

    def __init__(self, label: str, owner: Any = None, track: bool = False):
        self.scope = _Scope(label, owner, track)

    def __enter__(self) -> _Scope:
        stack = getattr(_tls, "scopes", None)
        if stack is None:
            stack = _tls.scopes = []
        stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc) -> bool:
        stack = getattr(_tls, "scopes", None)
        if stack and stack[-1] is self.scope:
            stack.pop()
        elif stack and self.scope in stack:  # tolerate imbalance
            stack.remove(self.scope)
        return False


def _current_scope() -> Optional[_Scope]:
    stack = getattr(_tls, "scopes", None)
    return stack[-1] if stack else None


def _on_duration_event(name: str, duration_s: float, **kwargs) -> None:
    """The jax.monitoring listener: every backend compile lands here,
    on the compiling thread, and is attributed to that thread's ambient
    scope (``other`` when none).  Never raises — telemetry must not
    fail the compile it observes."""
    try:
        if name not in _COMPILE_EVENTS:
            return
        scope = _current_scope()
        if scope is not None:
            scope.counted += 1
            record_compile(
                scope.label, duration_s, owner=scope.owner,
                track=scope.track,
            )
        else:
            record_compile("other", duration_s)
    except Exception:  # noqa: BLE001
        pass


def install_monitoring() -> bool:
    """Register the compile-duration listener with ``jax.monitoring``
    (idempotent; False when this JAX has no monitoring API).  Hooked by
    ``ensure_compilation_cache`` and the serving engine, so either
    entry point arms the observatory."""
    global _monitoring
    if _monitoring:
        return True
    with _install_lock:
        if _monitoring:
            return True
        try:
            from jax import monitoring as _jm

            _jm.register_event_duration_secs_listener(_on_duration_event)
            _monitoring = True
        except Exception:  # noqa: BLE001 — no jax / old jax: fallback timing
            return False
    return True


def monitoring_installed() -> bool:
    return _monitoring


# ---------------------------------------------------------------------------
# Compile recording + the recompile-storm detector

_storm_lock = threading.Lock()
# (program, engine_id) -> compiles seen for that exact program identity
# (tracked calls only).  Recompile semantics live HERE, not on the bare
# label: one process may hold N engines of different geometries, each
# legitimately compiling "decode_chunk" once — a recompile is the SAME
# engine's program compiling again.
_per_owner_compiles: Dict[Tuple[str, str], int] = {}
# (program, engine_id) -> deque of recompile timestamps in the window
_recompiles: Dict[Tuple[str, str], deque] = {}
# (program, engine_id) latched storms, cleared when the window drains
_latched: Dict[Tuple[str, str], float] = {}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


_STORM_N = max(2, int(_env_float("TDX_RECOMPILE_STORM_N", 3)))
_STORM_WINDOW_S = _env_float("TDX_RECOMPILE_STORM_WINDOW_S", 30.0)


def storm_config(
    threshold: Optional[int] = None, window_s: Optional[float] = None
) -> Tuple[int, float]:
    """Read (and optionally set — tests) the storm detector's knobs:
    ``threshold`` recompiles of one program within ``window_s`` seconds
    latch the storm.  Returns the previous ``(threshold, window_s)``."""
    global _STORM_N, _STORM_WINDOW_S
    prev = (_STORM_N, _STORM_WINDOW_S)
    if threshold is not None:
        if threshold < 2:
            raise ValueError("storm threshold must be >= 2")
        _STORM_N = int(threshold)
    if window_s is not None:
        if window_s <= 0:
            raise ValueError("storm window_s must be > 0")
        _STORM_WINDOW_S = float(window_s)
    return prev


def record_compile(
    prog: str, duration_s: float, owner: Any = None, track: bool = False
) -> None:
    """Count one compile of ``prog``: the count/time families always;
    with ``track`` (the label denotes one exact program identity — a
    :class:`JitProgram` call site), also the per-``(program, owner)``
    recompile counter past that identity's first compile, and the storm
    check."""
    c = _core.counter("compile.count", program=prog)
    c.add()
    _core.histogram("compile.time_s", program=prog).observe(
        max(0.0, float(duration_s))
    )
    if track:
        _note_tracked_compile(prog, owner)


def _owner_eid(owner: Any) -> str:
    return str(getattr(owner, "engine_id", "")) if owner is not None else ""


def _note_tracked_compile(prog: str, owner: Any) -> None:
    eid = _owner_eid(owner)
    key = (prog, eid)
    now = time.monotonic()
    cut = now - _STORM_WINDOW_S
    with _storm_lock:
        n = _per_owner_compiles.get(key, 0) + 1
        _per_owner_compiles[key] = n
        if n <= 1:
            return  # this identity's FIRST compile: not a recompile
        dq = _recompiles.setdefault(key, deque())
        dq.append(now)
        while dq and dq[0] < cut:
            dq.popleft()
        storming = len(dq) >= _STORM_N
        fresh = storming and key not in _latched
        if storming:
            _latched[key] = now
    _core.counter("compile.recompiles", program=prog).add()
    if not fresh:
        return
    # Side effects OUTSIDE the lock (flight_dump is file I/O and the
    # owner hook may take engine-side locks).
    _T_STORMS.add()
    if eid:
        _core.gauge("serve.recompile_storm", engine=eid).set(1)
    _core.event(
        "perf.recompile_storm", engine=eid or None, program=prog,
        n=_STORM_N, window_s=_STORM_WINDOW_S,
    )
    _core.flight_dump(
        "recompile_storm", program=prog, engine=eid or None,
        n_recompiles=_STORM_N, window_s=_STORM_WINDOW_S,
        ledger=ledger.components(),
    )
    # Trigger-fired profiler capture (rate-limited; no-op with no
    # trigger installed): a storm's dump comes with a device profile of
    # the recompiling window — the compile stalls are IN it.
    _timeplane.fire_profile("recompile_storm", engine=eid or None, program=prog)
    if owner is not None:
        try:
            # The stall-watchdog convention: OVERLOADED routes a fleet
            # around the engine; its own healthy ticks restore READY.
            owner._mark_stalled()
        except Exception:  # noqa: BLE001 — observability never fails serving
            pass


def _maybe_unlatch(prog: str, owner: Any) -> None:
    """Clear a latched storm once ``prog`` has gone a full window with
    no recompile (called from the wrapped-call fast path — one dict
    probe when nothing is latched)."""
    if not _latched:
        return
    eid = _owner_eid(owner)
    key = (prog, eid)
    with _storm_lock:
        last = _latched.get(key)
        if last is None or time.monotonic() - last < _STORM_WINDOW_S:
            return
        del _latched[key]
        # The engine gauge covers EVERY program on the engine: it only
        # clears when the last of the engine's latched storms drains —
        # one program going quiet must not mask another still churning.
        still_latched = any(k[1] == eid for k in _latched)
    if eid and not still_latched:
        _core.gauge("serve.recompile_storm", engine=eid).set(0)


# ---------------------------------------------------------------------------
# JitProgram: exact per-program compile detection at the call site


def _cache_size(fn: Any) -> Optional[int]:
    """The jitted callable's executable-cache entry count, or None for
    anything that is not a live jit wrapper (plain functions, chaos
    stand-ins) — those pass through uninstrumented."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 — foreign wrapper: pass through
        return None


class JitProgram:
    """One jitted program under a stable observatory label.

    ``resolve`` is a zero-arg callable returning the CURRENT function —
    late-bound so a module-global the engine's tests monkeypatch
    (``engine._decode_chunk``) stays patchable; a stand-in without a
    jit cache is simply not instrumented.  ``call`` passes everything
    through and, when the call grew the jit cache, records the compile
    under ``program`` (per-call override for bucketed variants) against
    ``owner`` (the engine the storm detector should mark)."""

    __slots__ = ("resolve", "program")

    def __init__(self, resolve: Callable[[], Any], program: str):
        self.resolve = resolve
        self.program = program

    def call(
        self, owner: Any, prog: Optional[str], *args, **kwargs
    ) -> Any:
        fn = self.resolve()
        n0 = _cache_size(fn)
        if n0 is None:
            return fn(*args, **kwargs)
        label = prog or self.program
        t0 = time.perf_counter()
        with program(label, owner, track=True) as scope:
            out = fn(*args, **kwargs)
        n1 = _cache_size(fn)
        if n1 is not None and n1 > n0 and not scope.counted:
            # The cache grew but no compile event landed on THIS thread:
            # a persistent-cache deserialize (no backend compile), or —
            # these jit fns are module-global — ANOTHER engine's
            # concurrent compile bumping the shared cache.  Count the
            # program load, but feed recompile/storm tracking only when
            # monitoring is absent entirely: with the listener armed, it
            # is the exact per-thread source, and attributing a peer's
            # compile here could storm-latch a healthy engine.
            record_compile(
                label, time.perf_counter() - t0, owner=owner,
                track=not _monitoring,
            )
        elif n1 is not None and n1 <= n0 and not scope.counted:
            _maybe_unlatch(label, owner)
        return out


# ---------------------------------------------------------------------------
# HBM ledger


def pytree_nbytes(tree: Any) -> int:
    """Total array bytes of a pytree (jax arrays, numpy — anything with
    ``nbytes``)."""
    import jax

    return sum(
        int(getattr(x, "nbytes", 0)) for x in jax.tree.leaves(tree)
    )


class Ledger:
    """Attribute device bytes to named components.

    ``register(component, nbytes, owner=...)`` sets one owner's share of
    a component; the exported ``mem.hbm_bytes{component=}`` gauge is the
    sum over owners, so N engines each registering their ``kv_pool``
    read as one pool total, while N engines sharing ONE params pytree
    register ``weights`` under the same owner key and count once.  An
    owner that goes away (engine close) ``unregister``-s; a component
    whose last owner leaves is pruned from the registry — bounded
    cardinality, same rule as the tenant families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], int] = {}

    def register(
        self, component: str, nbytes: int, owner: Any = None
    ) -> None:
        key = (str(component), str(owner) if owner is not None else "")
        with self._lock:
            # Gauge update INSIDE the lock: a register racing an
            # unregister (hot swap tearing v1 down while v2 builds)
            # must not apply its total after the other's prune and
            # leave a live component missing from /metrics.  (The
            # registry lock nests under this one and never takes it
            # back — no ordering cycle.)
            self._entries[key] = int(nbytes)
            _core.gauge("mem.hbm_bytes", component=component).set(
                self._component_total(component)
            )

    def unregister(self, component: str, owner: Any = None) -> None:
        key = (str(component), str(owner) if owner is not None else "")
        with self._lock:
            self._entries.pop(key, None)
            total = self._component_total(component)
            if total:
                _core.gauge("mem.hbm_bytes", component=component).set(total)
            else:
                _core.remove("mem.hbm_bytes", component=component)

    def _component_total(self, component: str) -> int:
        return sum(
            v for (c, _), v in self._entries.items() if c == component
        )

    def components(self) -> Dict[str, int]:
        """``{component: total bytes}`` — the snapshot OOM dumps carry."""
        with self._lock:
            out: Dict[str, int] = {}
            for (c, _), v in self._entries.items():
                out[c] = out.get(c, 0) + v
        return out

    def owners(self, component: Optional[str] = None) -> Dict[
        Tuple[str, str], int
    ]:
        """``{(component, owner): bytes}`` — the per-owner attribution.

        This is what the model plane's eviction policy reads
        (docs/serving.md, "Model plane"): real registered numbers for
        who holds what — ``weights`` per model, ``kv_pool`` per engine,
        ``prefix_cache_held`` per engine — not estimates recomputed on
        the side.  ``component`` filters to one component's owners."""
        with self._lock:
            return {
                k: v
                for k, v in self._entries.items()
                if component is None or k[0] == component
            }

    def total(self) -> int:
        with self._lock:
            return sum(self._entries.values())

    def _clear(self) -> None:
        with self._lock:
            comps = {c for c, _ in self._entries}
            self._entries.clear()
        for c in comps:
            _core.remove("mem.hbm_bytes", component=c)


ledger = Ledger()


# ---------------------------------------------------------------------------
# OOM forensics


def is_oom(err: BaseException) -> bool:
    """True when ``err`` is a device out-of-memory (the
    RESOURCE_EXHAUSTED family XLA raises)."""
    msg = f"{type(err).__name__}: {err}"
    return any(marker in msg for marker in _OOM_MARKERS)


def oom_dump(reason: str, *, engine: Optional[str] = None, **attrs) -> int:
    """The OOM post-mortem moment: count it, emit the event, and dump
    the flight ring with the HBM ledger snapshot attached — so the
    record of *what held the memory* survives the failure.  ``reason``
    is ``"device_oom"`` for a RESOURCE_EXHAUSTED device call and
    ``"pool_exhausted"`` for a page-pool reservation that could not be
    met.  Returns the number of flight records dumped."""
    _T_OOMS.add()
    components = ledger.components()
    _core.event(
        "mem.oom", engine=engine, reason=reason,
        hbm_bytes=components, **attrs,
    )
    return _core.flight_dump(
        reason, engine=engine, ledger=components,
        hbm_total_bytes=sum(components.values()), **attrs,
    )


# ---------------------------------------------------------------------------
# Test isolation: telemetry.reset() clears perf state too


def _reset() -> None:
    with _storm_lock:
        _per_owner_compiles.clear()
        _recompiles.clear()
        _latched.clear()
    ledger._clear()


_core.on_reset(_reset)
