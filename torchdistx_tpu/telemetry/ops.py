"""Live ops plane: metrics exposition, health, SLO burn-rate, stall watchdog.

PR 9 made every request reconstructible *after the fact*; this module is
the **live** operational surface (the vLLM/Orca-style scrape plane): a
running engine or fleet answers "what is happening right now" over HTTP
instead of requiring a process kill and a JSONL post-mortem.  Four
pieces, all opt-in (``Engine(ops_port=...)`` / ``FleetRouter(ops_port=
...)`` / ``TDX_OPS_PORT``) and all free when off:

* **Metrics exposition** — :class:`OpsServer`, a stdlib ``http.server``
  endpoint serving

  - ``/metrics``: the whole telemetry registry (counters, gauges,
    histograms) rendered as Prometheus text exposition format
    (:func:`render_prometheus`).  Canonical labeled names
    (``serve.health{engine=eng0}``) re-emit as proper Prometheus labels
    (``serve_health{engine="eng0",state="ready"} 1``); histograms render
    the full ``_bucket``/``_sum``/``_count`` series with a ``+Inf``
    bucket, snapshotted under each histogram's lock so a concurrent tick
    can never tear a scrape.
  - ``/healthz``: per-engine :class:`~torchdistx_tpu.serving.lifecycle
    .Health` states as JSON — HTTP 200 while any watched engine is
    READY/STARTING, 503 otherwise (and connection-refused once the
    plane is torn down; ``Engine.close()``/STOPPED unwatches, and the
    last unwatch shuts the listener down — no dangling threads).
  - ``/requests``: a JSON snapshot of in-flight request timelines,
    rebuilt in-process by ``scripts/trace_report.reconstruct()`` over
    the live flight-recorder ring (or the in-memory collector when the
    ring is off) — "where is request X right now" without killing the
    process.  Bounded: the ``?limit=`` most-recent timelines (default
    256), so a long-lived engine can never return an unbounded body.
  - ``/profile?seconds=N``: on-demand bounded profiler capture through
    the time plane's rate-limited trigger
    (:mod:`torchdistx_tpu.telemetry.timeplane`) — 200 with the artifact
    path, 429 when the cooldown suppressed it.

* **Per-tick utilization attribution** — the engine tick loop (gated on
  this plane being attached, or :func:`enable_tick_attribution`)
  publishes per-engine labeled gauges each tick: ``serve.occupancy``
  (decode-batch slots in use / total), ``serve.prefill_budget`` (chunk
  budget used), ``serve.page_util`` (physical page-pool utilization),
  ``serve.churn`` (preempt/swap/recovery events this tick), a
  ``serve.tick_s`` histogram, and ``serve.goodput`` — committed decode
  tokens per tick-second, the serving analogue of train-side MFU.
  Together they decompose "TTFT is high" live into queue-bound vs
  prefill-bound vs page-bound vs preemption-bound.  The disabled path
  (no ``ops_port``, no ``TDX_OPS_PORT``) computes and allocates nothing
  per tick — pinned by a record-bomb-style test.

* **SLO burn-rate monitor** — :class:`SLOMonitor` subscribes to the
  telemetry record stream (:func:`torchdistx_tpu.telemetry
  .add_listener`) and tracks, per tenant over fast/slow rolling windows
  (the classic multi-window burn-rate alert), deadline-hit rate vs the
  SLO target, TTFT p95 vs target, and shed/failover rates.  Breaching
  the burn threshold in BOTH windows fires a callback — by default a
  telemetry ``flight_dump("slo_burn")`` — and flips the
  ``serve.slo_burning{tenant=...}`` gauge a router (or an alerting
  scrape) can read; recovery flips it back, and a tenant idle past the
  slow window is pruned from the monitor AND the registry
  (:func:`torchdistx_tpu.telemetry.remove`), so free-form tenant ids
  cannot grow either without bound.

* **Stall watchdog** — :class:`StallWatchdog`, a daemon thread per
  watched engine detecting the failure mode chaos can't: a *silent
  stall*, where work is pending (queued or running) but the tick loop
  makes no progress — no tick, no token, no prefill dispatch — beyond
  ``stall_deadline_s``.  On detection it flight-dumps with
  ``reason="stall"``, emits an ``ops.stall`` event, bumps
  ``serve.stalls``, sets ``serve.stalled{engine=...}``, and marks the
  engine OVERLOADED so a fleet router routes around it.  Progress
  resuming clears the latch (and the engine's own tick restores READY).

Composition: an :class:`OpsPlane` owns one server + one monitor and
watches N engines (one watchdog each).  ``Engine(ops_port=...)`` creates
or joins the plane on that port and unwatches itself at STOPPED;
``FleetRouter(ops_port=...)`` additionally ``retain()``-s the plane so
it outlives replica churn, watching replicas as they join and unwatching
as they are reaped.  The plane closes — server shut down, monitor
unsubscribed, watchdogs stopped — when the last engine AND the last
retain are gone.

This module never imports the serving package (the serving package
imports telemetry): engines are duck-typed — ``health()``,
``engine_id``, ``_tick_no``/``_decode_tokens``/``_prefill_no``,
``scheduler``, ``_n_running()``, ``_mark_stalled()``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from . import _core
from . import timeplane as _timeplane

__all__ = [
    "OpsConfig",
    "OpsPlane",
    "OpsServer",
    "SLOConfig",
    "SLOMonitor",
    "StallWatchdog",
    "attach_engine",
    "enable_tick_attribution",
    "get_plane",
    "render_prometheus",
    "tick_attribution_enabled",
]

_T_SCRAPES = _core.counter("ops.scrapes")
_T_STALLS = _core.counter("serve.stalls")
_T_SLO_BURNS = _core.counter("serve.slo_burns")

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# ---------------------------------------------------------------------------
# Prometheus text exposition rendering

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _parse_labeled(name: str) -> Tuple[str, Dict[str, str]]:
    """Split a canonical registry name (``serve.health{engine=eng0}``,
    see ``_core._labeled``) back into ``(base, labels)``.  Label values
    are percent-escaped by ``_core._label_escape`` at registration, so
    free-form values (a tenant id containing ``,`` or ``=``) split
    correctly and round-trip through ``_label_unescape``."""
    i = name.find("{")
    if i < 0 or not name.endswith("}"):
        return name, {}
    labels: Dict[str, str] = {}
    for part in name[i + 1 : -1].split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k] = _core._label_unescape(v)
    return name[:i], labels


def _prom_name(base: str) -> str:
    """``serve.queue_wait_s`` → ``serve_queue_wait_s`` (Prometheus metric
    names admit only ``[a-zA-Z0-9_:]`` and must not start with a digit)."""
    n = _NAME_SANITIZE.sub("_", base)
    if n and n[0].isdigit():
        n = "_" + n
    return n or "_"


def _escape_label(v: Any) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{_escape_label(labels[k])}"'
        for k in sorted(labels)
    )
    return "{" + inner + "}"


def _fmt_value(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render_prometheus() -> str:
    """The whole telemetry registry in Prometheus text exposition format.

    Counters render as ``counter`` families, numeric gauges as ``gauge``
    families, and non-numeric gauges (``serve.health`` holds a Health
    *string*) as enum-style gauges — the value becomes a ``state`` label
    with sample value 1 (``serve_health{engine="eng0",state="ready"} 1``)
    so a dead-simple alert (``serve_health{state="ready"} < 1``) works
    without a value mapping.  Histograms render the cumulative
    ``_bucket`` series (``le`` upper edges + ``+Inf``), ``_sum``, and
    ``_count``; each histogram's series is one locked snapshot
    (:meth:`~._core.Histogram.bucket_counts`), so the ``+Inf`` bucket
    always equals ``_count`` even mid-tick.  ``# TYPE`` is emitted once
    per family — labeled instruments of one base name group under it.
    A name registered as more than one KIND (``serve.ttft_s`` is both a
    back-compat last-reading gauge and a labeled histogram family)
    would render two conflicting ``# TYPE`` lines, which Prometheus
    rejects outright — the non-histogram family re-emits as
    ``<name>_value`` (histograms keep the base name: their
    ``_bucket``/``_sum``/``_count`` series are the ones dashboards
    aggregate)."""
    counters, gauges, histograms = _core.registry_view()
    lines: List[str] = []

    hfams: Dict[str, List[Tuple[Dict[str, Any], Any]]] = {}
    for name, h in histograms.items():
        base, labels = _parse_labeled(name)
        hfams.setdefault(_prom_name(base), []).append((labels, h))
    reserved = set(hfams)
    for p in list(reserved):
        reserved.update((f"{p}_bucket", f"{p}_sum", f"{p}_count"))

    fams: Dict[str, List[Tuple[Dict[str, Any], Any]]] = {}
    for name, c in counters.items():
        base, labels = _parse_labeled(name)
        pname = _prom_name(base)
        if pname in reserved:
            pname += "_value"
        fams.setdefault(pname, []).append((labels, c.value))
    for pname in sorted(fams):
        lines.append(f"# TYPE {pname} counter")
        for labels, v in fams[pname]:
            lines.append(f"{pname}{_fmt_labels(labels)} {_fmt_value(v)}")
    reserved.update(fams)

    fams = {}
    for name, g in gauges.items():
        v = g.value
        if v is None:
            continue
        base, labels = _parse_labeled(name)
        if not isinstance(v, (int, float, bool)):
            labels = {**labels, "state": str(v)}
            v = 1
        pname = _prom_name(base)
        if pname in reserved:
            pname += "_value"
        fams.setdefault(pname, []).append((labels, v))
    for pname in sorted(fams):
        lines.append(f"# TYPE {pname} gauge")
        for labels, v in fams[pname]:
            lines.append(f"{pname}{_fmt_labels(labels)} {_fmt_value(v)}")
    for pname in sorted(hfams):
        lines.append(f"# TYPE {pname} histogram")
        for labels, h in hfams[pname]:
            bounds, cum, total, hsum = h.bucket_counts()
            for edge, c in zip(bounds, cum):
                lines.append(
                    f"{pname}_bucket"
                    f"{_fmt_labels({**labels, 'le': format(edge, 'g')})} {c}"
                )
            lines.append(
                f"{pname}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} "
                f"{total}"
            )
            lines.append(
                f"{pname}_sum{_fmt_labels(labels)} {_fmt_value(hsum)}"
            )
            lines.append(f"{pname}_count{_fmt_labels(labels)} {total}")

    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# /requests: in-process timeline reconstruction over the live ring

_reconstruct: Any = "__unset__"


def _load_reconstruct() -> Optional[Callable]:
    """Lazy import of ``scripts/trace_report.reconstruct`` — the same
    reconstruction path bench and the CI gates use, so the live
    ``/requests`` view can never drift from the post-mortem one.  In a
    checkout (editable install) the scripts directory sits beside the
    package; an installation without it degrades ``/requests`` to 503."""
    global _reconstruct
    if _reconstruct != "__unset__":
        return _reconstruct
    try:
        from trace_report import reconstruct  # scripts/ already on path
    except ImportError:
        scripts = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            "scripts",
        )
        reconstruct = None
        if os.path.isfile(os.path.join(scripts, "trace_report.py")):
            if scripts not in sys.path:
                sys.path.insert(0, scripts)
            try:
                from trace_report import reconstruct
            except ImportError:  # pragma: no cover — half-broken checkout
                reconstruct = None
    _reconstruct = reconstruct
    return reconstruct


# ---------------------------------------------------------------------------
# SLO burn-rate monitor


@dataclasses.dataclass
class SLOConfig:
    """Targets and windows of the burn-rate monitor.

    ``slo`` is the target deadline-hit rate (the error budget is
    ``1 - slo``); the burn rate of a window is its SLO-relevant failure
    fraction divided by that budget (burn 1.0 = exactly consuming
    budget).  A tenant starts *burning* when the burn rate meets
    ``burn_threshold`` in BOTH the fast and the slow window (the
    multi-window rule: the fast window makes the alert prompt, the slow
    window keeps a single blip from firing it), or when its fast-window
    TTFT p95 exceeds ``ttft_target_s`` (when set).  Windows with fewer
    than ``min_samples`` terminal events never fire."""

    slo: float = 0.99
    ttft_target_s: Optional[float] = None
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    burn_threshold: float = 4.0
    min_samples: int = 10
    # (tenant, info) -> None; None = flight_dump("slo_burn", ...)
    on_burn: Optional[Callable[[str, Dict[str, Any]], None]] = None


class _TenantWindows:
    """One tenant's fast/slow rolling windows with incremental per-kind
    counters: appends and evictions are O(1) amortized, so the monitor
    costs O(1) per event on the emitting (serving) thread instead of
    rescanning the whole slow-window deque."""

    __slots__ = ("fast", "slow", "fast_n", "slow_n")

    def __init__(self):
        self.fast: deque = deque()  # (ts, kind, value) in the fast window
        self.slow: deque = deque()  # ... in the slow window
        self.fast_n: Dict[str, int] = {}
        self.slow_n: Dict[str, int] = {}

    def add(self, ts, kind, value, fast_cut, slow_cut) -> None:
        self.fast.append((ts, kind, value))
        self.fast_n[kind] = self.fast_n.get(kind, 0) + 1
        self.slow.append((ts, kind, value))
        self.slow_n[kind] = self.slow_n.get(kind, 0) + 1
        self.evict(fast_cut, slow_cut)

    def evict(self, fast_cut, slow_cut) -> None:
        for dq, counts, cut in (
            (self.fast, self.fast_n, fast_cut),
            (self.slow, self.slow_n, slow_cut),
        ):
            while dq and dq[0][0] < cut:
                _, kind, _ = dq.popleft()
                left = counts[kind] - 1
                if left:
                    counts[kind] = left
                else:
                    del counts[kind]

    @staticmethod
    def terminal(counts: Dict[str, int]) -> int:
        return (
            counts.get("good", 0)
            + counts.get("miss", 0)
            + counts.get("infra", 0)
        )

    @staticmethod
    def rates(counts: Dict[str, int]) -> Dict[str, Any]:
        t = _TenantWindows.terminal(counts)
        return {
            "n": t,
            "deadline_hit_rate": round(counts.get("good", 0) / max(1, t), 4),
            "shed": counts.get("shed", 0),
            "failovers": counts.get("failover", 0),
        }

    def fast_ttfts(self) -> List[float]:
        return [v for _, kind, v in self.fast if kind == "ttft"]


class SLOMonitor:
    """Windowed SLO tracker over the request-lifecycle event stream.

    Subscribed as a telemetry record listener (:func:`subscribe`), it
    watches ``req.*`` events: ``req.submitted`` binds a rid to its
    tenant, ``req.finished`` counts good, ``req.failed`` classifies by
    error type (``DeadlineExceeded`` → miss, ``EngineOverloaded`` →
    shed, client cancels ignored, other *non-retryable* terminals →
    infra; retryable failures are a router's to heal and only feed the
    shed/failover rates), ``req.first_token`` feeds the TTFT window,
    ``req.failover_hop`` the failover rate.  Event timestamps — not the
    wall clock — drive the windows, so replayed traces evaluate
    deterministically.

    State is bounded: the rid→tenant map is a capped LRU, window deques
    drop past the slow window, and a tenant with no events left is
    pruned from the monitor and its ``serve.slo_burning`` gauge removed
    from the registry.

    Locking: window state mutates under the monitor's lock on the
    emitting thread, but state-transition SIDE EFFECTS — the gauge
    write, the burn counter, and the ``on_burn`` callback (default:
    ``flight_dump`` file I/O) — run after it is released, so a callback
    that reads :meth:`summary`/:meth:`burning` cannot deadlock the
    serving tick loop."""

    _RID_CAP = 8192
    _PRUNE_EVERY = 512

    def __init__(self, config: Optional[SLOConfig] = None):
        self.config = config or SLOConfig()
        if not 0.0 < self.config.slo < 1.0:
            raise ValueError("slo must be in (0, 1)")
        if self.config.fast_window_s > self.config.slow_window_s:
            raise ValueError("fast_window_s must be <= slow_window_s")
        self._lock = threading.Lock()
        self._rid_ctx: OrderedDict = OrderedDict()  # rid -> tenant
        self._events: Dict[str, _TenantWindows] = {}
        self._burning: Dict[str, bool] = {}
        self._n_seen = 0
        # Burn-transition listeners (add_burn_listener): consumers of
        # burn state — an autoscaler, a pager bridge — that COMPOSE with
        # the primary on_burn callback instead of replacing it.
        self._listeners: List[Callable[[str, bool, Optional[Dict]], None]] = []

    # -- lifecycle ----------------------------------------------------------

    def subscribe(self) -> "SLOMonitor":
        _core.add_listener(self._on_record)
        return self

    def close(self) -> None:
        _core.remove_listener(self._on_record)
        with self._lock:
            tenants = set(self._events) | set(self._burning)
            self._events.clear()
            self._rid_ctx.clear()
            self._burning.clear()
            self._listeners.clear()
        for tenant in tenants:
            _core.remove("serve.slo_burning", tenant=tenant)

    # -- burn-transition listeners ------------------------------------------

    def add_burn_listener(
        self, fn: Callable[[str, bool, Optional[Dict[str, Any]]], None]
    ) -> None:
        """Register ``fn(tenant, burning, info)`` for burn-state
        transitions.  Unlike ``SLOConfig.on_burn`` — the PRIMARY
        callback, which replaces the default flight-dump action —
        listeners COMPOSE: the primary runs first, then every listener
        in registration order, so an autoscaler subscribing here never
        silences the flight recorder.  Listeners see BOTH edges:
        ``burning=True`` with the burn info dict, and ``burning=False``
        with ``info=None`` when the tenant genuinely recovers.  A tenant
        pruned for idleness does NOT emit a recovery edge — no traffic
        is not evidence the SLO is healthy again — its gauge simply
        leaves the registry."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_burn_listener(self, fn) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    # -- the listener -------------------------------------------------------

    def _on_record(self, rec: Dict[str, Any]) -> None:
        if rec.get("type") != "event":
            return
        name = rec.get("name", "")
        if not name.startswith("req."):
            return
        rid = rec.get("rid")
        if rid is None:
            return
        attrs = rec.get("attrs") or {}
        ts = float(rec.get("ts") or time.time())
        transition = None
        with self._lock:
            if name == "req.submitted":
                tenant = attrs.get("tenant")
                if tenant is not None:
                    self._rid_ctx[rid] = str(tenant)
                    self._rid_ctx.move_to_end(rid)
                    while len(self._rid_ctx) > self._RID_CAP:
                        self._rid_ctx.popitem(last=False)
                return
            tenant = self._rid_ctx.get(rid)
            if tenant is None:
                return
            if name == "req.first_token":
                t = attrs.get("ttft_s")
                if t is not None:
                    transition = self._observe(tenant, ts, "ttft", float(t))
            elif name == "req.failover_hop":
                transition = self._observe(tenant, ts, "failover", 1.0)
            elif name == "req.finished":
                self._rid_ctx.pop(rid, None)
                transition = self._observe(tenant, ts, "good", 1.0)
            elif name == "req.failed":
                err = attrs.get("error", "")
                retryable = bool(attrs.get("retryable", False))
                if err == "RequestCancelled":
                    self._rid_ctx.pop(rid, None)  # the client's own doing
                elif err == "DeadlineExceeded":
                    self._rid_ctx.pop(rid, None)
                    transition = self._observe(tenant, ts, "miss", 1.0)
                elif err == "EngineOverloaded":
                    # Shed is retryable (a router re-places it): rate
                    # signal only, the rid stays bound for its retry.
                    transition = self._observe(tenant, ts, "shed", 1.0)
                elif not retryable:
                    self._rid_ctx.pop(rid, None)
                    transition = self._observe(tenant, ts, "infra", 1.0)
                # retryable non-shed failures: a hop will follow.
        if transition is not None:
            self._apply_transition(*transition)

    # -- windows ------------------------------------------------------------

    def _observe(self, tenant: str, ts: float, kind: str, value: float):
        """Record one observation; returns a ``(tenant, burning, info)``
        state transition for the caller to apply OUTSIDE the lock, or
        None."""
        cfg = self.config
        tw = self._events.setdefault(tenant, _TenantWindows())
        tw.add(
            ts, kind, value,
            ts - cfg.fast_window_s, ts - cfg.slow_window_s,
        )
        self._n_seen += 1
        if self._n_seen % self._PRUNE_EVERY == 0:
            self._prune_idle(ts)
        return self._evaluate(tenant, tw)

    def _evaluate(self, tenant: str, tw: _TenantWindows):
        cfg = self.config
        budget = max(1e-9, 1.0 - cfg.slo)

        def burn(counts: Dict[str, int]) -> float:
            t = _TenantWindows.terminal(counts)
            if t < cfg.min_samples:
                return 0.0
            return (
                (counts.get("miss", 0) + counts.get("infra", 0)) / t
            ) / budget

        burning = (
            burn(tw.fast_n) >= cfg.burn_threshold
            and burn(tw.slow_n) >= cfg.burn_threshold
        )
        ttft_p95 = None
        if cfg.ttft_target_s is not None:
            xs = tw.fast_ttfts()
            if len(xs) >= cfg.min_samples:
                xs.sort()
                ttft_p95 = xs[min(len(xs) - 1, int(0.95 * len(xs)))]
                burning = burning or ttft_p95 > cfg.ttft_target_s
        prev = self._burning.get(tenant, False)
        if burning == prev:
            return None
        self._burning[tenant] = burning
        if not burning:
            return tenant, False, None
        info = {
            "burn_fast": round(burn(tw.fast_n), 3),
            "burn_slow": round(burn(tw.slow_n), 3),
            "fast": _TenantWindows.rates(tw.fast_n),
            "slow": _TenantWindows.rates(tw.slow_n),
        }
        if ttft_p95 is not None:
            info["ttft_p95_s"] = round(ttft_p95, 6)
        return tenant, True, info

    def _apply_transition(
        self, tenant: str, burning: bool, info: Optional[Dict[str, Any]]
    ) -> None:
        """Side effects of a burn-state change, run WITHOUT the
        monitor's lock: the gauge write, the counter, and the user (or
        default flight-dump) callback — an ``on_burn`` that reads
        :meth:`summary` must not deadlock the serving thread."""
        _core.gauge("serve.slo_burning", tenant=tenant).set(int(burning))
        if burning:
            _T_SLO_BURNS.add()
            # The PRIMARY action first (user on_burn replaces the
            # default flight dump), then the composing listeners — an
            # autoscaler reacting to the burn must find the dump already
            # on the ring, not race it.
            cb = self.config.on_burn or self._default_on_burn
            try:
                cb(tenant, info)
            except Exception:  # noqa: BLE001 — monitoring never fails serving
                pass
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(tenant, burning, info)
            except Exception:  # noqa: BLE001 — monitoring never fails serving
                pass

    @staticmethod
    def _default_on_burn(tenant: str, info: Dict[str, Any]) -> None:
        # The post-mortem moment the flight recorder exists for: the
        # ring holds the requests that burned the budget — and, with a
        # profiler trigger installed (docs/observability.md, "Time
        # plane"), a bounded device profile of the burning window rides
        # along.
        _core.flight_dump("slo_burn", tenant=tenant, **info)
        _timeplane.fire_profile("slo_burn", tenant=tenant)

    def _drop_tenant(self, tenant: str) -> None:
        # Deliberately NOT a burn transition: a tenant pruned while
        # burning went idle, it did not recover — listeners (the
        # autoscaler's cooldown logic) never see a False edge here, and
        # the gauge is removed rather than zeroed.
        self._events.pop(tenant, None)
        self._burning.pop(tenant, None)
        # Registry prune: an idle tenant's gauge leaves /metrics (and
        # the exported counters snapshots) entirely — bounded
        # cardinality under free-form tenant ids.  (Registry removal
        # takes only the registry lock — no user code, no I/O — so it
        # is safe under the monitor's lock.)
        _core.remove("serve.slo_burning", tenant=tenant)

    def _prune_idle(self, now: float) -> None:
        cutoff = now - self.config.slow_window_s
        for tenant in [
            t
            for t, tw in self._events.items()
            if not tw.slow or tw.slow[-1][0] < cutoff
        ]:
            self._drop_tenant(tenant)

    # -- introspection ------------------------------------------------------

    def burning(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._burning)

    def summary(self) -> Dict[str, Any]:
        """Per-tenant fast/slow window rates (the live SLO view)."""
        cfg = self.config
        out: Dict[str, Any] = {}
        with self._lock:
            for tenant, tw in self._events.items():
                if not tw.slow:
                    continue
                now = tw.slow[-1][0]
                tw.evict(now - cfg.fast_window_s, now - cfg.slow_window_s)
                out[tenant] = {
                    "burning": self._burning.get(tenant, False),
                    "fast": _TenantWindows.rates(tw.fast_n),
                    "slow": _TenantWindows.rates(tw.slow_n),
                }
        return out


# ---------------------------------------------------------------------------
# Stall watchdog


class StallWatchdog(threading.Thread):
    """Detect a silently stalled engine: work pending but no progress.

    A daemon thread samples the engine's progress key — ``(_tick_no,
    _decode_tokens, _prefill_no)`` (ticks executed, decode tokens
    committed, prefill chunks dispatched) — every ``poll_s``.  When work
    is pending (waiting queue non-empty or slots occupied) and the key
    has not moved for ``deadline_s``, the engine's tick loop has stopped
    making progress — a wedged driver, a hung device call, a consumer
    that stopped pulling — the exact failure mode that raises nothing
    and that chaos soaks survive without noticing.  Detection:
    ``flight_dump(reason="stall")``, an ``ops.stall`` event, the
    ``serve.stalls`` counter, ``serve.stalled{engine=...}`` set to 1,
    the engine marked OVERLOADED (``_mark_stalled``) so a fleet router
    routes around it, and the optional ``on_stall`` callback.  The latch
    clears (gauge back to 0) when progress resumes; the engine's own
    next tick restores READY.

    Reads are lock-free snapshots of ints (exact under the GIL); a
    torn read costs one poll, never a crash."""

    def __init__(
        self,
        engine,
        deadline_s: float = 30.0,
        poll_s: Optional[float] = None,
        on_stall: Optional[Callable] = None,
    ):
        eid = getattr(engine, "engine_id", "eng?")
        super().__init__(name=f"tdx-stall-{eid}", daemon=True)
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.engine = engine
        self.deadline_s = float(deadline_s)
        self.poll_s = (
            float(poll_s)
            if poll_s is not None
            else min(max(self.deadline_s / 4.0, 0.01), 0.25)
        )
        self.on_stall = on_stall
        self.stalls = 0
        self._eid = eid
        self._stop_evt = threading.Event()
        self._gauge = _core.gauge("serve.stalled", engine=eid)
        self._gauge.set(0)

    def stop(self) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=max(1.0, 4 * self.poll_s))
        # A fleet respawning replicas mints fresh engine ids: the
        # stopped watchdog's gauge leaves the registry with it, so
        # replica churn cannot grow /metrics one series per engine ever
        # seen (same bounded-cardinality rule as the tenant families).
        _core.remove("serve.stalled", engine=self._eid)

    def run(self) -> None:
        last_key = None
        last_change = time.monotonic()
        fired = False
        while not self._stop_evt.wait(self.poll_s):
            eng = self.engine
            try:
                if getattr(eng.health(), "value", None) == "stopped":
                    break
                key = (eng._tick_no, eng._decode_tokens, eng._prefill_no)
                pending = len(eng.scheduler) + eng._n_running()
            except Exception:  # noqa: BLE001 — mid-teardown races
                continue
            now = time.monotonic()
            if key != last_key or pending == 0:
                last_key = key
                last_change = now
                if fired:
                    fired = False
                    self._gauge.set(0)
                continue
            if not fired and now - last_change >= self.deadline_s:
                fired = True
                self._fire(pending)

    def _fire(self, pending: int) -> None:
        self.stalls += 1
        _T_STALLS.add()
        self._gauge.set(1)
        eid = getattr(self.engine, "engine_id", "eng?")
        _core.event(
            "ops.stall",
            engine=eid,
            pending=pending,
            deadline_s=self.deadline_s,
        )
        _core.flight_dump(
            "stall", engine=eid, pending=pending, deadline_s=self.deadline_s
        )
        # Trigger-fired profiler capture (rate-limited; no-op with no
        # trigger installed): the stall's flight dump comes with a
        # bounded device profile of the wedged window.
        _timeplane.fire_profile("stall", engine=eid, pending=pending)
        try:
            self.engine._mark_stalled()
        except Exception:  # noqa: BLE001 — a dying engine is already routed out
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(self.engine)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# The plane: server + monitor + watchdogs, refcounted


@dataclasses.dataclass
class OpsConfig:
    """Knobs of one :class:`OpsPlane` (engine/router ``ops_config=``)."""

    host: str = "127.0.0.1"
    stall_deadline_s: float = 30.0
    watchdog_poll_s: Optional[float] = None
    watchdog: bool = True
    monitor: bool = True
    slo: Optional[SLOConfig] = None  # None → SLOConfig() defaults


_PLANES: Dict[int, "OpsPlane"] = {}
_PLANES_LOCK = threading.Lock()


class OpsPlane:
    """One live ops plane: HTTP server + SLO monitor + per-engine
    watchdogs.  Engines :meth:`watch`/:meth:`unwatch`; a router
    :meth:`retain`-s across replica churn.  The plane closes itself —
    server down (connection refused, no dangling listener thread),
    monitor unsubscribed, watchdogs stopped — when the last watched
    engine and the last retain are gone."""

    def __init__(self, port: int = 0, config: Optional[OpsConfig] = None):
        self.config = config or OpsConfig()
        self._lock = threading.RLock()
        self._engines: "OrderedDict[int, tuple]" = OrderedDict()
        self._retained = 0
        self._closed = False
        self.monitor: Optional[SLOMonitor] = None
        if self.config.monitor:
            self.monitor = SLOMonitor(self.config.slo).subscribe()
        try:
            self.server = OpsServer(self, port, host=self.config.host)
        except OSError:
            # Bind failure (port in use, privileged port): the half-built
            # plane is unreachable, so its listener must not outlive it —
            # a leaked listener keeps events_enabled() True process-wide.
            if self.monitor is not None:
                self.monitor.close()
            raise
        self.port = self.server.port
        with _PLANES_LOCK:
            _PLANES[self.port] = self

    @property
    def closed(self) -> bool:
        return self._closed

    def engines(self) -> List[Any]:
        with self._lock:
            return [eng for eng, _ in self._engines.values()]

    def watch(self, engine) -> None:
        """Register an engine: healthz entry + stall watchdog + the
        per-tick attribution gate (the engine's ``_ops_plane`` back-ref,
        set only when the engine doesn't already carry one).  Idempotent
        per engine."""
        with self._lock:
            if self._closed:
                raise RuntimeError("ops plane is closed")
            if id(engine) in self._engines:
                return
            wd = None
            if self.config.watchdog:
                wd = StallWatchdog(
                    engine,
                    deadline_s=self.config.stall_deadline_s,
                    poll_s=self.config.watchdog_poll_s,
                )
                wd.start()
            self._engines[id(engine)] = (engine, wd)
        if getattr(engine, "_ops_plane", "__missing__") is None:
            engine._ops_plane = self

    def unwatch(self, engine) -> None:
        """Drop an engine (idempotent); closes the plane when it was the
        last and nothing retains it."""
        with self._lock:
            ent = self._engines.pop(id(engine), None)
        if ent is None:
            return
        _, wd = ent
        if wd is not None:
            wd.stop()
        if getattr(engine, "_ops_plane", None) is self:
            engine._ops_plane = None
        self._maybe_close()

    def retain(self) -> "OpsPlane":
        with self._lock:
            self._retained += 1
        return self

    def release(self) -> None:
        with self._lock:
            self._retained = max(0, self._retained - 1)
        self._maybe_close()

    def _maybe_close(self) -> None:
        with self._lock:
            if self._closed or self._engines or self._retained > 0:
                return
        self.close()

    def close(self) -> None:
        """Tear the plane down NOW: watchdogs stopped, monitor
        unsubscribed, server shut (its port refuses connections — the
        strongest form of a non-200 ``/healthz``).  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._engines.values())
            self._engines.clear()
        for engine, wd in entries:
            if wd is not None:
                wd.stop()
            if getattr(engine, "_ops_plane", None) is self:
                engine._ops_plane = None
        if self.monitor is not None:
            self.monitor.close()
        self.server.close()
        with _PLANES_LOCK:
            if _PLANES.get(self.port) is self:
                del _PLANES[self.port]

    # -- endpoint bodies ----------------------------------------------------

    def _healthz(self) -> Tuple[int, Dict[str, Any]]:
        states: Dict[str, str] = {}
        ready = False
        for eng in self.engines():
            try:
                hv = getattr(eng.health(), "value", str(eng.health()))
            except Exception:  # noqa: BLE001 — an engine mid-teardown
                hv = "unknown"
            states[str(getattr(eng, "engine_id", id(eng)))] = hv
            ready = ready or hv in ("ready", "starting")
        return (
            200 if ready else 503,
            {"status": "ok" if ready else "unavailable", "engines": states},
        )

    def _requests(self, limit: int = 256) -> Tuple[int, Dict[str, Any]]:
        reconstruct = _load_reconstruct()
        if reconstruct is None:
            return 503, {
                "error": "scripts/trace_report.py not importable in this "
                "installation"
            }
        records = _core.flight_records()
        source = "flight"
        if not records and _core._state.collect:
            records = list(_core._state.spans)
            source = "collector"
        report = reconstruct(records)
        # Bounded response: the `limit` MOST-RECENT timelines (by last
        # event timestamp), so a long-lived engine's flight ring can
        # never produce an unbounded JSON body.  `?limit=N` overrides;
        # `n_timelines` is the unbounded count for the caller to page.
        def last_ts(rid: str) -> float:
            return max(
                (float(e.get("ts") or 0.0)
                 for e in report.requests[rid].events),
                default=0.0,
            )

        rids = sorted(report.requests, key=lambda r: (last_ts(r), r))
        if limit > 0:
            rids = rids[-limit:]
        return 200, {
            "source": source,
            "n_records": len(records),
            "n_timelines": len(report.requests),
            "limit": limit,
            "requests": [
                report.requests[rid].summary() for rid in sorted(rids)
            ],
        }

    def _profile(self, seconds: Optional[float]) -> Tuple[int, Dict[str, Any]]:
        """On-demand bounded profiler capture (``/profile?seconds=N``):
        fires the process trigger (created into a temp directory when
        none is configured) and reports the artifact path, or 429 when
        the rate limit (cooldown / capture in flight) suppressed it."""
        trigger = _timeplane.get_trigger(create_default=True)
        window = seconds if seconds is not None else trigger.seconds
        path = trigger.fire("manual", seconds=window)
        if path is None:
            return 429, {
                "fired": False,
                "reason": "suppressed: capture in flight or inside the "
                f"{trigger.cooldown_s}s cooldown",
            }
        return 200, {"fired": True, "path": path, "seconds": window}


class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "tdx-ops/1"

    def log_message(self, *args) -> None:  # silent: telemetry, not noise
        pass

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        path, _, query = self.path.partition("?")
        qs = parse_qs(query)
        plane: OpsPlane = self.server.plane  # type: ignore[attr-defined]
        try:
            if path == "/metrics":
                _T_SCRAPES.add()
                body = render_prometheus().encode("utf-8")
                code, ctype = 200, PROM_CONTENT_TYPE
            elif path == "/healthz":
                code, payload = plane._healthz()
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            elif path == "/requests":
                try:
                    limit = int(qs.get("limit", ["256"])[0])
                    if limit < 1:  # the bound is the endpoint's contract
                        raise ValueError
                except ValueError:
                    code, payload = 400, {"error": "limit must be an int >= 1"}
                else:
                    code, payload = plane._requests(limit=limit)
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            elif path == "/profile":
                try:
                    seconds = (
                        float(qs["seconds"][0]) if "seconds" in qs else None
                    )
                    if seconds is not None and not 0 < seconds <= 600:
                        raise ValueError
                except ValueError:
                    code, payload = 400, {
                        "error": "seconds must be a float in (0, 600]"
                    }
                else:
                    code, payload = plane._profile(seconds)
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            else:
                code, ctype = 404, "text/plain"
                body = b"not found: /metrics /healthz /requests /profile\n"
        except Exception as e:  # noqa: BLE001 — a scrape must never crash
            code, ctype = 500, "text/plain"
            body = f"ops endpoint error: {e!r}\n".encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):  # pragma: no cover
            pass


class OpsServer:
    """The HTTP listener: a ``ThreadingHTTPServer`` on a daemon thread.
    ``port=0`` binds an ephemeral port (read it back from ``.port``)."""

    def __init__(self, plane: OpsPlane, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _OpsHandler)
        self._httpd.daemon_threads = True
        self._httpd.plane = plane  # type: ignore[attr-defined]
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"tdx-ops-{self.port}",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Wiring helpers


def get_plane(
    port: int = 0, config: Optional[OpsConfig] = None
) -> OpsPlane:
    """The plane listening on ``port``, created if absent.  ``port=0``
    always creates a fresh plane on an ephemeral port.  ``config``
    applies only at creation — joiners share the creator's plane as-is."""
    port = int(port)
    if port:
        with _PLANES_LOCK:
            plane = _PLANES.get(port)
        if plane is not None and not plane.closed:
            return plane
    return OpsPlane(port, config)


def attach_engine(
    engine, port: int = 0, config: Optional[OpsConfig] = None
) -> OpsPlane:
    """``Engine(ops_port=...)``'s implementation: get-or-create the
    plane on ``port`` and watch the engine."""
    plane = get_plane(port, config)
    plane.watch(engine)
    return plane


def env_ops_port() -> Optional[int]:
    """``TDX_OPS_PORT`` as an int, or None (unset/empty/malformed)."""
    raw = os.environ.get("TDX_OPS_PORT", "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


# Per-tick attribution without a server (bench: utilization numbers with
# no HTTP listener).  The engine's gate is
# ``self._ops_plane is not None or ops.tick_attribution_enabled()`` —
# one attribute read and one module-global read per tick, no allocation.
_TICK_ATTRIBUTION = False


def enable_tick_attribution(on: bool = True) -> bool:
    """Force per-tick utilization attribution on (or off) process-wide,
    independent of any ops server.  Returns the previous value so a
    scope (bench) can restore it."""
    global _TICK_ATTRIBUTION
    prev = _TICK_ATTRIBUTION
    _TICK_ATTRIBUTION = bool(on)
    return prev


def tick_attribution_enabled() -> bool:
    return _TICK_ATTRIBUTION
