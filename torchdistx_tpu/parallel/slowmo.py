"""SlowMo (Slow Momentum) — communication-efficient data-parallel training.

TPU-native rebuild of the reference's SlowMo feature
(/root/reference/src/python/torchdistx/slowmo/slowmo_comm.py,
slowmo_optimizer.py; paper arXiv:1910.00643).  The reference wraps FSDP
``NO_SHARD`` replicas: per-step gradient all-reduce over an *intra-node*
subgroup (slowmo_comm.py:30-43), a local base-optimizer step, exact parameter
averaging across nodes every ``slowmo_freq`` steps via
``PeriodicModelAverager``, and a slow-momentum update
(slowmo_optimizer.py:191-227):

    m    ← slowmo_factor · m + (prev − cur) / base_lr
    prev ← prev − slowmo_lr · base_lr · m
    cur  ← prev                                     (all on averaging steps)

TPU-native design
-----------------
No process groups, no comm hooks.  Replicas that *diverge* between averaging
steps are represented as a stacked leading axis of size ``dp`` on every
parameter/gradient leaf, sharded ``PartitionSpec("dp", ...)`` over the mesh's
DCN-major axis.  Then:

* "intra-node gradient all-reduce" = nothing to do: each replica's gradient
  is computed over its own batch shard, and any tensor/fsdp sharding *within*
  a replica is reduced automatically by SPMD autodiff over the ICI axes —
  the subgroup structure of slowmo_comm.py:24-27 falls out of the mesh.
* "inter-node exact averaging" = ``mean`` over the stacked axis — XLA lowers
  it to one all-reduce over the ``dp`` (DCN) axis, only on steps where the
  ``lax.cond`` takes the averaging branch.
* the slow momentum/prev buffers live *unstacked* (they are identical on all
  replicas after every averaging step, as in the reference where every rank
  holds the same ``_prev_parameters`` after ``average_parameters``).

Everything is a pure function over an explicit :class:`SlowMoState` pytree —
jit/grad/checkpoint (orbax) compatible.
"""

from __future__ import annotations

from typing import Any, NamedTuple

__all__ = [
    "SlowMoState",
    "SlowMomentumOptimizer",
    "slowmo_grad_sync",
    "slowmo_state_dict",
    "load_slowmo_state_dict",
]


class SlowMoState(NamedTuple):
    """Optimizer state pytree (checkpointable with orbax as-is)."""

    base: Any  # per-replica (stacked) base optimizer state
    prev: Any  # replica-shared previous ("slow") parameters
    momentum: Any  # replica-shared slow momentum buffers
    step: Any  # scalar int32


def slowmo_grad_sync(grads, axis_name: str = "intra", *, enabled: bool = True):
    """Gradient all-mean over a named mesh axis — the analog of
    ``slowmo_hook`` / ``SlowMoState(sync_grads=...)`` (slowmo_comm.py:12-43)
    for ``shard_map``/``pmap`` train steps with an explicit intra axis.

    Under the stacked-replica representation used by
    :class:`SlowMomentumOptimizer` this is usually unnecessary (SPMD autodiff
    already reduces over intra-replica axes); it exists for hand-rolled
    per-device train steps.
    """
    if not enabled:
        return grads
    import jax

    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), grads)


class SlowMomentumOptimizer:
    """Wraps any optax optimizer with the SlowMo algorithm.

    Analog of ``SlowMomentumOptimizer`` (slowmo_optimizer.py:11-235), with
    the same hyperparameters, validation, and update math; pure-functional
    ``init``/``update`` instead of a stateful ``.step()``.

    Usage::

        opt = SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1,
                                    slowmo_freq=48, slowmo_factor=0.5,
                                    slowmo_lr=1.0)
        state = opt.init(stacked_params)         # leaves: (dp, ...)
        params, state = opt.update(stacked_grads, state, stacked_params)
    """

    def __init__(
        self,
        base,
        *,
        base_lr: float,
        slowmo_freq: int = 48,
        slowmo_factor: float = 0.5,
        slowmo_lr: float = 1.0,
    ):
        # Averaging cadence: fires at steps slowmo_freq, 2·slowmo_freq, …
        # The reference's PeriodicModelAverager (step counted from 0) also
        # averages on the very first step, with the momentum update skipped
        # there; steady-state behavior is identical, the phase differs by
        # one deliberate step (a warmup average of identical replicas is a
        # no-op in this functional formulation, where replicas start equal
        # by construction).
        # Same ctor validation as the reference (slowmo_optimizer.py:96-115,
        # tested upstream at test_slowmo_fsdp.py:326-364).
        if slowmo_freq < 1:
            raise ValueError(
                "Invalid ``slowmo_freq`` parameter, must be at least 1"
            )
        if slowmo_factor < 0.0:
            raise ValueError(
                "Invalid ``slowmo_factor`` parameter, must be non-negative"
            )
        if slowmo_lr < 0.0:
            raise ValueError(
                "Invalid ``slowmo_lr`` parameter, must be non-negative"
            )
        if base_lr <= 0.0:
            raise ValueError("Invalid ``base_lr`` parameter, must be positive")
        self.base = base
        self.base_lr = float(base_lr)
        self.slowmo_freq = int(slowmo_freq)
        self.slowmo_factor = float(slowmo_factor)
        self.slowmo_lr = float(slowmo_lr)

    # -- functional API -----------------------------------------------------

    def init(self, stacked_params) -> SlowMoState:
        import jax
        import jax.numpy as jnp

        base_state = jax.vmap(self.base.init)(stacked_params)
        prev = jax.tree.map(lambda p: p[0], stacked_params)
        momentum = jax.tree.map(jnp.zeros_like, prev)
        return SlowMoState(
            base=base_state,
            prev=prev,
            momentum=momentum,
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def update(self, stacked_grads, state: SlowMoState, stacked_params):
        """One SlowMo step.  Returns ``(new_stacked_params, new_state)``."""
        import jax
        import jax.numpy as jnp
        import optax

        # Local base step, independently per replica (slowmo_optimizer.py:199).
        updates, new_base = jax.vmap(self.base.update)(
            stacked_grads, state.base, stacked_params
        )
        params = optax.apply_updates(stacked_params, updates)
        step = state.step + 1

        def averaging_step(operand):
            params, prev, momentum = operand
            # Exact inter-replica averaging — one all-reduce over the "dp"
            # axis (slowmo_optimizer.py:202 / PeriodicModelAverager).
            avg = jax.tree.map(lambda p: jnp.mean(p, axis=0), params)
            # Slow momentum update (slowmo_optimizer.py:206-227).
            momentum = jax.tree.map(
                lambda m, pv, a: self.slowmo_factor * m
                + (pv - a) / self.base_lr,
                momentum,
                prev,
                avg,
            )
            prev = jax.tree.map(
                lambda pv, m: pv - self.slowmo_lr * self.base_lr * m,
                prev,
                momentum,
            )
            params = jax.tree.map(
                lambda p, pv: jnp.broadcast_to(pv[None], p.shape).astype(
                    p.dtype
                ),
                params,
                prev,
            )
            return params, prev, momentum

        params, prev, momentum = jax.lax.cond(
            step % self.slowmo_freq == 0,
            averaging_step,
            lambda operand: operand,
            (params, state.prev, state.momentum),
        )
        return params, SlowMoState(new_base, prev, momentum, step)

    # -- checkpointing ------------------------------------------------------
    # The state is a pytree — orbax checkpoints it directly.  These helpers
    # mirror the reference's state_dict contract, which persists the
    # hyperparameters alongside the buffers and validates them on load
    # (slowmo_optimizer.py:156-189).

    def state_dict(self, state: SlowMoState) -> dict:
        return slowmo_state_dict(self, state)

    def load_state_dict(self, d: dict) -> SlowMoState:
        return load_slowmo_state_dict(self, d)


def slowmo_state_dict(opt: SlowMomentumOptimizer, state: SlowMoState) -> dict:
    return {
        "state": state,
        "slowmo_freq": opt.slowmo_freq,
        "slowmo_factor": opt.slowmo_factor,
        "slowmo_lr": opt.slowmo_lr,
        "base_lr": opt.base_lr,
        "step": int(state.step),
    }


def load_slowmo_state_dict(opt: SlowMomentumOptimizer, d: dict) -> SlowMoState:
    """Restore a SlowMo state dict.

    .. warning:: Mutates ``opt``'s hyperparameters in place (the loaded
       ``slowmo_freq/factor/lr/base_lr`` overwrite the constructor's) —
       faithful to the reference's stateful ``load_state_dict`` contract
       (slowmo_optimizer.py:156-189), and the one intentionally non-
       functional seam in this API.
    """
    # Validation parity with slowmo_optimizer.py:180-189 (missing learning
    # rate → ValueError, tested upstream test_slowmo_fsdp.py:318-324).
    for key in ("slowmo_freq", "slowmo_factor", "slowmo_lr", "base_lr"):
        if key not in d:
            raise ValueError(
                f"SlowMo state dict is missing required entry '{key}'."
            )
    opt.slowmo_freq = int(d["slowmo_freq"])
    opt.slowmo_factor = float(d["slowmo_factor"])
    opt.slowmo_lr = float(d["slowmo_lr"])
    opt.base_lr = float(d["base_lr"])
    return d["state"]
