"""Multi-host runtime: process-group init + DCN-aware hybrid meshes.

The reference's multi-node story is ``torch.distributed.init_process_group``
(NCCL/Gloo rendezvous) plus ``dist.new_subgroups()`` for the intra-node /
inter-node split (slowmo_comm.py:8-27).  The TPU-native equivalents:

* :func:`initialize` — one call per host process, wrapping
  ``jax.distributed.initialize`` (coordinator rendezvous; on Cloud TPU /
  GKE every argument is auto-detected from the environment, matching the
  reference's env-var init method).  After it returns, ``jax.devices()``
  is the *global* device set and every jit/collective in this framework is
  automatically multi-host SPMD — there is no separate multi-host code
  path anywhere else in the package.
* :func:`make_hybrid_mesh` — meshes spanning several pod slices: each
  axis's extent is split into an ICI factor (within a slice) and a DCN
  factor (across slices), DCN-major, so only the axes you place on DCN
  (SlowMo's ``dp`` averaging axis, classically) ever cross the data-center
  network, and everything else rides ICI.  This is the mesh-construction
  recipe of the scaling playbook: pick the mesh, let XLA route the
  collectives.

Single-host development needs none of this — :func:`make_mesh` over the
local devices is the whole story — and both functions degrade to that case.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from .mesh import MeshSpec, make_mesh

__all__ = [
    "ProcessInfo",
    "any_flag",
    "any_flags",
    "initialize",
    "make_hybrid_mesh",
]

_initialized = False


@dataclass(frozen=True)
class ProcessInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int


def world_info() -> ProcessInfo:
    import jax

    return ProcessInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> ProcessInfo:
    """Join the multi-host process group (init_process_group analog).

    Call once per host process *before* any other JAX API.  With no
    arguments, every parameter is auto-detected on Cloud TPU/GKE (the
    reference's env-var rendezvous, torch.distributed "env://").  Explicit
    arguments serve bare-metal/CPU rendezvous:
    ``initialize("10.0.0.1:8476", num_processes=4, process_id=rank)``.

    Idempotent: a second call (or a call in an already-initialized runtime)
    returns the current :class:`ProcessInfo` instead of raising.
    """
    global _initialized
    import jax

    already = _initialized
    if not already:
        # Adopt a runtime initialized by an outer launcher/framework.
        is_init = getattr(jax.distributed, "is_initialized", None)
        if is_init is not None:
            already = bool(is_init())

    if not already:
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as e:
            # Double-init fallback for jax versions without
            # is_initialized(); the message is "distributed.initialize
            # should only be called once.".
            msg = str(e).lower()
            if "already" not in msg and "once" not in msg:
                raise
    _initialized = True
    return world_info()


def any_flag(local: bool) -> bool:
    """Agree on a host-local boolean across all hosts: True anywhere →
    True everywhere.  Single-flag convenience over :func:`any_flags`."""
    return any_flags((local,))[0]


def any_flags(local: "Sequence[bool]") -> tuple:
    """Agree on a vector of host-local booleans across all hosts, in ONE
    collective: position i of the result is True iff any host passed
    True at position i.

    The preemption/exit protocol's collective (see
    :mod:`torchdistx_tpu.resilience.preemption`): the scheduler may
    SIGTERM hosts at different instants, and per-host data streams may
    exhaust at different steps, but a resumable checkpoint needs every
    host to stop at the SAME step — so ``fit()`` folds its exit flags
    (preemption requested, data exhausted) through this small
    all-reduce (an element-wise max over processes) at each step
    boundary before acting on either.

    Degrades to the local flags in a single-process runtime (the common
    dev/test case — no collective, no cost).  Must be called by every
    process at the same point in the program, like any collective.
    """
    import jax

    if jax.process_count() == 1:
        return tuple(bool(x) for x in local)
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(list(local), dtype=np.int32)
    )
    agreed = np.asarray(gathered).reshape(-1, len(list(local))).max(axis=0)
    return tuple(bool(x) for x in agreed)


def _degenerate_cpu_slices(devices) -> bool:
    """True when every device reports the SAME ``slice_index`` on a CPU
    backend — metadata that carries no DCN structure (multi-process CPU
    backends report slice 0 everywhere).  On real accelerators a uniform
    slice_index is genuine single-slice topology and must NOT be treated
    as degenerate, so a caller requesting more DCN granules than the
    topology has fails loudly instead of silently relabeling an ICI
    boundary as DCN.  Shared by :func:`_slice_granules` and
    :func:`make_hybrid_mesh` so the two paths can never disagree."""
    slice_keys = {getattr(d, "slice_index", None) for d in devices}
    return len(slice_keys) == 1 and all(
        getattr(d, "platform", None) == "cpu" for d in devices
    )


def _slice_granules(devices) -> list:
    """Group devices into DCN granules (pod slices / hosts).

    Real TPU devices carry ``slice_index``; grouping falls back to
    ``process_index`` (one granule per host) and finally to a single
    granule.  Granule order is the sorted key order, so every process
    builds the identical mesh.

    Reachability from :func:`make_hybrid_mesh`: only the degenerate-CPU
    and missing-``slice_index`` branches arrive here (real slice metadata
    takes ``create_hybrid_device_mesh`` up there), so the slice-keyed
    branch below serves direct callers and tests.
    """
    # All-or-nothing key domain, with the SAME degeneracy rule as
    # make_hybrid_mesh (:func:`_degenerate_cpu_slices`): mixing
    # slice_index with process_index fallbacks would interleave unrelated
    # id spaces in the sorted granule order; degenerate CPU metadata falls
    # through to process_index (one granule per host).
    slice_keys = [getattr(d, "slice_index", None) for d in devices]
    if all(k is not None for k in slice_keys) and not _degenerate_cpu_slices(
        devices
    ):
        keys = slice_keys
    else:
        keys = [getattr(d, "process_index", 0) for d in devices]
    granules: dict = {}
    for key, d in zip(keys, devices):
        granules.setdefault(key, []).append(d)
    return [granules[k] for k in sorted(granules)]


def make_hybrid_mesh(
    ici: MeshSpec,
    dcn: MeshSpec,
    *,
    devices: Optional[Sequence] = None,
):
    """Build a mesh over multiple slices: ``axis = dcn_factor × ici_factor``.

    ``ici`` shapes each slice's devices; ``dcn`` spans slices.  Every axis
    is DCN-major (the slower network varies the outer index), so a
    ``P("dp")``-sharded collective with ``dcn=MeshSpec(dp=n_slices)``
    crosses DCN exactly ``log`` once while fsdp/tp collectives stay inside
    a slice — the SlowMo intra/inter split on TPU interconnect.

    Falls back to :func:`make_mesh` when ``dcn`` is trivial.  Devices with
    real ``slice_index`` metadata (TPU pods) are placed by
    ``mesh_utils.create_hybrid_device_mesh`` (ICI-topology-aware; genuine
    topology errors propagate); otherwise granules assemble by
    ``process_index`` or a contiguous split (virtual/CPU meshes — the
    test rig).
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if dcn.size == 1:
        return make_mesh(ici, devices=devices)

    # Canonical axis order with per-axis (dcn, ici) factors.
    from .mesh import AXIS_ORDER

    names, ici_sizes, dcn_sizes = [], [], []
    for name in AXIS_ORDER:
        i = getattr(ici, name)
        d = getattr(dcn, name)
        if i > 1 or d > 1:
            names.append(name)
            ici_sizes.append(i)
            dcn_sizes.append(d)
    total = int(np.prod(ici_sizes)) * int(np.prod(dcn_sizes))
    if total != len(devices):
        raise ValueError(
            f"Hybrid mesh ici={ici_sizes} × dcn={dcn_sizes} needs {total} "
            f"devices, got {len(devices)}."
        )

    from jax.sharding import Mesh

    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    # Degenerate CPU slice metadata (see _degenerate_cpu_slices) takes the
    # granule fallback below (grouped by process_index).  Real accelerators
    # keep the topology-aware path even with one slice, so a genuine
    # mismatch (dcn extent 2 on a single-slice pod) still raises instead
    # of silently relabeling an ICI boundary as DCN.
    if None not in slice_ids and not _degenerate_cpu_slices(devices):
        # Real slice metadata (TPU pods): use jax's slice- and
        # ICI-topology-aware placement, and let genuine topology errors
        # (unmappable ici factors, wrong dcn extent) propagate instead of
        # degrading to a metadata-blind layout.
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_sizes), tuple(dcn_sizes), devices=list(devices)
        )
        return Mesh(dev_array, tuple(names))

    granules = _slice_granules(list(devices))
    n_slices = int(np.prod(dcn_sizes))
    per_slice = int(np.prod(ici_sizes))
    if len(granules) == 1 and n_slices > 1:
        # No granule metadata at all (a flat virtual device list — the CPU
        # test rig): split contiguously.
        flat = granules[0]
        granules = [
            flat[i * per_slice : (i + 1) * per_slice] for i in range(n_slices)
        ]
    elif len(granules) != n_slices:
        # Real metadata that contradicts the requested DCN extent must NOT
        # degrade to a contiguous split — that would silently lay ICI axes
        # across hosts/DCN.
        raise ValueError(
            f"Requested {n_slices} DCN granule(s) but the devices form "
            f"{len(granules)} (by slice_index/process_index); adjust the "
            "dcn spec to match the topology."
        )
    if any(len(g) != per_slice for g in granules):
        raise ValueError(
            f"Each slice must contribute {per_slice} devices; got "
            f"{[len(g) for g in granules]}."
        )

    k = len(names)
    arr = np.array(
        [np.asarray(g, dtype=object).reshape(tuple(ici_sizes)) for g in granules],
        dtype=object,
    ).reshape(tuple(dcn_sizes) + tuple(ici_sizes))
    # (dcn_0..dcn_k, ici_0..ici_k) → per-axis (dcn_i, ici_i) pairs, then
    # merge each pair: DCN-major within every named axis.
    perm = [x for i in range(k) for x in (i, k + i)]
    arr = arr.transpose(perm).reshape(
        tuple(d * i for d, i in zip(dcn_sizes, ici_sizes))
    )
    return Mesh(arr, tuple(names))
