from .mesh import make_mesh, MeshSpec  # noqa: F401
from .distributed import (  # noqa: F401
    ProcessInfo,
    any_flag,
    any_flags,
    initialize,
    make_hybrid_mesh,
)
from .fit import fit  # noqa: F401
from .sharding import (  # noqa: F401
    fsdp_plan,
    fsdp_over,
    tp_plan_gpt2,
    tp_plan_llama,
    combine_plans,
    replicated_plan,
)

# JAX-dependent modules (slowmo, ring_attention, train_step) import lazily —
# `from torchdistx_tpu.parallel import slowmo` etc. — so the torch-only
# surface (mesh specs, plan builders) stays importable without JAX.
