from .mesh import make_mesh, MeshSpec  # noqa: F401
from .sharding import (  # noqa: F401
    fsdp_plan,
    fsdp_over,
    tp_plan_gpt2,
    tp_plan_llama,
    combine_plans,
    replicated_plan,
)
