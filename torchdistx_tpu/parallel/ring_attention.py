"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context attention for sequences too large for one chip's HBM: the
sequence dim of Q/K/V is sharded over the ``sp`` mesh axis; each device keeps
its Q block resident and the K/V blocks rotate around the ring via
``lax.ppermute`` (one neighbor hop per step — the collective rides ICI), with
a numerically stable *online softmax* merging each visiting block's
contribution (the blockwise-attention recurrence of Ring Attention,
arXiv:2310.01889).  After ``sp`` steps every Q block has attended to the full
sequence; peak memory per device is O(S/sp · S/sp) logits instead of O(S²).
Causal runs skip fully-future blocks behind a ``lax.cond`` — a device
computes only its lower-triangle steps, forward and transposed backward.
Under the default *contiguous* block assignment this saves FLOPs/energy
but not wall-clock (the last device computes on every step and the
unconditional per-step ``ppermute`` keeps the ring in lockstep with it).
``schedule="zigzag"`` rebalances causal work for wall-clock too: each
device owns one *early* and one *late* half-block (device ``i`` holds
halves ``i`` and ``2n-1-i``), so every device computes exactly two
half-block contributions per ring step (three on its diagonal step) —
the per-step critical path drops from one full block to ~half.  The
zigzag sequence permutation is applied/inverted outside the ``shard_map``
(one resharding gather each way).

Implemented as ``shard_map`` over the mesh + ``lax.scan`` over ring steps, so
it nests inside the jitted train step and is reverse-differentiable (scan and
ppermute both transpose); wrap the caller in ``jax.checkpoint`` to avoid
storing per-step residuals.

The reference framework has no sequence parallelism (SURVEY.md §2.3) — this
is native new capability shaped by the TPU interconnect.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention"]

_NEG_INF = float("-inf")


def _axis_size(axis: str) -> int:
    """Static size of the mapped axis — ``jax.lax.axis_size`` on current
    jax; jax < 0.6 exposes it only as the axis-env frame."""
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis)
    from jax._src.core import axis_frame

    return axis_frame(axis)


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def _block_contrib(q, k, v, q_off, k_off, causal):
    """One K/V block's unnormalized contribution (GQA-aware).

    q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D).  Returns
    (num (B,Sq,Hq,D) f32, m (B,Sq,Hq,1) f32, l (B,Sq,Hq,1) f32) where
    num = exp(logits - m) @ v, m = row max, l = row sum.
    """
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv
    scale = 1.0 / (d**0.5)
    qg = q.reshape(b, sq, hkv, groups, d)
    logits = (
        jnp.einsum("bqhgd,bkhd->bqhgk", qg, k).astype(jnp.float32) * scale
    )  # (B, Sq, Hkv, G, Sk)
    if causal:
        qi = q_off + jnp.arange(sq)
        ki = k_off + jnp.arange(sk)
        mask = qi[:, None] >= ki[None, :]
        logits = jnp.where(mask[None, :, None, None, :], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # (B,Sq,Hkv,G,1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe)
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    num = jnp.einsum("bqhgk,bkhd->bqhgd", p, v.astype(jnp.float32))
    m = jnp.where(jnp.isfinite(m), m, _NEG_INF)
    return (
        num.reshape(b, sq, hq, d),
        m.reshape(b, sq, hq, 1),
        l.reshape(b, sq, hq, 1),
    )


def _merge(acc, blk):
    """Online-softmax merge of two partial (num, m, l) triples."""
    num_a, m_a, l_a = acc
    num_b, m_b, l_b = blk
    m_new = jnp.maximum(m_a, m_b)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m_a), jnp.exp(m_a - m_safe), 0.0)
    beta = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - m_safe), 0.0)
    return (num_a * alpha + num_b * beta, m_new, l_a * alpha + l_b * beta)


def _ring_body(q, k, v, *, axis: str, causal: bool):
    """Per-device body under shard_map: local blocks in, local out."""
    n = _axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, sl, hq, d = q.shape
    q_off = idx * sl

    num0 = jnp.zeros((b, sl, hq, d), dtype=jnp.float32)
    m0 = jnp.full((b, sl, hq, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, sl, hq, 1), dtype=jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, acc = carry
        src = (idx - t) % n
        # Causal: a K/V block strictly in this Q block's future contributes
        # nothing — skip its einsums entirely (without the gate, the ring
        # wastes (n-1)/2n of its compute on all-masked blocks).  Same
        # deadlock-freedom invariant as the pipeline's tick gating: the
        # predicate varies only over the ring axis and the ppermute below
        # runs unconditionally every step.
        def visit(operand):
            k_b, v_b, acc_in = operand
            blk = _block_contrib(q, k_b, v_b, q_off, src * sl, causal)
            return _merge(acc_in, blk)

        if causal:
            acc = jax.lax.cond(
                src <= idx, visit, lambda op: op[2], (k_blk, v_blk, acc)
            )
        else:
            acc = visit((k_blk, v_blk, acc))
        k_next = jax.lax.ppermute(k_blk, axis, perm)
        v_next = jax.lax.ppermute(v_blk, axis, perm)
        return (k_next, v_next, acc), None

    (_, _, (num, m, l)), _ = jax.lax.scan(
        step, (k, v, (num0, m0, l0)), jnp.arange(n)
    )
    out = num / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def _zigzag_ring_body(q, k, v, *, axis: str):
    """Balanced causal ring body: per-device q/k/v hold halves (i, 2n-1-i).

    Case analysis per ring step visiting source block ``src`` (half indices
    ``src`` and ``2n-1-src``), against this device's halves ``idx`` and
    ``2n-1-idx``:

    * ``q_hi`` vs ``k_lo`` — ``2n-1-idx > src`` always: full, every step;
    * ``src < idx``  — ``q_lo`` vs ``k_lo`` full;
    * ``src == idx`` — both diagonal (triangular-masked) pairs;
    * ``src > idx``  — ``q_hi`` vs ``k_hi`` full
      (``2n-1-src < 2n-1-idx``);
    * ``q_lo`` vs ``k_hi`` — ``idx < 2n-1-src`` always: never computed.

    Exactly two half-contributions per step (three on the diagonal step),
    on every device — the causal load balance the contiguous assignment
    lacks.
    """
    n = _axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, sl, hq, d = q.shape
    h = sl // 2

    def split(x):
        return x[:, :h], x[:, h:]

    q_lo, q_hi = split(q)

    def zero_acc():
        return (
            jnp.zeros((b, h, hq, d), dtype=jnp.float32),
            jnp.full((b, h, hq, 1), _NEG_INF, dtype=jnp.float32),
            jnp.zeros((b, h, hq, 1), dtype=jnp.float32),
        )

    def full(acc, qh, kh, vh):
        return _merge(acc, _block_contrib(qh, kh, vh, 0, 0, causal=False))

    def diag(acc, qh, kh, vh):
        return _merge(acc, _block_contrib(qh, kh, vh, 0, 0, causal=True))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, acc_lo, acc_hi = carry
        src = (idx - t) % n
        k_lo, k_hi = split(k_blk)
        v_lo, v_hi = split(v_blk)
        acc_hi = full(acc_hi, q_hi, k_lo, v_lo)

        def before(accs):  # src strictly earlier than idx
            a_lo, a_hi = accs
            return full(a_lo, q_lo, k_lo, v_lo), a_hi

        def diagonal(accs):
            a_lo, a_hi = accs
            return (
                diag(a_lo, q_lo, k_lo, v_lo),
                diag(a_hi, q_hi, k_hi, v_hi),
            )

        def after(accs):  # src strictly later than idx
            a_lo, a_hi = accs
            return a_lo, full(a_hi, q_hi, k_hi, v_hi)

        acc_lo, acc_hi = jax.lax.switch(
            jnp.clip(jnp.sign(src - idx) + 1, 0, 2),
            [before, diagonal, after],
            (acc_lo, acc_hi),
        )
        k_next = jax.lax.ppermute(k_blk, axis, perm)
        v_next = jax.lax.ppermute(v_blk, axis, perm)
        return (k_next, v_next, acc_lo, acc_hi), None

    (_, _, (num_l, m_l, l_l), (num_h, m_h, l_h)), _ = jax.lax.scan(
        step, (k, v, zero_acc(), zero_acc()), jnp.arange(n)
    )
    out_lo = num_l / jnp.maximum(l_l, 1e-30)
    out_hi = num_h / jnp.maximum(l_h, 1e-30)
    return jnp.concatenate([out_lo, out_hi], axis=1).astype(q.dtype)


def _zigzag_perm(s: int, n: int):
    """Global seq permutation placing halves (i, 2n-1-i) on device ``i``.

    Returns ``(perm, inv)`` index vectors: ``x_zig = x[:, perm]`` and
    ``x = x_zig[:, inv]``.
    """
    import numpy as np

    if s % (2 * n):
        raise ValueError(f"zigzag needs seq {s} divisible by 2·sp={2 * n}")
    h = s // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * h, (i + 1) * h))
        order.extend(range((2 * n - 1 - i) * h, (2 * n - i) * h))
    perm = np.asarray(order)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s)
    return perm, inv


def ring_attention(
    q,
    k,
    v,
    *,
    mesh,
    axis: str = "sp",
    causal: bool = True,
    batch_axes: Sequence[str] = ("dp", "fsdp"),
    head_axes: Sequence[str] = ("tp",),
    schedule: str = "contiguous",
    pre_permuted: bool = False,
):
    """Sequence-parallel attention.  Layout ``(B, S, H, D)`` (global shapes).

    ``q``/``k``/``v`` are sharded ``P(batch, sp, heads, None)``; the result
    carries the same sharding.  ``batch_axes``/``head_axes`` name the mesh
    axes the batch/head dims are sharded over (entries absent from ``mesh``
    are ignored), so the shard_map composes with dp/fsdp/tp sharding without
    forcing reshards.

    ``schedule``: ``"contiguous"`` (default) or ``"zigzag"`` — the
    load-balanced causal schedule (see module docstring); requires
    ``causal=True`` and a sequence divisible by ``2·sp``.

    .. note:: zigzag permutes q/k/v in and the output back *per call*
       (four sequence-global reshards per layer, replayed in backward).
       The balance win pays when per-device attention compute dominates —
       long local sequence, large head count; for short sequences the
       reshard traffic can exceed the saving.  ``pre_permuted=True`` skips
       the per-call permutation entirely: the caller keeps the *whole
       model's* activations in zigzag sequence order (permute tokens and
       position ids once at the embedding, align the targets at the loss
       — see ``models.llama.loss_fn(seq_layout="zigzag")``), and outputs
       stay in zigzag order.
    """
    names = set(mesh.axis_names)
    if axis not in names:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {mesh.axis_names})")
    batch = tuple(a for a in batch_axes if a in names) or None
    heads = tuple(a for a in head_axes if a in names) or None
    spec = P(batch, axis, heads, None)

    if schedule == "zigzag":
        if not causal:
            raise ValueError("zigzag schedule is a causal-only optimization")
        n = mesh.shape[axis]
        s = q.shape[1]
        if s % (2 * n):
            raise ValueError(
                f"zigzag needs seq {s} divisible by 2·{axis}={2 * n}"
            )
        body = functools.partial(_zigzag_ring_body, axis=axis)
        zz = _shard_map(
            body, mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
        if pre_permuted:
            return zz(q, k, v)
        perm, inv = _zigzag_perm(s, n)
        qz, kz, vz = (jnp.take(x, perm, axis=1) for x in (q, k, v))
        return jnp.take(zz(qz, kz, vz), inv, axis=1)
    if schedule != "contiguous":
        raise ValueError(f"unknown schedule: {schedule!r}")
    if pre_permuted:
        raise ValueError("pre_permuted requires schedule='zigzag'")
    body = functools.partial(_ring_body, axis=axis, causal=causal)
    return _shard_map(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
