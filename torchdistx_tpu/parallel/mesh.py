"""Device-mesh construction over ICI/DCN.

The reference consumes `torch.distributed` process groups: NCCL/Gloo
transport, `dist.new_subgroups()` for intra-node groups, the default world
group for inter-node collectives (slowmo_comm.py:8-27).  The TPU-native
communication substrate is the `jax.sharding.Mesh`: named axes over the
device topology, with XLA inserting collectives that ride ICI within a pod
slice and DCN across slices.  The subgroup notion maps to mesh sub-axes; no
transport code is needed at all (SURVEY.md §2.3).

Conventions used throughout this framework:

* ``"dp"``   — data parallel (SlowMo's *inter-node* averaging axis; DCN-major)
* ``"fsdp"`` — parameter/optimizer sharding (ZeRO-style; usually the larger
  ICI axis)
* ``"tp"``   — tensor parallel (innermost, fastest ICI axis)
* ``"sp"``   — sequence/context parallel for ring attention (aliases "tp" on
  small meshes)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple


# Canonical DCN-major → ICI-minor axis order, shared by every mesh builder
# (make_mesh, distributed.make_hybrid_mesh).
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "fsdp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    """Named mesh shape, e.g. ``MeshSpec(dp=2, fsdp=2, tp=2)``."""

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    def axes(self) -> Tuple[Tuple[str, int], ...]:
        return tuple(
            (name, size)
            for name, size in (
                (name, getattr(self, name)) for name in AXIS_ORDER
            )
            if size > 1
        ) or (("dp", 1),)

    @property
    def size(self) -> int:
        n = 1
        for _, s in self.axes():
            n *= s
        return n


def make_mesh(
    spec: Optional[MeshSpec] = None,
    *,
    devices: Optional[Sequence] = None,
    axis_names: Optional[Sequence[str]] = None,
    shape: Optional[Sequence[int]] = None,
):
    """Build a ``jax.sharding.Mesh``.

    With a :class:`MeshSpec`, axes are laid out DCN-major → ICI-minor ("dp"
    outermost, "tp" innermost) so tensor-parallel collectives ride the
    fastest ICI links and only the periodic SlowMo averaging crosses "dp"
    (the reference's intra-node/inter-node split, slowmo_comm.py:24-27,
    mapped onto the TPU interconnect hierarchy).

    Uses ``mesh_utils.create_device_mesh`` for ICI-topology-aware device
    ordering when the devices form a single slice; falls back to a reshape
    for virtual/CPU devices.
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if spec is not None:
        names = [n for n, _ in spec.axes()]
        sizes = [s for _, s in spec.axes()]
    else:
        names = list(axis_names or ("dp",))
        sizes = list(shape or (len(devices),))
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"Mesh of shape {dict(zip(names, sizes))} needs {n} devices, "
            f"got {len(devices)}."
        )
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            tuple(sizes), devices=list(devices)
        )
    except Exception:
        dev_array = np.asarray(list(devices)).reshape(tuple(sizes))
    from jax.sharding import Mesh

    return Mesh(dev_array, tuple(names))
