"""Sharding-plan builders: parameter name/shape → ``PartitionSpec``.

The shard-then-materialize flow (docs/src/deferred_init.rst:17-44 is the
reference's motivation; it never implements the sharding itself) needs a
*plan*: a mapping from parameter names/shapes to mesh partition specs.  A
plan is any ``(name, shape) -> PartitionSpec | None`` callable; builders here
compose FSDP-style and Megatron-TP-style rules.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, Optional, Sequence, Tuple

Plan = Callable[[str, Tuple[int, ...]], object]


def _pspec(*axes):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*axes)


def fit_spec_to_mesh(spec, mesh):
    """Drop axis names the mesh doesn't have (e.g. a tp rule on a dp-only
    mesh) — the single implementation used by the model stack and the train
    steps."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            return kept or None
        return entry if entry in names else None

    return _pspec(*[keep(a) for a in spec])


def fit_shardings(specs, abstract, mesh):
    """Spec pytree + abstract (shape) pytree → ``NamedSharding`` pytree,
    applying :func:`fit_spec_to_mesh` then :func:`replicate_indivisible` to
    every leaf.  Model-agnostic: used by the train steps and the model
    families' ``init_sharded``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.tree.map(
        lambda s, ab: NamedSharding(
            mesh,
            replicate_indivisible(fit_spec_to_mesh(s, mesh), ab.shape, mesh),
        ),
        specs,
        abstract,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def replicate_indivisible(spec, shape, mesh):
    """Replicate dims whose size isn't divisible by their assigned axis
    product (e.g. a 32000 vocab over tp=7): a sharded init value would be
    ill-defined.  Frameworks wanting sharded odd dims pad them instead."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    fixed = []
    for dim, axes in enumerate(entries):
        if axes is None:
            fixed.append(None)
            continue
        axis_tuple = axes if isinstance(axes, tuple) else (axes,)
        size = 1
        for a in axis_tuple:
            size *= mesh.shape[a]
        fixed.append(axes if shape[dim] % size == 0 else None)
    return _pspec(*fixed)


def replicated_plan() -> Plan:
    return lambda name, shape: _pspec()


def fsdp_plan(
    axis: str = "fsdp",
    *,
    min_size: int = 1024,
    largest_dim: bool = True,
) -> Plan:
    """ZeRO-3-style parameter sharding: shard every big-enough param along
    one dimension of the ``axis`` mesh axis.

    ``largest_dim=True`` shards the largest dimension (best balance and the
    dimension most likely divisible by the axis size); otherwise dim 0.
    Params smaller than ``min_size`` elements stay replicated (the classic
    FSDP small-tensor exemption).
    """

    def plan(name: str, shape: Tuple[int, ...]):
        n = 1
        for s in shape:
            n *= s
        if not shape or n < min_size:
            return _pspec()
        dim = max(range(len(shape)), key=lambda i: shape[i]) if largest_dim else 0
        spec = [None] * len(shape)
        spec[dim] = axis
        return _pspec(*spec)

    return plan


def _regex_plan(rules: Iterable[Tuple[str, Sequence[Optional[str]]]]) -> Plan:
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def plan(name: str, shape: Tuple[int, ...]):
        for pat, spec in compiled:
            if pat.search(name):
                # A spec shorter than the rank is implicitly None-padded by
                # PartitionSpec semantics.
                return _pspec(*list(spec)[: len(shape)])
        return None

    return plan


def tp_plan_gpt2(axis: str = "tp") -> Plan:
    """Megatron-style TP rules for GPT-2-family (HF naming, Conv1D weights
    are (in, out)): column-parallel QKV/MLP-up on the out dim, row-parallel
    proj/MLP-down on the in dim, embeddings on vocab/model dim."""
    return _regex_plan(
        [
            (r"c_attn\.weight$", (None, axis)),
            (r"c_attn\.bias$", (axis,)),
            (r"c_fc\.weight$", (None, axis)),
            (r"c_fc\.bias$", (axis,)),
            (r"c_proj\.weight$", (axis, None)),
            (r"c_proj\.bias$", ()),
            (r"(wte|lm_head)\.weight$", (axis, None)),
            (r"wpe\.weight$", ()),
            (r"ln_\w*\.(weight|bias)$", ()),
        ]
    )


def tp_plan_llama(axis: str = "tp") -> Plan:
    """Megatron-style TP rules for Llama-family (HF naming, Linear weights
    are (out, in)): column-parallel q/k/v/gate/up on dim 0, row-parallel
    o/down on dim 1, vocab-parallel embeddings."""
    return _regex_plan(
        [
            (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)\.weight$", (axis, None)),
            (r"(o_proj|down_proj)\.weight$", (None, axis)),
            (r"(embed_tokens|lm_head)\.weight$", (axis, None)),
            (r"norm\.weight$", ()),
        ]
    )


def fsdp_over(base: Plan, axis: str = "fsdp", *, min_size: int = 1024) -> Plan:
    """2-D sharding: apply ``base`` (e.g. a TP plan), then additionally shard
    the largest still-unsharded dimension along ``axis`` — the FSDP+TP
    layout of BASELINE config 5 (Llama-70B on v5p-128)."""

    def plan(name: str, shape: Tuple[int, ...]):
        spec = base(name, shape)
        entries = list(spec) if spec is not None else []
        entries += [None] * (len(shape) - len(entries))
        n = 1
        for s in shape:
            n *= s
        if n >= min_size:
            free = [i for i, e in enumerate(entries) if e is None]
            if free:
                dim = max(free, key=lambda i: shape[i])
                entries[dim] = axis
        return _pspec(*entries)

    return plan


def combine_plans(*plans: Plan) -> Plan:
    """First plan returning a non-None spec wins; else replicated.

    An explicit empty ``PartitionSpec()`` *is* a match ("replicate this
    param") and stops the search — e.g. a TP rule replicating a norm weight
    must not be overridden by a later FSDP catch-all.  For genuine 2-D
    sharding (FSDP over the dims TP left free) use :func:`fsdp_over`.
    """

    def plan(name: str, shape: Tuple[int, ...]):
        for p in plans:
            spec = p(name, shape)
            if spec is not None:
                return spec
        return _pspec()

    return plan
