"""Elastic training loop: periodic checkpointing + automatic resume.

The reference leaves the training loop to the user and checkpoints only the
SlowMo optimizer state (slowmo_optimizer.py:156-189).  On a preemptible TPU
fleet the loop itself is part of the framework's job: run ``n_steps``,
checkpoint every ``checkpoint_every`` steps, and — after a preemption or a
re-shard — resume from the latest checkpoint, *including onto a different
mesh*: restore targets are abstract arrays carrying the new mesh's
shardings, so orbax reads each shard straight to its new owning device
(no full-tensor host round-trip; see utils/checkpoint.py).

Resilience (see :mod:`torchdistx_tpu.resilience` and docs/resilience.md):

* **Preemption** — SIGTERM/SIGINT set a flag (handlers installed on
  entry); every step boundary agrees on it across hosts
  (:func:`~torchdistx_tpu.parallel.distributed.any_flag`), saves a final
  checkpoint at the last completed step, flushes telemetry counters to
  the trace, and returns — the next invocation resumes exactly there.
* **Retries** — checkpoint IO and the data iterator run under a
  :class:`~torchdistx_tpu.resilience.retry.RetryPolicy` (``ckpt.retries``
  / ``data.retries`` counters).
* **Non-finite guard** — steps built by :func:`make_train_step` report
  ``metrics["nonfinite"]``; the loop counts skips (``train.skipped_steps``)
  and raises :class:`~torchdistx_tpu.resilience.guard.NonFiniteError`
  after ``max_consecutive_nonfinite`` in a row.  The flag is read with a
  small lag so the host never stalls dispatch waiting on the device.
* **Fault injection** — the ``data.next`` and ``step.exec`` sites consult
  :mod:`~torchdistx_tpu.resilience.faults` (``TDX_FAULT``), so CI can
  prove every path above deterministically.

Telemetry: every step runs under a ``train.step`` span (with
``TDX_TELEMETRY_JAX=1`` that is a ``StepTraceAnnotation``, so the XLA
profiler's step view works out of the box), and the loop derives
``steps_per_s`` / ``tokens_per_s`` / ``mfu`` throughput, publishing them as
gauges AND merging them into the metrics dict handed to ``on_metrics``.
Throughput is wall time between successive ``step_fn`` returns: dispatch is
async, so the first measured steps read fast until device backpressure
aligns dispatch with execution — steady-state values are the meaningful
ones (the first step, which carries compilation, is skipped entirely).
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Callable, Iterable, Optional

from .. import telemetry as _telemetry
from ..resilience import faults as _faults
from ..resilience import guard as _guard
from ..resilience import preemption as _preemption
from ..resilience.retry import RetryPolicy

__all__ = ["fit"]

_T_STEPS = _telemetry.counter("train.steps")
_T_STEPS_S = _telemetry.gauge("train.steps_per_s")
_T_TOKENS_S = _telemetry.gauge("train.tokens_per_s")
_T_MFU = _telemetry.gauge("train.mfu")
_T_DATA_RETRIES = _telemetry.counter("data.retries")
_T_PREEMPTIONS = _telemetry.counter("train.preemptions")

# Steps of lag before the host reads a step's `nonfinite` flag: reading a
# device scalar blocks until that step finishes, so checking the freshest
# flag every step would serialize dispatch with execution.  Two steps of
# lag keeps the async-dispatch pipeline full while bounding how late an
# escalation fires.
_NONFINITE_LAG = 2


def _batch_tokens(batch) -> Optional[int]:
    """Token count of one batch: the ``tokens`` leaf's element count (the
    ``{"tokens", "targets"}`` convention of make_train_step)."""
    if not isinstance(batch, dict):
        return None
    shape = getattr(batch.get("tokens"), "shape", None)
    if not shape:
        return None
    return int(math.prod(shape))


def fit(
    init_fn: Callable,
    step_fn: Callable,
    batches: Iterable[Any],
    *,
    key,
    n_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    checkpoint_sync: bool = False,
    on_metrics: Optional[Callable[[int, Any], None]] = None,
    tokens_per_batch: Optional[int] = None,
    flops_per_step: Optional[float] = None,
    peak_flops: Optional[float] = None,
    retry: Optional[RetryPolicy] = RetryPolicy(),
    handle_preemption: bool = True,
    max_consecutive_nonfinite: int = 8,
    exit_sync_every: int = 1,
):
    """Run up to ``n_steps`` optimizer steps, resuming from checkpoints.

    ``init_fn(key) -> state`` and ``step_fn(state, batch) -> (state,
    metrics)`` are the pair built by :func:`make_train_step` (any functions
    with those signatures work).  ``batches`` yields one batch per step;
    steps already completed by a restored checkpoint are skipped by
    *advancing* the iterator, so a deterministic data stream stays aligned
    with the optimizer step count after resume.

    Resilience knobs (module docstring has the semantics):

    * ``retry`` — policy for checkpoint IO and batch pulls (None
      disables; the default allows 3 attempts with ~0.1 s backoff).
    * ``handle_preemption`` — install SIGTERM/SIGINT handlers and drain
      gracefully at the next step boundary (checkpoint, flush, return).
      The run is resumable whether it stopped by preemption, crash, or
      completion; callers distinguish via ``state.step`` / the
      checkpoint directory.
    * ``checkpoint_sync`` — wait for each periodic save to commit before
      continuing (defaults to overlapping saves with subsequent steps;
      synchronous saves bound the replay window to exactly
      ``checkpoint_every`` steps even under a hard kill).
    * ``max_consecutive_nonfinite`` — escalation threshold for the
      non-finite guard (``<= 0`` counts skips but never raises).
    * ``exit_sync_every`` — how often (in steps) the cross-host
      exit-flag collective runs.  The default (1, every boundary) is
      always safe; raising it amortizes the per-step host allgather on
      multihost runs with fast steps, at the price of acting on a
      preemption up to that many steps late.  With a value > 1, data
      exhaustion and pull failures still trigger the collective
      immediately, which stays symmetric across hosts as long as
      per-host streams yield the same number of batches (the invariant
      SPMD data pipelines already require — a host with a missing batch
      would hang the jitted step's collectives anyway).

    Throughput telemetry (see module docstring): ``steps_per_s`` is always
    derived; ``tokens_per_s`` additionally needs the batch token count
    (``tokens_per_batch``, or auto-detected from a ``{"tokens": ...}``
    batch dict); ``mfu`` additionally needs ``flops_per_step`` (model
    FLOPs per optimizer step) and ``peak_flops`` (the chip's peak, in
    FLOP/s — see bench.py's per-device-kind table).  When ``metrics`` is a
    dict, the derived values are merged in before ``on_metrics`` sees it.

    Returns ``(state, last_metrics)``.
    """
    import jax

    from .distributed import any_flags

    state = None
    start = 0
    ckptr = None
    if checkpoint_dir is not None:
        from ..utils.checkpoint import Checkpointer

        ckptr = Checkpointer(checkpoint_dir, retry=retry)
        # Abstract restore target: init_fn is jitted with out_shardings, so
        # eval_shape leaves already carry the mesh shardings — no init
        # compute, and never two full states in HBM during restore.
        abstract = jax.eval_shape(init_fn, key)
        step, restored = ckptr.restore_latest(
            target=abstract,
            shardings=jax.tree.map(lambda l: l.sharding, abstract),
        )
        if step is not None:
            state, start = restored, step
    if state is None:
        state = init_fn(key)

    metrics = None
    if start >= n_steps:
        return state, metrics

    handlers_preexisting = True
    if handle_preemption:
        handlers_preexisting = _preemption.installed()
        _preemption.install()

    it = iter(batches)

    def _pull(step):
        """Next batch for ``step``, through fault site + retry policy."""
        first_error = []

        def _next():
            _faults.fire("data.next", step)
            try:
                return next(it)
            except StopIteration:
                if first_error:
                    # A retryable failure already came out of this pull:
                    # a generator-based iterator is CLOSED by it, so this
                    # StopIteration is bogus — surfacing it would make a
                    # real IO error look like clean data exhaustion and
                    # silently truncate the run.  Re-raise the real
                    # error (the retry loop then fails loudly).
                    raise first_error[0]
                raise
            except Exception as e:
                if not first_error:
                    first_error.append(e)
                raise

        if retry is None:
            return _next()
        return retry.call(
            _next, counter=_T_DATA_RETRIES, site=f"data.next[{step}]"
        )

    tracker = _guard.SkipTracker(max_consecutive_nonfinite)
    pending_flags: deque = deque()  # (step, device nonfinite scalar)
    completed = start  # last step whose state we hold
    saved_at = start  # last step with a dispatched checkpoint
    preempted = False
    pull_error: Optional[BaseException] = None
    t_prev = None
    step_no = 0  # last data-stream position consumed (1-based steps);
    # starts at 0 even on resume — batches 1..start are pulled and
    # discarded so the deterministic stream realigns with the step count

    try:
        # Fast-forward the data stream to the resume point.  No step runs
        # here and every host resumed from the same checkpoint (same
        # `start`), so the replay length is identical everywhere — no
        # per-batch collective needed (a 50k-step resume must not pay 50k
        # allgathers just to realign the stream).
        while step_no < start and step_no < n_steps:
            try:
                _pull(step_no + 1)
            except StopIteration:
                raise ValueError(
                    f"data stream exhausted at batch {step_no + 1} while "
                    f"replaying to the resume point (checkpoint step "
                    f"{start}): the stream is shorter than the run it is "
                    "supposed to realign with"
                ) from None
            step_no += 1

        while step_no < n_steps:
            pulling = step_no + 1
            batch = None
            exhausted = False
            pull_error = None
            try:
                batch = _pull(pulling)
            except StopIteration:
                exhausted = True
            except Exception as e:
                # Held, not raised: the error must travel through the
                # exit collective first, or this host would abandon the
                # allgather while its peers wait in it (deadlock).  It
                # re-raises below, after the tail checkpoint is saved.
                pull_error = e
            # Step boundary: ONE small collective agrees on every exit
            # cause across hosts — the scheduler signals hosts at
            # different instants and a data source may fail on one host
            # only, but every host must stop at (and checkpoint) the
            # SAME step, and a host that stopped calling the collective
            # while others still wait in it would deadlock the job.
            # Local stop conditions always sync (symmetric across hosts
            # for same-length streams — see exit_sync_every docs);
            # pure preemption polling runs every exit_sync_every steps.
            must_sync = exhausted or pull_error is not None
            if must_sync or pulling % max(1, exit_sync_every) == 0:
                preempted_any, exhausted_any, failed_any = any_flags(
                    (
                        handle_preemption and _preemption.requested(),
                        exhausted,
                        pull_error is not None,
                    )
                )
                if preempted_any:
                    preempted = True
                    break
                if failed_any or exhausted_any:
                    break
            step_no = pulling
            done = step_no
            kind = _faults.fire("step.exec", done)
            if kind == "nan" and isinstance(batch, dict):
                # Cooperative poison: make_train_step turns this
                # reserved key into a NaN loss inside jit, so the
                # injected fault exercises the REAL guard path.
                batch = {**batch, "_tdx_nan": True}
            with _telemetry.span("train.step", step=done):
                state, metrics = step_fn(state, batch)
            completed = done
            _T_STEPS.add()
            now = time.perf_counter()
            if t_prev is not None and now > t_prev:
                steps_per_s = 1.0 / (now - t_prev)
                _T_STEPS_S.set(steps_per_s)
                derived = {"steps_per_s": steps_per_s}
                n_tok = tokens_per_batch or _batch_tokens(batch)
                if n_tok:
                    tokens_per_s = n_tok * steps_per_s
                    _T_TOKENS_S.set(tokens_per_s)
                    derived["tokens_per_s"] = tokens_per_s
                if flops_per_step and peak_flops:
                    mfu = flops_per_step * steps_per_s / peak_flops
                    _T_MFU.set(mfu)
                    derived["mfu"] = mfu
                if isinstance(metrics, dict):
                    metrics = {**metrics, **derived}
            t_prev = now
            if isinstance(metrics, dict) and "nonfinite" in metrics:
                pending_flags.append((done, metrics["nonfinite"]))
                while (
                    pending_flags
                    and done - pending_flags[0][0] >= _NONFINITE_LAG
                ):
                    s, flag = pending_flags.popleft()
                    tracker.observe(bool(flag), s)
            if on_metrics is not None:
                on_metrics(done, metrics)
            if ckptr is not None and (
                done % checkpoint_every == 0 or done == n_steps
            ):
                # Saves overlap with subsequent steps unless
                # checkpoint_sync; the finally below finalizes whichever
                # save is still in flight — including when a later step
                # raises, so every dispatched checkpoint stays durable
                # for the post-crash resume.
                ckptr.save(done, state, wait=checkpoint_sync)
                saved_at = done

        # Drain the lagged guard flags so a poisoned tail still counts
        # (and can still escalate) before the loop returns.
        while pending_flags:
            s, flag = pending_flags.popleft()
            tracker.observe(bool(flag), s)

        # Always persist the final completed step: the loop may exit with
        # work done since the last periodic save — `batches` exhausted
        # before n_steps, or a preemption — and losing that tail would
        # silently rewind the resume point.
        if ckptr is not None and completed > saved_at:
            ckptr.save(completed, state, wait=False)
            saved_at = completed
        if preempted:
            _T_PREEMPTIONS.add()
            with _telemetry.span("train.preempt", step=completed):
                pass  # event span: the preemption is visible in traces
            # The request has been acted on (state saved): clear it so a
            # later fit() in the same process can resume instead of
            # instantly re-preempting.  A platform that is really going
            # down keeps signalling.
            _preemption.clear()
    finally:
        if ckptr is not None:
            ckptr.wait_until_finished()
        if handle_preemption and not handlers_preexisting:
            # Restore whatever handlers the caller had: fit() must not
            # permanently swallow the user's Ctrl-C.
            _preemption.uninstall()
    if pull_error is not None:
        # The failure that stopped the loop, raised only now: progress
        # up to the agreed stop step is already checkpointed, and every
        # host left the collective cleanly first.
        raise pull_error
    if preempted:
        # Flush counters (retries, skips, the preemption itself) to the
        # JSONL trace before the process is torn down.
        _telemetry.emit_counters()
    return state, metrics
