"""Elastic training loop: periodic checkpointing + automatic resume.

The reference leaves the training loop to the user and checkpoints only the
SlowMo optimizer state (slowmo_optimizer.py:156-189).  On a preemptible TPU
fleet the loop itself is part of the framework's job: run ``n_steps``,
checkpoint every ``checkpoint_every`` steps, and — after a preemption or a
re-shard — resume from the latest checkpoint, *including onto a different
mesh*: restore targets are abstract arrays carrying the new mesh's
shardings, so orbax reads each shard straight to its new owning device
(no full-tensor host round-trip; see utils/checkpoint.py).

Telemetry: every step runs under a ``train.step`` span (with
``TDX_TELEMETRY_JAX=1`` that is a ``StepTraceAnnotation``, so the XLA
profiler's step view works out of the box), and the loop derives
``steps_per_s`` / ``tokens_per_s`` / ``mfu`` throughput, publishing them as
gauges AND merging them into the metrics dict handed to ``on_metrics``.
Throughput is wall time between successive ``step_fn`` returns: dispatch is
async, so the first measured steps read fast until device backpressure
aligns dispatch with execution — steady-state values are the meaningful
ones (the first step, which carries compilation, is skipped entirely).
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Iterable, Optional

from .. import telemetry as _telemetry

__all__ = ["fit"]

_T_STEPS = _telemetry.counter("train.steps")
_T_STEPS_S = _telemetry.gauge("train.steps_per_s")
_T_TOKENS_S = _telemetry.gauge("train.tokens_per_s")
_T_MFU = _telemetry.gauge("train.mfu")


def _batch_tokens(batch) -> Optional[int]:
    """Token count of one batch: the ``tokens`` leaf's element count (the
    ``{"tokens", "targets"}`` convention of make_train_step)."""
    if not isinstance(batch, dict):
        return None
    shape = getattr(batch.get("tokens"), "shape", None)
    if not shape:
        return None
    return int(math.prod(shape))


def fit(
    init_fn: Callable,
    step_fn: Callable,
    batches: Iterable[Any],
    *,
    key,
    n_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    on_metrics: Optional[Callable[[int, Any], None]] = None,
    tokens_per_batch: Optional[int] = None,
    flops_per_step: Optional[float] = None,
    peak_flops: Optional[float] = None,
):
    """Run up to ``n_steps`` optimizer steps, resuming from checkpoints.

    ``init_fn(key) -> state`` and ``step_fn(state, batch) -> (state,
    metrics)`` are the pair built by :func:`make_train_step` (any functions
    with those signatures work).  ``batches`` yields one batch per step;
    steps already completed by a restored checkpoint are skipped by
    *advancing* the iterator, so a deterministic data stream stays aligned
    with the optimizer step count after resume.

    Throughput telemetry (see module docstring): ``steps_per_s`` is always
    derived; ``tokens_per_s`` additionally needs the batch token count
    (``tokens_per_batch``, or auto-detected from a ``{"tokens": ...}``
    batch dict); ``mfu`` additionally needs ``flops_per_step`` (model
    FLOPs per optimizer step) and ``peak_flops`` (the chip's peak, in
    FLOP/s — see bench.py's per-device-kind table).  When ``metrics`` is a
    dict, the derived values are merged in before ``on_metrics`` sees it.

    Returns ``(state, last_metrics)``.
    """
    import jax

    state = None
    start = 0
    ckptr = None
    if checkpoint_dir is not None:
        from ..utils.checkpoint import Checkpointer

        ckptr = Checkpointer(checkpoint_dir)
        # Abstract restore target: init_fn is jitted with out_shardings, so
        # eval_shape leaves already carry the mesh shardings — no init
        # compute, and never two full states in HBM during restore.
        abstract = jax.eval_shape(init_fn, key)
        step, restored = ckptr.restore_latest(
            target=abstract,
            shardings=jax.tree.map(lambda l: l.sharding, abstract),
        )
        if step is not None:
            state, start = restored, step
    if state is None:
        state = init_fn(key)

    metrics = None
    if start >= n_steps:
        return state, metrics
    try:
        it = iter(batches)
        t_prev = None
        for i, batch in enumerate(it):
            if i >= n_steps:
                break
            if i < start:
                continue  # replay the data stream up to the resume point
            done = i + 1
            with _telemetry.span("train.step", step=done):
                state, metrics = step_fn(state, batch)
            _T_STEPS.add()
            now = time.perf_counter()
            if t_prev is not None and now > t_prev:
                steps_per_s = 1.0 / (now - t_prev)
                _T_STEPS_S.set(steps_per_s)
                derived = {"steps_per_s": steps_per_s}
                n_tok = tokens_per_batch or _batch_tokens(batch)
                if n_tok:
                    tokens_per_s = n_tok * steps_per_s
                    _T_TOKENS_S.set(tokens_per_s)
                    derived["tokens_per_s"] = tokens_per_s
                if flops_per_step and peak_flops:
                    mfu = flops_per_step * steps_per_s / peak_flops
                    _T_MFU.set(mfu)
                    derived["mfu"] = mfu
                if isinstance(metrics, dict):
                    metrics = {**metrics, **derived}
            t_prev = now
            if on_metrics is not None:
                on_metrics(done, metrics)
            if ckptr is not None and (
                done % checkpoint_every == 0 or done == n_steps
            ):
                # Saves overlap with subsequent steps; the finally below
                # finalizes whichever save is still in flight — including
                # when a later step raises, so every dispatched checkpoint
                # stays durable for the post-crash resume.
                ckptr.save(done, state, wait=False)
    finally:
        if ckptr is not None:
            ckptr.wait_until_finished()
    return state, metrics
