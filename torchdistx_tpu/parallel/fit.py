"""Elastic training loop: periodic checkpointing + automatic resume.

The reference leaves the training loop to the user and checkpoints only the
SlowMo optimizer state (slowmo_optimizer.py:156-189).  On a preemptible TPU
fleet the loop itself is part of the framework's job: run ``n_steps``,
checkpoint every ``checkpoint_every`` steps, and — after a preemption or a
re-shard — resume from the latest checkpoint, *including onto a different
mesh*: restore targets are abstract arrays carrying the new mesh's
shardings, so orbax reads each shard straight to its new owning device
(no full-tensor host round-trip; see utils/checkpoint.py).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

__all__ = ["fit"]


def fit(
    init_fn: Callable,
    step_fn: Callable,
    batches: Iterable[Any],
    *,
    key,
    n_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    on_metrics: Optional[Callable[[int, Any], None]] = None,
):
    """Run up to ``n_steps`` optimizer steps, resuming from checkpoints.

    ``init_fn(key) -> state`` and ``step_fn(state, batch) -> (state,
    metrics)`` are the pair built by :func:`make_train_step` (any functions
    with those signatures work).  ``batches`` yields one batch per step;
    steps already completed by a restored checkpoint are skipped by
    *advancing* the iterator, so a deterministic data stream stays aligned
    with the optimizer step count after resume.

    Returns ``(state, last_metrics)``.
    """
    import jax

    state = None
    start = 0
    ckptr = None
    if checkpoint_dir is not None:
        from ..utils.checkpoint import Checkpointer

        ckptr = Checkpointer(checkpoint_dir)
        # Abstract restore target: init_fn is jitted with out_shardings, so
        # eval_shape leaves already carry the mesh shardings — no init
        # compute, and never two full states in HBM during restore.
        abstract = jax.eval_shape(init_fn, key)
        step, restored = ckptr.restore_latest(
            target=abstract,
            shardings=jax.tree.map(lambda l: l.sharding, abstract),
        )
        if step is not None:
            state, start = restored, step
    if state is None:
        state = init_fn(key)

    metrics = None
    if start >= n_steps:
        return state, metrics
    try:
        it = iter(batches)
        for i, batch in enumerate(it):
            if i >= n_steps:
                break
            if i < start:
                continue  # replay the data stream up to the resume point
            state, metrics = step_fn(state, batch)
            done = i + 1
            if on_metrics is not None:
                on_metrics(done, metrics)
            if ckptr is not None and (
                done % checkpoint_every == 0 or done == n_steps
            ):
                # Saves overlap with subsequent steps; the finally below
                # finalizes whichever save is still in flight — including
                # when a later step raises, so every dispatched checkpoint
                # stays durable for the post-crash resume.
                ckptr.save(done, state, wait=False)
    finally:
        if ckptr is not None:
            ckptr.wait_until_finished()
    return state, metrics
