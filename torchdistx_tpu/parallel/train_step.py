"""Sharded training steps: pjit over a named mesh, FSDP/TP/DP/SP + SlowMo.

The reference framework's training story is SlowMo over FSDP ``NO_SHARD``
replicas wired through torch.distributed process groups
(/root/reference/src/python/torchdistx/slowmo/).  The TPU-native story is a
single jitted SPMD program over a ``jax.sharding.Mesh``:

* **FSDP/TP** — parameters placed by :func:`models.llama.param_specs`; XLA's
  SPMD partitioner inserts the all-gathers/reduce-scatters (ZeRO-3) and the
  Megatron psums (TP).  No wrapper classes, no hooks.
* **DP** — the batch dim is sharded over the data axes; gradient all-reduce
  is just autodiff of the sharded loss mean.
* **SP** — ``seq_axis`` routes attention through ring attention
  (:mod:`torchdistx_tpu.parallel.ring_attention`).
* **SlowMo** — :func:`make_slowmo_train_step` keeps *diverging* replicas as
  a stacked leading ``dp`` axis (vmapped forward), with the periodic exact
  averaging lowering to one all-reduce over the DCN-major ``dp`` axis — the
  intra-node/inter-node split of the reference mapped onto ICI/DCN
  (SURVEY.md §2.3).

All state lives in an explicit :class:`TrainState` pytree (orbax-
checkpointable; see :mod:`torchdistx_tpu.utils.checkpoint`).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import llama
from ..resilience import guard as _guard
from .sharding import fit_shardings
from .slowmo import SlowMomentumOptimizer, SlowMoState

__all__ = [
    "TrainState",
    "make_train_step",
    "make_slowmo_train_step",
    "batch_sharding",
]


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: Any  # scalar int32


def _named(mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def batch_sharding(mesh, *, data_axes=("dp", "fsdp")) -> NamedSharding:
    """Sharding for ``(B, S)`` token batches: batch dim over the data axes."""
    present = tuple(a for a in data_axes if a in mesh.axis_names)
    return _named(mesh, P(present or None, None))


def _match_param_shardings(mesh, params_abstract, param_shardings, target):
    """Sharding for an arbitrary state pytree (optimizer moments etc.).

    Optax moment trees (adam's mu/nu, sgd's trace, ...) embed the *params
    tree structure*, so a state leaf whose tree-path suffix + shape match a
    parameter leaf inherits that parameter's sharding.  Matching by shape
    alone is wrong: wq ``(L, D, D)`` and wo ``(L, D, D)`` collide while
    their shardings are transposed.  Shape matching remains only as a
    fallback when it is unambiguous; everything else (counts, scalars)
    replicates.
    """
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    p_leaves, _ = tree_flatten_with_path(params_abstract)
    s_leaves = jax.tree.leaves(param_shardings)
    by_path = {}
    by_shape = {}
    for (path, leaf), sh in zip(p_leaves, s_leaves):
        keys = tuple(str(k) for k in path)
        by_path[keys] = (leaf.shape, sh)
        by_shape.setdefault(leaf.shape, set()).add(sh)
    suffix_lens = sorted({len(p) for p in by_path}, reverse=True)
    rep = _named(mesh, P())

    t_leaves, treedef = tree_flatten_with_path(target)
    out = []
    for path, leaf in t_leaves:
        keys = tuple(str(k) for k in path)
        shape = getattr(leaf, "shape", None)
        placed = None
        for n in suffix_lens:
            hit = by_path.get(keys[-n:]) if n <= len(keys) else None
            if hit is not None and hit[0] == shape:
                placed = hit[1]
                break
        if placed is None and shape in by_shape and len(by_shape[shape]) == 1:
            placed = next(iter(by_shape[shape]))
        out.append(placed or rep)
    return tree_unflatten(treedef, out)


def make_train_step(
    cfg,
    mesh,
    tx,
    *,
    model=llama,
    tp: Optional[str] = "tp",
    fsdp: Optional[str] = "fsdp",
    seq_axis: Optional[str] = None,
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
    pp_schedule: str = "gpipe",
    attn_impl: str = "auto",
    seq_layout: str = "contiguous",
    loss_fn: Optional[Callable] = None,
    nonfinite_guard: bool = True,
) -> Tuple[Callable, Callable]:
    """Build ``(init_fn, step_fn)`` for standard optax training.

    ``model`` is a model family module implementing the protocol
    ``init_params(key, cfg)`` / ``abstract_params(cfg)`` /
    ``param_specs(cfg, tp=, fsdp=)`` / ``loss_fn(params, tokens, targets,
    cfg, ...)`` — :mod:`torchdistx_tpu.models.llama` (default) and
    :mod:`~torchdistx_tpu.models.gpt2` both qualify.

    ``init_fn(key) -> TrainState`` — shard-then-materialize: parameters are
    initialized by one compiled program whose ``out_shardings`` place every
    shard on its own device (no full tensor anywhere).

    ``step_fn(state, batch) -> (state, metrics)`` — one jitted SPMD training
    step; ``batch`` is ``{"tokens": (B,S), "targets": (B,S)}`` sharded with
    :func:`batch_sharding`.  State buffers are donated.

    ``nonfinite_guard`` (default on) adds a jit-side all-reduced
    finiteness check over loss and gradients: a poisoned step returns
    the PRIOR state bit-identical (params, optimizer moments, and step
    counter all unchanged — one NaN gradient must not corrupt optimizer
    state forever) and reports ``metrics["nonfinite"]=True`` so the
    training loop can count skips and escalate (see
    :mod:`torchdistx_tpu.resilience.guard`).  A clean step's update is
    unaffected — the select picks the freshly computed state.  The
    reserved batch key ``_tdx_nan`` (injected by ``fit()`` under a
    ``TDX_FAULT=step.exec:N:nan`` spec) deterministically poisons the
    loss for fault-injection tests.

    ``pp_schedule``: ``"gpipe"`` (autodiff through the pipeline scan) or
    ``"1f1b"`` (hand-written interleaved backward with O(P) live
    activations — :func:`parallel.pipeline.pipeline_value_and_grad`;
    requires a model family exposing ``pp_value_and_grad``, e.g. llama).
    """
    # pp kwargs are only passed when pipeline parallelism is requested, so
    # custom model families implementing the base protocol
    # (param_specs(cfg, *, tp, fsdp); loss_fn without pp kwargs) still work.
    pp_spec_kw = {"pp": pp_axis} if pp_axis is not None else {}
    pp_loss_kw = (
        {"pp_axis": pp_axis, "n_microbatches": n_microbatches}
        if pp_axis is not None
        else {}
    )
    specs = model.param_specs(cfg, tp=tp, fsdp=fsdp, **pp_spec_kw)
    abstract = model.abstract_params(cfg)
    param_shardings = fit_shardings(specs, abstract, mesh)
    # Only forwarded when non-default, so model families without the kwarg
    # (gpt2/moe) keep working with the base protocol.
    layout_kw = (
        {"seq_layout": seq_layout} if seq_layout != "contiguous" else {}
    )
    if loss_fn is not None and seq_layout != "contiguous":
        # The layout is applied inside the model's own loss_fn (token
        # permutation + target alignment); it cannot be injected into a
        # user-provided loss, so silently ignoring it would train on a
        # contiguous layout the caller did not ask for.
        raise ValueError(
            f"seq_layout={seq_layout!r} cannot be combined with a custom "
            "loss_fn — apply the layout inside your loss_fn and pass "
            "seq_layout='contiguous'."
        )
    _loss = loss_fn or functools.partial(
        model.loss_fn, cfg=cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, **pp_loss_kw, **layout_kw,
    )

    if pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pp_schedule: {pp_schedule!r}")
    value_and_grad = None
    if pp_schedule == "1f1b":
        if pp_axis is None:
            raise ValueError("pp_schedule='1f1b' requires pp_axis=")
        if loss_fn is not None:
            raise ValueError(
                "pp_schedule='1f1b' computes the loss inside the pipeline "
                "and cannot wrap a custom loss_fn"
            )
        if seq_axis is not None or seq_layout != "contiguous":
            # pp_pieces has no sequence-parallel path; silently training on
            # a contiguous layout would diverge from the same call under
            # pp_schedule='gpipe'.
            raise ValueError(
                "pp_schedule='1f1b' does not compose with seq_axis/"
                "seq_layout — use pp_schedule='gpipe' for sp×pp"
            )
        if not hasattr(model, "pp_value_and_grad"):
            raise ValueError(
                f"pp_schedule='1f1b' requires {model.__name__} to expose "
                "pp_value_and_grad (see models.llama / models.gpt2)"
            )
        value_and_grad = functools.partial(
            model.pp_value_and_grad, cfg=cfg, mesh=mesh, pp_axis=pp_axis,
            n_microbatches=n_microbatches, attn_impl=attn_impl,
        )

    opt_abstract = jax.eval_shape(tx.init, abstract)
    opt_shardings = _match_param_shardings(
        mesh, abstract, param_shardings, opt_abstract
    )
    state_shardings = TrainState(
        params=param_shardings,
        opt_state=opt_shardings,
        step=_named(mesh, P()),
    )

    @functools.partial(jax.jit, out_shardings=state_shardings)
    def init_fn(key):
        params = model.init_params(key, cfg)
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    @functools.partial(
        jax.jit, out_shardings=(state_shardings, None), donate_argnums=(0,)
    )
    def step_fn(state: TrainState, batch):
        if value_and_grad is not None:
            loss, grads = value_and_grad(
                state.params, batch["tokens"], batch["targets"]
            )
        else:
            loss, grads = jax.value_and_grad(_loss)(
                state.params, batch["tokens"], batch["targets"]
            )
        if "_tdx_nan" in batch:
            # Deterministic fault injection (resilience.faults, kind
            # "nan"): poison the loss so the guard's real detection path
            # trips — the key only exists on injected calls, so clean
            # steps compile without this select.
            loss = jnp.where(
                jnp.asarray(batch["_tdx_nan"]),
                jnp.asarray(jnp.nan, dtype=loss.dtype),
                loss,
            )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        import optax

        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(params, opt_state, state.step + 1)
        if nonfinite_guard:
            ok = _guard.tree_allfinite(loss, grads)
            new_state = _guard.select_tree(ok, new_state, state)
            metrics = {
                "loss": loss,
                "step": new_state.step,
                "nonfinite": ~ok,
            }
        else:
            metrics = {"loss": loss, "step": new_state.step}
        return new_state, metrics

    return init_fn, step_fn


# ---------------------------------------------------------------------------
# SlowMo training step (stacked-replica DP over the dp axis)


def make_slowmo_train_step(
    cfg,
    mesh,
    opt: SlowMomentumOptimizer,
    *,
    model=llama,
    dp_axis: str = "dp",
    tp: Optional[str] = "tp",
    fsdp: Optional[str] = "fsdp",
    attn_impl: str = "auto",
) -> Tuple[Callable, Callable]:
    """Build ``(init_fn, step_fn)`` for SlowMo training.

    Replicas that diverge between averaging steps are a stacked leading axis
    of size ``mesh.shape[dp_axis]`` on every parameter leaf, sharded over
    ``dp_axis`` — each replica trains on its own batch shard with its own
    base-optimizer state; every ``slowmo_freq`` steps the ``lax.cond`` branch
    runs the exact averaging (one all-reduce over DCN) + slow-momentum
    update.  Within a replica, fsdp/tp shard the *trailing* dims as usual.

    ``step_fn(state, batch)`` takes ``batch`` ``{"tokens","targets"}`` of
    shape ``(dp, B, S)`` sharded ``P("dp", fsdp-axes, None)``.
    """
    ndp = mesh.shape[dp_axis]
    specs = jax.tree.map(
        lambda s: P(dp_axis, *s),
        model.param_specs(cfg, tp=tp, fsdp=fsdp),
        is_leaf=lambda x: isinstance(x, P),
    )
    abstract = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((ndp,) + l.shape, l.dtype),
        model.abstract_params(cfg),
    )
    stacked_shardings = fit_shardings(specs, abstract, mesh)
    state_abstract = jax.eval_shape(opt.init, abstract)
    # prev/momentum are unstacked (replica-shared); base state is stacked.
    unstacked_shardings = jax.tree.map(
        lambda sh: _named(mesh, P(*sh.spec[1:])), stacked_shardings
    )
    opt_shardings = SlowMoState(
        base=_match_param_shardings(
            mesh, abstract, stacked_shardings, state_abstract.base
        ),
        prev=unstacked_shardings,
        momentum=unstacked_shardings,
        step=_named(mesh, P()),
    )
    state_shardings = TrainState(
        params=stacked_shardings, opt_state=opt_shardings, step=_named(mesh, P())
    )

    # The per-replica loss runs under vmap over the stacked dp axis, where
    # neither the flash kernel's shard_map wrapper (its dp batch spec would
    # split a replica's local batch across the axis replicas diverge over)
    # nor the bare Mosaic kernel (no SPMD rules) can run — pin "auto" to
    # XLA's jnp attention and refuse an explicit "pallas".
    if attn_impl == "pallas":
        raise ValueError(
            "attn_impl='pallas' is not supported in the SlowMo step (the "
            "loss is vmapped over stacked replicas); use 'auto' or 'jnp'"
        )
    resolved_impl = "jnp" if attn_impl == "auto" else attn_impl

    def _loss(params, tokens, targets):
        # mesh is forwarded for ring/seq-parallel dispatch decisions.
        return model.loss_fn(
            params, tokens, targets, cfg, mesh=mesh, attn_impl=resolved_impl
        )

    @functools.partial(jax.jit, out_shardings=state_shardings)
    def init_fn(key):
        params = model.init_params(key, cfg)
        stacked = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (ndp,) + p.shape), params
        )
        return TrainState(
            params=stacked,
            opt_state=opt.init(stacked),
            step=jnp.zeros((), jnp.int32),
        )

    @functools.partial(
        jax.jit, out_shardings=(state_shardings, None), donate_argnums=(0,)
    )
    def step_fn(state: TrainState, batch):
        # Per-replica loss/grads — the vmap axis IS the dp axis.
        losses, grads = jax.vmap(jax.value_and_grad(_loss))(
            state.params, batch["tokens"], batch["targets"]
        )
        params, opt_state = opt.update(grads, state.opt_state, state.params)
        metrics = {"loss": losses.mean(), "step": state.step + 1}
        return TrainState(params, opt_state, state.step + 1), metrics

    return init_fn, step_fn


def slowmo_batch_sharding(mesh, *, dp_axis="dp", data_axes=("fsdp",)):
    present = tuple(a for a in data_axes if a in mesh.axis_names)
    return _named(mesh, P(dp_axis, present or None, None))
