"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

The stacked-layer representation the model families already use
(``(n_layers, ...)`` leaves scanned by ``lax.scan``) extends naturally to
pipeline parallelism: shard the layer dim over ``pp`` so each device holds a
contiguous *stage* of ``n_layers / pp`` blocks, split the batch into
microbatches, and run the classic GPipe schedule — at tick ``t`` stage ``p``
processes microbatch ``t - p``, handing activations to stage ``p+1`` with a
single neighbor ``ppermute`` hop (ICI).  ``M + P - 1`` ticks drain the
pipeline; bubble fraction ``(P-1)/(M+P-1)`` shrinks with more microbatches.

Implemented as ``shard_map`` + ``lax.scan`` over ticks: nests inside the
jitted train step, composes with dp/fsdp/tp on the other mesh axes, and is
reverse-differentiable (scan + ppermute transpose), so pipeline-parallel
*training* works through plain ``jax.grad``.

Cost model (per device, ``P`` stages, ``M`` microbatches, ``T`` = one
stage's per-microbatch compute):

* **Ticks**: ``M + P - 1``; wall-clock ``(M + P - 1) · T`` against a
  perfectly overlapped ideal of ``M · T`` → bubble overhead
  ``(P - 1)/M``, amortized away by raising ``n_microbatches``.
* **FLOPs**: stage compute is gated behind ``lax.cond`` on tick validity
  (``0 ≤ t − p < M``), so ramp-up/drain ticks execute the identity branch —
  each device performs exactly ``M`` stage-computations of real work, the
  same FLOP count as an unpipelined run, in both the forward and the
  ``cond``-transposed backward pass.  (An earlier revision ran every stage
  on every tick: ``(P−1)/M`` pure waste.)
* **Activation memory**: inputs are replicated over the ``pp`` axis (every
  stage re-slices its current microbatch locally — no gather from stage 0),
  which costs ``B·…`` per device *once*; they remain sharded as usual over
  the automatic dp/fsdp axes, so the replication factor applies only to the
  per-dp-shard slice.  Weights are never replicated: each stage holds its
  ``L/P`` layers (sharded further by tp/fsdp on trailing dims).

The reference framework has no pipeline parallelism (SURVEY.md §2.3) — this
is native new capability, like ring attention.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_forward", "stage_specs"]


def _shard_map(fn, mesh, in_specs, out_specs, manual_axes):
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names=frozenset(manual_axes), check_vma=False,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            auto=frozenset(mesh.axis_names) - frozenset(manual_axes),
            check_rep=False,
        )


def stage_specs(layer_specs, *, pp: str = "pp"):
    """Prefix every stacked-layer spec with the ``pp`` axis on the layer dim
    (composes with tp/fsdp on the trailing dims)."""
    return jax.tree.map(
        lambda s: P(pp, *s), layer_specs, is_leaf=lambda x: isinstance(x, P)
    )


def pipeline_forward(
    x,
    layer_params,
    block_fn: Callable,
    *,
    mesh,
    axis: str = "pp",
    n_microbatches: int,
):
    """Run stacked layers over ``x`` with a GPipe schedule.

    ``x``: an activation array ``(B, ...)`` or a *pytree* of them (every
    leaf with the same leading batch dim) — side channels like an MoE
    router aux-loss accumulator travel through the pipeline alongside the
    hidden state.  ``layer_params``: pytree with leading layer dim on every
    leaf, sharded ``P(axis, ...)`` (see :func:`stage_specs`).
    ``block_fn(x, lp) -> x`` is one transformer block given one layer's
    (unstacked) params, preserving the pytree structure of ``x``.
    ``n_microbatches`` must divide the global batch ``B``.

    Only the ``axis`` dimension is manual inside the ``shard_map`` — every
    other mesh axis (dp/fsdp/tp) stays *automatic*, so activations keep
    their batch sharding and stage weights keep their fsdp/tp sharding with
    XLA inserting the usual Megatron/ZeRO collectives inside each stage (no
    all-gather of stage weights, no duplicated matmuls).
    """
    names = set(mesh.axis_names)
    if axis not in names:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {mesh.axis_names})")
    n_stages = mesh.shape[axis]
    leaves = jax.tree.leaves(x)
    batch = leaves[0].shape[0]
    if any(l.shape[0] != batch for l in leaves):
        raise ValueError("all activation leaves must share the batch dim")
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {n_microbatches} microbatches"
        )
    x_spec = jax.tree.map(lambda l: P(*([None] * l.ndim)), x)
    param_specs_local = jax.tree.map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), layer_params
    )

    def body(x_local, params_local):
        # x_local leaves: (B_local, ...); params_local: (L/P, ...) stage.
        p = jax.lax.axis_index(axis)
        bt = batch // n_microbatches
        micro = jax.tree.map(
            lambda l: l.reshape((n_microbatches, bt) + l.shape[1:]), x_local
        )

        def run_stage(act):
            def scan_block(h, lp):
                return block_fn(h, lp), None

            out, _ = jax.lax.scan(scan_block, act, params_local)
            return out

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        n_ticks = n_microbatches + n_stages - 1
        out0 = jax.tree.map(jnp.zeros_like, micro)
        carry0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), micro)

        def tick(carry, t):
            incoming, outputs = carry
            m = t - p  # microbatch this stage works on at tick t
            valid = (m >= 0) & (m < n_microbatches)
            m_idx = jnp.clip(m, 0, n_microbatches - 1)
            stage_in = jax.tree.map(
                lambda mic, inc: jnp.where(
                    p == 0,
                    jax.lax.dynamic_index_in_dim(mic, m_idx, 0,
                                                 keepdims=False),
                    inc,
                ),
                micro, incoming,
            )
            # Gate the stage behind the validity predicate: ramp-up/drain
            # ticks take the identity branch, skipping the stage's FLOPs in
            # both the forward and (via cond's transpose) the backward pass.
            # Deadlock-freedom invariant: the predicate varies only over the
            # pp axis (it derives from this stage's axis_index and the tick),
            # so every member of any tp/fsdp collective group XLA forms
            # *inside* run_stage takes the same branch, and the pp-wide
            # ppermute below runs unconditionally every tick.  A collective
            # whose group spans pp must never move inside a branch.
            y = jax.lax.cond(valid, run_stage, lambda act: act, stage_in)
            # Last stage banks its (valid) result.
            outputs = jax.tree.map(
                lambda out, yl: jax.lax.dynamic_update_index_in_dim(
                    out,
                    out[m_idx]
                    + jnp.where(
                        valid & (p == n_stages - 1), yl, 0.0
                    ).astype(out.dtype),
                    m_idx,
                    0,
                ),
                outputs, y,
            )
            # Hand activations to the next stage.
            incoming = jax.tree.map(
                lambda yl: jax.lax.ppermute(yl, axis, perm), y
            )
            return (incoming, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (carry0, out0), jnp.arange(n_ticks)
        )
        # Only the last stage holds real outputs; make them visible on all
        # stages (they're zeros elsewhere, so a psum is a broadcast).
        outputs = jax.lax.psum(outputs, axis)
        return jax.tree.map(
            lambda out, l: out.reshape(l.shape), outputs, x_local
        )

    return _shard_map(
        body, mesh, in_specs=(x_spec, param_specs_local), out_specs=x_spec,
        manual_axes={axis},
    )(x, layer_params)
