"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

The stacked-layer representation the model families already use
(``(n_layers, ...)`` leaves scanned by ``lax.scan``) extends naturally to
pipeline parallelism: shard the layer dim over ``pp`` so each device holds a
contiguous *stage* of ``n_layers / pp`` blocks, split the batch into
microbatches, and run the classic GPipe schedule — at tick ``t`` stage ``p``
processes microbatch ``t - p``, handing activations to stage ``p+1`` with a
single neighbor ``ppermute`` hop (ICI).  ``M + P - 1`` ticks drain the
pipeline; bubble fraction ``(P-1)/(M+P-1)`` shrinks with more microbatches.

Implemented as ``shard_map`` + ``lax.scan`` over ticks: nests inside the
jitted train step, composes with dp/fsdp/tp on the other mesh axes, and is
reverse-differentiable (scan + ppermute transpose), so pipeline-parallel
*training* works through plain ``jax.grad``.

Cost model (per device, ``P`` stages, ``M`` microbatches, ``T`` = one
stage's per-microbatch compute):

* **Ticks**: ``M + P - 1``; wall-clock ``(M + P - 1) · T`` against a
  perfectly overlapped ideal of ``M · T`` → bubble overhead
  ``(P - 1)/M``, amortized away by raising ``n_microbatches``.
* **FLOPs**: stage compute is gated behind ``lax.cond`` on tick validity
  (``0 ≤ t − p < M``), so ramp-up/drain ticks execute the identity branch —
  each device performs exactly ``M`` stage-computations of real work, the
  same FLOP count as an unpipelined run, in both the forward and the
  ``cond``-transposed backward pass.  (An earlier revision ran every stage
  on every tick: ``(P−1)/M`` pure waste.)
* **Activation memory**: inputs are replicated over the ``pp`` axis (every
  stage re-slices its current microbatch locally — no gather from stage 0),
  which costs ``B·…`` per device *once*; they remain sharded as usual over
  the automatic dp/fsdp axes, so the replication factor applies only to the
  per-dp-shard slice.  Weights are never replicated: each stage holds its
  ``L/P`` layers (sharded further by tp/fsdp on trailing dims).

The reference framework has no pipeline parallelism (SURVEY.md §2.3) — this
is native new capability, like ring attention.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_forward", "pipeline_value_and_grad", "stage_specs"]


def _shard_map(fn, mesh, in_specs, out_specs, manual_axes):
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names=frozenset(manual_axes), check_vma=False,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            auto=frozenset(mesh.axis_names) - frozenset(manual_axes),
            check_rep=False,
        )


def stage_specs(layer_specs, *, pp: str = "pp"):
    """Prefix every stacked-layer spec with the ``pp`` axis on the layer dim
    (composes with tp/fsdp on the trailing dims)."""
    return jax.tree.map(
        lambda s: P(pp, *s), layer_specs, is_leaf=lambda x: isinstance(x, P)
    )


def pipeline_forward(
    x,
    layer_params,
    block_fn: Callable,
    *,
    mesh,
    axis: str = "pp",
    n_microbatches: int,
):
    """Run stacked layers over ``x`` with a GPipe schedule.

    ``x``: an activation array ``(B, ...)`` or a *pytree* of them (every
    leaf with the same leading batch dim) — side channels like an MoE
    router aux-loss accumulator travel through the pipeline alongside the
    hidden state.  ``layer_params``: pytree with leading layer dim on every
    leaf, sharded ``P(axis, ...)`` (see :func:`stage_specs`).
    ``block_fn(x, lp) -> x`` is one transformer block given one layer's
    (unstacked) params, preserving the pytree structure of ``x``.
    ``n_microbatches`` must divide the global batch ``B``.

    Only the ``axis`` dimension is manual inside the ``shard_map`` — every
    other mesh axis (dp/fsdp/tp) stays *automatic*, so activations keep
    their batch sharding and stage weights keep their fsdp/tp sharding with
    XLA inserting the usual Megatron/ZeRO collectives inside each stage (no
    all-gather of stage weights, no duplicated matmuls).
    """
    names = set(mesh.axis_names)
    if axis not in names:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {mesh.axis_names})")
    n_stages = mesh.shape[axis]
    leaves = jax.tree.leaves(x)
    batch = leaves[0].shape[0]
    if any(l.shape[0] != batch for l in leaves):
        raise ValueError("all activation leaves must share the batch dim")
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {n_microbatches} microbatches"
        )
    x_spec = jax.tree.map(lambda l: P(*([None] * l.ndim)), x)
    param_specs_local = jax.tree.map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), layer_params
    )

    def body(x_local, params_local):
        # x_local leaves: (B_local, ...); params_local: (L/P, ...) stage.
        p = jax.lax.axis_index(axis)
        bt = batch // n_microbatches
        micro = jax.tree.map(
            lambda l: l.reshape((n_microbatches, bt) + l.shape[1:]), x_local
        )

        def run_stage(act):
            def scan_block(h, lp):
                return block_fn(h, lp), None

            out, _ = jax.lax.scan(scan_block, act, params_local)
            return out

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        n_ticks = n_microbatches + n_stages - 1
        out0 = jax.tree.map(jnp.zeros_like, micro)
        carry0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), micro)

        def tick(carry, t):
            incoming, outputs = carry
            m = t - p  # microbatch this stage works on at tick t
            valid = (m >= 0) & (m < n_microbatches)
            m_idx = jnp.clip(m, 0, n_microbatches - 1)
            stage_in = jax.tree.map(
                lambda mic, inc: jnp.where(
                    p == 0,
                    jax.lax.dynamic_index_in_dim(mic, m_idx, 0,
                                                 keepdims=False),
                    inc,
                ),
                micro, incoming,
            )
            # Gate the stage behind the validity predicate: ramp-up/drain
            # ticks take the identity branch, skipping the stage's FLOPs in
            # both the forward and (via cond's transpose) the backward pass.
            # Deadlock-freedom invariant: the predicate varies only over the
            # pp axis (it derives from this stage's axis_index and the tick),
            # so every member of any tp/fsdp collective group XLA forms
            # *inside* run_stage takes the same branch, and the pp-wide
            # ppermute below runs unconditionally every tick.  A collective
            # whose group spans pp must never move inside a branch.
            y = jax.lax.cond(valid, run_stage, lambda act: act, stage_in)
            # Last stage banks its (valid) result.
            outputs = jax.tree.map(
                lambda out, yl: jax.lax.dynamic_update_index_in_dim(
                    out,
                    out[m_idx]
                    + jnp.where(
                        valid & (p == n_stages - 1), yl, 0.0
                    ).astype(out.dtype),
                    m_idx,
                    0,
                ),
                outputs, y,
            )
            # Hand activations to the next stage.
            incoming = jax.tree.map(
                lambda yl: jax.lax.ppermute(yl, axis, perm), y
            )
            return (incoming, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (carry0, out0), jnp.arange(n_ticks)
        )
        # Only the last stage holds real outputs; make them visible on all
        # stages (they're zeros elsewhere, so a psum is a broadcast).
        outputs = jax.lax.psum(outputs, axis)
        return jax.tree.map(
            lambda out, l: out.reshape(l.shape), outputs, x_local
        )

    return _shard_map(
        body, mesh, in_specs=(x_spec, param_specs_local), out_specs=x_spec,
        manual_axes={axis},
    )(x, layer_params)


# ---------------------------------------------------------------------------
# 1F1B: interleaved forward/backward schedule with O(P) live activations.
#
# GPipe above relies on jax autodiff of the tick scan, which saves one
# stage-input activation per tick — O(M + P) microbatch activations live at
# the forward/backward boundary (plus per-layer residuals unless the block
# is rematerialized).  The classic fix is 1F1B (PipeDream-flush /
# Megatron-LM): a stage starts microbatch m's backward as soon as its
# gradient arrives, so at most ~P microbatches are ever in flight per stage.
#
# JAX's autodiff cannot express that interleaving (the transpose of a scan
# runs strictly after the whole forward), so :func:`pipeline_value_and_grad`
# writes the backward BY HAND inside the same tick scan: each tick a stage
# may run one forward (activation stashed in a ring buffer) and one
# backward (``jax.vjp`` re-runs the stage forward from the stashed input —
# full rematerialization — then transposes it), accumulating parameter
# gradients in the scan carry.  The loss head runs inside the LAST stage,
# per microbatch, which is what lets gradients start flowing while later
# microbatches are still going forward.
#
# Schedule (0-indexed stage p of P, microbatch m of M, one fwd slot + one
# bwd slot per tick):
#
#   fwd(m, p) = max(m + p,  2m + 2p - P + 1)     # GPipe ramp, then 1-in-2
#   bwd(m, p) = 2P - 2 - p + 2m                  # drains one stage per tick
#   ticks     = bwd(M-1, 0) + 1 = 2M + 2P - 3
#
# Steady state alternates fwd (cost T) and bwd (recompute+transpose, ~3T)
# ticks per stage, with the phases offset across stages such that every
# stage performs 4T of work per 2 ticks — the same wall-clock as GPipe with
# rematerialized blocks, at a fraction of the activation memory.
#
# Liveness: a microbatch is live on stage p from fwd(m, p) to bwd(m, p);
# the in-flight count is bounded by (3P - 3p - 2)/2, so a ring buffer of
# ``3P//2 + 1`` slots (indexed m mod slots) never collides:
# write(m + slots) > bwd(m) for every stage.  That bound — O(P), not
# O(M + P) — is the entire point; ``last_stash_slots`` exposes it to tests.

last_stash_slots = 0  # introspection: ring-buffer depth of the last trace
last_n_ticks = 0
last_grad_acc_shapes = ()  # (name, shape, dtype) of the last trace's grad accumulators


def pipeline_value_and_grad(
    embed_params,
    layer_params,
    head_params,
    tokens,
    targets,
    embed_fn: Callable,
    block_fn: Callable,
    head_loss_fn: Callable,
    *,
    mesh,
    axis: str = "pp",
    n_microbatches: int,
    shared_params=None,
):
    """Compute ``(loss, (g_embed, g_layers, g_head))`` with a 1F1B schedule.

    ``embed_fn(embed_params, tokens_mb) -> h`` runs on stage 0 per
    microbatch; ``block_fn(h, lp) -> h`` is one transformer block (scanned
    over the stage's ``L/P`` layers); ``head_loss_fn(head_params, h,
    targets_mb) -> scalar`` runs on the last stage per microbatch (mean
    over the microbatch's tokens).  ``tokens``/``targets``: ``(B, S)`` with
    ``B % n_microbatches == 0``.  The activation ``h`` may be a PYTREE —
    side channels (an MoE router aux-loss accumulator) ride the pipeline
    in every buffer (stash, hops) alongside the hidden state, exactly as
    in :func:`pipeline_forward`.

    ``shared_params``: parameters used by BOTH the embedding and the head
    (GPT-2's tied token embedding).  When given, ``embed_fn(ep, tokens_mb,
    sp)`` and ``head_loss_fn(hp, h, targets_mb, sp)`` receive it as a
    trailing argument, it is carried with ONE f32 gradient accumulator,
    and the return becomes ``(loss, (g_embed, g_layers, g_head,
    g_shared))`` — duplicating a tied (V, D) tensor into both ep and hp
    would instead cost two vocab-sized accumulators and psums per stage.

    Gradients are accumulated across microbatches in float32 and cast back
    to the parameter dtypes; the loss is the mean over microbatches.  Only
    the ``axis`` dimension is manual — dp/fsdp/tp stay automatic exactly as
    in :func:`pipeline_forward`, with the same deadlock-freedom invariant
    (every branch predicate varies only over the pp axis).
    """
    global last_stash_slots, last_n_ticks
    if axis not in set(mesh.axis_names):
        raise ValueError(f"mesh has no axis {axis!r} (axes: {mesh.axis_names})")
    n_stages = mesh.shape[axis]
    M = n_microbatches
    B, S = tokens.shape
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    bt = B // M
    n_slots = (3 * n_stages) // 2 + 1
    n_ticks = 2 * M + 2 * n_stages - 3
    last_stash_slots, last_n_ticks = n_slots, n_ticks

    def stage_fn(lp, h):
        out, _ = jax.lax.scan(lambda c, l: (block_fn(c, l), None), h, lp)
        return out

    f32 = jnp.float32

    # Normalize the optional shared-params channel: internally the embed
    # and head always take a trailing ``sp`` (empty dict when unused).
    has_shared = shared_params is not None
    sp_in = shared_params if has_shared else {}

    def embed(ep_, tok_, sp_):
        return embed_fn(ep_, tok_, sp_) if has_shared else embed_fn(ep_, tok_)

    def head(hp_, y_, tgt_, sp_):
        return (
            head_loss_fn(hp_, y_, tgt_, sp_)
            if has_shared
            else head_loss_fn(hp_, y_, tgt_)
        )

    def body(ep, lp, hp, sp, tokens, targets):
        tmap = jax.tree.map
        p = jax.lax.axis_index(axis)
        up = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        down = [(i, (i - 1) % n_stages) for i in range(n_stages)]
        tok_mb = tokens.reshape(M, bt, S)
        tgt_mb = targets.reshape(M, bt, S)
        # Activation pytree structure/shapes (side channels included).
        h_ab = jax.eval_shape(
            embed, ep, jax.ShapeDtypeStruct((bt, S), tokens.dtype), sp
        )

        def zeros_h():
            return tmap(lambda a: jnp.zeros(a.shape, a.dtype), h_ab)

        def stash_read(stash, slot):
            return tmap(
                lambda st: jax.lax.dynamic_index_in_dim(
                    st, slot, 0, keepdims=False
                ),
                stash,
            )

        def stash_write(stash, slot, val):
            return tmap(
                lambda st, v: jax.lax.dynamic_update_index_in_dim(
                    st, v, slot, 0
                ),
                stash,
                val,
            )

        def zeros_f32_like(tree):
            return tmap(lambda l: jnp.zeros(l.shape, f32), tree)

        carry0 = dict(
            fc=jnp.zeros((), jnp.int32),
            bc=jnp.zeros((), jnp.int32),
            stash=tmap(
                lambda a: jnp.zeros((n_slots,) + a.shape, a.dtype), h_ab
            ),
            inc_y=zeros_h(),
            inc_m=jnp.full((), -1, jnp.int32),
            inc_g=zeros_h(),
            g_ep=zeros_f32_like(ep),
            g_lp=zeros_f32_like(lp),
            g_hp=zeros_f32_like(hp),
            g_sp=zeros_f32_like(sp),
            loss=jnp.zeros((), f32),
        )
        # Introspection for tests: the per-stage f32 gradient accumulators
        # carried through the scan (proves e.g. a tied embedding is carried
        # ONCE via shared_params, not duplicated into ep and hp).
        global last_grad_acc_shapes
        last_grad_acc_shapes = tuple(
            (name, tuple(leaf.shape), str(leaf.dtype))
            for name in ("g_ep", "g_lp", "g_hp", "g_sp")
            for leaf in jax.tree.leaves(carry0[name])
        )

        def tick(carry, t):
            # 1. Ingest the forward activation sent last tick (stages > 0).
            slot_in = jnp.maximum(carry["inc_m"], 0) % n_slots
            take = (carry["inc_m"] >= 0) & (p > 0)
            cur = stash_read(carry["stash"], slot_in)
            stash = stash_write(
                carry["stash"],
                slot_in,
                tmap(
                    lambda y, c: jnp.where(take, y, c), carry["inc_y"], cur
                ),
            )

            fc, bc = carry["fc"], carry["bc"]
            do_fwd = (
                t == jnp.maximum(fc + p, 2 * fc + 2 * p - n_stages + 1)
            ) & (fc < M)
            do_bwd = (t == 2 * n_stages - 2 - p + 2 * bc) & (bc < M)

            # 2. Forward slot.  Stage 0 embeds its microbatch and stashes
            # it; later stages read the stash.  The LAST stage never runs a
            # separate forward — its backward slot recomputes the stage via
            # vjp and feeds the head in one go.
            fi = jnp.minimum(fc, M - 1)

            def fwd_slot(stash):
                h_in = jax.lax.cond(
                    p == 0,
                    lambda: embed(
                        ep,
                        jax.lax.dynamic_index_in_dim(
                            tok_mb, fi, 0, keepdims=False
                        ),
                        sp,
                    ),
                    lambda: stash_read(stash, fi % n_slots),
                )
                stash = jax.lax.cond(
                    p == 0,
                    lambda s: stash_write(s, fi % n_slots, h_in),
                    lambda s: s,
                    stash,
                )
                y = jax.lax.cond(
                    p == n_stages - 1,
                    zeros_h,
                    lambda: stage_fn(lp, h_in),
                )
                return stash, y

            stash, y_out = jax.lax.cond(
                do_fwd,
                fwd_slot,
                lambda s: (s, zeros_h()),
                stash,
            )
            m_out = jnp.where(do_fwd & (p < n_stages - 1), fc, -1)

            # 3. Backward slot.  Recompute the stage forward from the
            # stashed input (full remat), transpose it with the cotangent —
            # the incoming pipeline gradient, or, on the last stage, the
            # head loss gradient computed right here.
            bi = jnp.minimum(bc, M - 1)

            def bwd_slot():
                h_in = stash_read(stash, bi % n_slots)
                y, vjp = jax.vjp(stage_fn, lp, h_in)

                def head_branch():
                    tgt = jax.lax.dynamic_index_in_dim(
                        tgt_mb, bi, 0, keepdims=False
                    )
                    loss_mb, (g_hp_mb, g_y, g_sp_mb) = jax.value_and_grad(
                        head, argnums=(0, 1, 3)
                    )(hp, y, tgt, sp)
                    return loss_mb.astype(f32), g_hp_mb, g_y, g_sp_mb

                loss_mb, g_hp_mb, g_y, g_sp_head = jax.lax.cond(
                    p == n_stages - 1,
                    head_branch,
                    lambda: (
                        jnp.zeros((), f32),
                        tmap(jnp.zeros_like, hp),
                        tmap(jnp.zeros_like, y),
                        tmap(jnp.zeros_like, sp),
                    ),
                )
                dh_out = tmap(
                    lambda a, b: jnp.where(p == n_stages - 1, a, b),
                    g_y,
                    carry["inc_g"],
                )
                g_lp_mb, g_h = vjp(dh_out)

                def embed_branch():
                    _, evjp = jax.vjp(
                        lambda e, s_: embed(
                            e,
                            jax.lax.dynamic_index_in_dim(
                                tok_mb, bi, 0, keepdims=False
                            ),
                            s_,
                        ),
                        ep,
                        sp,
                    )
                    return evjp(g_h)

                g_ep_mb, g_sp_embed = jax.lax.cond(
                    p == 0,
                    embed_branch,
                    lambda: (
                        tmap(jnp.zeros_like, ep),
                        tmap(jnp.zeros_like, sp),
                    ),
                )
                # Tied params: one accumulator, both contributions (at most
                # one is nonzero on any given stage).
                g_sp_mb = tmap(jnp.add, g_sp_head, g_sp_embed)
                return loss_mb, g_lp_mb, g_ep_mb, g_hp_mb, g_sp_mb, g_h

            (
                loss_mb, g_lp_mb, g_ep_mb, g_hp_mb, g_sp_mb, g_out
            ) = jax.lax.cond(
                do_bwd,
                bwd_slot,
                lambda: (
                    jnp.zeros((), f32),
                    tmap(jnp.zeros_like, lp),
                    tmap(jnp.zeros_like, ep),
                    tmap(jnp.zeros_like, hp),
                    tmap(jnp.zeros_like, sp),
                    zeros_h(),
                ),
            )

            acc = lambda a, b: a + b.astype(f32)  # noqa: E731
            new_carry = dict(
                fc=fc + do_fwd.astype(jnp.int32),
                bc=bc + do_bwd.astype(jnp.int32),
                stash=stash,
                # 4. Hand off: activations up, gradients down — both
                # unconditional every tick (deadlock freedom).
                inc_y=tmap(
                    lambda l: jax.lax.ppermute(l, axis, up), y_out
                ),
                inc_m=jax.lax.ppermute(m_out, axis, up),
                inc_g=tmap(
                    lambda l: jax.lax.ppermute(l, axis, down), g_out
                ),
                g_ep=jax.tree.map(acc, carry["g_ep"], g_ep_mb),
                g_lp=jax.tree.map(acc, carry["g_lp"], g_lp_mb),
                g_hp=jax.tree.map(acc, carry["g_hp"], g_hp_mb),
                g_sp=jax.tree.map(acc, carry["g_sp"], g_sp_mb),
                loss=carry["loss"] + loss_mb,
            )
            return new_carry, None

        out, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
        inv_m = 1.0 / M
        loss = jax.lax.psum(out["loss"], axis) * inv_m
        cast = lambda g, ref: (g * inv_m).astype(ref.dtype)  # noqa: E731
        g_ep = jax.tree.map(
            cast, jax.lax.psum(out["g_ep"], axis), ep
        )
        g_hp = jax.tree.map(
            cast, jax.lax.psum(out["g_hp"], axis), hp
        )
        g_sp = jax.tree.map(
            cast, jax.lax.psum(out["g_sp"], axis), sp
        )
        g_lp = jax.tree.map(cast, out["g_lp"], lp)
        return loss, g_ep, g_lp, g_hp, g_sp

    rep = lambda tree: jax.tree.map(  # noqa: E731
        lambda l: P(*([None] * l.ndim)), tree
    )
    lp_spec = jax.tree.map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), layer_params
    )
    loss, g_ep, g_lp, g_hp, g_sp = _shard_map(
        body,
        mesh,
        in_specs=(
            rep(embed_params),
            lp_spec,
            rep(head_params),
            rep(sp_in),
            P(None, None),
            P(None, None),
        ),
        out_specs=(
            P(),
            rep(embed_params),
            lp_spec,
            rep(head_params),
            rep(sp_in),
        ),
        manual_axes={axis},
    )(embed_params, layer_params, head_params, sp_in, tokens, targets)
    if has_shared:
        return loss, (g_ep, g_lp, g_hp, g_sp)
    return loss, (g_ep, g_lp, g_hp)
