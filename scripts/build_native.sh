#!/usr/bin/env bash
# Build the native core into torchdistx_tpu/lib/ (where _native.py looks).
#
# Usage: scripts/build_native.sh [--sanitizers "asan;ubsan"]
set -euo pipefail
cd "$(dirname "$0")/.."

SANS=""
if [[ "${1:-}" == "--sanitizers" ]]; then
  SANS="$2"
fi

mkdir -p build torchdistx_tpu/lib
cmake -S src/cc -B build -G Ninja \
  -DCMAKE_BUILD_TYPE=Release \
  -DTDX_SANITIZERS="${SANS}" >/dev/null
cmake --build build >/dev/null
cp build/libtdx_core.so torchdistx_tpu/lib/
echo "built torchdistx_tpu/lib/libtdx_core.so"
