"""Per-class vmapped stacked draw, returned whole with out_shardings — does
the draw shard?  compile/exec cost?  Then eager per-instance slicing cost."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)


def fold(k, o):
    return jax.random.fold_in(jax.random.fold_in(k, o), 1)


CLASSES = [
    ((2048, 2048), P("x", None), 96),
    ((5504, 2048), P("x", None), 48),
    ((2048, 5504), P(None, "x"), 24),
    ((32000, 2048), P("x", None), 1),
    ((32000, 2048), P("x", None), 1),
]

tot_compile = 0.0
tot_exec = 0.0
outs = []
off = 0
for shp, spec, n in CLASSES:
    ords = np.arange(off, off + n, dtype=np.uint32)
    off += n
    if n == 1:
        def f(k, o, shp=shp):
            return jax.random.normal(fold(k, o[0]), shp, dtype=jnp.float32) * 0.02
        osh = NamedSharding(mesh, spec)
    else:
        def f(k, o, shp=shp):
            keys = jax.vmap(lambda oo: fold(k, oo))(o)
            return jax.vmap(
                lambda kk: jax.random.normal(kk, shp, dtype=jnp.float32) * 0.02
            )(keys)
        osh = NamedSharding(mesh, P(None, *spec))
    t0 = time.perf_counter()
    c = jax.jit(f, out_shardings=osh).lower(key, ords).compile()
    tot_compile += time.perf_counter() - t0
    txt = c.as_text()
    full3 = txt.count(f"f32[{n},{shp[0]},{shp[1]}]") if n > 1 else 0
    t0 = time.perf_counter()
    r = c(key, ords)
    r.block_until_ready()
    dt = time.perf_counter() - t0
    tot_exec += dt
    print(f"class {shp}x{n}: compile+ {dt:.1f}s-exec full3d={full3}")
    outs.append((r, n))

print(f"TOTAL compile {tot_compile:.1f}s exec {tot_exec:.1f}s")

# eager unstack cost
t0 = time.perf_counter()
leaves = []
for r, n in outs:
    if n == 1:
        leaves.append(r)
    else:
        for i in range(n):
            leaves.append(r[i])
jax.block_until_ready(leaves)
print(f"eager unstack of {sum(n for _, n in outs)}: "
      f"{time.perf_counter()-t0:.1f}s")
print("slice sharding:", leaves[2].sharding)
import resource
print(f"ru_maxrss {resource.getrusage(resource.RUSAGE_SELF).ru_maxrss/1048576:.1f}GB")
