#!/usr/bin/env bash
# C++ unit tests for the native core (src/cc/tdx_core/graph_test.cc) — the
# tests/cc dir the reference left as a TODO.  Run plain and under
# ASan+UBSan.

set -euo pipefail
cd "$(dirname "$0")/.."

BUILD=build/cctest
mkdir -p "$BUILD"

g++ -std=c++17 -O1 -g -Isrc/cc/tdx_core -o "$BUILD/graph_test" \
  src/cc/tdx_core/graph.cc src/cc/tdx_core/graph_test.cc
"$BUILD/graph_test"

g++ -std=c++17 -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
  -Isrc/cc/tdx_core -o "$BUILD/graph_test_asan" \
  src/cc/tdx_core/graph.cc src/cc/tdx_core/graph_test.cc
ASAN_OPTIONS=abort_on_error=1 UBSAN_OPTIONS=halt_on_error=1 \
  "$BUILD/graph_test_asan"

echo "native unit tests: OK"
