"""Probe: does out_shardings shard a flat threefry draw through slice+reshape?"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)
N, M = 32000, 2048
osh = NamedSharding(mesh, P("x", None))


def report(name, cfn):
    txt = cfn.as_text()
    n_gather = txt.count("all-gather")
    # per-device output buffer sizes via cost analysis is unreliable on CPU;
    # look at the root computation's parameter/op shapes for full-size f32
    full = f"f32[{N},{M}]"
    shard = f"f32[{N//8},{M}]"
    flat_full = f"f32[{N*M}]"
    flat_shard = f"f32[{N*M//8}]"
    print(
        f"{name}: all-gather={n_gather} full2d={txt.count(full)} "
        f"shard2d={txt.count(shard)} flatfull={txt.count(flat_full)} "
        f"flatshard={txt.count(flat_shard)}"
    )


# 1. direct 2D draw
f1 = jax.jit(lambda k: jax.random.normal(k, (N, M)), out_shardings=osh)
report("direct2d", f1.lower(key).compile())

# 2. flat draw + reshape
f2 = jax.jit(
    lambda k: jax.random.normal(k, (N * M,)).reshape(N, M), out_shardings=osh
)
report("flat+reshape", f2.lower(key).compile())

# 3. flat draw + identity slice + reshape (the lowering's exact chain)
f3 = jax.jit(
    lambda k: (jax.random.normal(k, (N * M,)) * 0.02 + 0.0)[: N * M].reshape(
        N, M
    ),
    out_shardings=osh,
)
report("flat+slice+reshape", f3.lower(key).compile())

# 4. with explicit constraint on the flat draw
def g(k):
    flat = jax.random.normal(k, (N * M,))
    flat = jax.lax.with_sharding_constraint(flat, NamedSharding(mesh, P("x")))
    return (flat * 0.02)[: N * M].reshape(N, M)

f4 = jax.jit(g, out_shardings=osh)
report("constrained flat", f4.lower(key).compile())

# value checks: sharded == unsharded
a = f3(key)
b = jax.jit(lambda k: (jax.random.normal(k, (N * M,)) * 0.02)[: N * M].reshape(N, M))(key)
print("f3 == unsharded:", bool(jnp.array_equal(a, b)))
