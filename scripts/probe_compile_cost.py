"""Compile-time shapes: 170 flat chains vs vmapped-per-shape vs split jobs."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)

LAYER_SHAPES = (
    [((2048, 2048), P("x", None))] * 4
    + [((5504, 2048), P("x", None))] * 2
    + [((2048, 5504), P(None, "x"))]
)


def entries():
    out = [((32000, 2048), P("x", None), "embed"),
           ((32000, 2048), P("x", None), "lm_head")]
    for li in range(24):
        for j, (shp, spec) in enumerate(LAYER_SHAPES):
            out.append((shp, spec, f"l{li}p{j}"))
    return out


E = entries()
ords = np.arange(len(E), dtype=np.uint32)


def fold(k, o):
    return jax.random.fold_in(jax.random.fold_in(k, o), 1)


# A: flat chains (current)
def fa(k, ords):
    out = {}
    for i, (shp, spec, nm) in enumerate(E):
        out[nm] = jax.random.normal(fold(k, ords[i]), shp, dtype=jnp.float32) * 0.02
    return out


osh = {nm: NamedSharding(mesh, spec) for shp, spec, nm in E}
t0 = time.perf_counter()
ca = jax.jit(fa, out_shardings=osh).lower(key, ords).compile()
print(f"A flat 170 chains: compile {time.perf_counter()-t0:.1f}s")

# B: vmapped per shape-class with per-instance keys + constraint + slices
from collections import defaultdict

classes = defaultdict(list)
for i, (shp, spec, nm) in enumerate(E):
    classes[(shp, str(spec))].append((i, spec, nm))


def fb(k, ords):
    out = {}
    for (shp, _), items in classes.items():
        idx = jnp.asarray([i for i, _, _ in items], dtype=jnp.uint32)
        keys = jax.vmap(lambda o: fold(k, o))(ords[idx])
        spec = items[0][1]
        if len(items) == 1:
            out[items[0][2]] = jax.random.normal(keys[0], shp, dtype=jnp.float32) * 0.02
            continue
        stacked = jax.vmap(
            lambda kk: jax.random.normal(kk, shp, dtype=jnp.float32) * 0.02
        )(keys)
        stacked = jax.lax.with_sharding_constraint(
            stacked, NamedSharding(mesh, P(None, *spec))
        )
        for j, (_, _, nm) in enumerate(items):
            out[nm] = stacked[j]
    return out


t0 = time.perf_counter()
cb = jax.jit(fb, out_shardings=osh).lower(key, ords).compile()
print(f"B vmapped classes: compile {time.perf_counter()-t0:.1f}s")
txt = cb.as_text()
print("B full bufs:", sum(txt.count(f"f32[{a},{b}]") for (a, b), _ in
                          [( (2048,5504), 0), ((5504,2048), 0), ((32000,2048), 0), ((2048,2048), 0)]))

# C: split per class jobs (compile each separately, sum)
t0 = time.perf_counter()
tot = 0.0
for (shp, _), items in classes.items():
    def fc(k, o, items=items, shp=shp):
        out = {}
        for j, (i, spec, nm) in enumerate(items):
            out[nm] = jax.random.normal(fold(k, o[j]), shp, dtype=jnp.float32) * 0.02
        return out
    o = np.asarray([i for i, _, _ in items], dtype=np.uint32)
    oshc = {nm: NamedSharding(mesh, spec) for _, spec, nm in items}
    t1 = time.perf_counter()
    jax.jit(fc, out_shardings=oshc).lower(key, o).compile()
    tot += time.perf_counter() - t1
print(f"C split jobs: total compile {tot:.1f}s")

# exec check for B
t0 = time.perf_counter()
r = cb(key, ords)
jax.block_until_ready(list(r.values()))
print(f"B exec: {time.perf_counter()-t0:.1f}s")
import resource
print(f"ru_maxrss {resource.getrusage(resource.RUSAGE_SELF).ru_maxrss/1048576:.1f}GB")
