"""Flash-attention block sweep / decomposition harness (real TPU).

Usage:
  python scripts/flash_sweep.py decompose     # fwd-only vs fwd+bwd timing
  python scripts/flash_sweep.py sweep         # interleaved block configs

Interleaved rounds with per-round min-of-k chained iterations; per-config
MEDIAN across rounds (single tunnel windows read 20-30% slow — keep the
median, not the best window).  Overrides require jax.clear_caches() — the
block globals are trace-time only (see flash_attention.py note).
"""

import statistics
import sys
import time

sys.path.insert(0, __file__.rsplit("/scripts/", 1)[0])

import jax
import jax.numpy as jnp

from torchdistx_tpu.ops.pallas import flash_attention as fa

S, B, H, D = 16384, 1, 8, 128
PEAK = 197.0  # v5e bf16 TF/s


def make_inputs():
    key = jax.random.PRNGKey(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D),
                          dtype=jnp.bfloat16)
        for i in range(3)
    )


def time_chained(step, q, k, v, n=20, reps=3, grads=True):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        x, y, z = q, k, v
        for _ in range(n):
            if grads:
                gq, gk, gv = step(x, y, z)
                x, y, z = gq.astype(x.dtype), gk.astype(y.dtype), gv.astype(z.dtype)
            else:
                o = step(x, y, z)
                x = o.astype(x.dtype)
        float(x.astype(jnp.float32).sum())
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def build(kind):
    if kind == "fwd":
        f = jax.jit(
            lambda q, k, v: fa.flash_attention(q, k, v, causal=True)
        )
        return f, False
    f = jax.jit(
        jax.grad(
            lambda q, k, v: fa.flash_attention(q, k, v, causal=True)
            .astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )
    )
    return f, True


def decompose():
    q, k, v = make_inputs()
    for kind in ("fwd", "fwdbwd"):
        step, grads = build(kind)
        r = step(q, k, v)
        jax.block_until_ready(r)
        dt = time_chained(step, q, k, v, grads=grads)
        fwd_flops = 2 * 2 * B * H * S * S * D * 0.5
        tot = fwd_flops * (3.5 if grads else 1.0)
        print(f"{kind}: {dt*1e3:.2f} ms  mfu={tot/dt/1e12/PEAK:.4f}")


CONFIGS = [
    # (bwd_q, bwd_kv, fwd_q, fwd_kv)
    (1024, 1024, 1024, 1024),   # CURRENT defaults (r5, mask-free bodies)
    (512, 1024, 512, 2048),     # r4 tuned
    (512, 1024, 1024, 1024),
    (1024, 1024, 512, 2048),
    (256, 2048, 512, 2048),
    (1024, 2048, 1024, 2048),
]


def sweep(rounds=3):
    q, k, v = make_inputs()
    times = {c: [] for c in CONFIGS}
    for r in range(rounds):
        for c in CONFIGS:
            fa._BWD_BLOCK_Q, fa._BWD_BLOCK_KV = c[0], c[1]
            fa._FWD_BLOCK_Q, fa._FWD_BLOCK_KV = c[2], c[3]
            jax.clear_caches()
            step, grads = build("fwdbwd")
            rr = step(q, k, v)
            jax.block_until_ready(rr)
            dt = time_chained(step, q, k, v, n=10, reps=2)
            times[c].append(dt)
            print(f"round{r} {c}: {dt*1e3:.2f} ms", flush=True)
    print("--- medians")
    fwd_flops = 2 * 2 * B * H * S * S * D * 0.5
    for c in CONFIGS:
        med = statistics.median(times[c])
        print(f"{c}: {med*1e3:.2f} ms  mfu={3.5*fwd_flops/med/1e12/PEAK:.4f}")


if __name__ == "__main__":
    {"decompose": decompose, "sweep": sweep}[sys.argv[1]]()
