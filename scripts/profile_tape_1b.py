"""Profile the tape-path (materialize_module_jax) 1.35B HF materialize.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python scripts/profile_tape_1b.py
"""

import threading
import time

_peak = [0.0]
_stop = [False]


def _rss_now_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024
    return 0.0


def _sampler():
    while not _stop[0]:
        _peak[0] = max(_peak[0], _rss_now_mb())
        time.sleep(0.05)


def main():
    import jax

    t0 = time.perf_counter()
    from transformers import LlamaConfig, LlamaForCausalLM

    import torchdistx_tpu.deferred_init as di
    from torchdistx_tpu.materialize import materialize_module_jax
    from torchdistx_tpu.parallel import MeshSpec, make_mesh
    from torchdistx_tpu.parallel.sharding import fsdp_plan

    print(f"imports: {time.perf_counter()-t0:.1f}s rss={_rss_now_mb():.0f}MB")

    config = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=24, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=2048,
    )
    t0 = time.perf_counter()
    model = di.deferred_init(LlamaForCausalLM, config)
    t_fake = time.perf_counter() - t0
    n = sum(p.numel() for p in model.parameters())
    print(f"fake build: {t_fake:.1f}s params={n/1e9:.2f}B rss={_rss_now_mb():.0f}MB")

    mesh = make_mesh(MeshSpec(fsdp=8))
    th = threading.Thread(target=_sampler, daemon=True)
    rss0 = _rss_now_mb()
    _peak[0] = rss0
    th.start()
    t0 = time.perf_counter()
    arrays = materialize_module_jax(model, mesh=mesh, plan=fsdp_plan())
    jax.block_until_ready(list(arrays.values()))
    t_mat = time.perf_counter() - t0
    _stop[0] = True
    th.join()
    from torchdistx_tpu import materialize as _m

    print("profile:", {
        k: (round(v, 2) if isinstance(v, float) else v)
        for k, v in _m.last_profile.items() if k != "jobs"
    })
    for label, s, rss in _m.last_profile.get("jobs", []):
        print(f"  job {label}: {s:.2f}s rss_after={rss:.0f}MB")
    print(
        f"materialize: {t_mat:.1f}s rss_now={_rss_now_mb():.0f}MB "
        f"peak={_peak[0]:.0f}MB growth_peak={( _peak[0]-rss0)/1024:.1f}GB"
    )
    # sharding check on the big singletons
    for name in (
        "model.embed_tokens.weight",
        "lm_head.weight",
        "model.layers.0.self_attn.q_proj.weight",
    ):
        a = arrays[name]
        print(
            name, a.shape, str(a.dtype),
            "replicated" if a.sharding.is_fully_replicated else a.sharding.spec,
        )


if __name__ == "__main__":
    main()
