#!/usr/bin/env python
"""Fault-injection smoke: run a tiny fit() under TDX_FAULT and assert the
telemetry trace recorded the recovery.

CI (.github/workflows/ci.yaml, fault-injection job) runs this under a
matrix of fault specs:

    TDX_FAULT="ckpt.save:2:io"   TDX_EXPECT_COUNTER=ckpt.retries
    TDX_FAULT="data.next:3:io"   TDX_EXPECT_COUNTER=data.retries
    TDX_FAULT="step.exec:2:nan"  TDX_EXPECT_COUNTER=train.skipped_steps

The run must COMPLETE (the whole point of the resilience layer) and the
JSONL trace pointed at by TDX_TELEMETRY must contain a counters snapshot
with the expected counter >= 1 — recovery that telemetry cannot see is
indistinguishable from silent corruption.

Run locally:
    TDX_FAULT="ckpt.save:2:io" TDX_EXPECT_COUNTER=ckpt.retries \
    TDX_TELEMETRY=/tmp/fault-trace.jsonl \
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/fault_smoke.py
"""

import json
import os
import sys
import tempfile

# Runnable from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 4


def main() -> int:
    fault = os.environ.get("TDX_FAULT", "")
    expect = os.environ.get("TDX_EXPECT_COUNTER", "")
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not (fault and expect and trace):
        print(
            "fault_smoke: set TDX_FAULT, TDX_EXPECT_COUNTER and "
            "TDX_TELEMETRY",
            file=sys.stderr,
        )
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import optax

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel import train_step as ts
    from torchdistx_tpu.parallel.fit import fit
    from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
    from torchdistx_tpu.resilience.retry import RetryPolicy

    cfg = llama.llama_test()
    mesh = make_mesh(MeshSpec(dp=len(jax.devices())))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    bs = ts.batch_sharding(mesh)

    def batches():
        key = jax.random.PRNGKey(42)
        while True:
            key, sub = jax.random.split(key)
            t = jax.device_put(
                jax.random.randint(sub, (8, 16), 0, cfg.vocab_size), bs
            )
            yield {"tokens": t, "targets": t}

    with tempfile.TemporaryDirectory() as ckpt_dir:
        state, _ = fit(
            init_fn,
            step_fn,
            batches(),
            key=jax.random.PRNGKey(0),
            n_steps=N_STEPS,
            checkpoint_dir=ckpt_dir,
            checkpoint_every=2,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.05),
        )
    telemetry.emit_counters()

    counters = {}
    with open(trace) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "counters":
                counters.update(rec.get("values", {}))
    got = counters.get(expect, 0)
    if got < 1:
        print(
            f"fault_smoke: FAIL — TDX_FAULT={fault!r} completed but the "
            f"trace shows {expect}={got} (counters: {counters})",
            file=sys.stderr,
        )
        return 1
    print(
        f"fault_smoke: OK — TDX_FAULT={fault!r} recovered "
        f"({expect}={got}, final step {int(state.step)})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
