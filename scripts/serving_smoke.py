#!/usr/bin/env python
"""Serving smoke: run the engine under TDX_FAULT and assert the telemetry
trace recorded the engine's spans and the recovery.

CI (.github/workflows/ci.yaml, serving job) runs this with:

    TDX_FAULT="serve.step:3:nan" TDX_TELEMETRY=$RUNNER_TEMP/serve.jsonl

The run must DRAIN (every request completes — the poisoned decode chunk
is skipped, not fatal), the trace must contain `serve.prefill` and
`serve.step` spans, and a counters snapshot must show
`serve.skipped_steps >= 1` plus all submitted tokens committed.  On top
of the fault path, the engine's output is asserted token-identical to
solo generate() — the skip must be invisible in the token stream.

Run locally:
    TDX_FAULT="serve.step:3:nan" TDX_TELEMETRY=/tmp/serve-trace.jsonl \
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/serving_smoke.py
"""

import json
import os
import sys

# Runnable from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EOS = 5


def main() -> int:
    trace = os.environ.get("TDX_TELEMETRY", "")
    fault = os.environ.get("TDX_FAULT", "")
    if not trace:
        print("serving_smoke: set TDX_TELEMETRY (and optionally TDX_FAULT)",
              file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.serving import Engine

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=2, block_size=8,
        max_model_len=48, eos_id=EOS, decode_chunk=2,
    )
    prompts = [np.arange(1, 7, dtype=np.int32) + i for i in range(4)]
    handles = [
        eng.submit(p, max_new_tokens=10, key=i)
        for i, p in enumerate(prompts)
    ]
    eng.drain()

    for i, (p, h) in enumerate(zip(prompts, handles)):
        ref = [
            int(t) for t in np.asarray(
                generate(
                    params, p[None], jax.random.PRNGKey(i), model=llama,
                    cfg=cfg, max_new_tokens=10, eos_id=EOS,
                )
            )[0]
        ]
        if EOS in ref:
            ref = ref[: ref.index(EOS) + 1]
        if h.result() != ref:
            print(
                f"serving_smoke: FAIL — request {i} diverged from solo "
                f"generate under TDX_FAULT={fault!r}",
                file=sys.stderr,
            )
            return 1

    telemetry.emit_counters()
    spans, counters = set(), {}
    with open(trace) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "span":
                spans.add(rec["name"])
            elif rec.get("type") == "counters":
                counters.update(rec.get("values", {}))
    missing = {"serve.prefill", "serve.step"} - spans
    if missing:
        print(
            f"serving_smoke: FAIL — trace missing engine spans {missing} "
            f"(got {sorted(s for s in spans if s.startswith('serve'))})",
            file=sys.stderr,
        )
        return 1
    if fault and counters.get("serve.skipped_steps", 0) < 1:
        print(
            f"serving_smoke: FAIL — TDX_FAULT={fault!r} drained but the "
            f"trace shows serve.skipped_steps="
            f"{counters.get('serve.skipped_steps', 0)} (counters: "
            f"{ {k: v for k, v in counters.items() if k.startswith('serve')} })",
            file=sys.stderr,
        )
        return 1
    print(
        "serving_smoke: OK — engine drained token-identically "
        f"(fault={fault!r}, skipped={counters.get('serve.skipped_steps', 0)}, "
        f"tokens={counters.get('serve.tokens_out', 0)})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
