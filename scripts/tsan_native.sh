#!/usr/bin/env bash
# ThreadSanitizer lane over the native core — the reference's tsan-wheel CI
# analog (/root/reference/cmake/Helpers.cmake:287-316,
# .github/workflows/_test_wheel.yaml:49-89).
#
# Python would flood TSan with interpreter-internal reports, so this lane
# drives tdx_core directly (src/cc/tdx_core/graph_stress.cc) under the same
# threading contract the bindings provide: mutations serialized (the GIL's
# role, played by a mutex), traversals concurrent.  See that file's header.

set -euo pipefail
cd "$(dirname "$0")/.."

BUILD=build/tsan
mkdir -p "$BUILD"

g++ -std=c++17 -O1 -g -fno-omit-frame-pointer -fsanitize=thread \
  -Isrc/cc/tdx_core \
  -o "$BUILD/graph_stress" \
  src/cc/tdx_core/graph.cc src/cc/tdx_core/graph_stress.cc \
  -lpthread

TSAN_OPTIONS=halt_on_error=1 "$BUILD/graph_stress"
echo "tsan lane: OK"
