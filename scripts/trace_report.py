#!/usr/bin/env python
"""Reconstruct per-request timelines from a telemetry JSONL trace.

The serving/fleet stack emits a request-scoped lifecycle event stream
(``req.submitted → req.queued → req.admitted → req.prefill_chunk×N →
req.first_token → req.preempted/req.swapped/req.resumed →
req.migrated_out/req.migrated_in/req.migration_fallback →
req.failover_hop → req.finished | req.failed``) where every event — and
every ``serve.*`` span started inside the request's trace scope —
carries the same ``rid`` (trace id), the ``engine`` that emitted it, and
the failover ``hop`` number (see docs/observability.md, "Request
tracing").  This analyzer groups a trace (chaos soak, bench, or
production) by ``rid`` and answers "where did this request's time go":

* a **phase breakdown** per request — queue wait, prefill, decode,
  preemption outage, migration transit, failover — attributed
  interval-by-interval between
  consecutive events, so the phases sum to the request's wall time
  (anything between events this tool does not recognize lands in
  ``unaccounted`` instead of silently inflating a known phase);
* **completeness validation** — every submitted request must reach a
  terminal event (``req.finished`` or ``req.failed``), hop numbers must
  be monotone, the terminal must be the timeline's last event, and no
  span may carry a ``rid`` that never submitted (an orphan span means a
  trace-context leak);
* aggregate percentiles (TTFT from the ``req.first_token`` events,
  per-outcome counts, fleet hop distribution) and optional JSON export.

Usage::

    python scripts/trace_report.py /tmp/chaos.jsonl            # summary
    python scripts/trace_report.py trace.jsonl --per-request   # + rows
    python scripts/trace_report.py trace.jsonl --json out.json
    python scripts/trace_report.py trace.jsonl --strict        # CI gate:
        # exit 1 on any incomplete timeline, orphan span, hop-order
        # violation, or unaccounted time above --tolerance (fraction of
        # the request's wall time, default 0.05)

``bench.py``'s serving scenarios import :func:`reconstruct` directly,
so bench numbers and post-mortem numbers come from the same
reconstruction path.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "RequestTimeline",
    "TraceReport",
    "load_records",
    "reconstruct",
]

# Interval attribution: the time between two consecutive events belongs
# to the phase the EARLIER event put the request in.
_STATE_AFTER = {
    "req.submitted": "queue",
    "req.queued": "queue",
    "req.admitted": "prefill",
    "req.prefill_chunk": "prefill",
    "req.first_token": "decode",
    "req.resumed": "decode",
    "req.preempted": "preempt",
    "req.swapped": "preempt",
    "req.failover_hop": "queue",  # placed on the peer; waiting to admit
    # Stream migration (docs/fleet.md, "Disaggregation & stream
    # migration"): pages in transit between the export and the import;
    # a fallback means the snapshot was dropped and the stream is down
    # until the cold replay re-places it — a failover outage.
    "req.migrated_out": "migrate",
    "req.migrated_in": "decode",
    "req.migration_fallback": "failover",
}
PHASES = (
    "queue", "prefill", "decode", "preempt", "migrate", "failover",
    "unaccounted",
)
_TERMINAL = ("req.finished", "req.failed")


class RequestTimeline:
    """One request's reconstructed life, across engines and hops."""

    def __init__(self, rid: str):
        self.rid = rid
        self.events: List[Dict[str, Any]] = []
        self.spans: List[Dict[str, Any]] = []

    # -- derived views ------------------------------------------------------

    def _sorted(self) -> List[Dict[str, Any]]:
        return sorted(self.events, key=lambda e: e["ts"])

    @property
    def outcome(self) -> str:
        """``"finished"``, ``"failed:<ErrorType>"``, or ``"incomplete"``.

        Only the LAST event decides: a retryable ``req.failed`` with a
        ``req.failover_hop`` after it was not the end of the request."""
        evs = self._sorted()
        if not evs:
            return "incomplete"
        last = evs[-1]
        if last["name"] == "req.finished":
            return "finished"
        if last["name"] == "req.failed":
            return f"failed:{(last.get('attrs') or {}).get('error', '?')}"
        return "incomplete"

    @property
    def complete(self) -> bool:
        evs = self._sorted()
        return bool(evs) and evs[-1]["name"] in _TERMINAL and any(
            e["name"] == "req.submitted" for e in evs
        )

    @property
    def truncated(self) -> bool:
        """Head events evicted by flight-ring wraparound: the timeline
        has events but no ``req.submitted``.  A bounded ring (the
        flight recorder, a capped collector) legitimately drops the
        oldest records, so a long-lived request reconstructed from a
        dump can lose its head — that is ring wraparound, not a
        trace-context leak, and :meth:`TraceReport.problems` excludes
        truncated timelines from ``--strict`` completeness accounting
        (counted separately in the summary) — but only when the trace
        actually contains a flight-dump window; in a full trace a
        headless timeline is still flagged as a leak."""
        return bool(self.events) and not any(
            e["name"] == "req.submitted" for e in self.events
        )

    @property
    def engines(self) -> List[str]:
        """Engines that touched the request, in order of first touch."""
        seen: List[str] = []
        for ev in self._sorted():
            eng = ev.get("engine")
            if eng and eng != "fleet" and eng not in seen:
                seen.append(eng)
        return seen

    @property
    def hops(self) -> List[int]:
        return [
            int(ev.get("hop", 0))
            for ev in self._sorted()
            if ev.get("hop") is not None
        ]

    @property
    def hops_monotone(self) -> bool:
        h = self.hops
        return all(a <= b for a, b in zip(h, h[1:]))

    @property
    def n_tokens(self) -> Optional[int]:
        for ev in reversed(self._sorted()):
            if ev["name"] in _TERMINAL:
                n = (ev.get("attrs") or {}).get("n_tokens")
                return None if n is None else int(n)
        return None

    @property
    def ttft_s(self) -> Optional[float]:
        for ev in self._sorted():
            if ev["name"] == "req.first_token":
                t = (ev.get("attrs") or {}).get("ttft_s")
                return None if t is None else float(t)
        return None

    @property
    def digest(self) -> Optional[str]:
        """The request's determinism digest (docs/observability.md,
        "Audit plane"): the full-stream snapshot from req.finished when
        the request completed, else the admitted-identity snapshot from
        req.first_token."""
        for ev in reversed(self._sorted()):
            if ev["name"] in ("req.finished", "req.first_token"):
                d = (ev.get("attrs") or {}).get("digest")
                if d is not None:
                    return str(d)
        return None

    def phases(self) -> Dict[str, float]:
        """Wall-clock per phase, summing to the request's total.

        Interval attribution between consecutive events; an interval
        following a *retryable* ``req.failed`` is ``failover`` (the
        stream is down until the hop re-places it), and one following
        an event this tool does not know is ``unaccounted``."""
        out = {p: 0.0 for p in PHASES}
        evs = self._sorted()
        if len(evs) < 2:
            out["total"] = 0.0
            return out
        state = "queue"
        for prev, nxt in zip(evs, evs[1:]):
            name = prev["name"]
            if name == "req.failed":
                # Retryable + anything after it = failover outage.
                state = "failover"
            else:
                state = _STATE_AFTER.get(name, "unaccounted")
            out[state] += max(0.0, nxt["ts"] - prev["ts"])
        out["total"] = max(0.0, evs[-1]["ts"] - evs[0]["ts"])
        return out

    def problems(self, tolerance: float = 0.05) -> List[str]:
        """Validation failures for this timeline (empty = clean).
        Truncated timelines (head evicted by ring wraparound) validate
        vacuously — their phase attribution and completeness cannot be
        judged without the missing head."""
        if self.truncated:
            return []
        out: List[str] = []
        evs = self._sorted()
        if not any(e["name"] == "req.submitted" for e in evs):
            out.append("no req.submitted event")
        if not evs or evs[-1]["name"] not in _TERMINAL:
            out.append(
                "incomplete: timeline does not end in req.finished/"
                "req.failed"
            )
        if not self.hops_monotone:
            out.append(f"hop numbers not monotone: {self.hops}")
        ph = self.phases()
        if ph["total"] > 0 and ph["unaccounted"] > tolerance * ph["total"]:
            out.append(
                f"unaccounted wall time {ph['unaccounted']:.4f}s exceeds "
                f"{tolerance:.0%} of total {ph['total']:.4f}s"
            )
        return out

    def summary(self) -> Dict[str, Any]:
        ph = self.phases()
        return {
            "rid": self.rid,
            "outcome": self.outcome,
            "truncated": self.truncated,
            "engines": self.engines,
            "max_hop": max(self.hops, default=0),
            "n_events": len(self.events),
            "n_spans": len(self.spans),
            "n_tokens": self.n_tokens,
            "ttft_s": self.ttft_s,
            "digest": self.digest,
            "phases": {k: round(v, 6) for k, v in ph.items()},
        }


class TraceReport:
    """Whole-trace reconstruction: timelines + trace-level validation."""

    def __init__(self):
        self.requests: Dict[str, RequestTimeline] = {}
        self.orphan_spans: List[Dict[str, Any]] = []
        self.flight_dumps: List[Dict[str, Any]] = []

    def problems(self, tolerance: float = 0.05) -> List[str]:
        out: List[str] = []
        for rid in sorted(self.requests):
            tl = self.requests[rid]
            if tl.truncated and not self.flight_dumps:
                # Ring wraparound is only possible in a dumped ring
                # window — and every dump carries its header marker.  A
                # headless timeline in a trace with NO dump windows is a
                # genuine trace-context leak (a full TDX_TELEMETRY trace
                # never drops a head), so --strict still catches it.
                out.append(
                    f"{rid}: no req.submitted event (and no flight-dump "
                    "window in the trace to explain ring truncation)"
                )
                continue
            for p in tl.problems(tolerance):
                out.append(f"{rid}: {p}")
        if self.orphan_spans:
            names = sorted({s["name"] for s in self.orphan_spans})
            out.append(
                f"{len(self.orphan_spans)} orphan span(s) carrying a rid "
                f"that never submitted: {names}"
            )
        return out

    def summary(self, tolerance: float = 0.05) -> Dict[str, Any]:
        outcomes: Dict[str, int] = {}
        totals = {p: 0.0 for p in PHASES}
        ttfts: List[float] = []
        hops: List[int] = []
        for tl in self.requests.values():
            key = tl.outcome
            outcomes[key] = outcomes.get(key, 0) + 1
            for p, v in tl.phases().items():
                if p in totals:
                    totals[p] += v
            if tl.ttft_s is not None:
                ttfts.append(tl.ttft_s)
            hops.append(max(tl.hops, default=0))
        out: Dict[str, Any] = {
            "n_requests": len(self.requests),
            "outcomes": dict(sorted(outcomes.items())),
            "complete": sum(tl.complete for tl in self.requests.values()),
            "truncated": sum(
                tl.truncated for tl in self.requests.values()
            ),
            "phase_totals_s": {k: round(v, 4) for k, v in totals.items()},
            "failovers": sum(h > 0 for h in hops),
            "max_hop": max(hops, default=0),
            "flight_dumps": len(self.flight_dumps),
            "orphan_spans": len(self.orphan_spans),
            "problems": self.problems(tolerance),
        }
        if ttfts:
            ttfts.sort()

            def pct(p):
                return round(ttfts[min(len(ttfts) - 1,
                                       int(p / 100.0 * len(ttfts)))], 4)

            out["ttft_p50_s"] = pct(50)
            out["ttft_p95_s"] = pct(95)
        return out


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trace file (malformed lines fail loudly — a trace
    that doesn't parse is a bug, not noise)."""
    records = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i}: unparseable trace line: {e}")
    return records


def reconstruct(records: Iterable[Dict[str, Any]]) -> TraceReport:
    """Group a record stream (from :func:`load_records` or the in-memory
    collector's ``snapshot()["spans"]``) into per-request timelines."""
    report = TraceReport()
    spans_with_rid = []
    for rec in records:
        kind = rec.get("type")
        if kind == "event":
            name = rec.get("name", "")
            rid = rec.get("rid")
            if rid is None or not name.startswith("req."):
                continue
            rid = str(rid)
            tl = report.requests.get(rid)
            if tl is None:
                tl = report.requests[rid] = RequestTimeline(rid)
            tl.events.append(rec)
        elif kind == "span":
            if rec.get("rid") is not None:
                spans_with_rid.append(rec)
        elif kind == "flight_dump":
            report.flight_dumps.append(rec)
    for rec in spans_with_rid:
        tl = report.requests.get(str(rec["rid"]))
        if tl is None:
            report.orphan_spans.append(rec)
        else:
            tl.spans.append(rec)
    return report


def _fmt_row(s: Dict[str, Any]) -> str:
    ph = s["phases"]
    return (
        f"{s['rid']:<18} {s['outcome']:<28} hop={s['max_hop']} "
        f"eng={'+'.join(s['engines']) or '-':<12} "
        f"tok={s['n_tokens'] if s['n_tokens'] is not None else '-':<5} "
        f"total={ph.get('total', 0.0):7.3f}s  "
        f"q={ph['queue']:6.3f} pf={ph['prefill']:6.3f} "
        f"dec={ph['decode']:6.3f} pre={ph['preempt']:6.3f} "
        f"mig={ph['migrate']:6.3f} "
        f"fo={ph['failover']:6.3f} ?={ph['unaccounted']:6.3f}"
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-request timeline reconstruction from a "
        "telemetry JSONL trace"
    )
    ap.add_argument("trace", help="JSONL trace file (TDX_TELEMETRY output)")
    ap.add_argument("--json", help="write the full report to this path")
    ap.add_argument(
        "--per-request", action="store_true",
        help="print one row per request",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 1 on any validation problem (CI gate)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.05,
        help="max unaccounted fraction of a request's wall time "
        "(default 0.05)",
    )
    ap.add_argument(
        "--require-flight-dump", action="store_true",
        help="with --strict: also fail unless the trace contains at "
        "least one flight_dump marker",
    )
    ap.add_argument(
        "--format", choices=("report", "perfetto"), default="report",
        help="'perfetto': export the trace as a Chrome/Perfetto "
        "trace-event timeline (scripts/timeline_export.py) to --json "
        "(or <trace>.perfetto.json) instead of the text report",
    )
    args = ap.parse_args(argv)

    if args.format == "perfetto":
        import timeline_export  # noqa: PLC0415 — sibling script

        argv2 = [args.trace]
        if args.json:
            argv2 += ["-o", args.json]
        if args.strict:
            argv2.append("--validate")
        return timeline_export.main(argv2)

    report = reconstruct(load_records(args.trace))
    summary = report.summary(args.tolerance)

    if args.per_request:
        for rid in sorted(report.requests):
            print(_fmt_row(report.requests[rid].summary()))
        print()
    print(f"requests:      {summary['n_requests']}")
    print(f"complete:      {summary['complete']}")
    if summary["truncated"]:
        print(f"truncated:     {summary['truncated']} (ring wraparound)")
    print(f"outcomes:      {summary['outcomes']}")
    print(f"phase totals:  {summary['phase_totals_s']}")
    print(
        f"failovers:     {summary['failovers']} "
        f"(max hop {summary['max_hop']})"
    )
    print(f"flight dumps:  {summary['flight_dumps']}")
    if "ttft_p50_s" in summary:
        print(
            f"ttft:          p50={summary['ttft_p50_s']}s "
            f"p95={summary['ttft_p95_s']}s"
        )
    problems = summary["problems"]
    if args.require_flight_dump and not report.flight_dumps:
        problems = problems + ["no flight_dump marker in the trace"]
    if problems:
        print(f"\nPROBLEMS ({len(problems)}):", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "summary": summary,
                    "requests": [
                        report.requests[rid].summary()
                        for rid in sorted(report.requests)
                    ],
                },
                f, indent=2,
            )
        print(f"\nreport written to {args.json}")

    if args.strict and problems:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
