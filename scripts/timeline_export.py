#!/usr/bin/env python
"""Perfetto / Chrome trace-event export of a telemetry JSONL trace.

Merges the request-scoped event stream, the engine spans, and the time
plane's per-tick phase segments (``serve.tick`` events,
docs/observability.md "Time plane") into ONE timeline a flight dump or
chaos trace opens directly in https://ui.perfetto.dev (legacy Chrome
JSON is Perfetto's native import format):

* a **track per engine tick loop** — each tick a slice, its phase
  segments (schedule / prefill_dispatch / decode_dispatch /
  device_wait / commit / audit_pump) nested inside, host-overhead
  fraction in the args;
* a **track per request** — one thread per rid, the inter-event
  intervals sliced by phase (queue / prefill / decode / preempt /
  failover — the same attribution ``trace_report.py`` reports) with an
  instant marker per lifecycle event;
* **flow arrows** linking ``req.submitted → req.admitted →
  req.first_token`` — across failover hops, so a mid-stream failover
  reads as one arrow chain hopping engines;
* **host-thread tracks** for the raw telemetry spans (``serve.step``,
  ``serve.prefill``, ``serve.recover`` ...), which nest exactly as the
  span stack recorded them;
* flight-dump markers as global instants.

Importable (:func:`to_perfetto` / :func:`validate`) — the CI chaos jobs
export each soak trace and validate it (every request id present,
slices nest, flow chains resolve) before uploading the timeline as an
artifact.  ``trace_report.py --format=perfetto`` routes here too.

Usage::

    python scripts/timeline_export.py trace.jsonl -o timeline.json
    python scripts/timeline_export.py trace.jsonl --validate   # CI gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_report import _STATE_AFTER, load_records  # noqa: E402

__all__ = ["to_perfetto", "validate", "load_records"]

PID_HOST = 1  # raw span records, one tid per recording thread
PID_REQUESTS = 2  # one tid per request timeline
PID_ENGINES_BASE = 100  # one pid per engine tick loop

_US = 1e6  # trace-event timestamps are microseconds


def _meta(pid: int, name: str, tid: Optional[int] = None) -> Dict[str, Any]:
    ev = {
        "ph": "M",
        "name": "process_name" if tid is None else "thread_name",
        "pid": pid,
        "args": {"name": name},
    }
    if tid is not None:
        ev["tid"] = tid
    return ev


def _slice(
    pid: int, tid: int, name: str, ts: float, dur: float,
    cat: str = "tdx", args: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    ev = {
        "ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
        "ts": ts * _US, "dur": max(0.0, dur) * _US,
    }
    if args:
        ev["args"] = args
    return ev


def to_perfetto(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON (``{"traceEvents": [...]}``) from a
    telemetry record stream (:func:`load_records` or the in-memory
    collector's ``snapshot()["spans"]``)."""
    events: List[Dict[str, Any]] = []
    engine_pids: Dict[str, int] = {}
    host_tids: Dict[int, int] = {}
    req_events: Dict[str, List[Dict[str, Any]]] = {}

    def engine_pid(eid: str) -> int:
        pid = engine_pids.get(eid)
        if pid is None:
            pid = PID_ENGINES_BASE + len(engine_pids)
            engine_pids[eid] = pid
            events.append(_meta(pid, f"engine {eid}"))
            events.append(_meta(pid, "tick loop", tid=1))
        return pid

    events.append(_meta(PID_HOST, "host threads"))
    events.append(_meta(PID_REQUESTS, "requests"))

    for rec in records:
        kind = rec.get("type")
        if kind == "span":
            dur = rec.get("dur_s")
            ts = rec.get("ts")
            if dur is None or ts is None:
                continue
            thread = int(rec.get("thread") or 0)
            tid = host_tids.get(thread)
            if tid is None:
                tid = host_tids[thread] = len(host_tids) + 1
                events.append(
                    _meta(PID_HOST, f"thread {thread}", tid=tid)
                )
            args: Dict[str, Any] = {}
            for k in ("rid", "engine", "hop"):
                if rec.get(k) is not None:
                    args[k] = rec[k]
            if rec.get("attrs"):
                args.update(rec["attrs"])
            events.append(
                _slice(
                    PID_HOST, tid, rec.get("name", "span"),
                    float(ts), float(dur), cat="span", args=args or None,
                )
            )
        elif kind == "flight_dump":
            events.append({
                "ph": "i", "s": "g", "pid": PID_HOST, "tid": 0,
                "name": f"flight_dump:{rec.get('reason', '?')}",
                "cat": "flight", "ts": float(rec.get("ts") or 0.0) * _US,
            })
        elif kind == "event":
            name = rec.get("name", "")
            attrs = rec.get("attrs") or {}
            if name == "serve.tick":
                eid = str(rec.get("engine") or attrs.get("engine") or "eng?")
                pid = engine_pid(eid)
                t0 = float(attrs.get("t0") or rec.get("ts") or 0.0)
                dur = float(attrs.get("dur_s") or 0.0)
                events.append(
                    _slice(
                        pid, 1, f"tick {attrs.get('tick', '?')}", t0, dur,
                        cat="tick",
                        args={
                            "host_overhead_frac": attrs.get(
                                "host_overhead_frac"
                            ),
                            "tick_s": attrs.get("tick_s", dur),
                        },
                    )
                )
                for seg in attrs.get("segments") or []:
                    phase, off, seg_dur = seg[0], float(seg[1]), float(seg[2])
                    # Clamp into the parent so float rounding can never
                    # push a phase slice past its tick.
                    off = max(0.0, min(off, dur))
                    seg_dur = max(0.0, min(seg_dur, dur - off))
                    events.append(
                        _slice(pid, 1, phase, t0 + off, seg_dur, cat="phase")
                    )
            elif name.startswith("req.") and rec.get("rid") is not None:
                req_events.setdefault(str(rec["rid"]), []).append(rec)

    # Request tracks: one tid per rid, phase interval slices + instants
    # + the submit→admit→first_token flow chain (across hops).
    flow_id = 0
    for idx, rid in enumerate(sorted(req_events), start=1):
        evs = sorted(req_events[rid], key=lambda e: float(e["ts"]))
        events.append(_meta(PID_REQUESTS, rid, tid=idx))
        for prev, nxt in zip(evs, evs[1:]):
            pname = prev.get("name", "")
            if pname == "req.failed":
                state = "failover"
            else:
                state = _STATE_AFTER.get(pname, "unaccounted")
            dur = float(nxt["ts"]) - float(prev["ts"])
            if dur <= 0:
                continue
            args = {"after": pname}
            if prev.get("engine"):
                args["engine"] = prev["engine"]
            if prev.get("hop") is not None:
                args["hop"] = prev["hop"]
            events.append(
                _slice(
                    PID_REQUESTS, idx, state, float(prev["ts"]), dur,
                    cat="req", args=args,
                )
            )
        for ev in evs:
            events.append({
                "ph": "i", "s": "t", "pid": PID_REQUESTS, "tid": idx,
                "name": ev.get("name", "event"), "cat": "req",
                "ts": float(ev["ts"]) * _US,
                "args": {
                    k: ev[k] for k in ("engine", "hop") if ev.get(k) is not None
                },
            })
        # The flow chain: start at the first submit, step through every
        # admit/failover hop, finish at the LAST first_token — so a
        # failover's re-prefill on the peer engine is one arrow chain.
        points: List[Tuple[str, float]] = []
        for ev in evs:
            if ev["name"] in (
                "req.submitted", "req.admitted", "req.failover_hop",
                "req.first_token",
            ):
                points.append((ev["name"], float(ev["ts"])))
        firsts = [i for i, (n, _) in enumerate(points) if n == "req.first_token"]
        if points and firsts and points[0][0] == "req.submitted":
            chain = points[: firsts[-1] + 1]
            flow_id += 1
            for i, (pname, ts) in enumerate(chain):
                ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
                ev: Dict[str, Any] = {
                    "ph": ph, "pid": PID_REQUESTS, "tid": idx,
                    "name": "req-flow", "cat": "flow", "id": flow_id,
                    "ts": ts * _US,
                }
                if ph == "f":
                    ev["bp"] = "e"  # bind to the enclosing slice
                events.append(ev)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "torchdistx_tpu scripts/timeline_export.py",
            "n_engines": len(engine_pids),
            "n_requests": len(req_events),
        },
    }


# ---------------------------------------------------------------------------
# Validation (the CI gate)


def validate(
    trace: Dict[str, Any], records: Optional[Iterable[Dict[str, Any]]] = None
) -> List[str]:
    """Structural problems of an exported timeline (empty = valid):

    * every request id carrying ``req.*`` events in ``records`` (when
      given) has a named track and at least one event on it;
    * "X" slices NEST within each (pid, tid) — a slice starting inside
      another ends inside it;
    * every flow chain resolves: exactly one start and one finish per
      id, timestamps monotone, and every flow event binds to a slice or
      instant at its (pid, tid, ts).
    """
    problems: List[str] = []
    events = trace.get("traceEvents") or []
    eps = 1.5  # µs tolerance for float rounding

    # -- request-id coverage ------------------------------------------------
    track_names = {
        ev["args"]["name"]
        for ev in events
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
        and ev.get("pid") == PID_REQUESTS
    }
    if records is not None:
        want = {
            str(rec["rid"])
            for rec in records
            if rec.get("type") == "event"
            and str(rec.get("name", "")).startswith("req.")
            and rec.get("rid") is not None
        }
        missing = want - track_names
        if missing:
            problems.append(
                f"{len(missing)} request id(s) missing a timeline track: "
                f"{sorted(missing)[:5]}"
            )

    # -- slice nesting ------------------------------------------------------
    by_track: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_track.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    for (pid, tid), slices in by_track.items():
        slices.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: List[Tuple[float, float, str]] = []  # (ts, end, name)
        for ev in slices:
            ts, end = ev["ts"], ev["ts"] + ev.get("dur", 0.0)
            while stack and ts >= stack[-1][1] - eps:
                stack.pop()
            if stack and end > stack[-1][1] + eps:
                problems.append(
                    f"pid={pid} tid={tid}: slice {ev['name']!r} "
                    f"[{ts:.1f}, {end:.1f}] escapes enclosing "
                    f"{stack[-1][2]!r} ending {stack[-1][1]:.1f}"
                )
                continue
            stack.append((ts, end, ev["name"]))

    # -- flow resolution ----------------------------------------------------
    flows: Dict[Any, List[Dict[str, Any]]] = {}
    for ev in events:
        if ev.get("ph") in ("s", "t", "f"):
            flows.setdefault(ev.get("id"), []).append(ev)
    anchors: Dict[Tuple[Any, Any], List[Tuple[float, float]]] = {}
    for ev in events:
        key = (ev.get("pid"), ev.get("tid"))
        if ev.get("ph") == "X":
            anchors.setdefault(key, []).append(
                (ev["ts"], ev["ts"] + ev.get("dur", 0.0))
            )
        elif ev.get("ph") == "i":
            anchors.setdefault(key, []).append((ev["ts"], ev["ts"]))
    for fid, chain in flows.items():
        chain.sort(key=lambda e: e["ts"])
        phs = [ev["ph"] for ev in chain]
        if phs.count("s") != 1 or phs.count("f") != 1:
            problems.append(
                f"flow {fid}: unresolved chain (phases {phs} — need "
                "exactly one start and one finish)"
            )
            continue
        if phs[0] != "s" or phs[-1] != "f":
            problems.append(f"flow {fid}: start/finish out of order ({phs})")
        for ev in chain:
            spans = anchors.get((ev.get("pid"), ev.get("tid")), [])
            if not any(
                t0 - eps <= ev["ts"] <= t1 + eps for t0, t1 in spans
            ):
                problems.append(
                    f"flow {fid}: {ev['ph']!r} event at ts={ev['ts']:.1f} "
                    f"binds to no slice on pid={ev.get('pid')} "
                    f"tid={ev.get('tid')}"
                )
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Export a telemetry JSONL trace as a Perfetto/Chrome "
        "trace-event timeline"
    )
    ap.add_argument("trace", help="JSONL trace file (TDX_TELEMETRY output)")
    ap.add_argument(
        "-o", "--out",
        help="output path (default: <trace>.perfetto.json)",
    )
    ap.add_argument(
        "--validate", action="store_true",
        help="validate the exported timeline (CI gate): request-id "
        "coverage, slice nesting, flow resolution — exit 1 on problems",
    )
    args = ap.parse_args(argv)

    records = load_records(args.trace)
    trace = to_perfetto(records)
    out = args.out or (args.trace + ".perfetto.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    other = trace["otherData"]
    print(
        f"timeline_export: {len(trace['traceEvents'])} trace events "
        f"({other['n_requests']} request tracks, {other['n_engines']} "
        f"engine tick tracks) -> {out}"
    )
    if args.validate:
        problems = validate(trace, records)
        if problems:
            print(
                f"\ntimeline_export: INVALID ({len(problems)} problems):",
                file=sys.stderr,
            )
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 1
        print("timeline_export: timeline validates (tracks, nesting, flows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
