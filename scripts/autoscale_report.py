#!/usr/bin/env python
"""Read back the autoscaler's decision timeline from a telemetry trace.

The fleet autoscaler (:mod:`torchdistx_tpu.fleet.autoscale`) emits one
``fleet.autoscale`` event per control tick carrying the decision
(``hold`` or one of the action reasons), the live replica count, the
signals the decision was made on (occupancy, queue depth, queue slope,
burning), and the tick number.  This tool reconstructs that timeline
from a JSONL trace (chaos soak, bench, or production) and answers "what
did the control loop see, and what did it do about it":

* a **decision log** — every non-hold tick as a row: tick number,
  reason, replica count before/after, and the signal snapshot that
  justified it;
* **action counts** per reason (``burn``, ``occupancy``, ``ttft``,
  ``queue_slope``, ``below_min``, ``replace_diverging``, ``quiet``)
  cross-checked against the ``fleet.scale_outs`` / ``fleet.scale_ins``
  counters in the same trace — a mismatch means ticks ran with the
  trace sink detached and the timeline is partial;
* **replica-count envelope** (min/max/final) and the burn story:
  ticks spent with an active SLO burn and whether the trace ends calm.

Usage::

    python scripts/autoscale_report.py /tmp/autoscale.jsonl
    python scripts/autoscale_report.py trace.jsonl --json out.json
    python scripts/autoscale_report.py trace.jsonl --require-actions
        # CI gate: exit 1 unless the trace contains at least one
        # scale-out AND one scale-in decision (the elastic round trip)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List

__all__ = ["load_events", "summarize"]


def load_events(path: str):
    """``fleet.autoscale`` event records (tick order) + final counter
    snapshot from a JSONL trace."""
    ticks: List[Dict[str, Any]] = []
    counters: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "event" and rec.get("name") == "fleet.autoscale":
                attrs = rec.get("attrs") or {}
                if "tick" in attrs:
                    ticks.append(dict(attrs, ts=rec.get("ts")))
            elif rec.get("type") == "counters":
                counters.update(rec.get("values") or {})
    ticks.sort(key=lambda a: a["tick"])
    return ticks, counters


_OUT_REASONS = ("burn", "occupancy", "ttft", "queue_slope", "below_min",
                "replace_diverging")
_IN_REASONS = ("quiet",)


def summarize(ticks, counters) -> Dict[str, Any]:
    actions = [t for t in ticks if t.get("decision") not in (None, "hold")]
    by_reason: Dict[str, int] = {}
    for t in actions:
        by_reason[t["decision"]] = by_reason.get(t["decision"], 0) + 1
    replicas = [t.get("replicas", 0) for t in ticks]
    outs = sum(n for r, n in by_reason.items() if r in _OUT_REASONS)
    ins = sum(n for r, n in by_reason.items() if r in _IN_REASONS)
    burn_ticks = sum(1 for t in ticks if t.get("burning"))
    return {
        "ticks": len(ticks),
        "actions": len(actions),
        "by_reason": by_reason,
        "scale_out_decisions": outs,
        "scale_in_decisions": ins,
        "replicas_min": min(replicas) if replicas else 0,
        "replicas_max": max(replicas) if replicas else 0,
        "replicas_final": replicas[-1] if replicas else 0,
        "burn_ticks": burn_ticks,
        "ends_burning": bool(ticks and ticks[-1].get("burning")),
        "counter_scale_outs": counters.get("fleet.scale_outs", 0),
        "counter_scale_ins": counters.get("fleet.scale_ins", 0),
        "decision_log": [
            {
                "tick": t["tick"],
                "reason": t["decision"],
                "replicas": t.get("replicas"),
                "occupancy": t.get("occupancy"),
                "queue": t.get("queue"),
                "queue_slope": t.get("queue_slope"),
                "burning": t.get("burning"),
            }
            for t in actions
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Autoscaler decision-timeline readback")
    ap.add_argument("trace", help="telemetry JSONL trace path")
    ap.add_argument("--json", metavar="OUT", help="write summary as JSON")
    ap.add_argument(
        "--require-actions", action="store_true",
        help="exit 1 unless the trace holds >=1 scale-out and >=1 "
             "scale-in decision",
    )
    args = ap.parse_args(argv)

    ticks, counters = load_events(args.trace)
    s = summarize(ticks, counters)

    print(f"autoscale_report: {s['ticks']} ticks, {s['actions']} actions")
    print(
        f"  replicas {s['replicas_min']}..{s['replicas_max']} "
        f"(final {s['replicas_final']}), burn on {s['burn_ticks']} ticks"
        + (" — ENDS BURNING" if s["ends_burning"] else "")
    )
    for row in s["decision_log"]:
        print(
            f"  tick {row['tick']:>5}  {row['reason']:<18} "
            f"replicas={row['replicas']}  occ={row['occupancy']}  "
            f"queue={row['queue']}  slope={row['queue_slope']}"
            f"{'  [burning]' if row['burning'] else ''}"
        )
    if not s["decision_log"]:
        print("  (no non-hold decisions in trace)")
    print(
        f"  counters: fleet.scale_outs={s['counter_scale_outs']} "
        f"fleet.scale_ins={s['counter_scale_ins']}"
    )

    rc = 0
    # Counter cross-check: decision events and counters travel separate
    # paths; fewer events than counted actions means a partial timeline.
    if (s["scale_out_decisions"] < s["counter_scale_outs"]
            or s["scale_in_decisions"] < s["counter_scale_ins"]):
        print(
            "autoscale_report: WARNING — trace has fewer decision events "
            "than counted actions (timeline partial?)", file=sys.stderr,
        )
    if args.require_actions:
        if s["scale_out_decisions"] < 1:
            print("autoscale_report: FAIL — no scale-out decision in trace",
                  file=sys.stderr)
            rc = 1
        if s["scale_in_decisions"] < 1:
            print("autoscale_report: FAIL — no scale-in decision in trace",
                  file=sys.stderr)
            rc = 1
        if s["ends_burning"]:
            print("autoscale_report: FAIL — trace ends with an active burn",
                  file=sys.stderr)
            rc = 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(s, f, indent=2, sort_keys=True)
        print(f"autoscale_report: wrote {args.json}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
