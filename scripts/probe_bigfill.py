"""Bisect: why does the multi-output bigfill program replicate?"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)
osh_row = NamedSharding(mesh, P("x", None))


def report(name, cfn, shard_shapes, full_shapes):
    txt = cfn.as_text()
    shard = sum(txt.count(s) for s in shard_shapes)
    full = sum(txt.count(s) for s in full_shapes)
    print(f"{name}: shard-shaped={shard} full-shaped={full} "
          f"allgather={txt.count('all-gather')}")


N, M = 32000, 2048

# A: two outputs, dict, concrete key per draw via fold_in on TRACED ords
ords = np.asarray([3, 7], dtype=np.uint32)
s1 = np.asarray([0.02, 0.02], dtype=np.float32)


def fa(k, ords, s1):
    out = {}
    for i, nm in enumerate(["a", "b"]):
        kk = jax.random.fold_in(k, ords[i])
        flat = jax.random.normal(kk, (N * M,), dtype=jnp.float32) * s1[i]
        out[nm] = flat[: N * M].reshape(N, M)
    return out


cfa = jax.jit(fa, out_shardings={"a": osh_row, "b": osh_row}).lower(
    key, ords, s1
).compile()
report("A fold_in-traced 2-out", cfa, [f"f32[{N//8},{M}]", f"f32[{N*M//8}]"],
       [f"f32[{N},{M}]", f"f32[{N*M}]"])

# B: same but fold_in on STATIC python ints
def fb(k):
    out = {}
    for i, nm in enumerate(["a", "b"]):
        kk = jax.random.fold_in(k, [3, 7][i])
        flat = jax.random.normal(kk, (N * M,), dtype=jnp.float32) * 0.02
        out[nm] = flat[: N * M].reshape(N, M)
    return out


cfb = jax.jit(fb, out_shardings={"a": osh_row, "b": osh_row}).lower(
    key
).compile()
report("B fold_in-static 2-out", cfb, [f"f32[{N//8},{M}]", f"f32[{N*M//8}]"],
       [f"f32[{N},{M}]", f"f32[{N*M}]"])

# C: one traced fold_in, single output
def fc(k, o):
    kk = jax.random.fold_in(k, o[0])
    return (jax.random.normal(kk, (N * M,), dtype=jnp.float32) * 0.02)[
        : N * M
    ].reshape(N, M)


cfc = jax.jit(fc, out_shardings=osh_row).lower(key, ords).compile()
report("C fold_in-traced 1-out", cfc, [f"f32[{N//8},{M}]", f"f32[{N*M//8}]"],
       [f"f32[{N},{M}]", f"f32[{N*M}]"])

# timing A
t0 = time.perf_counter()
r = cfa(key, ords, s1)
jax.block_until_ready(r)
print(f"A exec: {time.perf_counter()-t0:.2f}s")
