"""(1) normal(k,(n,)).reshape == normal(k,shape)?  (2) CPU draw throughput,
threefry vs rbg, sharded vs single-device.  (3) does a direct 2-D draw shard
on dim 1?"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

k = jax.random.key(0)
a = jax.random.normal(k, (1024 * 512,)).reshape(1024, 512)
b = jax.random.normal(k, (1024, 512))
print("flat.reshape == 2d:", bool(jnp.array_equal(a, b)))

k2 = jax.random.key(0, impl="rbg")
a2 = jax.random.normal(k2, (1024 * 512,)).reshape(1024, 512)
b2 = jax.random.normal(k2, (1024, 512))
print("rbg flat.reshape == 2d:", bool(jnp.array_equal(a2, b2)))

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
osh_col = NamedSharding(mesh, P(None, "x"))

f = jax.jit(
    lambda kk: jax.random.normal(kk, (2048, 5504), dtype=jnp.float32) * 0.02,
    out_shardings=osh_col,
).lower(k).compile()
txt = f.as_text()
print("direct2d dim1-sharded: full bufs:",
      txt.count("f32[2048,5504]"), "shard bufs:", txt.count("f32[2048,688]"))

# throughput: 8-dev sharded draw of 512M elements
N = 512 * 1024 * 1024
g = jax.jit(
    lambda kk: jax.random.normal(kk, (N,), dtype=jnp.float32),
    out_shardings=NamedSharding(mesh, P("x")),
).lower(k).compile()
r = g(k); jax.block_until_ready(r)
t0 = time.perf_counter(); r = g(k); jax.block_until_ready(r)
dt = time.perf_counter() - t0
print(f"threefry sharded 512M: {dt:.2f}s = {N/dt/1e6:.0f}M elem/s")

g2 = jax.jit(
    lambda kk: jax.random.normal(kk, (N,), dtype=jnp.float32),
    out_shardings=NamedSharding(mesh, P("x")),
).lower(k2).compile()
r = g2(k2); jax.block_until_ready(r)
t0 = time.perf_counter(); r = g2(k2); jax.block_until_ready(r)
dt = time.perf_counter() - t0
print(f"rbg sharded 512M: {dt:.2f}s = {N/dt/1e6:.0f}M elem/s")

# single-device
d0 = jax.devices()[0]
g3 = jax.jit(lambda kk: jax.random.normal(kk, (N // 8,), dtype=jnp.float32))
r = g3(k); jax.block_until_ready(r)
t0 = time.perf_counter(); r = g3(k); jax.block_until_ready(r)
dt = time.perf_counter() - t0
print(f"threefry 1-dev 64M: {dt:.2f}s = {N/8/dt/1e6:.0f}M elem/s")
