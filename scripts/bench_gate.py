#!/usr/bin/env python
"""Bench regression gate: fresh numbers vs the best of recorded history.

Five rounds of trajectory live in ``BENCH_r0*.json`` and nothing stops
the next change from quietly regressing the headline serving bench —
the ROADMAP's ratchet needs a *gate*, not a log line someone might
read.  This script compares a candidate bench result against the best
value each metric ever achieved across the history, inside a per-metric
tolerance band, and emits a machine-readable verdict:

    python scripts/bench_gate.py --candidate BENCH_r05.json
    python scripts/bench_gate.py --candidate fresh.json --baseline 'BENCH_r0*.json'
    python scripts/bench_gate.py --run-fast          # CI: CPU-sized scenario

Exit code 0 = every gated metric inside its band; nonzero = regression
(or a metric the history tracks vanished from the candidate — a bench
that silently stops reporting a number is itself a regression).

Gated metrics (ISSUE 12): materialize wall (cold + warm), and the
serving bench's sustained decode tok/s, TTFT p95, TPOT p95, and goodput.
Metrics absent from ALL history rounds gate vacuously (``no_baseline``)
— the serving family enters the gate the first round that records it.

``--run-fast`` runs a CPU-sized serving scenario in-process (tiny
llama, same shape as the chaos soak) and asserts the **compile
observatory invariants** the full bench also enforces: the decode chunk
compiles exactly once (steady-state recompiles == 0 — the engine's
whole perf model rests on it) and the HBM ledger attributes the pool.
Its JSON row is written to ``--output`` so a CI can archive fast-round
history; tolerance gating against that history applies when
``--baseline`` names fast rounds.

File formats accepted: a raw ``bench.py`` line (``{"metric", ...,
"details": {...}}``) or the archived wrapper (``{"parsed": {...}}``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SERVING = ("details", "serving_llama_350m_continuous")

# (name, path into the bench JSON, higher_is_better, tolerance).
# Tolerance is the fractional band around the historical best a
# candidate may sit on the worse side of: generous to start (tunneled-
# backend wall clocks drift 20-30% between windows — see bench.py's
# min-of-N discipline); tighten per metric as rounds accumulate.
METRICS: List[Tuple[str, Tuple[str, ...], bool, float]] = [
    ("materialize_gpt2xl_s",
     ("details", "gpt2xl_1p6b_bf16", "ours_s"), False, 0.35),
    ("materialize_gpt2xl_warm_s",
     ("details", "gpt2xl_1p6b_bf16", "ours_warm_s"), False, 0.50),
    ("serving_sustained_decode_tok_s",
     _SERVING + ("sustained_decode_tokens_per_s",), True, 0.20),
    ("serving_ttft_p95_s", _SERVING + ("ttft_p95_s",), False, 0.35),
    ("serving_tpot_p95_s", _SERVING + ("tpot_p95_s",), False, 0.35),
    ("serving_goodput_tok_s",
     _SERVING + ("goodput_tokens_per_s",), True, 0.20),
    # Audit plane (ISSUE 14): sustained tok/s with the shadow auditor
    # at 100% sampling over sustained tok/s without it, same trace.  A
    # ratio collapse means auditing stopped being shadow traffic
    # (preempting/queueing ahead of user work, or recompiling).  Gates
    # vacuously (no_baseline) until a round records it.
    ("serving_audit_sustained_ratio",
     _SERVING + ("audit", "sustained_ratio"), True, 0.25),
    # Autoscale loop (ISSUE 16): the elastic-fleet probe's burn-edge →
    # recovery-edge wall time, the flash-crowd ramp TTFT p95 under the
    # autoscaler, and the dropped count — zero tolerance: once history
    # records dropped == 0, any drop at all regresses.  All gate
    # vacuously (no_baseline) until a round records them.
    ("autoscale_recover_s",
     ("details", "fleet_autoscale", "time_to_recover_s"), False, 0.60),
    ("autoscale_ramp_ttft_p95_s",
     ("details", "fleet_autoscale", "ramp_ttft_p95_s"), False, 0.50),
    ("autoscale_dropped",
     ("details", "fleet_autoscale", "dropped"), False, 0.0),
    # Stream migration (ISSUE 17): the warm hand-off's wall time, the
    # consumer-visible p95 pull latency of a migrated stream (must stay
    # well under the cold-replay arm's), and the decode tier's p95
    # inter-token gap while a long prompt lands on the prefill peer.
    # All gate vacuously (no_baseline) until a round records them.
    ("migration_handoff_p95_s",
     ("details", "fleet_migration", "migration_handoff_p95_s"),
     False, 0.60),
    ("migration_pull_p95_s",
     ("details", "fleet_migration", "migrated_pull_p95_s"), False, 0.50),
    ("migration_disagg_tpot_p95_ms",
     ("details", "fleet_migration", "disagg_chat_tpot_p95_ms"),
     False, 0.50),
    # Model plane (ISSUE 18): the mixed four-model wave's warm TTFT
    # p95 (includes re-warm stalls under eviction thrash), the
    # materialize stall p95 from the pool's own clock, the decode
    # recompile delta across models (zero tolerance — same-geometry
    # models must share the one compiled chunk), and the n=4 fork page
    # amplification vs 4x solo (must stay far below 1.0: prompt pages
    # are donor-shared, only divergence CoW-copies).  All gate
    # vacuously (no_baseline) until a round records them.
    ("models_warm_ttft_p95_s",
     ("details", "model_plane", "warm_ttft_p95_s"), False, 0.50),
    ("models_materialize_p95_s",
     ("details", "model_plane", "materialize_p95_s"), False, 0.50),
    ("models_decode_recompiles",
     ("details", "model_plane", "decode_recompiles"), False, 0.0),
    ("models_fork_page_amplification",
     ("details", "model_plane", "fork_page_amplification_vs_4x"),
     False, 0.30),
    # Durability plane (ISSUE 20): journal-on sustained tok/s over
    # journal-off, same trace, per fsync policy — the default per-tick
    # group commit carries a HARD 0.9 floor as a run-fast invariant,
    # and these rows ratchet the ratio from history on top — plus the
    # cold-resume wall for the fast wave's in-flight streams.  All
    # gate vacuously (no_baseline) until a round records them.
    ("serving_journal_sustained_ratio",
     _SERVING + ("journal", "sustained_ratio_tick"), True, 0.10),
    ("serving_journal_fsync_always_ratio",
     _SERVING + ("journal", "sustained_ratio_always"), True, 0.25),
    ("serving_journal_recovery_s",
     _SERVING + ("journal", "recovery_s"), False, 0.60),
]


def load_bench(path: str) -> Optional[Dict[str, Any]]:
    """One bench round as its raw result dict, whatever the wrapper."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "details" not in doc:
        return None
    return doc


def extract(doc: Dict[str, Any], path: Tuple[str, ...]) -> Optional[float]:
    node: Any = doc
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def best_of(
    values: List[float], higher_is_better: bool
) -> Optional[float]:
    if not values:
        return None
    return max(values) if higher_is_better else min(values)


def gate(
    candidate: Dict[str, Any],
    history: List[Tuple[str, Dict[str, Any]]],
    tolerance_override: Optional[float] = None,
) -> Dict[str, Any]:
    """The verdict: per metric, candidate vs best-of-history inside the
    tolerance band.  ``pass`` is True iff nothing regressed."""
    verdict: Dict[str, Any] = {
        "baseline_rounds": [name for name, _ in history],
        "metrics": {},
        "pass": True,
    }
    for name, path, higher, tol in METRICS:
        if tolerance_override is not None:
            tol = tolerance_override
        baseline = best_of(
            [
                v for _, doc in history
                if (v := extract(doc, path)) is not None
            ],
            higher,
        )
        cand = extract(candidate, path)
        row: Dict[str, Any] = {
            "baseline_best": baseline,
            "candidate": cand,
            "higher_is_better": higher,
            "tolerance": tol,
        }
        if baseline is None:
            row["status"] = "no_baseline"
        elif cand is None:
            # History tracks this number and the candidate stopped
            # reporting it: the bench itself regressed.
            row["status"] = "missing_from_candidate"
            verdict["pass"] = False
        else:
            limit = (
                baseline * (1.0 - tol) if higher else baseline * (1.0 + tol)
            )
            row["limit"] = round(limit, 6)
            ok = cand >= limit if higher else cand <= limit
            row["status"] = "ok" if ok else "regressed"
            if baseline and cand:
                row["vs_best"] = round(
                    cand / baseline if higher else baseline / cand, 4
                )
            if not ok:
                verdict["pass"] = False
        verdict["metrics"][name] = row
    return verdict


# ---------------------------------------------------------------------------
# --run-fast: the CPU-sized serving scenario + observatory invariants


def run_fast() -> Dict[str, Any]:
    """A minutes-not-hours serving round: tiny llama on whatever backend
    is present (CI: the virtual CPU mesh), reporting the same serving
    metric names the headline bench feeds the gate — plus the compile
    observatory's per-program counts, the steady-state decode-recompile
    invariant (asserted WITH the shadow auditor at 100% sampling: audit
    replays must reuse the same compiled geometries), the audit
    on/off sustained ratio, and the HBM ledger rows."""
    sys.path.insert(0, REPO)
    import jax

    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import Engine

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def make_engine(journal=None):
        return Engine(
            params, model=llama, cfg=cfg, num_slots=4, block_size=8,
            num_blocks=41, max_model_len=64, decode_chunk=4,
            handle_preemption=False, journal=journal,
        )

    rng = np.random.default_rng(0)
    n_req = 24
    prompts = [
        rng.integers(0, cfg.vocab_size, size=int(p)).astype(np.int32)
        for p in rng.integers(4, 17, size=n_req)
    ]
    outs = rng.integers(8, 25, size=n_req)
    arrival = np.cumsum(rng.poisson(1.0, size=n_req))

    # Warm every program on a throwaway engine; the measured engine
    # reuses the jit cache, so ANY compile it triggers is a recompile
    # the steady-state invariant forbids.
    warm = make_engine()
    for p in (4, 8, 16):
        warm.submit(
            np.arange(1, 1 + p, dtype=np.int32), max_new_tokens=2, key=0
        )
    warm.drain()
    warm.close()

    import time

    def run_trace(eng):
        t0 = time.perf_counter()
        i = tick = 0
        while (
            i < n_req or len(eng.scheduler) or eng.stats()["running"]
            or eng.audit_backlog()
        ):
            while i < n_req and arrival[i] <= tick:
                eng.submit(prompts[i], max_new_tokens=int(outs[i]), key=i)
                i += 1
            eng.step()
            tick += 1
        return time.perf_counter() - t0, eng.stats()

    from torchdistx_tpu.telemetry import ops as tdx_ops

    c0 = telemetry.counters()
    # Time plane on (no HTTP listener): the fast round must produce the
    # host/device split and the tick-phase breakdown the full bench
    # reports — invariants checked in main().
    prev_attr = tdx_ops.enable_tick_attribution(True)
    eng = make_engine()
    wall, st = run_trace(eng)
    from torchdistx_tpu.telemetry import timeplane

    host_frac = telemetry.gauge(
        "serve.host_overhead_frac", engine=eng.engine_id
    ).value
    tick_phases = {
        phase: summ["count"]
        for phase, summ in timeplane.phase_summaries(eng.engine_id).items()
    }
    tdx_ops.enable_tick_attribution(prev_attr)
    # The same trace with the shadow auditor at 100% sampling: the
    # decode-recompile invariant below covers this run too — audit
    # replays must compile NOTHING new — and the sustained ratio is
    # the audit-overhead acceptance number.
    aeng = Engine(
        params, model=llama, cfg=cfg, num_slots=4, block_size=8,
        num_blocks=41, max_model_len=64, decode_chunk=4,
        handle_preemption=False, audit_sample=1.0,
    )
    _a_wall, a_st = run_trace(aeng)
    c1 = telemetry.counters()

    compile_counts = {
        k: v - c0.get(k, 0)
        for k, v in c1.items()
        if k.startswith("compile.count") and v - c0.get(k, 0)
    }
    decode_recompiles = c1.get(
        "compile.count{program=decode_chunk}", 0
    ) - c0.get("compile.count{program=decode_chunk}", 0)
    hbm = {
        k: v
        for k, v in telemetry.gauges().items()
        if k.startswith("mem.hbm_bytes")
    }
    eng.close()
    audit_row = {
        "audit_sample": 1.0,
        "sustained_decode_tokens_per_s": a_st.get("decode_tokens_per_s"),
        "audit_checked": a_st.get("audit_checked"),
        "audit_divergences": a_st.get("audit_divergences"),
    }
    if st.get("decode_tokens_per_s") and a_st.get("decode_tokens_per_s"):
        audit_row["sustained_ratio"] = round(
            a_st["decode_tokens_per_s"] / st["decode_tokens_per_s"], 3
        )
    aeng.close()
    # The same trace again with the request journal on, once per fsync
    # policy — the durability-overhead acceptance numbers.  The default
    # per-tick group commit carries a HARD 0.9 floor (checked in
    # main()); always/async are reported for the record.  Runs after
    # the c0/c1 window on purpose: resume replays prefill
    # prompt+committed, whose lengths can land in buckets the warm-up
    # never saw — legitimate compiles, not steady-state leaks.
    import shutil
    import tempfile

    from torchdistx_tpu.serving import RequestJournal

    jroot = tempfile.mkdtemp(prefix="tdx-bench-journal-")
    journal_row: Dict[str, Any] = {"fsync_policy_default": "tick"}
    try:
        for policy in ("tick", "always", "async"):
            jeng = make_engine(
                journal=RequestJournal(
                    os.path.join(jroot, policy), fsync=policy
                )
            )
            _j_wall, j_st = run_trace(jeng)
            jeng.close()
            tps = j_st.get("decode_tokens_per_s")
            journal_row[f"decode_tokens_per_s_{policy}"] = tps
            if st.get("decode_tokens_per_s") and tps:
                journal_row[f"sustained_ratio_{policy}"] = round(
                    tps / st["decode_tokens_per_s"], 3
                )
        # Cold-resume wall: journal the whole wave, kill the engine
        # mid-decode (in-process kill -9 stand-in: drop the journal
        # unclosed, free the live pid's lock), then time a fresh
        # engine from resume_from_journal through completion of every
        # resumed stream — replay prefills included.
        rdir = os.path.join(jroot, "recover")
        jeng = make_engine(journal=RequestJournal(rdir))
        for i in range(n_req):
            jeng.submit(prompts[i], max_new_tokens=int(outs[i]), key=i)
        for _ in range(4):
            jeng.step()
        jj = jeng._journal
        jeng._journal = None
        jj.release()
        jeng.close()
        reng = make_engine()
        t0 = time.perf_counter()
        handles = reng.resume_from_journal(RequestJournal(rdir))
        reng.drain()
        journal_row["recovery_s"] = round(time.perf_counter() - t0, 4)
        journal_row["recovered_streams"] = sum(
            1 for h in handles.values() if h.error is None
        )
        reng.close()
    finally:
        shutil.rmtree(jroot, ignore_errors=True)
    return {
        "details": {
            "serving_llama_350m_continuous": {
                # The fast scenario reports under the same keys the
                # headline bench uses, so fast rounds gate against fast
                # history with the same METRICS table.
                "sustained_decode_tokens_per_s": st.get(
                    "decode_tokens_per_s"
                ),
                "ttft_p95_s": st.get("ttft_p95_s"),
                "tpot_p95_s": st.get("tpot_p95_s"),
                "wall_s": round(wall, 3),
                "n_requests": n_req,
                "compile_counts": compile_counts,
                "decode_recompiles_steady": decode_recompiles,
                "hbm_bytes": hbm,
                "host_overhead_frac": host_frac,
                "tick_phase_counts": tick_phases,
                "audit": audit_row,
                "journal": journal_row,
            }
        },
        "fast": True,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--baseline", action="append", default=None,
        help="history file or glob (repeatable; default BENCH_r0*.json "
        "in the repo root)",
    )
    ap.add_argument("--candidate", help="bench JSON to gate")
    ap.add_argument(
        "--run-fast", action="store_true",
        help="run the CPU-sized serving scenario as the candidate and "
        "enforce the compile-observatory invariants",
    )
    ap.add_argument(
        "--tolerance", type=float, default=None,
        help="override every metric's tolerance band (fraction)",
    )
    ap.add_argument("--output", help="write the verdict JSON here too")
    args = ap.parse_args(argv)

    # --run-fast produces a serving-only row from a DIFFERENT scenario
    # than the headline bench: it gates against history only when the
    # caller names fast-round baselines explicitly — never against the
    # full-bench BENCH_r0* numbers, whose materialize metrics it could
    # only ever "miss".
    if args.baseline:
        patterns = args.baseline
    elif args.run_fast:
        patterns = []
    else:
        patterns = [os.path.join(REPO, "BENCH_r0*.json")]
    history: List[Tuple[str, Dict[str, Any]]] = []
    for pat in patterns:
        for path in sorted(glob.glob(pat)):
            doc = load_bench(path)
            if doc is not None:
                history.append((os.path.basename(path), doc))

    invariant_failures: List[str] = []
    if args.run_fast:
        candidate = run_fast()
        fast = candidate["details"]["serving_llama_350m_continuous"]
        if fast["decode_recompiles_steady"] != 0:
            invariant_failures.append(
                "steady-state decode recompiles = "
                f"{fast['decode_recompiles_steady']} (must be 0 — WITH "
                "auditing enabled: the decode chunk compiled again after "
                "warm-up, a shape leak in the serving or audit path)"
            )
        if not fast["hbm_bytes"]:
            invariant_failures.append(
                "HBM ledger empty: mem.hbm_bytes{component=} rows missing"
            )
        hf = fast.get("host_overhead_frac")
        if hf is None or not 0.0 <= hf <= 1.0:
            invariant_failures.append(
                f"host_overhead_frac missing or out of [0,1]: {hf!r} — "
                "the time plane's tick decomposition did not run"
            )
        if not fast.get("tick_phase_counts"):
            invariant_failures.append(
                "serve.tick_phase_s rows missing — no tick-phase "
                "breakdown recorded"
            )
        audit = fast.get("audit") or {}
        if not audit.get("audit_checked"):
            invariant_failures.append(
                "shadow auditor checked nothing in the audited fast round"
            )
        if audit.get("audit_divergences"):
            invariant_failures.append(
                f"audit.divergences = {audit['audit_divergences']} in the "
                "fast round — determinism broke under audit replay"
            )
        journal = fast.get("journal") or {}
        jr = journal.get("sustained_ratio_tick")
        if jr is None:
            invariant_failures.append(
                "journal overhead row missing from the fast round — the "
                "journaled trace did not report a sustained ratio"
            )
        elif jr < 0.9:
            invariant_failures.append(
                f"journal-on sustained tok/s ratio {jr} < 0.9 under the "
                "default per-tick group commit — durability is over "
                "budget (ISSUE 20 acceptance floor)"
            )
        if not journal.get("recovered_streams"):
            invariant_failures.append(
                "cold resume recovered no streams in the fast round — "
                "resume_from_journal re-admitted nothing"
            )
    elif args.candidate:
        candidate = load_bench(args.candidate)
        if candidate is None:
            print(
                f"bench_gate: cannot parse candidate {args.candidate}",
                file=sys.stderr,
            )
            return 2
    else:
        ap.error("one of --candidate or --run-fast is required")
        return 2  # pragma: no cover — argparse exits

    verdict = gate(candidate, history, args.tolerance)
    if args.run_fast:
        verdict["fast_serving"] = candidate["details"][
            "serving_llama_350m_continuous"
        ]
    if invariant_failures:
        verdict["pass"] = False
        verdict["invariant_failures"] = invariant_failures

    out = json.dumps(verdict, indent=2, sort_keys=True)
    print(out)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out + "\n")
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
