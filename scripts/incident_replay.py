#!/usr/bin/env python
"""Incident replay: turn a flight-dump JSONL into a runnable repro.

Every flight dump the ops plane already produces — ``stall``,
``recompile_storm``, ``device_oom``, ``pool_exhausted``, ``slo_burn``,
``divergence`` — carries the recent event window: ``req.submitted``
events with each traced request's **replay identity** (prompt token
ids + normalized sampling key), ``req.admitted`` admission order,
``req.finished`` events with the per-request **determinism digest**
(docs/observability.md, "Audit plane"), the ``serve.engine_config``
geometry event, and ``fault.fired`` markers for any injected faults.
This tool closes the loop:

1. **Reconstruct** the request set from the dump (prompt, key,
   max_new_tokens, tenant/priority, admission order) and the engine
   geometry from ``serve.engine_config``.
2. **Re-run** it against a fresh engine (weights from ``--model`` —
   bytes don't live in traces), sequentially in admission order.
   Engine output is token-identical to solo ``generate()`` and
   batch-order invariant, so the sequential re-run IS the
   deterministic ground truth for every request.
3. **Bisect**: any request whose recorded digest differs from its
   re-run digest is a reproduced divergence.  When the dump carries
   the incident's token streams (a ``reason="divergence"`` dump from
   the shadow auditor always does), the first diverging token maps to
   the exact chunk that committed it (token 0 = the prefill's
   first-token sample = chunk 0; decode chunk j commits tokens
   ``1+(j-1)*decode_chunk .. j*decode_chunk``).
4. Optionally (``--with-faults``) re-arm the dump's ``fault.fired``
   schedule and re-run again: for a single-stream incident the faulted
   re-run must reproduce the recorded digests exactly — the incident
   is now a deterministic, replayable artifact.

Exit codes: ``0`` — analysis completed (divergences, if any recorded,
were reproduced and bisected); ``1`` — the dump records a divergence
this replay could NOT reproduce, or a ``--with-faults`` reproduction
failed; ``2`` — nothing replayable in the dump (no traced requests
with replay identities, or no parsable records).

The same loop closes over the durability plane's request journal
(docs/resilience.md, "Durability"): ``--journal DIR`` folds the WAL
segments (admits carry the replay identity, commits the committed
tokens + rolling-digest snapshots), re-runs every journaled stream
solo, and bisects any entry whose committed prefix disagrees with the
deterministic ground truth — plus a WAL self-check that each entry's
journaled digest snapshot matches the digest of its journaled tokens
(a torn or corrupted journal fails here before any re-run would).

Usage::

    python scripts/incident_replay.py /path/flight.jsonl
    python scripts/incident_replay.py flight.jsonl --with-faults --json out.json
    python scripts/incident_replay.py --journal /path/journal-dir
    python scripts/incident_replay.py --drill        # CI: end-to-end
        # corrupt-fault incident drill — seeds a corrupt fault under
        # load at 100% audit sampling, asserts the auditor flight-dumps
        # the divergence, then replays its own dump and asserts the
        # bisection lands on the faulted chunk.
    python scripts/incident_replay.py --journal-drill  # CI: journal
        # forensics drill — journals a corrupt-fault run, then the
        # --journal analysis must find exactly the corrupted stream and
        # bisect to the same token/chunk the shadow auditor flagged.

``--model`` selects the weights: ``llama-test`` (the CI/chaos tiny
llama, default) or ``module.path:factory`` returning
``(params, model_module, cfg)``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

__all__ = ["analyze", "analyze_journal", "load_dump", "load_journal", "main"]


# ---------------------------------------------------------------------------
# Dump parsing


def load_dump(path: str) -> List[Dict[str, Any]]:
    """All parsable JSONL records in the dump (bad lines skipped — a
    truncated tail must not void the post-mortem)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
    return records


def _attrs(rec: Dict[str, Any]) -> Dict[str, Any]:
    return rec.get("attrs") or {}


def parse_incident(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The incident's structure: requests (with replay identity and
    recorded digests), engine config, fault schedule, divergence dumps."""
    requests: Dict[str, Dict[str, Any]] = {}
    config: Dict[str, Any] = {}
    faults_fired: List[Dict[str, Any]] = []
    divergence_dumps: List[Dict[str, Any]] = []
    dump_reasons: List[str] = []
    for rec in records:
        rtype = rec.get("type")
        if rtype == "flight_dump":
            dump_reasons.append(rec.get("reason"))
            if rec.get("reason") == "divergence":
                divergence_dumps.append(_attrs(rec))
            continue
        if rtype != "event":
            continue
        name = rec.get("name")
        attrs = _attrs(rec)
        if name == "serve.engine_config":
            config = dict(attrs)
            config["engine"] = rec.get("engine")
            continue
        if name == "fault.fired":
            faults_fired.append(dict(attrs))
            continue
        rid = rec.get("rid")
        if rid is None:
            continue
        req = requests.setdefault(rid, {"rid": rid})
        if name == "req.submitted":
            # Engine-level re-submissions repeat req.submitted per hop;
            # the replay identity (prompt/key) is identical on each —
            # first one with a prompt wins.  Audit replays are marked
            # and excluded from the re-run (the re-run IS the audit).
            if "prompt" in attrs and "prompt" not in req:
                req["prompt"] = attrs["prompt"]
                req["key"] = attrs.get("key")
                req["max_new"] = attrs.get("max_new")
                req["tenant"] = attrs.get("tenant", "default")
                req["priority"] = attrs.get("priority", 0)
            if attrs.get("audit_of") is not None:
                req["audit_of"] = attrs["audit_of"]
            req.setdefault("submitted_ts", rec.get("ts"))
        elif name == "req.admitted":
            req.setdefault("admitted_ts", rec.get("ts"))
        elif name == "req.finished":
            req["digest"] = attrs.get("digest")
            req["n_tokens"] = attrs.get("n_tokens")
        elif name == "req.failed":
            req["failed"] = attrs.get("error")
    return {
        "requests": requests,
        "config": config,
        "faults_fired": faults_fired,
        "divergence_dumps": divergence_dumps,
        "dump_reasons": dump_reasons,
    }


# ---------------------------------------------------------------------------
# Model factories


def _model_llama_test():
    import jax

    from torchdistx_tpu.models import llama

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return params, llama, cfg


def resolve_model(spec: str):
    """``llama-test`` or ``module.path:factory`` →
    ``(params, model_module, cfg)``."""
    if spec == "llama-test":
        return _model_llama_test()
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(
            f"--model {spec!r}: expected 'llama-test' or 'module:factory'"
        )
    import importlib

    return getattr(importlib.import_module(mod_name), fn_name)()


# ---------------------------------------------------------------------------
# The replay


def _build_engine(config: Dict[str, Any], params, model, cfg, **overrides):
    from torchdistx_tpu.serving import Engine

    kw = dict(
        num_slots=config.get("num_slots", 4),
        block_size=config.get("block_size", 8),
        num_blocks=config.get("num_blocks"),
        max_model_len=config.get("max_model_len"),
        temperature=config.get("temperature", 0.0),
        top_k=config.get("top_k"),
        eos_id=config.get("eos_id"),
        decode_chunk=config.get("decode_chunk", 8),
        prefill_chunk=config.get("prefill_chunk", 512),
        max_prefills_per_tick=config.get("max_prefills_per_tick", 1),
        scheduler=config.get("scheduler", "fifo"),
        model_version=config.get("model_version", "v0"),
        handle_preemption=False,
    )
    kw.update(overrides)
    return Engine(params, model=model, cfg=cfg, **kw)


def analyze(
    records: List[Dict[str, Any]],
    *,
    model: str = "llama-test",
    with_faults: bool = False,
    max_requests: Optional[int] = None,
) -> Dict[str, Any]:
    """Re-run a parsed dump against a fresh engine and bisect any
    divergence (importable — the drill and the tests call this)."""
    import numpy as np

    from torchdistx_tpu.resilience import faults as faults_mod
    from torchdistx_tpu.serving import RequestError
    from torchdistx_tpu.telemetry import audit

    incident = parse_incident(records)
    config = incident["config"]
    decode_chunk = int(config.get("decode_chunk", 8) or 8)

    # The replayable set: traced user requests (not audit replays) that
    # carried a replay identity and were admitted, in admission order.
    replayable = sorted(
        (
            r for r in incident["requests"].values()
            if "prompt" in r and "audit_of" not in r
            and r.get("admitted_ts") is not None
        ),
        key=lambda r: (r["admitted_ts"], r.get("submitted_ts") or 0),
    )
    if max_requests is not None:
        replayable = replayable[:max_requests]
    result: Dict[str, Any] = {
        "dump_reasons": incident["dump_reasons"],
        "n_requests_in_dump": len(incident["requests"]),
        "n_replayable": len(replayable),
        "engine_config": config,
        "faults_fired": incident["faults_fired"],
        "divergences": [],
        "reproduced": False,
    }
    if not replayable:
        result["error"] = (
            "nothing replayable: no traced request in the dump carries a "
            "replay identity (prompt/key on req.submitted)"
        )
        return result

    params, model_mod, cfg = resolve_model(model)

    def run_all(engine):
        """Sequential ground-truth re-run: one request at a time, in
        admission order (token identity is batch-invariant, so this is
        a valid oracle for any original interleaving)."""
        out = {}
        for r in replayable:
            key = np.asarray(r["key"], np.uint32)
            try:
                h = engine.submit(
                    np.asarray(r["prompt"], np.int32),
                    max_new_tokens=int(r["max_new"]),
                    key=key,
                )
                toks = h.result()
            except (RequestError, ValueError) as err:
                out[r["rid"]] = {"error": f"{type(err).__name__}: {err}"}
                continue
            out[r["rid"]] = {"tokens": toks, "digest": h.digest}
        return out

    # Pass 1: clean ground truth (no faults, no auditor).
    eng = _build_engine(config, params, model_mod, cfg, audit_sample=0.0)
    try:
        truth = run_all(eng)
    finally:
        eng.close()

    # Incident token streams, where the dump carries them (divergence
    # dumps always do: expected_tokens is the ORIGINAL stream).
    incident_streams = {
        d.get("rid"): d.get("expected_tokens")
        for d in incident["divergence_dumps"]
        if d.get("rid") is not None
    }

    recorded_mismatch = False
    for r in replayable:
        rid = r["rid"]
        rerun = truth.get(rid, {})
        recorded_digest = r.get("digest")
        if recorded_digest is None or "digest" not in rerun:
            continue
        if rerun["digest"] == recorded_digest:
            continue
        recorded_mismatch = True
        row = {
            "rid": rid,
            "recorded_digest": recorded_digest,
            "rerun_digest": rerun["digest"],
        }
        stream = incident_streams.get(rid)
        if stream is not None:
            idx = audit.first_divergence(stream, rerun["tokens"])
            row["first_diverging_token"] = idx
            row["first_diverging_chunk"] = audit.token_chunk(
                idx, decode_chunk
            )
            row["incident_token"] = (
                int(stream[idx]) if idx < len(stream) else None
            )
            row["true_token"] = (
                int(rerun["tokens"][idx])
                if idx < len(rerun["tokens"])
                else None
            )
        result["divergences"].append(row)

    # The dump RECORDED a divergence iff an auditor dumped one; the
    # replay reproduces it iff the clean re-run disagrees with the
    # recorded digests the same way.
    recorded_divergence = bool(incident["divergence_dumps"])
    result["recorded_divergence"] = recorded_divergence
    result["reproduced"] = (
        recorded_mismatch if recorded_divergence else not recorded_mismatch
    )

    # Pass 2 (opt-in): re-arm the incident's fault schedule and re-run —
    # the faulted engine must reproduce the RECORDED digests, proving
    # the dump is a complete deterministic repro.  Only meaningful when
    # fault step numbers align (single-stream incidents; the drill).
    if with_faults and incident["faults_fired"]:
        spec = ",".join(
            f"{f['site']}:{f['step']}:{f['kind']}"
            for f in incident["faults_fired"]
            if f.get("kind") not in ("crash", "sigterm", "fatal")
        )
        faults_mod.reset(spec or "")
        eng2 = _build_engine(config, params, model_mod, cfg, audit_sample=0.0)
        try:
            faulted = run_all(eng2)
        finally:
            eng2.close()
            faults_mod.reset("")
        repro = all(
            faulted.get(r["rid"], {}).get("digest") == r.get("digest")
            for r in replayable
            if r.get("digest") is not None
        )
        result["faulted_rerun_matches_incident"] = repro
        if not repro:
            result["reproduced"] = False
    return result


# ---------------------------------------------------------------------------
# Journal forensics (--journal)


def load_journal(dirpath: str):
    """Fold a request-journal directory into ``(entries, config)`` —
    ``entries`` maps uid → :class:`~torchdistx_tpu.serving.JournalEntry`
    (torn tails tolerated, exactly as recovery reads them)."""
    from torchdistx_tpu.serving import journal as journal_mod

    records = list(journal_mod.read_records(dirpath))
    entries, config = journal_mod.fold_records(records)
    return entries, (config or {})


def analyze_journal(
    dirpath: str,
    *,
    model: str = "llama-test",
    max_requests: Optional[int] = None,
) -> Dict[str, Any]:
    """Verify a request journal against deterministic ground truth.

    Two independent checks per journaled stream:

    1. **WAL integrity** — the journaled rolling-digest snapshot must
       equal the digest of the journaled tokens themselves (catches a
       corrupted/hand-edited journal with no model run at all);
    2. **Determinism** — a solo re-run of the entry's replay identity
       must reproduce its committed prefix token-for-token; any
       mismatch bisects to the exact token and decode chunk.
    """
    import numpy as np

    from torchdistx_tpu.serving import RequestError
    from torchdistx_tpu.telemetry import audit

    entries, config = load_journal(dirpath)
    decode_chunk = int(config.get("decode_chunk", 8) or 8)
    todo = [entries[u] for u in sorted(entries)]
    if max_requests is not None:
        todo = todo[:max_requests]
    result: Dict[str, Any] = {
        "mode": "journal",
        "journal_dir": dirpath,
        "n_entries": len(entries),
        "n_unretired": sum(1 for e in entries.values() if not e.retired),
        "engine_config": config,
        "entries": [],
        "divergences": [],
        "digest_inconsistencies": [],
        "reproduced": False,
    }
    if not todo:
        result["error"] = f"nothing replayable: no journal entries in {dirpath}"
        return result

    params, model_mod, cfg = resolve_model(model)
    eng = _build_engine(config, params, model_mod, cfg, audit_sample=0.0)
    try:
        for e in todo:
            row: Dict[str, Any] = {
                "uid": e.uid,
                "n_committed": len(e.tokens),
                "retired": e.retired,
                "outcome": e.outcome,
            }
            committed = [int(t) for t in e.tokens]
            if e.digest is not None:
                dig = audit.DeterminismDigest(
                    np.asarray(e.prompt, np.int32),
                    np.asarray(e.key, np.uint32),
                )
                dig.update(committed, e.model_version)
                row["digest_consistent"] = dig.hexdigest() == e.digest
                if not row["digest_consistent"]:
                    result["digest_inconsistencies"].append(dict(row))
            try:
                h = eng.submit(
                    np.asarray(e.prompt, np.int32),
                    max_new_tokens=int(e.max_new_tokens),
                    key=np.asarray(e.key, np.uint32),
                )
                toks = h.result()
            except (RequestError, ValueError) as err:
                row["error"] = f"{type(err).__name__}: {err}"
                result["entries"].append(row)
                continue
            row["n_rerun"] = len(toks)
            if toks[: len(committed)] != committed:
                idx = audit.first_divergence(committed, toks)
                row["first_diverging_token"] = idx
                row["first_diverging_chunk"] = audit.token_chunk(
                    idx, decode_chunk
                )
                row["journaled_token"] = (
                    committed[idx] if idx < len(committed) else None
                )
                row["true_token"] = (
                    int(toks[idx]) if idx < len(toks) else None
                )
                result["divergences"].append(dict(row))
            result["entries"].append(row)
    finally:
        eng.close()
    result["reproduced"] = (
        not result["divergences"] and not result["digest_inconsistencies"]
    )
    return result


# ---------------------------------------------------------------------------
# The CI drills


def journal_drill() -> int:
    """End-to-end journal forensics drill: a journaled run with a seeded
    ``corrupt`` fault must leave a WAL whose ``--journal`` analysis
    finds exactly the corrupted stream — bisected to the same token and
    chunk the shadow auditor (100% sampling) flagged live."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.resilience import faults as faults_mod
    from torchdistx_tpu.serving import Engine, RequestJournal

    params, model_mod, cfg = _model_llama_test()
    jdir = os.path.join(tempfile.mkdtemp(prefix="tdx-jdrill-"), "journal")
    fault_chunk = 6
    faults_mod.reset(f"serve.step:{fault_chunk}:corrupt")
    rng = np.random.default_rng(7)
    try:
        eng = Engine(
            params, model=model_mod, cfg=cfg, num_slots=4, block_size=8,
            num_blocks=41, max_model_len=64, decode_chunk=4,
            max_prefills_per_tick=4,
            handle_preemption=False, audit_sample=1.0,
            journal=RequestJournal(jdir),
        )
        handles = [
            eng.submit(
                rng.integers(0, cfg.vocab_size, size=6).astype(np.int32),
                max_new_tokens=24,
                key=i,
            )
            for i in range(4)
        ]
        eng.drain()
        for h in handles:
            assert h.error is None, f"drill request failed: {h.error!r}"
        st = eng.stats()
        assert st["audit_divergences"] == 1, (
            "the auditor must flag EXACTLY the corrupted stream, "
            f"got {st['audit_divergences']}"
        )
        detail = eng._auditor.divergence_detail[0]
        eng.close()
        faults_mod.reset("")

        result = analyze_journal(jdir)
        assert result["n_entries"] == 4, result
        assert not result["digest_inconsistencies"], (
            "the WAL itself must be internally consistent — it recorded "
            f"the corrupted stream faithfully: {result}"
        )
        assert len(result["divergences"]) == 1, result
        row = result["divergences"][0]
        # Independent cross-check: the live auditor's bisection and the
        # post-hoc journal analysis must land on the same token/chunk.
        assert row["first_diverging_token"] == detail["first_diverging_token"]
        assert row["first_diverging_chunk"] == detail["first_diverging_chunk"]
        print(
            "incident_replay journal drill OK — corrupt fault journaled, "
            f"WAL self-check passed on all {result['n_entries']} entries, "
            f"analysis bisected entry uid={row['uid']} to token "
            f"{row['first_diverging_token']} chunk "
            f"{row['first_diverging_chunk']} (matches the live auditor)"
        )
        return 0
    finally:
        faults_mod.reset("")


def drill() -> int:
    """End-to-end incident drill: a seeded ``corrupt`` fault under load
    at 100% audit sampling must produce a ``reason="divergence"``
    flight dump naming exactly one stream, and replaying that dump must
    bisect the divergence to the exact faulted chunk."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.resilience import faults as faults_mod
    from torchdistx_tpu.serving import Engine

    params, model_mod, cfg = _model_llama_test()
    flight_path = os.path.join(
        tempfile.mkdtemp(prefix="tdx-incident-"), "flight.jsonl"
    )
    prev = telemetry.configure(flight=flight_path, flight_capacity=8192)
    # The faulted decode chunk: deep enough that every stream below is
    # decoding when it fires (all prompts admit within the first ticks).
    fault_chunk = 6
    faults_mod.reset(f"serve.step:{fault_chunk}:corrupt")
    rng = np.random.default_rng(7)
    try:
        eng = Engine(
            params, model=model_mod, cfg=cfg, num_slots=4, block_size=8,
            num_blocks=41, max_model_len=64, decode_chunk=4,
            max_prefills_per_tick=4,
            handle_preemption=False, audit_sample=1.0,
        )
        handles = [
            eng.submit(
                rng.integers(0, cfg.vocab_size, size=6).astype(np.int32),
                max_new_tokens=24,
                key=i,
            )
            for i in range(4)
        ]
        eng.drain()  # user streams AND their shadow audits
        for h in handles:
            assert h.error is None, f"drill request failed: {h.error!r}"
        st = eng.stats()
        assert st["audit_checked"] >= 4, st
        assert st["audit_divergences"] == 1, (
            "the auditor must flag EXACTLY the corrupted stream, "
            f"got {st['audit_divergences']}"
        )
        detail = eng._auditor.divergence_detail[0]
        eng.close()
        faults_mod.reset("")

        records = load_dump(flight_path)
        assert any(
            r.get("type") == "flight_dump" and r.get("reason") == "divergence"
            for r in records
        ), "no reason=divergence flight dump in the ring"
        result = analyze(records, with_faults=True)
        assert result["reproduced"], result
        assert result["faulted_rerun_matches_incident"], result
        assert len(result["divergences"]) == 1, result
        row = result["divergences"][0]
        assert row["rid"] == detail["rid"], (row, detail)
        # Independent cross-check: the auditor's own bisection (incident
        # stream vs its clean shadow replay) and the dump replay's
        # bisection (incident stream vs the fresh ground-truth re-run)
        # must land on the same token and chunk.
        assert row["first_diverging_token"] == detail["first_diverging_token"]
        assert row["first_diverging_chunk"] == detail["first_diverging_chunk"]
        print(
            "incident_replay drill OK — corrupt fault at decode chunk "
            f"{fault_chunk} caught by the auditor "
            f"(checked={st['audit_checked']}, divergences=1), dump "
            f"replayed, bisected to request {row['rid']} token "
            f"{row['first_diverging_token']} chunk "
            f"{row['first_diverging_chunk']}, faulted re-run reproduced "
            "the incident digests"
        )
        return 0
    finally:
        faults_mod.reset("")
        telemetry.configure(**prev)


# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dump", nargs="?", help="flight-dump JSONL to replay")
    ap.add_argument(
        "--journal", metavar="DIR",
        help="analyze a request-journal directory instead of a flight "
        "dump: WAL self-check + solo re-run of every journaled stream",
    )
    ap.add_argument(
        "--model", default="llama-test",
        help="weights source: 'llama-test' or module.path:factory "
        "returning (params, model_module, cfg)",
    )
    ap.add_argument(
        "--with-faults", action="store_true",
        help="also re-run with the dump's fault.fired schedule re-armed "
        "and require the recorded digests to reproduce",
    )
    ap.add_argument(
        "--max-requests", type=int, default=None,
        help="replay at most N requests (admission order)",
    )
    ap.add_argument("--json", help="write the analysis JSON here")
    ap.add_argument(
        "--drill", action="store_true",
        help="run the self-contained corrupt-fault incident drill "
        "(CI acceptance gate); ignores the other arguments",
    )
    ap.add_argument(
        "--journal-drill", action="store_true",
        help="run the self-contained journal forensics drill "
        "(CI acceptance gate); ignores the other arguments",
    )
    args = ap.parse_args(argv)

    if args.drill:
        return drill()
    if args.journal_drill:
        return journal_drill()
    if not args.dump and not args.journal:
        ap.error("a dump path, --journal DIR, or --drill is required")

    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.journal:
        result = analyze_journal(
            args.journal,
            model=args.model,
            max_requests=args.max_requests,
        )
    else:
        records = load_dump(args.dump)
        if not records:
            print(f"incident_replay: no parsable records in {args.dump}",
                  file=sys.stderr)
            return 2
        result = analyze(
            records,
            model=args.model,
            with_faults=args.with_faults,
            max_requests=args.max_requests,
        )
    out = json.dumps(result, indent=2, sort_keys=True, default=str)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    if result.get("error"):
        return 2
    return 0 if result["reproduced"] else 1


if __name__ == "__main__":
    sys.exit(main())
