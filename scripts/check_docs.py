#!/usr/bin/env python
"""Docs gate — the analog of the reference's built-and-checked Sphinx site.

The docs are plain markdown by design; this gate keeps them honest:

* every relative link / image in docs/*.md and README.md resolves;
* every `path/file.py`, `src/...`, `scripts/...` code reference in the docs
  points at a file that exists (docstrings cite the reference tree, which
  isn't shipped — "reference `...`" citations are exempt).

Run: python scripts/check_docs.py   (CI runs it in the docs job).
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DOCS = sorted((ROOT / "docs").glob("*.md")) + [ROOT / "README.md"]

LINK = re.compile(r"\[[^\]]*\]\(([^)#\s]+)(?:#[^)]*)?\)")
# `some/path.py` or `some/path.cc` style code refs (repo-relative).
PATHREF = re.compile(
    r"`((?:torchdistx_tpu|src|scripts|tests|docs|packaging)/[\w./-]+?"
    r"\.(?:py|cc|h|md|sh|yaml|toml))`"
)

errors: list[str] = []

for doc in DOCS:
    text = doc.read_text()
    for m in LINK.finditer(text):
        target = m.group(1)
        if re.match(r"^[a-z]+://", target) or target.startswith("mailto:"):
            continue  # external: not checked (zero-egress CI lanes)
        resolved = (doc.parent / target).resolve()
        if not resolved.exists():
            errors.append(f"{doc.relative_to(ROOT)}: broken link {target!r}")
    for m in PATHREF.finditer(text):
        # Citations into the (unshipped) reference tree are exempt —
        # they're provenance, marked "reference `...`" in prose.
        if text[max(0, m.start() - 32):m.start()].rstrip().endswith(
            "reference"
        ):
            continue
        ref = ROOT / m.group(1)
        if not ref.exists():
            errors.append(
                f"{doc.relative_to(ROOT)}: dangling code ref {m.group(1)!r}"
            )

if errors:
    print("\n".join(errors))
    sys.exit(1)
print(f"docs gate: OK ({len(DOCS)} pages)")
