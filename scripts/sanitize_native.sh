#!/usr/bin/env bash
# Build the native core with ASan+UBSan and run the native-path tests under
# it.  The reference treats sanitizer lanes as first-class CI
# (/root/reference/cmake/Helpers.cmake:287-316, .github/workflows/
# _test_wheel.yaml:49-89); this is the analog for the two tdx_core
# artifacts.  Leak detection is disabled (libtorch_python/numpy hold known
# suppressed leaks — the reference ships LSan.supp for the same reason);
# the lane's oracle is heap-corruption/UB errors in tdx_core frames.

set -euo pipefail
cd "$(dirname "$0")/.."

SAN="-fsanitize=address,undefined -fno-omit-frame-pointer"
LIBDIR=torchdistx_tpu/lib
mkdir -p "$LIBDIR"

g++ -std=c++17 -O1 -g -fPIC -shared $SAN \
  -o "$LIBDIR/libtdx_core.so" src/cc/tdx_core/graph.cc
PY_INCLUDE=$(python -c "import sysconfig; print(sysconfig.get_paths()['include'])")
g++ -std=c++17 -O1 -g -fPIC -shared $SAN -I"$PY_INCLUDE" -Isrc/cc/tdx_core \
  -o "$LIBDIR/_tdx_stack.so" src/cc/tdx_core/stack.cc src/cc/tdx_core/graph.cc

# Touch the libs so the loaders' staleness check doesn't rebuild over the
# sanitized artifacts.
touch "$LIBDIR"/libtdx_core.so "$LIBDIR"/_tdx_stack.so

ASAN_LIB=$(g++ -print-file-name=libasan.so)
UBSAN_LIB=$(g++ -print-file-name=libubsan.so)

LD_PRELOAD="$ASAN_LIB $UBSAN_LIB" \
ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1 \
python -m pytest tests/test_native_tape.py tests/test_fake.py \
  tests/test_deferred_init.py tests/test_data_interception.py -q

# Rebuild un-sanitized so later local runs aren't preloaded-dependent.
g++ -std=c++17 -O2 -fPIC -shared \
  -o "$LIBDIR/libtdx_core.so" src/cc/tdx_core/graph.cc
g++ -std=c++17 -O2 -fPIC -shared -I"$PY_INCLUDE" -Isrc/cc/tdx_core \
  -o "$LIBDIR/_tdx_stack.so" src/cc/tdx_core/stack.cc src/cc/tdx_core/graph.cc
echo "sanitizer lane: OK"
