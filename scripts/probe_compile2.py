"""Direct-chain compile cost: inline fold_in vs precomputed key inputs;
scaling with chain count."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)

LAYER = ([((2048, 2048), P("x", None))] * 4
         + [((5504, 2048), P("x", None))] * 2
         + [((2048, 5504), P(None, "x"))])
E = [((32000, 2048), P("x", None), "embed"),
     ((32000, 2048), P("x", None), "lm_head")]
for li in range(24):
    for j, (shp, spec) in enumerate(LAYER):
        E.append((shp, spec, f"l{li}p{j}"))
ords = np.arange(len(E), dtype=np.uint32)
osh = {nm: NamedSharding(mesh, spec) for _, spec, nm in E}


def fold(k, o):
    return jax.random.fold_in(jax.random.fold_in(k, o), 1)


# precomputed keys (one vmapped fold, executed eagerly)
keys_all = jax.jit(lambda k, o: jax.vmap(lambda oo: fold(k, oo))(o))(key, ords)


def f_keys(keys_in):
    out = {}
    for i, (shp, spec, nm) in enumerate(E):
        out[nm] = jax.random.normal(keys_in[i], shp, dtype=jnp.float32) * 0.02
    return out


t0 = time.perf_counter()
ck = jax.jit(f_keys, out_shardings=osh).lower(keys_all).compile()
print(f"precomputed-keys 170 chains: compile {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
r = ck(keys_all)
jax.block_until_ready(list(r.values()))
print(f"exec {time.perf_counter()-t0:.1f}s")

# scaling: 43 chains (quarter)
E4 = E[: len(E) // 4]
osh4 = {nm: osh[nm] for _, _, nm in E4}


def f4(keys_in):
    out = {}
    for i, (shp, spec, nm) in enumerate(E4):
        out[nm] = jax.random.normal(keys_in[i], shp, dtype=jnp.float32) * 0.02
    return out


t0 = time.perf_counter()
c4 = jax.jit(f4, out_shardings=osh4).lower(keys_all).compile()
print(f"precomputed-keys 43 chains: compile {time.perf_counter()-t0:.1f}s")
import resource
print(f"ru_maxrss {resource.getrusage(resource.RUSAGE_SELF).ru_maxrss/1048576:.1f}GB")
