"""Compile the real 1.35B bigfill job in isolation; inspect HLO + time exec."""
import re
import time

import jax
import numpy as np
from transformers import LlamaConfig, LlamaForCausalLM

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu import _tape
from torchdistx_tpu.deferred_init import _get_record
from torchdistx_tpu.materialize import (
    _base_key, _make_bigfill_fn, _named_fakes, _plan_big_fills,
    _plan_fill_bins, _plan_groups, _resolve_spec,
)
from torchdistx_tpu.parallel import MeshSpec, make_mesh
from torchdistx_tpu.parallel.sharding import fsdp_plan
from torchdistx_tpu.utils.dtypes import jnp_dtype_of

config = LlamaConfig(
    vocab_size=32000, hidden_size=2048, intermediate_size=5504,
    num_hidden_layers=24, num_attention_heads=16,
    num_key_value_heads=16, max_position_embeddings=2048,
)
model = di.deferred_init(LlamaForCausalLM, config)
mesh = make_mesh(MeshSpec(fsdp=8))
plan = fsdp_plan()

named = _named_fakes(model)
fakes = dict(named)
stacks = {n: _tape.build_call_stack(_get_record(f).node) for n, f in named}
tdts = {n: jnp_dtype_of(f.dtype) for n, f in named}
group_list, fused = _plan_groups([n for n, _ in named], fakes, stacks, tdts)
tape_ordinals = {}
for name, _ in named:
    for nd in stacks[name]:
        tape_ordinals.setdefault(nd.base_nr, len(tape_ordinals))
bin_list, fill_ins, tmpl = _plan_fill_bins(
    group_list, stacks, tdts, tape_ordinals
)
big_list, big_ins, tmpl = _plan_big_fills(tmpl, stacks, tdts, tape_ordinals)
print(f"groups={len(group_list)} fused={len(fused)} bins={len(bin_list)} "
      f"big_subgroups={len(big_list)} rest_groups={len(tmpl)}")
n_entries = sum(len(sg["entries"]) for sg in big_list)
print(f"bigfill entries={n_entries}")

from jax.sharding import NamedSharding

names = [e["name"] for sg in big_list for e in sg["entries"]]
osh = {
    n: NamedSharding(mesh, _resolve_spec(plan, n, fakes[n], mesh))
    for n in names
}
n_repl = sum(1 for s in osh.values() if s.is_fully_replicated)
print(f"replicated out_shardings: {n_repl}/{len(osh)}")

base_key = _base_key(0, "threefry2x32")
fn = _make_bigfill_fn(big_list)
t0 = time.perf_counter()
cfn = jax.jit(fn, out_shardings=osh).lower(base_key, list(big_ins)).compile()
print(f"compile: {time.perf_counter()-t0:.1f}s")
txt = cfn.as_text()
# find any big full-size buffers (>= 2048x2048 unsharded)
fulls = set()
for m in re.finditer(r"f32\[(\d+)(?:,(\d+))?\]", txt):
    a = int(m.group(1))
    b = int(m.group(2)) if m.group(2) else 1
    if a * b >= 2048 * 2048:
        fulls.add((a, b))
print("big buffer shapes:", sorted(fulls)[:20])
print("allgather:", txt.count("all-gather"), " allreduce:", txt.count("all-reduce"))

t0 = time.perf_counter()
r = cfn(base_key, list(big_ins))
jax.block_until_ready(list(r.values()))
print(f"exec: {time.perf_counter()-t0:.1f}s")
mem = [0.0]
with open("/proc/self/status") as f:
    for line in f:
        if line.startswith("VmHWM:"):
            mem[0] = int(line.split()[1]) / 1024
print(f"VmHWM: {mem[0]:.0f}MB")
