#!/usr/bin/env bash
# Preflight gate: run before ANY end-of-round / milestone commit.
#
# Round 3 shipped a half-finished refactor that broke 1F1B for every model
# because nothing gated the snapshot commit.  This script is the gate: a
# fast pytest subset covering the paths the driver artifacts depend on,
# plus the full multi-chip dryrun.  ~5 minutes; refuse to commit if red.
#
# Usage: scripts/preflight.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== preflight: fast pytest subset =="
python -m pytest \
    tests/test_pipeline.py \
    tests/test_train_step.py \
    tests/test_deferred_init.py \
    tests/test_materialize_jax.py \
    -x -q "$@"

echo "== preflight: multi-chip dryrun (8 virtual devices) =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== preflight: single-chip entry compile check =="
python - <<'EOF'
import jax
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn).lower(*args).compile()
print("entry() compiles:", out is not None)
EOF

echo "preflight OK"
