"""One compiled draw program per class, executed per instance."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)

CLASSES = [
    ((2048, 2048), P("x", None), 96),
    ((5504, 2048), P("x", None), 48),
    ((2048, 5504), P(None, "x"), 24),
    ((32000, 2048), P("x", None), 2),
]
total = sum(n for _, _, n in CLASSES)
ords = np.arange(total, dtype=np.uint32)
keys_all = jax.jit(
    lambda k, o: jax.vmap(
        lambda oo: jax.random.fold_in(jax.random.fold_in(k, oo), 1)
    )(o)
)(key, ords)
jax.block_until_ready(keys_all)

t0 = time.perf_counter()
progs = []
for shp, spec, n in CLASSES:
    def f(kk, shp=shp):
        return jax.random.normal(kk, shp, dtype=jnp.float32) * 0.02
    c = jax.jit(f, out_shardings=NamedSharding(mesh, spec)).lower(
        keys_all[0]
    ).compile()
    progs.append((c, n))
print(f"compile {len(CLASSES)} class programs: {time.perf_counter()-t0:.1f}s")

t0 = time.perf_counter()
outs = []
i = 0
for c, n in progs:
    for _ in range(n):
        outs.append(c(keys_all[i]))
        i += 1
jax.block_until_ready(outs)
print(f"exec {total} dispatches: {time.perf_counter()-t0:.1f}s")
import resource
print(f"ru_maxrss {resource.getrusage(resource.RUSAGE_SELF).ru_maxrss/1048576:.1f}GB")
print("sharding sample:", outs[0].sharding.spec, outs[-1].sharding.spec)
