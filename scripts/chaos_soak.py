#!/usr/bin/env python
"""Chaos soak: seeded randomized faults over the serving engine + a
SIGTERM drain, with trace assertions (ISSUE 5 acceptance gate).

Phase 1 — soak: a seeded fault schedule (``serve.admit`` /
``serve.prefill`` / ``serve.step`` / ``serve.recover`` sites, io/nan
kinds) plus seeded *device failures* (the donated page pool consumed
mid-decode — the case TDX_FAULT cannot express, injected by wrapping the
compiled decode chunk) runs under ≥200 mixed-length requests with random
tiny deadlines and client cancels.  Every request must either complete
**token-identical to solo generate()** or fail with a **typed**
RequestError; the drive loop is bounded (a hang fails), the allocator
must end with zero pages owned, and the engine must be back to READY.

Phase 1.5 — prefix soak (ISSUE 7 acceptance gate): 80% of a second
request wave shares one system prompt, served by a prefix-cache +
chunked-prefill engine (``prefix_cache=True``, ``prefill_chunk=8``)
under injected ``serve.prefill`` faults, deadlines, and cancels.  Every
request must stay token-identical (page sharing and copy-on-write are
invisible in the stream) or fail typed, and at drain the allocator must
hold exactly the index's pages with every refcount 1 — zero leaked
pages, zero stale-refcount pages.

Phase 1.6 — QoS soak (ISSUE 8 acceptance gate): three tenants with
skewed fair-queueing weights and priority classes over a
``scheduler="qos"`` prefix-cached engine sized for page pressure.  A
low-priority wave fills every slot, then a mixed high/low wave (80%
shared system prompt, deadlines, cancels, ``serve.swap`` io faults
knocking some swaps back to drop-and-replay) forces preemptions via
BOTH mechanisms.  Every request must stay token-identical across
preempt-and-resume or fail typed; at drain: zero leaked pages, zero
refcount drift, zero phantom swapped pages, and ``serve.preemptions_*``
visible in the trace.

Phase 2 — drain: under live load, a real SIGTERM goes through the real
handler chain.  The engine must reach STOPPED within the drain deadline,
finishing in-flight work or failing it with a retryable typed error —
completed streams are re-checked against solo generate() (no silent
truncation).

Finally the exported telemetry trace must record the recoveries: the
``serve.recover`` and ``serve.drain`` spans and a
``serve.recoveries >= 1`` counter snapshot.

**Fleet mode** (``python scripts/chaos_soak.py fleet``, ISSUE 6
acceptance gate): the same mixed traffic runs against a
:class:`~torchdistx_tpu.fleet.FleetRouter` over two engines — greedy
and sampled sub-phases — with one engine **killed mid-load** (device
failure: its page pool deleted, then ``close()``) and one **hot swap**
triggered under the remaining load.  Every request must complete
token-identical to solo ``generate()`` on SOME replica or fail typed by
its own deadline/cancel — zero requests lost to infrastructure — with
zero leaked pages on every replica, and the exported trace must show
the ``fleet.swap`` span and ``fleet.failovers >= 1``.

CI (.github/workflows/ci.yaml, chaos-soak + fleet-chaos jobs) runs both
modes with ``TDX_TELEMETRY`` set.  Locally:

    TDX_TELEMETRY=/tmp/chaos.jsonl JAX_PLATFORMS=cpu \\
    python scripts/chaos_soak.py [fleet]
"""

import json
import os
import signal
import sys

# Runnable from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EOS = 5
SEED = int(os.environ.get("TDX_CHAOS_SEED", "5"))
N_REQUESTS = int(os.environ.get("TDX_CHAOS_REQUESTS", "200"))
MAX_STEPS = 60 * N_REQUESTS


def fail(msg: str) -> int:
    print(f"chaos_soak: FAIL — {msg}", file=sys.stderr)
    return 1


def parse_trace(path):
    """Span names + merged counter snapshots from a JSONL trace."""
    spans, counters = set(), {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "span":
                spans.add(rec["name"])
            elif rec.get("type") == "counters":
                counters.update(rec.get("values", {}))
    return spans, counters


def main() -> int:
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import torchdistx_tpu.serving.engine as eng_mod
    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.resilience import faults
    from torchdistx_tpu.serving import Engine, Health, RequestError

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    solo_cache = {}

    def solo(prompt, key, max_new):
        k = (prompt.tobytes(), key, max_new)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    # Seeded fault schedule over every serving site.
    specs = []
    for site, hi, kinds in [
        ("serve.admit", N_REQUESTS, ["io", "nan"]),
        ("serve.prefill", N_REQUESTS, ["io", "nan"]),
        ("serve.step", 4 * N_REQUESTS, ["io", "nan"]),
        ("serve.recover", 6, ["io"]),
    ]:
        for step in rng.integers(1, hi, size=6):
            specs.append(f"{site}:{int(step)}:{rng.choice(kinds)}")
    schedule = ",".join(sorted(set(specs)))
    faults.reset(schedule)

    # Seeded DEVICE failures: consume the donated pool and raise — the
    # supervisor must rebuild and replay token-identically.
    real_decode = eng_mod._decode_chunk
    fail_at = set(
        int(x) for x in rng.integers(3, 3 * N_REQUESTS, size=5)
    )
    state = {"chunk": 0}

    def flaky_decode(p, paged, *args, **kwargs):
        state["chunk"] += 1
        if state["chunk"] in fail_at:
            for leaf in jax.tree.leaves(paged):
                leaf.delete()
            raise RuntimeError(f"chaos device failure at chunk {state['chunk']}")
        return real_decode(p, paged, *args, **kwargs)

    eng_mod._decode_chunk = flaky_decode

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
        )

    # ---------------- Phase 1: the soak ----------------
    eng = make_engine()
    reqs = []
    budgets = (4, 8, 12)
    for i in range(N_REQUESTS):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = eng.submit(prompt, max_new_tokens=mnt, key=i, deadline_s=deadline)
        if rng.random() < 0.05:
            h.cancel()
        reqs.append((prompt, mnt, i, h))

    for _ in range(MAX_STEPS):
        if not (len(eng.scheduler) or eng._n_running()):
            break
        eng.step()
    else:
        return fail(f"soak did not drain within {MAX_STEPS} steps (hang)")

    n_ok = n_typed = 0
    for prompt, mnt, key, h in reqs:
        if not h.done:
            return fail(f"request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(
                    f"request {key} failed UNTYPED: {type(h.error).__name__}: "
                    f"{h.error}"
                )
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(f"request {key} diverged from solo generate()")
            n_ok += 1
    if eng.allocator.num_in_use != 0:
        return fail(f"soak leaked {eng.allocator.num_in_use} pages")
    if eng.health() is not Health.READY:
        return fail(f"engine health {eng.health()} != READY after soak")
    if eng.stats()["recoveries"] < 1:
        return fail("fault schedule produced no recovery events")
    print(
        f"chaos_soak: soak OK — {n_ok} token-identical, {n_typed} typed "
        f"failures, {eng.stats()['recoveries']} recoveries "
        f"(seed={SEED}, n={N_REQUESTS})"
    )

    # ---------------- Phase 1.5: prefix-heavy soak ----------------
    # The production traffic shape: 80% of requests share one system
    # prompt, served by a prefix-cache + chunked-prefill engine under
    # injected serve.prefill faults, deadlines, and cancels.  The gate:
    # token identity survives page sharing and CoW, and at drain the
    # allocator holds EXACTLY the index's pages, every refcount 1 — zero
    # leaked pages, zero stale refcounts.
    faults.reset("")
    eng_mod._decode_chunk = real_decode
    pspecs = []
    for step in rng.integers(1, N_REQUESTS, size=8):
        pspecs.append(f"serve.prefill:{int(step)}:{rng.choice(['io', 'nan'])}")
    faults.reset(",".join(sorted(set(pspecs))))
    engp = Engine(
        params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
        block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
        prefill_chunk=8, prefix_cache=True,
        max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
    )
    system = rng.integers(0, cfg.vocab_size, size=16).astype(np.int32)
    preqs = []
    for i in range(N_REQUESTS):
        tail = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(2, 24))
        ).astype(np.int32)
        prompt = (
            np.concatenate([system, tail]) if rng.random() < 0.8 else tail
        )
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = engp.submit(
            prompt, max_new_tokens=mnt, key=2000 + i, deadline_s=deadline
        )
        if rng.random() < 0.05:
            h.cancel()
        preqs.append((prompt, mnt, 2000 + i, h))

    for _ in range(MAX_STEPS):
        if not (len(engp.scheduler) or engp._n_running()):
            break
        engp.step()
    else:
        return fail(f"prefix soak did not drain within {MAX_STEPS} steps")

    n_ok = n_typed = 0
    for prompt, mnt, key, h in preqs:
        if not h.done:
            return fail(f"prefix request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(f"prefix request {key} failed UNTYPED: {h.error!r}")
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"prefix request {key} diverged from solo generate()"
                )
            n_ok += 1
    st = engp.stats()
    if st["prefix_hits"] < N_REQUESTS // 4:
        return fail(
            f"prefix soak hit rate implausibly low ({st['prefix_hits']})"
        )
    # Zero leaked pages: everything still owned belongs to the index...
    if engp.allocator.num_in_use != len(engp.prefix):
        return fail(
            f"prefix soak leaked pages: {engp.allocator.num_in_use} in use "
            f"vs {len(engp.prefix)} indexed"
        )
    # ...and zero refcount drift: every indexed page rc exactly 1.
    drift = engp.prefix.check(engp.allocator)
    if drift is not None:
        return fail(f"prefix soak refcount drift: {drift}")
    stale = [
        p for p in list(engp.prefix._pages.values())
        if engp.allocator.refcount(p) != 1
    ]
    if stale:
        return fail(f"prefix soak stale refcounts on pages {stale}")
    engp.prefix.release(engp.allocator)
    if engp.allocator.num_in_use != 0:
        return fail("prefix index release left pages owned")
    print(
        f"chaos_soak: prefix soak OK — {n_ok} token-identical, {n_typed} "
        f"typed failures, hits={st['prefix_hits']}, "
        f"hit_tokens={st['prefix_hit_tokens']}, cow={st['cow_copies']}, "
        f"evictions={st['prefix_evictions']}"
    )

    # ---------------- Phase 1.6: QoS multi-tenant soak ----------------
    # Three tenants with skewed weights and priority classes over a
    # QoS-scheduled, prefix-cached engine sized for page pressure: a
    # low-priority wave occupies every slot first, then a mixed wave
    # (80% shared system prompt, tiny deadlines, cancels) with
    # high-priority arrivals forces preemptions — swap-to-host AND
    # drop-and-replay (serve.swap io faults knock some swaps back to
    # replay).  The gate: every request token-identical or typed, zero
    # leaked pages, zero refcount drift, zero phantom swapped pages,
    # and serve.preemptions_* visible in the trace.
    faults.reset("")
    qspecs = [f"serve.swap:{int(s)}:io" for s in rng.integers(1, 5, size=2)]
    for step in rng.integers(1, N_REQUESTS, size=4):
        qspecs.append(
            f"serve.prefill:{int(step)}:{rng.choice(['io', 'nan'])}"
        )
    faults.reset(",".join(sorted(set(qspecs))))
    # 12 usable pages against 4 slots of 4-6-page requests: page
    # pressure is chronic, so high-priority arrivals must preempt.
    engq = Engine(
        params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
        block_size=8, num_blocks=13, max_model_len=64, decode_chunk=4,
        prefill_chunk=8, max_prefills_per_tick=2, prefix_cache=True,
        scheduler="qos",
        tenant_weights={"gold": 8.0, "silver": 2.0, "bronze": 1.0},
        max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
    )
    tenants = [("gold", 2), ("silver", 1), ("bronze", 0)]
    qreqs = []
    for i in range(8):  # the preemption fodder: slots fill with bronze
        prompt = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(6, 12))
        ).astype(np.int32)
        h = engq.submit(
            prompt, max_new_tokens=24, key=3000 + i, tenant="bronze",
            priority=0,
        )
        qreqs.append((prompt, 24, 3000 + i, h))
    for _ in range(8):
        engq.step()
    system = rng.integers(0, cfg.vocab_size, size=16).astype(np.int32)
    for i in range(N_REQUESTS):
        tenant, prio = tenants[int(rng.integers(0, 3))]
        tail = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(2, 20))
        ).astype(np.int32)
        prompt = (
            np.concatenate([system, tail]) if rng.random() < 0.8 else tail
        )
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = engq.submit(
            prompt, max_new_tokens=mnt, key=3100 + i, deadline_s=deadline,
            tenant=tenant, priority=prio,
        )
        if rng.random() < 0.05:
            h.cancel()
        qreqs.append((prompt, mnt, 3100 + i, h))

    for _ in range(MAX_STEPS):
        if not (len(engq.scheduler) or engq._n_running()):
            break
        engq.step()
    else:
        return fail(f"QoS soak did not drain within {MAX_STEPS} steps")

    n_ok = n_typed = 0
    for prompt, mnt, key, h in qreqs:
        if not h.done:
            return fail(f"QoS request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(f"QoS request {key} failed UNTYPED: {h.error!r}")
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"QoS request {key} diverged from solo generate() "
                    "(preempt/resume broke token identity)"
                )
            n_ok += 1
    qst = engq.stats()
    if qst["preemptions_swap"] + qst["preemptions_replay"] < 1:
        return fail("QoS soak produced no preemptions — pressure too soft")
    if qst["swapped_pages"] != 0 or engq.allocator.num_swapped != 0:
        return fail(
            f"QoS soak left {engq.allocator.num_swapped} phantom "
            "swapped pages"
        )
    if engq.allocator.num_in_use != len(engq.prefix):
        return fail(
            f"QoS soak leaked pages: {engq.allocator.num_in_use} in use "
            f"vs {len(engq.prefix)} indexed"
        )
    drift = engq.prefix.check(engq.allocator)
    if drift is not None:
        return fail(f"QoS soak refcount drift: {drift}")
    engq.prefix.release(engq.allocator)
    if engq.allocator.num_in_use != 0:
        return fail("QoS prefix release left pages owned")
    print(
        f"chaos_soak: QoS soak OK — {n_ok} token-identical, {n_typed} "
        f"typed failures, preempt_swap={qst['preemptions_swap']}, "
        f"preempt_replay={qst['preemptions_replay']}"
    )

    # ---------------- Phase 2: SIGTERM drain under load ----------------
    faults.reset("")
    eng_mod._decode_chunk = real_decode
    eng2 = make_engine()
    dreqs = []
    for i in range(12):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        h = eng2.submit(prompt, max_new_tokens=mnt, key=1000 + i)
        dreqs.append((prompt, mnt, 1000 + i, h))
    for _ in range(4):  # fill all four slots: real in-flight work to drain
        eng2.step()
    os.kill(os.getpid(), signal.SIGTERM)  # the REAL preemption path
    steps = 0
    while eng2.health() is not Health.STOPPED:
        eng2.step()
        steps += 1
        if steps > MAX_STEPS:
            return fail("drain did not reach STOPPED (hang)")
    n_done = n_preempted = 0
    for prompt, mnt, key, h in dreqs:
        if not h.done:
            return fail(f"drain left request {key} pending")
        if h.error is None:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"request {key} silently truncated by the drain"
                )
            n_done += 1
        else:
            if not (isinstance(h.error, RequestError) and h.error.retryable):
                return fail(
                    f"drained request {key} failed non-retryably: {h.error!r}"
                )
            n_preempted += 1
    if eng2.allocator.num_in_use != 0:
        return fail(f"drain leaked {eng2.allocator.num_in_use} pages")
    print(
        f"chaos_soak: drain OK — {n_done} completed in full, "
        f"{n_preempted} failed retryable, STOPPED in {steps} ticks"
    )

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters = parse_trace(trace)
    missing = {"serve.recover", "serve.drain", "serve.prefill", "serve.step"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("serve.recoveries", 0) < 1:
        return fail(
            "trace shows no serve.recoveries "
            f"({ {k: v for k, v in counters.items() if k.startswith('serve')} })"
        )
    if counters.get("serve.prefix_hits", 0) < 1:
        return fail(
            "trace shows no serve.prefix_hits — the prefix-heavy phase "
            "left no mark"
        )
    if (
        counters.get("serve.preemptions_swap", 0)
        + counters.get("serve.preemptions_replay", 0)
        < 1
    ):
        return fail(
            "trace shows no serve.preemptions_* — the QoS phase left "
            "no mark"
        )
    print(
        "chaos_soak: trace OK — recoveries="
        f"{counters.get('serve.recoveries')}, "
        f"shed={counters.get('serve.shed', 0)}, "
        f"expired={counters.get('serve.expired', 0)}, "
        f"preempted={counters.get('serve.preempted', 0)}"
    )
    return 0


def fleet_main() -> int:
    """Fleet chaos (ISSUE 6): kill an engine mid-load, hot-swap under
    load, assert zero silent loss and zero leaked pages everywhere."""
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.fleet import FleetRouter, hot_swap
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.serving import (
        DeadlineExceeded,
        Engine,
        RequestCancelled,
        RequestError,
    )

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)
    budgets = (4, 8, 12)

    solo_cache = {}

    def solo(prompt, key, max_new, temperature, top_k):
        k = (prompt.tobytes(), key, max_new, temperature, top_k)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS, temperature=temperature, top_k=top_k,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    def make_engine(temperature, top_k):
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            temperature=temperature, top_k=top_k, drain_deadline_s=120.0,
            handle_preemption=False,
        )

    def phase(label, temperature, top_k, n, key_base):
        """One fleet sub-phase: n mixed requests over 2 engines, kill A
        at 50% of the pulls, hot-swap the survivor at 75%.  Returns an
        error string or None."""
        eng_a = make_engine(temperature, top_k)
        eng_b = make_engine(temperature, top_k)
        router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
        reqs = []
        for i in range(n):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(
                np.int32
            )
            mnt = int(rng.choice(budgets))
            deadline = None if rng.random() > 0.05 else 1e-6
            h = router.submit(
                prompt, max_new_tokens=mnt, key=key_base + i,
                deadline_s=deadline,
            )
            if rng.random() < 0.05:
                h.cancel()
            reqs.append((prompt, mnt, key_base + i, h))

        eng_c = {"eng": None}
        n_ok = n_typed = 0
        for idx, (prompt, mnt, key, h) in enumerate(reqs):
            if idx == n // 2:
                # Kill A mid-load: device failure (pool consumed) + close.
                for leaf in jax.tree.leaves(eng_a._cache):
                    leaf.delete()
                eng_a.close()
                router.poll()
            if idx == (3 * n) // 4:
                # Upgrade under the remaining load.  Same weights (an
                # operational upgrade drill): every stream still checks
                # against one solo oracle, whichever version served it.
                eng_c["eng"] = make_engine(temperature, top_k)
                hot_swap(router, lambda: eng_c["eng"], version="v2")
            try:
                toks = h.result()
            except RequestError:
                pass
            if not h.done:
                return f"[{label}] request {key} neither finished nor failed"
            if h.error is not None:
                if not isinstance(h.error, RequestError):
                    return (
                        f"[{label}] request {key} failed UNTYPED: "
                        f"{type(h.error).__name__}: {h.error}"
                    )
                if not isinstance(
                    h.error, (DeadlineExceeded, RequestCancelled)
                ):
                    # Anything but the client's own deadline/cancel is a
                    # request LOST to infrastructure — the router's job
                    # was to retry it to completion.
                    return (
                        f"[{label}] request {key} lost to infrastructure: "
                        f"{h.error!r}"
                    )
                n_typed += 1
            else:
                if toks != solo(prompt, key, mnt, temperature, top_k):
                    return (
                        f"[{label}] request {key} diverged from solo "
                        "generate()"
                    )
                n_ok += 1
        for name, eng in (
            ("A", eng_a), ("B", eng_b), ("C", eng_c["eng"]),
        ):
            if eng is not None and eng.allocator.num_in_use != 0:
                return (
                    f"[{label}] replica {name} leaked "
                    f"{eng.allocator.num_in_use} pages"
                )
        versions = [r.version for r in router.replicas()]
        if versions != ["v2"]:
            return f"[{label}] fleet did not converge on v2: {versions}"
        print(
            f"chaos_soak: fleet {label} OK — {n_ok} token-identical, "
            f"{n_typed} typed deadline/cancel failures "
            f"(n={n}, failovers so far="
            f"{telemetry.counter('fleet.failovers').value})"
        )
        return None

    n = max(2, N_REQUESTS // 2)
    err = phase("greedy", 0.0, None, n, key_base=0)
    if err is None:
        err = phase("sampled", 0.7, 8, n, key_base=10_000)
    if err is not None:
        return fail(err)
    if telemetry.counter("fleet.failovers").value < 1:
        return fail("fleet soak produced no failovers")

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters = parse_trace(trace)
    missing = {"fleet.swap", "serve.drain", "serve.prefill"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("fleet.failovers", 0) < 1:
        return fail(
            "trace shows no fleet.failovers "
            f"({ {k: v for k, v in counters.items() if k.startswith('fleet')} })"
        )
    if counters.get("fleet.submitted", 0) < 2 * n:
        return fail(
            f"trace shows fleet.submitted={counters.get('fleet.submitted')}"
            f" < {2 * n}"
        )
    if counters.get("fleet.swaps", 0) < 2:
        return fail(f"trace shows fleet.swaps={counters.get('fleet.swaps')}")
    print(
        "chaos_soak: fleet trace OK — "
        f"submitted={counters.get('fleet.submitted')}, "
        f"failovers={counters.get('fleet.failovers')}, "
        f"swaps={counters.get('fleet.swaps')}, "
        f"hops_exhausted={counters.get('fleet.hops_exhausted', 0)}"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        sys.exit(fleet_main())
    sys.exit(main())
