#!/usr/bin/env python
"""Chaos soak: seeded randomized faults over the serving engine + a
SIGTERM drain, with trace assertions (ISSUE 5 acceptance gate).

Phase 1 — soak: a seeded fault schedule (``serve.admit`` /
``serve.prefill`` / ``serve.step`` / ``serve.recover`` sites, io/nan
kinds) plus seeded *device failures* (the donated page pool consumed
mid-decode — the case TDX_FAULT cannot express, injected by wrapping the
compiled decode chunk) runs under ≥200 mixed-length requests with random
tiny deadlines and client cancels.  Every request must either complete
**token-identical to solo generate()** or fail with a **typed**
RequestError; the drive loop is bounded (a hang fails), the allocator
must end with zero pages owned, and the engine must be back to READY.

Phase 2 — drain: under live load, a real SIGTERM goes through the real
handler chain.  The engine must reach STOPPED within the drain deadline,
finishing in-flight work or failing it with a retryable typed error —
completed streams are re-checked against solo generate() (no silent
truncation).

Finally the exported telemetry trace must record the recoveries: the
``serve.recover`` and ``serve.drain`` spans and a
``serve.recoveries >= 1`` counter snapshot.

CI (.github/workflows/ci.yaml, chaos-soak job) runs this with
``TDX_TELEMETRY`` set.  Locally:

    TDX_TELEMETRY=/tmp/chaos.jsonl JAX_PLATFORMS=cpu \\
    python scripts/chaos_soak.py
"""

import json
import os
import signal
import sys

# Runnable from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EOS = 5
SEED = int(os.environ.get("TDX_CHAOS_SEED", "5"))
N_REQUESTS = int(os.environ.get("TDX_CHAOS_REQUESTS", "200"))
MAX_STEPS = 60 * N_REQUESTS


def fail(msg: str) -> int:
    print(f"chaos_soak: FAIL — {msg}", file=sys.stderr)
    return 1


def main() -> int:
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import torchdistx_tpu.serving.engine as eng_mod
    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.resilience import faults
    from torchdistx_tpu.serving import Engine, Health, RequestError

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    solo_cache = {}

    def solo(prompt, key, max_new):
        k = (prompt.tobytes(), key, max_new)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    # Seeded fault schedule over every serving site.
    specs = []
    for site, hi, kinds in [
        ("serve.admit", N_REQUESTS, ["io", "nan"]),
        ("serve.prefill", N_REQUESTS, ["io", "nan"]),
        ("serve.step", 4 * N_REQUESTS, ["io", "nan"]),
        ("serve.recover", 6, ["io"]),
    ]:
        for step in rng.integers(1, hi, size=6):
            specs.append(f"{site}:{int(step)}:{rng.choice(kinds)}")
    schedule = ",".join(sorted(set(specs)))
    faults.reset(schedule)

    # Seeded DEVICE failures: consume the donated pool and raise — the
    # supervisor must rebuild and replay token-identically.
    real_decode = eng_mod._decode_chunk
    fail_at = set(
        int(x) for x in rng.integers(3, 3 * N_REQUESTS, size=5)
    )
    state = {"chunk": 0}

    def flaky_decode(p, paged, *args, **kwargs):
        state["chunk"] += 1
        if state["chunk"] in fail_at:
            for leaf in jax.tree.leaves(paged):
                leaf.delete()
            raise RuntimeError(f"chaos device failure at chunk {state['chunk']}")
        return real_decode(p, paged, *args, **kwargs)

    eng_mod._decode_chunk = flaky_decode

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
        )

    # ---------------- Phase 1: the soak ----------------
    eng = make_engine()
    reqs = []
    budgets = (4, 8, 12)
    for i in range(N_REQUESTS):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = eng.submit(prompt, max_new_tokens=mnt, key=i, deadline_s=deadline)
        if rng.random() < 0.05:
            h.cancel()
        reqs.append((prompt, mnt, i, h))

    for _ in range(MAX_STEPS):
        if not (len(eng.scheduler) or eng._n_running()):
            break
        eng.step()
    else:
        return fail(f"soak did not drain within {MAX_STEPS} steps (hang)")

    n_ok = n_typed = 0
    for prompt, mnt, key, h in reqs:
        if not h.done:
            return fail(f"request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(
                    f"request {key} failed UNTYPED: {type(h.error).__name__}: "
                    f"{h.error}"
                )
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(f"request {key} diverged from solo generate()")
            n_ok += 1
    if eng.allocator.num_in_use != 0:
        return fail(f"soak leaked {eng.allocator.num_in_use} pages")
    if eng.health() is not Health.READY:
        return fail(f"engine health {eng.health()} != READY after soak")
    if eng.stats()["recoveries"] < 1:
        return fail("fault schedule produced no recovery events")
    print(
        f"chaos_soak: soak OK — {n_ok} token-identical, {n_typed} typed "
        f"failures, {eng.stats()['recoveries']} recoveries "
        f"(seed={SEED}, n={N_REQUESTS})"
    )

    # ---------------- Phase 2: SIGTERM drain under load ----------------
    faults.reset("")
    eng_mod._decode_chunk = real_decode
    eng2 = make_engine()
    dreqs = []
    for i in range(12):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        h = eng2.submit(prompt, max_new_tokens=mnt, key=1000 + i)
        dreqs.append((prompt, mnt, 1000 + i, h))
    for _ in range(4):  # fill all four slots: real in-flight work to drain
        eng2.step()
    os.kill(os.getpid(), signal.SIGTERM)  # the REAL preemption path
    steps = 0
    while eng2.health() is not Health.STOPPED:
        eng2.step()
        steps += 1
        if steps > MAX_STEPS:
            return fail("drain did not reach STOPPED (hang)")
    n_done = n_preempted = 0
    for prompt, mnt, key, h in dreqs:
        if not h.done:
            return fail(f"drain left request {key} pending")
        if h.error is None:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"request {key} silently truncated by the drain"
                )
            n_done += 1
        else:
            if not (isinstance(h.error, RequestError) and h.error.retryable):
                return fail(
                    f"drained request {key} failed non-retryably: {h.error!r}"
                )
            n_preempted += 1
    if eng2.allocator.num_in_use != 0:
        return fail(f"drain leaked {eng2.allocator.num_in_use} pages")
    print(
        f"chaos_soak: drain OK — {n_done} completed in full, "
        f"{n_preempted} failed retryable, STOPPED in {steps} ticks"
    )

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters = set(), {}
    with open(trace) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "span":
                spans.add(rec["name"])
            elif rec.get("type") == "counters":
                counters.update(rec.get("values", {}))
    missing = {"serve.recover", "serve.drain", "serve.prefill", "serve.step"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("serve.recoveries", 0) < 1:
        return fail(
            "trace shows no serve.recoveries "
            f"({ {k: v for k, v in counters.items() if k.startswith('serve')} })"
        )
    print(
        "chaos_soak: trace OK — recoveries="
        f"{counters.get('serve.recoveries')}, "
        f"shed={counters.get('serve.shed', 0)}, "
        f"expired={counters.get('serve.expired', 0)}, "
        f"preempted={counters.get('serve.preempted', 0)}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
