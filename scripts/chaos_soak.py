#!/usr/bin/env python
"""Chaos soak: seeded randomized faults over the serving engine + a
SIGTERM drain, with trace assertions (ISSUE 5 acceptance gate).

Phase 1 — soak: a seeded fault schedule (``serve.admit`` /
``serve.prefill`` / ``serve.step`` / ``serve.recover`` sites, io/nan
kinds) plus seeded *device failures* (the donated page pool consumed
mid-decode — the case TDX_FAULT cannot express, injected by wrapping the
compiled decode chunk) runs under ≥200 mixed-length requests with random
tiny deadlines and client cancels.  Every request must either complete
**token-identical to solo generate()** or fail with a **typed**
RequestError; the drive loop is bounded (a hang fails), the allocator
must end with zero pages owned, and the engine must be back to READY.

Phase 1.5 — prefix soak (ISSUE 7 acceptance gate): 80% of a second
request wave shares one system prompt, served by a prefix-cache +
chunked-prefill engine (``prefix_cache=True``, ``prefill_chunk=8``)
under injected ``serve.prefill`` faults, deadlines, and cancels.  Every
request must stay token-identical (page sharing and copy-on-write are
invisible in the stream) or fail typed, and at drain the allocator must
hold exactly the index's pages with every refcount 1 — zero leaked
pages, zero stale-refcount pages.

Phase 1.6 — QoS soak (ISSUE 8 acceptance gate): three tenants with
skewed fair-queueing weights and priority classes over a
``scheduler="qos"`` prefix-cached engine sized for page pressure.  A
low-priority wave fills every slot, then a mixed high/low wave (80%
shared system prompt, deadlines, cancels, ``serve.swap`` io faults
knocking some swaps back to drop-and-replay) forces preemptions via
BOTH mechanisms.  Every request must stay token-identical across
preempt-and-resume or fail typed; at drain: zero leaked pages, zero
refcount drift, zero phantom swapped pages, and ``serve.preemptions_*``
visible in the trace.

Phase 1.7 — wedge (ISSUE 10 acceptance gate): a dedicated engine on a
tight-deadline ops plane has its tick loop deliberately stopped with
in-flight work.  The stall watchdog must detect it within the deadline,
flight-dump ``reason=stall``, set ``serve.stalled``/503 ``/healthz``,
and mark the engine OVERLOADED; resuming ticks must clear the latch and
finish the stream token-identical, and ``Engine.close()`` must tear the
listener down (connection refused).  ISSUE 15 rides this phase: a
profiler trigger is installed for it, the stall must fire EXACTLY ONE
rate-limited capture (an ``ops.profile`` event whose artifact path
exists on disk), and a second trigger inside the cooldown must be
suppressed; the final trace assertion also requires the time plane's
``serve.tick`` phase events (the Perfetto exporter's tick-loop track).

Phase 2 — drain: under live load, a real SIGTERM goes through the real
handler chain.  The engine must reach STOPPED within the drain deadline,
finishing in-flight work or failing it with a retryable typed error —
completed streams are re-checked against solo generate() (no silent
truncation).

Throughout (ISSUE 10): every soak engine joins one live ops plane
(``Engine(ops_port=...)``); ``/metrics`` is scraped mid-soak inside the
drive loops and at every phase boundary, each scrape validated as
Prometheus text exposition (TYPE-before-sample, cumulative buckets,
``+Inf`` == ``_count``) with coherent per-tick attribution (occupancy /
prefill budget / page util in [0, 1], goodput > 0 observed while
decoding), and the per-tenant queue-depth family must be pruned from
the scrape once tenants idle.  Fleet mode adds a wedged-replica
segment: the watchdog marks the replica OVERLOADED and the router must
route around it, then readmit it after recovery.

Finally the exported telemetry trace must record the recoveries: the
``serve.recover`` and ``serve.drain`` spans and a
``serve.recoveries >= 1`` counter snapshot.

**Fleet mode** (``python scripts/chaos_soak.py fleet``, ISSUE 6
acceptance gate): the same mixed traffic runs against a
:class:`~torchdistx_tpu.fleet.FleetRouter` over two engines — greedy
and sampled sub-phases — with one engine **killed mid-load** (device
failure: its page pool deleted, then ``close()``) and one **hot swap**
triggered under the remaining load.  Every request must complete
token-identical to solo ``generate()`` on SOME replica or fail typed by
its own deadline/cancel — zero requests lost to infrastructure — with
zero leaked pages on every replica, and the exported trace must show
the ``fleet.swap`` span and ``fleet.failovers >= 1``.

**Audit plane** (ISSUE 14): with ``TDX_AUDIT_SAMPLE`` set (CI runs
both modes at 1.0), every soak engine shadow-audits its completed
requests — re-executing them through the same programs and comparing
determinism digests.  The drive loops wait out the audit backlog, and
the final trace assertion gates ``audit.checked >= 1`` AND
``audit.divergences == 0``: a soak whose faults, preemptions,
failovers, and swaps all replay token-identically must ALSO re-execute
divergence-free at 100% sampling.

**Migration mode** (``python scripts/chaos_soak.py migration``, ISSUE
17 acceptance gate): live KV-page stream migration under chaos.  A
**role-split fleet** (one ``role="prefill"`` + one ``role="decode"``
engine) serves a long-prompt + chatty mix while ``router.step()``'s
rebalance ships decode phases across engines mid-stream; a
**drain-by-migration** scale-in drill empties a replica with zero
recomputed prefill tokens; an injected ``serve.migrate_in`` io fault
forces a **verified fallback-to-replay** (the stream still completes
token-identical); and an engine **killed mid-migration** (pool
deleted before the export can run) proves cold replay remains the
path when the source pool is gone.  Gates: zero requests lost
untyped, ``audit.divergences == 0`` at 100% sampling, zero leaked
pages / refcount drift on every replica, and the exported trace shows
the ``serve.migrate_out``/``serve.migrate_in`` spans,
``fleet.migrations >= 2``, and at least one ``req.migration_fallback``
event (``fleet.migration_fallbacks >= 1``).

**Autoscale mode** (``python scripts/chaos_soak.py autoscale``, ISSUE
16 acceptance gate): the observe→act loop under chaos.  A
:class:`~torchdistx_tpu.fleet.Autoscaler` owns a QoS fleet (min 1, max
3) on an ops plane with tight SLO windows, and three scenarios run
through it — a **flash crowd** (10× arrival step with deadline-doomed
requests that burn the SLO), a **diurnal ramp** (arrivals up then
down), and a **one-tenant runaway** under QoS weights — with a replica
**killed mid-crowd** and a **hot swap to v2** triggered concurrently,
at 100% audit sampling.  Gates: zero requests lost to infrastructure
(deadline/cancel typed failures only), ``audit.divergences == 0``, the
SLO burn fires AND recovers with no human action
(``scaler.recoveries >= 1``), scale-in lands back at ``min_replicas``
with a bounded decision count (no flap), and the exported trace shows
``fleet.scale_outs >= 1`` + ``fleet.scale_ins >= 1`` plus the
``fleet.autoscale`` decision events ``scripts/autoscale_report.py``
reads back.  ``trace_report --strict`` and ``timeline_export
--validate`` must stay green over the same trace (CI wires all three).

**Multimodel mode** (``python scripts/chaos_soak.py multimodel``, ISSUE
18 acceptance gate): the model plane under chaos.  One engine serves
its own weights plus THREE pool models (deferred-init skeletons,
materialize-on-demand) from one page pool with ``max_resident=2`` —
every third cold demand thrashes the LRU weight eviction — while a
mixed wave interleaves all four models with parallel-sampling forks
(``n`` up to 4), deadlines, cancels, and injected faults on every
serving site **including ``serve.materialize``** (a failed
materialization must retry next tick, skeleton intact).  A second
engine is **killed mid-materialize** (``serve.materialize:1:fatal``):
its queued work fails typed and a replacement engine re-registers the
skeletons and serves the same requests token-identically.  Gates:
every request token-identical to solo ``generate()`` under ITS model's
weights (fork sibling *i* under ``fold_in(base, i)``) or failed typed;
``audit.divergences == 0`` at 100% sampling; **zero decode recompiles
after warmup** (same-geometry models share the compiled chunk); zero
leaked pages / refcount drift; and the exported trace shows the
``serve.materialize`` span plus ``serve.materializations``,
``serve.model_evictions``, and ``serve.forks`` counters.

**Crash-restart mode** (``python scripts/chaos_soak.py crashrestart``,
ISSUE 20 acceptance gate): durability under a REAL ``kill -9``.  The
soak re-invokes itself three times: a reference child runs the full
seeded mixed wave (deadlines, cancels) uninterrupted and reports every
stream's tokens + determinism digest; a journaled child runs the SAME
wave (``Engine(journal=...)``, per-tick group commit) and is SIGKILLed
by the parent mid-decode — no handlers, no flushes, owner lock left
behind; a restart child steals the dead pid's stale lock via
``resume_from_journal`` under **100% audit sampling** and finishes
every stream.  Gates: **zero silently-lost requests** (every admitted
uid retired in the final journal fold — finished, cancelled, or
expired; never untyped), every stream finished in both runs
**digest-identical** to the uninterrupted reference,
``audit.divergences == 0``, and the restarted engine's allocator ends
with zero leaked pages / zero refcount drift.

CI (.github/workflows/ci.yaml, chaos-soak + fleet-chaos +
autoscale-chaos + multimodel-chaos + crash-restart jobs) runs all
modes with ``TDX_TELEMETRY`` set.  Locally:

    TDX_TELEMETRY=/tmp/chaos.jsonl JAX_PLATFORMS=cpu \\
    python scripts/chaos_soak.py \\
        [fleet|migration|autoscale|multimodel|crashrestart]
"""

import json
import os
import signal
import sys
import time

# Runnable from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EOS = 5
SEED = int(os.environ.get("TDX_CHAOS_SEED", "5"))
N_REQUESTS = int(os.environ.get("TDX_CHAOS_REQUESTS", "200"))
# 100% audit sampling roughly doubles the work per wave (every request
# re-executes once); the hang bound scales with it.  Parsed as a float:
# an explicit TDX_AUDIT_SAMPLE=0 means auditing OFF, not "gate on it".
try:
    AUDITING = float(os.environ.get("TDX_AUDIT_SAMPLE") or 0) > 0
except ValueError:
    AUDITING = True  # malformed: let Engine() raise the real error
MAX_STEPS = 60 * N_REQUESTS * (2 if AUDITING else 1)


def fail(msg: str) -> int:
    print(f"chaos_soak: FAIL — {msg}", file=sys.stderr)
    return 1


def parse_trace(path):
    """Span names + merged counter snapshots + flight-dump reasons +
    per-name event records from a JSONL trace."""
    spans, counters, dumps, events = set(), {}, [], {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "span":
                spans.add(rec["name"])
            elif rec.get("type") == "counters":
                counters.update(rec.get("values", {}))
            elif rec.get("type") == "flight_dump":
                dumps.append(rec.get("reason"))
            elif rec.get("type") == "event":
                events.setdefault(rec.get("name"), []).append(rec)
    return spans, counters, dumps, events


def check_exposition(text):
    """Validate a /metrics scrape as Prometheus text exposition: every
    line parses, TYPE is declared once and before its family's samples,
    histogram buckets are cumulative with ``+Inf`` == ``_count``.
    Returns ``{sample_name: [(labels, value)]}``."""
    import re

    fams, samples = {}, {}
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("#"):
            p = ln.split()
            assert p[:2] == ["#", "TYPE"] and len(p) == 4, f"bad comment: {ln!r}"
            assert p[2] not in fams, f"duplicate TYPE: {p[2]}"
            fams[p[2]] = p[3]
            continue
        m = re.match(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$', ln)
        assert m, f"unparseable sample: {ln!r}"
        name, lbl, val = m.group(1), m.group(2) or "", m.group(3)
        fam = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in fams:
                fam = name[: -len(suf)]
        assert fam in fams, f"sample before its TYPE: {ln!r}"
        labels = dict(re.findall(r'([a-zA-Z0-9_:]+)="((?:[^"\\]|\\.)*)"', lbl))
        samples.setdefault(name, []).append((labels, float(val)))
    for fam, kind in fams.items():
        if kind != "histogram":
            continue
        series, counts = {}, {}
        for labels, v in samples.get(fam + "_count", []):
            counts[tuple(sorted(labels.items()))] = v
        for labels, v in samples.get(fam + "_bucket", []):
            key = tuple(sorted((k, x) for k, x in labels.items() if k != "le"))
            series.setdefault(key, []).append((labels["le"], v))
        for key, buckets in series.items():
            vals = [v for _, v in buckets]
            assert vals == sorted(vals), f"{fam}: buckets not cumulative"
            inf = [v for le, v in buckets if le == "+Inf"]
            assert inf and inf[0] == counts[key], f"{fam}: +Inf != _count"
    return samples


def pick(samples, name, **labels):
    """First sample of ``name`` whose labels include ``labels``."""
    for slabels, value in samples.get(name, []):
        if all(slabels.get(k) == str(v) for k, v in labels.items()):
            return value
    return None


def scrape(url):
    """GET /metrics and validate the exposition; returns the samples."""
    import urllib.request

    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        assert r.status == 200, f"/metrics returned {r.status}"
        return check_exposition(r.read().decode())


def main() -> int:
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import torchdistx_tpu.serving.engine as eng_mod
    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.resilience import faults
    from torchdistx_tpu.serving import Engine, Health, RequestError
    from torchdistx_tpu.telemetry import ops as tdx_ops

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    # The live ops plane, shared by every soak engine: /metrics is
    # scraped and format-validated mid-soak at every phase boundary and
    # periodically inside the drive loops.  The shared plane's watchdog
    # deadline is generous (compile stalls are real but not wedges);
    # the deliberate-wedge phase below runs its own tight plane.
    plane = tdx_ops.get_plane(
        0, tdx_ops.OpsConfig(stall_deadline_s=60.0)
    ).retain()
    ops_url = plane.server.url
    attr_seen = {"goodput": False, "scrapes": 0}

    def scrape_check(eng):
        """One validated mid-soak scrape + attribution coherence."""
        samples = scrape(ops_url)
        attr_seen["scrapes"] += 1
        eid = eng.engine_id
        occ = pick(samples, "serve_occupancy", engine=eid)
        if occ is not None:
            assert 0 <= occ <= 1, f"occupancy {occ} out of range"
            budget = pick(samples, "serve_prefill_budget", engine=eid)
            util = pick(samples, "serve_page_util", engine=eid)
            goodput = pick(samples, "serve_goodput", engine=eid)
            assert 0 <= budget <= 1, f"prefill budget {budget} out of range"
            assert 0 <= util <= 1, f"page util {util} out of range"
            assert goodput >= 0
            # "goodput > 0 while decoding": a fault-skipped tick can
            # decode nothing, so the gate is cumulative — some scrape
            # must catch the engine mid-decode.
            if occ > 0 and goodput > 0:
                attr_seen["goodput"] = True
        return samples

    solo_cache = {}

    def solo(prompt, key, max_new):
        k = (prompt.tobytes(), key, max_new)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    # Seeded fault schedule over every serving site.
    specs = []
    for site, hi, kinds in [
        ("serve.admit", N_REQUESTS, ["io", "nan"]),
        ("serve.prefill", N_REQUESTS, ["io", "nan"]),
        ("serve.step", 4 * N_REQUESTS, ["io", "nan"]),
        ("serve.recover", 6, ["io"]),
    ]:
        for step in rng.integers(1, hi, size=6):
            specs.append(f"{site}:{int(step)}:{rng.choice(kinds)}")
    schedule = ",".join(sorted(set(specs)))
    faults.reset(schedule)

    # Seeded DEVICE failures: consume the donated pool and raise — the
    # supervisor must rebuild and replay token-identically.
    real_decode = eng_mod._decode_chunk
    fail_at = set(
        int(x) for x in rng.integers(3, 3 * N_REQUESTS, size=5)
    )
    state = {"chunk": 0}

    def flaky_decode(p, paged, *args, **kwargs):
        state["chunk"] += 1
        if state["chunk"] in fail_at:
            for leaf in jax.tree.leaves(paged):
                leaf.delete()
            raise RuntimeError(f"chaos device failure at chunk {state['chunk']}")
        return real_decode(p, paged, *args, **kwargs)

    eng_mod._decode_chunk = flaky_decode

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
            ops_port=plane.port,
        )

    # ---------------- Phase 1: the soak ----------------
    eng = make_engine()
    reqs = []
    budgets = (4, 8, 12)
    for i in range(N_REQUESTS):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = eng.submit(prompt, max_new_tokens=mnt, key=i, deadline_s=deadline)
        if rng.random() < 0.05:
            h.cancel()
        reqs.append((prompt, mnt, i, h))

    for tick in range(MAX_STEPS):
        if not (len(eng.scheduler) or eng._n_running() or eng.audit_backlog()):
            break
        eng.step()
        if tick % 25 == 10:
            scrape_check(eng)
    else:
        return fail(f"soak did not drain within {MAX_STEPS} steps (hang)")
    scrape_check(eng)

    n_ok = n_typed = 0
    for prompt, mnt, key, h in reqs:
        if not h.done:
            return fail(f"request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(
                    f"request {key} failed UNTYPED: {type(h.error).__name__}: "
                    f"{h.error}"
                )
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(f"request {key} diverged from solo generate()")
            n_ok += 1
    # prefix_cache is now the engine DEFAULT (ISSUE 12): at drain the
    # allocator may own exactly the index's cached pages — anything
    # beyond that is a leak, and every indexed page must hold exactly
    # one reference (zero refcount drift).
    if eng.allocator.num_in_use != len(eng.prefix):
        return fail(
            f"soak leaked pages: {eng.allocator.num_in_use} in use vs "
            f"{len(eng.prefix)} indexed"
        )
    drift = eng.prefix.check(eng.allocator)
    if drift is not None:
        return fail(f"soak refcount drift: {drift}")
    if eng.health() is not Health.READY:
        return fail(f"engine health {eng.health()} != READY after soak")
    if eng.stats()["recoveries"] < 1:
        return fail("fault schedule produced no recovery events")
    print(
        f"chaos_soak: soak OK — {n_ok} token-identical, {n_typed} typed "
        f"failures, {eng.stats()['recoveries']} recoveries "
        f"(seed={SEED}, n={N_REQUESTS})"
    )

    # ---------------- Phase 1.5: prefix-heavy soak ----------------
    # The production traffic shape: 80% of requests share one system
    # prompt, served by a prefix-cache + chunked-prefill engine under
    # injected serve.prefill faults, deadlines, and cancels.  The gate:
    # token identity survives page sharing and CoW, and at drain the
    # allocator holds EXACTLY the index's pages, every refcount 1 — zero
    # leaked pages, zero stale refcounts.
    faults.reset("")
    eng_mod._decode_chunk = real_decode
    pspecs = []
    for step in rng.integers(1, N_REQUESTS, size=8):
        pspecs.append(f"serve.prefill:{int(step)}:{rng.choice(['io', 'nan'])}")
    faults.reset(",".join(sorted(set(pspecs))))
    engp = Engine(
        params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
        block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
        prefill_chunk=8, prefix_cache=True,
        max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
        ops_port=plane.port,
    )
    system = rng.integers(0, cfg.vocab_size, size=16).astype(np.int32)
    preqs = []
    for i in range(N_REQUESTS):
        tail = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(2, 24))
        ).astype(np.int32)
        prompt = (
            np.concatenate([system, tail]) if rng.random() < 0.8 else tail
        )
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = engp.submit(
            prompt, max_new_tokens=mnt, key=2000 + i, deadline_s=deadline
        )
        if rng.random() < 0.05:
            h.cancel()
        preqs.append((prompt, mnt, 2000 + i, h))

    for tick in range(MAX_STEPS):
        if not (len(engp.scheduler) or engp._n_running() or engp.audit_backlog()):
            break
        engp.step()
        if tick % 25 == 10:
            scrape_check(engp)
    else:
        return fail(f"prefix soak did not drain within {MAX_STEPS} steps")
    scrape_check(engp)

    n_ok = n_typed = 0
    for prompt, mnt, key, h in preqs:
        if not h.done:
            return fail(f"prefix request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(f"prefix request {key} failed UNTYPED: {h.error!r}")
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"prefix request {key} diverged from solo generate()"
                )
            n_ok += 1
    st = engp.stats()
    if st["prefix_hits"] < N_REQUESTS // 4:
        return fail(
            f"prefix soak hit rate implausibly low ({st['prefix_hits']})"
        )
    # Zero leaked pages: everything still owned belongs to the index...
    if engp.allocator.num_in_use != len(engp.prefix):
        return fail(
            f"prefix soak leaked pages: {engp.allocator.num_in_use} in use "
            f"vs {len(engp.prefix)} indexed"
        )
    # ...and zero refcount drift: every indexed page rc exactly 1.
    drift = engp.prefix.check(engp.allocator)
    if drift is not None:
        return fail(f"prefix soak refcount drift: {drift}")
    stale = [
        p for p in list(engp.prefix._pages.values())
        if engp.allocator.refcount(p) != 1
    ]
    if stale:
        return fail(f"prefix soak stale refcounts on pages {stale}")
    engp.prefix.release(engp.allocator)
    if engp.allocator.num_in_use != 0:
        return fail("prefix index release left pages owned")
    print(
        f"chaos_soak: prefix soak OK — {n_ok} token-identical, {n_typed} "
        f"typed failures, hits={st['prefix_hits']}, "
        f"hit_tokens={st['prefix_hit_tokens']}, cow={st['cow_copies']}, "
        f"evictions={st['prefix_evictions']}"
    )

    # ---------------- Phase 1.6: QoS multi-tenant soak ----------------
    # Three tenants with skewed weights and priority classes over a
    # QoS-scheduled, prefix-cached engine sized for page pressure: a
    # low-priority wave occupies every slot first, then a mixed wave
    # (80% shared system prompt, tiny deadlines, cancels) with
    # high-priority arrivals forces preemptions — swap-to-host AND
    # drop-and-replay (serve.swap io faults knock some swaps back to
    # replay).  The gate: every request token-identical or typed, zero
    # leaked pages, zero refcount drift, zero phantom swapped pages,
    # and serve.preemptions_* visible in the trace.
    faults.reset("")
    qspecs = [f"serve.swap:{int(s)}:io" for s in rng.integers(1, 5, size=2)]
    for step in rng.integers(1, N_REQUESTS, size=4):
        qspecs.append(
            f"serve.prefill:{int(step)}:{rng.choice(['io', 'nan'])}"
        )
    faults.reset(",".join(sorted(set(qspecs))))
    # 12 usable pages against 4 slots of 4-6-page requests: page
    # pressure is chronic, so high-priority arrivals must preempt.
    engq = Engine(
        params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
        block_size=8, num_blocks=13, max_model_len=64, decode_chunk=4,
        prefill_chunk=8, max_prefills_per_tick=2, prefix_cache=True,
        scheduler="qos",
        tenant_weights={"gold": 8.0, "silver": 2.0, "bronze": 1.0},
        max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
        ops_port=plane.port,
    )
    tenants = [("gold", 2), ("silver", 1), ("bronze", 0)]
    qreqs = []
    for i in range(8):  # the preemption fodder: slots fill with bronze
        prompt = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(6, 12))
        ).astype(np.int32)
        h = engq.submit(
            prompt, max_new_tokens=24, key=3000 + i, tenant="bronze",
            priority=0,
        )
        qreqs.append((prompt, 24, 3000 + i, h))
    for _ in range(8):
        engq.step()
    system = rng.integers(0, cfg.vocab_size, size=16).astype(np.int32)
    for i in range(N_REQUESTS):
        tenant, prio = tenants[int(rng.integers(0, 3))]
        tail = rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(2, 20))
        ).astype(np.int32)
        prompt = (
            np.concatenate([system, tail]) if rng.random() < 0.8 else tail
        )
        mnt = int(rng.choice(budgets))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = engq.submit(
            prompt, max_new_tokens=mnt, key=3100 + i, deadline_s=deadline,
            tenant=tenant, priority=prio,
        )
        if rng.random() < 0.05:
            h.cancel()
        qreqs.append((prompt, mnt, 3100 + i, h))

    for tick in range(MAX_STEPS):
        if not (len(engq.scheduler) or engq._n_running() or engq.audit_backlog()):
            break
        engq.step()
        if tick % 25 == 10:
            scrape_check(engq)
    else:
        return fail(f"QoS soak did not drain within {MAX_STEPS} steps")
    qsamples = scrape_check(engq)
    # The per-tenant queue-depth family must be PRUNED at drain: free-
    # form tenant ids leave /metrics when their queues empty.
    if pick(qsamples, "serve_queue_depth", tenant="bronze") is not None:
        return fail("idle tenant gauge survived in /metrics (prune broken)")

    n_ok = n_typed = 0
    for prompt, mnt, key, h in qreqs:
        if not h.done:
            return fail(f"QoS request {key} neither finished nor failed")
        if h.error is not None:
            if not isinstance(h.error, RequestError):
                return fail(f"QoS request {key} failed UNTYPED: {h.error!r}")
            n_typed += 1
        else:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"QoS request {key} diverged from solo generate() "
                    "(preempt/resume broke token identity)"
                )
            n_ok += 1
    qst = engq.stats()
    if qst["preemptions_swap"] + qst["preemptions_replay"] < 1:
        return fail("QoS soak produced no preemptions — pressure too soft")
    if qst["swapped_pages"] != 0 or engq.allocator.num_swapped != 0:
        return fail(
            f"QoS soak left {engq.allocator.num_swapped} phantom "
            "swapped pages"
        )
    if engq.allocator.num_in_use != len(engq.prefix):
        return fail(
            f"QoS soak leaked pages: {engq.allocator.num_in_use} in use "
            f"vs {len(engq.prefix)} indexed"
        )
    drift = engq.prefix.check(engq.allocator)
    if drift is not None:
        return fail(f"QoS soak refcount drift: {drift}")
    engq.prefix.release(engq.allocator)
    if engq.allocator.num_in_use != 0:
        return fail("QoS prefix release left pages owned")
    print(
        f"chaos_soak: QoS soak OK — {n_ok} token-identical, {n_typed} "
        f"typed failures, preempt_swap={qst['preemptions_swap']}, "
        f"preempt_replay={qst['preemptions_replay']}"
    )

    # ---------------- Phase 1.7: deliberate tick-loop wedge ----------------
    # The failure mode none of the soaks above can catch: the tick loop
    # silently stops while work is pending — nothing raises, nothing
    # fails typed.  A dedicated engine on its own tight-deadline plane:
    # the stall watchdog must detect the wedge within its deadline,
    # flight-dump reason=stall, set serve.stalled, and mark the engine
    # OVERLOADED (visible as a 503 /healthz); resuming ticks must clear
    # the latch and finish the stream token-identical.
    import urllib.error
    import urllib.request

    from torchdistx_tpu.telemetry import timeplane

    faults.reset("")
    # Trigger-fired profiler capture (ISSUE 15 acceptance): the wedge's
    # stall must fire EXACTLY ONE rate-limited capture — an ops.profile
    # event with an existing artifact path — and a second trigger inside
    # the cooldown must be suppressed.  The trigger is installed for
    # this phase only (a long cooldown pins "exactly one"); earlier
    # phases fire no captures because no trigger was installed.
    profile_dir = os.path.join(
        os.path.dirname(os.path.abspath(trace)), "chaos-profiles"
    )
    trig = timeplane.ProfilerTrigger(
        profile_dir, seconds=0.2, cooldown_s=600.0
    )
    prev_trig = timeplane.set_trigger(trig)
    # No EOS on the wedge engine: an early EOS inside the first decode
    # chunk would finish the request in one tick, leaving nothing
    # pending — and stillness without pending work is (correctly) not a
    # stall.  The 24-token budget guarantees in-flight work to wedge.
    # audit_sample pinned OFF here: this phase deliberately stops the
    # tick loop, and a shadow audit admitted right before the
    # latch-clear wait would re-trip the (tight) stall deadline while
    # the driver is polling the gauge instead of stepping.  Audit
    # coverage comes from phases 1/1.5/1.6.
    engw = Engine(
        params, model=llama, cfg=cfg, num_slots=4,
        block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
        drain_deadline_s=120.0, ops_port=0, audit_sample=0.0,
        ops_config=tdx_ops.OpsConfig(
            stall_deadline_s=0.5, watchdog_poll_s=0.05
        ),
    )
    wurl = engw._ops_plane.server.url
    # Warm the compiled programs first: a compile pause is a real stall
    # to the watchdog, and this phase wants exactly one, deliberate one.
    hw = engw.submit(
        np.arange(1, 5, dtype=np.int32), max_new_tokens=4, key=7000
    )
    while not hw.done:
        engw.step()
    stalls_before = telemetry.counter("serve.stalls").value
    wedge_prompt = rng.integers(0, cfg.vocab_size, size=8).astype(np.int32)
    hw = engw.submit(wedge_prompt, max_new_tokens=24, key=7001)
    engw.step()  # prefill + first decode chunk — then the driver wedges
    if hw.done or not engw._n_running():
        return fail("wedge setup left no in-flight work to stall on")
    t0 = time.monotonic()
    while engw.health() is not Health.OVERLOADED:
        if time.monotonic() - t0 > 10.0:
            wd = next(
                (w for _, w in engw._ops_plane._engines.values()), None
            )
            return fail(
                "watchdog did not detect the wedge within 10 s "
                f"(health={engw.health()}, running={engw._n_running()}, "
                f"queued={len(engw.scheduler)}, "
                f"stalls={getattr(wd, 'stalls', None)}, "
                f"wd_alive={wd.is_alive() if wd else None})"
            )
        time.sleep(0.05)
    detect_s = time.monotonic() - t0
    wsamples = scrape(wurl)
    if pick(wsamples, "serve_stalled", engine=engw.engine_id) != 1:
        return fail("serve.stalled gauge not set on the wedged engine")
    if telemetry.counter("serve.stalls").value <= stalls_before:
        return fail("serve.stalls counter not bumped by the wedge")
    # The stall fired the profiler trigger: exactly one capture, with a
    # real artifact directory on disk; a second trigger inside the
    # cooldown is suppressed, never queued.
    if len(trig.captures) != 1:
        return fail(
            f"stall fired {len(trig.captures)} profiler captures "
            "(wanted exactly 1)"
        )
    if not os.path.isdir(trig.captures[0]):
        return fail(
            f"profiler capture artifact path missing: {trig.captures[0]}"
        )
    if timeplane.fire_profile("stall", engine=engw.engine_id) is not None:
        return fail(
            "second profiler trigger inside the cooldown was NOT suppressed"
        )
    if trig.suppressed < 1:
        return fail("cooldown suppression left no ops.profiles_suppressed")
    trig.wait(10.0)  # let the bounded capture window close cleanly
    timeplane.set_trigger(prev_trig)  # sentinel restores env-lazy state
    try:
        urllib.request.urlopen(wurl + "/healthz", timeout=10)
        return fail("/healthz returned 200 for a wedged sole engine")
    except urllib.error.HTTPError as e:
        if e.code != 503:
            return fail(f"/healthz returned {e.code}, wanted 503")
    # Un-wedge: the engine's own ticks clear the latch and restore READY.
    while not hw.done:
        engw.step()
    expect = [
        int(t) for t in np.asarray(
            generate(
                params, wedge_prompt[None], jax.random.PRNGKey(7001),
                model=llama, cfg=cfg, max_new_tokens=24,
            )
        )[0]
    ]
    if hw.result() != expect:
        return fail("wedged stream lost token identity after resume")
    engw.step()
    if engw.health() is not Health.READY:
        return fail(f"health {engw.health()} != READY after un-wedge")
    eid_w = engw.engine_id
    t0 = time.monotonic()  # latch clears on the watchdog's next poll
    while telemetry.gauges().get(f"serve.stalled{{engine={eid_w}}}") != 0:
        if time.monotonic() - t0 > 5.0:
            return fail("stall latch did not clear after progress resumed")
        time.sleep(0.05)
    engw.close()
    try:
        scrape(wurl)
        return fail("wedge plane still listening after Engine.close()")
    except OSError:
        pass  # connection refused: the listener is gone
    print(
        f"chaos_soak: wedge OK — detected in {detect_s:.2f}s "
        "(deadline 0.5s), stream resumed token-identical, plane torn down"
    )

    # ---------------- Phase 2: SIGTERM drain under load ----------------
    faults.reset("")
    eng_mod._decode_chunk = real_decode
    eng2 = make_engine()
    dreqs = []
    for i in range(12):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice(budgets))
        h = eng2.submit(prompt, max_new_tokens=mnt, key=1000 + i)
        dreqs.append((prompt, mnt, 1000 + i, h))
    for _ in range(4):  # fill all four slots: real in-flight work to drain
        eng2.step()
    os.kill(os.getpid(), signal.SIGTERM)  # the REAL preemption path
    steps = 0
    while eng2.health() is not Health.STOPPED:
        eng2.step()
        steps += 1
        if steps > MAX_STEPS:
            return fail("drain did not reach STOPPED (hang)")
    n_done = n_preempted = 0
    for prompt, mnt, key, h in dreqs:
        if not h.done:
            return fail(f"drain left request {key} pending")
        if h.error is None:
            if h.result() != solo(prompt, key, mnt):
                return fail(
                    f"request {key} silently truncated by the drain"
                )
            n_done += 1
        else:
            if not (isinstance(h.error, RequestError) and h.error.retryable):
                return fail(
                    f"drained request {key} failed non-retryably: {h.error!r}"
                )
            n_preempted += 1
    if eng2.allocator.num_in_use != 0:
        return fail(f"drain leaked {eng2.allocator.num_in_use} pages")
    print(
        f"chaos_soak: drain OK — {n_done} completed in full, "
        f"{n_preempted} failed retryable, STOPPED in {steps} ticks"
    )

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    plane.release()
    spans, counters, dumps, events = parse_trace(trace)
    if not attr_seen["goodput"]:
        return fail(
            "no mid-soak /metrics scrape observed occupancy > 0 with "
            "goodput > 0 — attribution never caught the engine decoding"
        )
    if counters.get("serve.stalls", 0) < 1:
        return fail("trace counters show no serve.stalls from the wedge")
    if os.environ.get("TDX_FLIGHT_RECORDER"):
        if "stall" not in dumps:
            return fail(
                f"trace shows no reason=stall flight dump (dumps: {dumps})"
            )
    else:
        print(
            "chaos_soak: note — TDX_FLIGHT_RECORDER off, stall-dump "
            "trace assertion skipped"
        )
    print(
        f"chaos_soak: ops OK — {attr_seen['scrapes']} validated /metrics "
        f"scrapes, stalls={counters.get('serve.stalls')}, "
        f"scrape_count={counters.get('ops.scrapes')}"
    )
    # Time plane (ISSUE 15): the wedge's stall produced EXACTLY ONE
    # ops.profile event (rate-limited; the in-cooldown retry shows as
    # suppressed), its artifact path exists, and the per-tick phase
    # events the Perfetto exporter lays out are in the trace.
    profiles = events.get("ops.profile", [])
    if len(profiles) != 1:
        return fail(
            f"trace shows {len(profiles)} ops.profile events (wanted "
            "exactly 1 — the rate limit leaked or the trigger never fired)"
        )
    ppath = (profiles[0].get("attrs") or {}).get("path")
    if not ppath or not os.path.isdir(ppath):
        return fail(f"ops.profile artifact path missing on disk: {ppath!r}")
    if counters.get("ops.profiles_suppressed", 0) < 1:
        return fail(
            "trace counters show no ops.profiles_suppressed from the "
            "in-cooldown retry"
        )
    if not events.get("serve.tick"):
        return fail(
            "trace shows no serve.tick phase events — the time plane "
            "never published"
        )
    print(
        "chaos_soak: time plane OK — 1 profiler capture "
        f"({os.path.basename(ppath)}), "
        f"suppressed={counters.get('ops.profiles_suppressed')}, "
        f"tick_events={len(events.get('serve.tick', []))}"
    )
    if AUDITING:
        if counters.get("audit.checked", 0) < 1:
            return fail(
                "TDX_AUDIT_SAMPLE set but the trace shows no audit.checked"
            )
        if counters.get("audit.divergences", 0) != 0:
            return fail(
                f"audit.divergences = {counters.get('audit.divergences')} "
                "!= 0 — the shadow auditor caught a non-token-identical "
                "replay (see the reason=divergence flight dump)"
            )
        print(
            f"chaos_soak: audit OK — checked={counters.get('audit.checked')}"
            f", divergences=0, dropped={counters.get('audit.dropped', 0)}, "
            f"aborted={counters.get('audit.aborted', 0)}"
        )
    missing = {"serve.recover", "serve.drain", "serve.prefill", "serve.step"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("serve.recoveries", 0) < 1:
        return fail(
            "trace shows no serve.recoveries "
            f"({ {k: v for k, v in counters.items() if k.startswith('serve')} })"
        )
    if counters.get("serve.prefix_hits", 0) < 1:
        return fail(
            "trace shows no serve.prefix_hits — the prefix-heavy phase "
            "left no mark"
        )
    if (
        counters.get("serve.preemptions_swap", 0)
        + counters.get("serve.preemptions_replay", 0)
        < 1
    ):
        return fail(
            "trace shows no serve.preemptions_* — the QoS phase left "
            "no mark"
        )
    print(
        "chaos_soak: trace OK — recoveries="
        f"{counters.get('serve.recoveries')}, "
        f"shed={counters.get('serve.shed', 0)}, "
        f"expired={counters.get('serve.expired', 0)}, "
        f"preempted={counters.get('serve.preempted', 0)}"
    )
    return 0


def fleet_main() -> int:
    """Fleet chaos (ISSUE 6): kill an engine mid-load, hot-swap under
    load, assert zero silent loss and zero leaked pages everywhere."""
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.fleet import FleetRouter, hot_swap
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.serving import (
        DeadlineExceeded,
        Engine,
        Health,
        RequestCancelled,
        RequestError,
    )
    from torchdistx_tpu.telemetry import ops as tdx_ops

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)
    budgets = (4, 8, 12)

    solo_cache = {}

    def solo(prompt, key, max_new, temperature, top_k):
        k = (prompt.tobytes(), key, max_new, temperature, top_k)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS, temperature=temperature, top_k=top_k,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    def make_engine(temperature, top_k):
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            temperature=temperature, top_k=top_k, drain_deadline_s=120.0,
            handle_preemption=False,
        )

    def phase(label, temperature, top_k, n, key_base):
        """One fleet sub-phase: n mixed requests over 2 engines, kill A
        at 50% of the pulls, hot-swap the survivor at 75%.  Returns an
        error string or None."""
        eng_a = make_engine(temperature, top_k)
        eng_b = make_engine(temperature, top_k)
        # Ops plane over the whole fleet; the traffic phases scrape it
        # mid-soak at the kill and swap points (watchdog off here: the
        # handles drive the engines pull-by-pull, so long idle gaps are
        # normal — the dedicated wedge segment below tests detection).
        router = FleetRouter(
            [eng_a, eng_b], version="v1", max_hops=4,
            ops_port=0, ops_config=tdx_ops.OpsConfig(watchdog=False),
        )
        ops_url = router.ops_plane.server.url
        reqs = []
        for i in range(n):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(
                np.int32
            )
            mnt = int(rng.choice(budgets))
            deadline = None if rng.random() > 0.05 else 1e-6
            h = router.submit(
                prompt, max_new_tokens=mnt, key=key_base + i,
                deadline_s=deadline,
            )
            if rng.random() < 0.05:
                h.cancel()
            reqs.append((prompt, mnt, key_base + i, h))

        eng_c = {"eng": None}
        n_ok = n_typed = 0
        for idx, (prompt, mnt, key, h) in enumerate(reqs):
            if idx == n // 2:
                # Kill A mid-load: device failure (pool consumed) + close.
                for leaf in jax.tree.leaves(eng_a._cache):
                    leaf.delete()
                eng_a.close()
                router.poll()
                # Mid-churn scrape: still valid exposition, and the
                # dead replica's /healthz entry is gone.
                scrape(ops_url)
            if idx == (3 * n) // 4:
                # Upgrade under the remaining load.  Same weights (an
                # operational upgrade drill): every stream still checks
                # against one solo oracle, whichever version served it.
                eng_c["eng"] = make_engine(temperature, top_k)
                hot_swap(router, lambda: eng_c["eng"], version="v2")
                scrape(ops_url)
            try:
                toks = h.result()
            except RequestError:
                pass
            if not h.done:
                return f"[{label}] request {key} neither finished nor failed"
            if h.error is not None:
                if not isinstance(h.error, RequestError):
                    return (
                        f"[{label}] request {key} failed UNTYPED: "
                        f"{type(h.error).__name__}: {h.error}"
                    )
                if not isinstance(
                    h.error, (DeadlineExceeded, RequestCancelled)
                ):
                    # Anything but the client's own deadline/cancel is a
                    # request LOST to infrastructure — the router's job
                    # was to retry it to completion.
                    return (
                        f"[{label}] request {key} lost to infrastructure: "
                        f"{h.error!r}"
                    )
                n_typed += 1
            else:
                if toks != solo(prompt, key, mnt, temperature, top_k):
                    return (
                        f"[{label}] request {key} diverged from solo "
                        "generate()"
                    )
                n_ok += 1
        # Shadow audits hold pages while they run like any request:
        # wait the surviving replicas' audit backlogs out before the
        # leak accounting (bounded — a stuck audit is a hang).
        for _ in range(MAX_STEPS):
            live = [
                rep.engine for rep in router.replicas()
                if rep.engine.health() is not Health.STOPPED
            ]
            if not any(
                len(e.scheduler) or e._n_running() or e.audit_backlog()
                for e in live
            ):
                break
            router.step()
        else:
            return f"[{label}] audit backlog did not drain (hang)"
        for name, eng in (
            ("A", eng_a), ("B", eng_b), ("C", eng_c["eng"]),
        ):
            if eng is None:
                continue
            # Stopped replicas (A killed, B drained out by the swap)
            # released their prefix index with the engine; the live
            # survivor C legitimately owns exactly its cached prefixes
            # (prefix_cache is the default now) — anything beyond is a
            # leak, and every indexed page must read refcount 1.
            indexed = (
                len(eng.prefix)
                if eng.prefix is not None
                and eng.health() is not Health.STOPPED
                else 0
            )
            if eng.allocator.num_in_use != indexed:
                return (
                    f"[{label}] replica {name} leaked "
                    f"{eng.allocator.num_in_use} pages "
                    f"({indexed} indexed)"
                )
            if indexed:
                drift = eng.prefix.check(eng.allocator)
                if drift is not None:
                    return (
                        f"[{label}] replica {name} refcount drift: {drift}"
                    )
        versions = [r.version for r in router.replicas()]
        if versions != ["v2"]:
            return f"[{label}] fleet did not converge on v2: {versions}"
        scrape(ops_url)  # final validated scrape for this phase
        router.close()
        try:
            scrape(ops_url)
            return f"[{label}] ops plane still up after router.close()"
        except OSError:
            pass  # connection refused: listener torn down with the fleet
        print(
            f"chaos_soak: fleet {label} OK — {n_ok} token-identical, "
            f"{n_typed} typed deadline/cancel failures "
            f"(n={n}, failovers so far="
            f"{telemetry.counter('fleet.failovers').value})"
        )
        return None

    n = max(2, N_REQUESTS // 2)
    err = phase("greedy", 0.0, None, n, key_base=0)
    if err is None:
        err = phase("sampled", 0.7, 8, n, key_base=10_000)
    if err is not None:
        return fail(err)
    if telemetry.counter("fleet.failovers").value < 1:
        return fail("fleet soak produced no failovers")

    # ---------------- Wedge detection + route-around ----------------
    # A replica whose tick loop silently stops (queued work, no
    # progress) must be detected by the plane's watchdog, marked
    # OVERLOADED, and ROUTED AROUND — then rejoin once it recovers.
    def make_wedge_engine():
        # No EOS: an early EOS could finish the wedge stream in one
        # tick, leaving nothing pending to stall on.  audit_sample
        # pinned OFF (as in the engine wedge phase): pending shadow
        # audits must not blur what "no progress with work pending"
        # means while the driver deliberately stops stepping.
        return Engine(
            params, model=llama, cfg=cfg, num_slots=4, block_size=8,
            num_blocks=33, max_model_len=64, decode_chunk=4,
            drain_deadline_s=120.0, handle_preemption=False,
            audit_sample=0.0,
        )

    eng_a = make_wedge_engine()
    eng_b = make_wedge_engine()
    router = FleetRouter(
        [eng_a, eng_b], version="v1",
        ops_port=0, ops_config=tdx_ops.OpsConfig(
            stall_deadline_s=0.5, watchdog_poll_s=0.05
        ),
    )
    ops_url = router.ops_plane.server.url
    for eng, key in ((eng_a, 20_000), (eng_b, 20_001)):  # warm compiles
        h = eng.submit(
            np.arange(1, 5, dtype=np.int32), max_new_tokens=4, key=key
        )
        while not h.done:
            eng.step()
    wprompt = rng.integers(0, cfg.vocab_size, size=8).astype(np.int32)
    hb = eng_b.submit(wprompt, max_new_tokens=24, key=20_002)
    eng_b.step()  # in-flight work on B — then B's driver wedges
    if hb.done or not eng_b._n_running():
        return fail("fleet wedge setup left no in-flight work to stall on")
    t0 = time.monotonic()
    while eng_b.health() is not Health.OVERLOADED:
        if time.monotonic() - t0 > 10.0:
            return fail("fleet watchdog did not detect the wedge in 10 s")
        time.sleep(0.05)
    detect_s = time.monotonic() - t0
    samples = scrape(ops_url)
    if pick(samples, "serve_stalled", engine=eng_b.engine_id) != 1:
        return fail("serve.stalled not set on the wedged replica")
    for _ in range(4):
        rep = router._pick()
        if rep is None or rep.engine is not eng_a:
            return fail("router still routing to the wedged replica")
    # Recovery: B's driver resumes, the stream finishes token-identical,
    # and the replica becomes routable again.
    while not hb.done:
        eng_b.step()
    expect = [
        int(t) for t in np.asarray(
            generate(
                params, wprompt[None], jax.random.PRNGKey(20_002),
                model=llama, cfg=cfg, max_new_tokens=24,
            )
        )[0]
    ]
    if hb.result() != expect:
        return fail("wedged replica's stream lost token identity")
    eng_b.step()
    if eng_b.health() is not Health.READY:
        return fail(f"wedged replica stuck {eng_b.health()} after resume")
    router.close()
    try:
        scrape(ops_url)
        return fail("fleet ops plane still up after router.close()")
    except OSError:
        pass
    print(
        f"chaos_soak: fleet wedge OK — detected in {detect_s:.2f}s, "
        "router avoided the replica, rejoined after recovery"
    )

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters, dumps, _events = parse_trace(trace)
    if counters.get("serve.stalls", 0) < 1:
        return fail("trace shows no serve.stalls from the fleet wedge")
    if os.environ.get("TDX_FLIGHT_RECORDER") and "stall" not in dumps:
        return fail(f"trace shows no reason=stall dump (dumps: {dumps})")
    if AUDITING:
        if counters.get("audit.checked", 0) < 1:
            return fail(
                "TDX_AUDIT_SAMPLE set but the fleet trace shows no "
                "audit.checked"
            )
        if counters.get("audit.divergences", 0) != 0:
            return fail(
                f"audit.divergences = {counters.get('audit.divergences')} "
                "!= 0 in the fleet soak"
            )
        print(
            "chaos_soak: fleet audit OK — "
            f"checked={counters.get('audit.checked')}, divergences=0"
        )
    missing = {"fleet.swap", "serve.drain", "serve.prefill"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("fleet.failovers", 0) < 1:
        return fail(
            "trace shows no fleet.failovers "
            f"({ {k: v for k, v in counters.items() if k.startswith('fleet')} })"
        )
    if counters.get("fleet.submitted", 0) < 2 * n:
        return fail(
            f"trace shows fleet.submitted={counters.get('fleet.submitted')}"
            f" < {2 * n}"
        )
    if counters.get("fleet.swaps", 0) < 2:
        return fail(f"trace shows fleet.swaps={counters.get('fleet.swaps')}")
    print(
        "chaos_soak: fleet trace OK — "
        f"submitted={counters.get('fleet.submitted')}, "
        f"failovers={counters.get('fleet.failovers')}, "
        f"swaps={counters.get('fleet.swaps')}, "
        f"hops_exhausted={counters.get('fleet.hops_exhausted', 0)}"
    )
    return 0


def migration_main() -> int:
    """Stream-migration chaos (ISSUE 17): role-split fleet with live
    prefill→decode handoffs, drain-by-migration, a verified
    fallback-to-replay, and an engine killed before its export — zero
    silent loss, zero recompute on the happy path, zero leaked pages."""
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.fleet import FleetRouter
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.resilience import faults
    from torchdistx_tpu.serving import (
        DeadlineExceeded,
        Engine,
        Health,
        RequestCancelled,
        RequestError,
    )

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    solo_cache = {}

    def solo(prompt, key, max_new, temperature=0.0, top_k=None):
        k = (prompt.tobytes(), key, max_new, temperature, top_k)
        if k not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        params, prompt[None], jax.random.PRNGKey(key),
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS, temperature=temperature, top_k=top_k,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[k] = toks
        return solo_cache[k]

    def make_engine(role="mixed", temperature=0.0, top_k=None):
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            temperature=temperature, top_k=top_k, drain_deadline_s=120.0,
            handle_preemption=False, role=role,
        )

    def settle(label, engines, step):
        """Drain audit backlogs (bounded), then leak-check every
        engine: pages in use == indexed prefixes, refcounts clean, no
        phantom swapped pages left behind by a migration."""
        for _ in range(MAX_STEPS):
            live = [e for e in engines if e.health() is not Health.STOPPED]
            if not any(
                len(e.scheduler) or e._n_running() or e.audit_backlog()
                for e in live
            ):
                break
            step()
        else:
            return f"[{label}] audit backlog did not drain (hang)"
        for e in engines:
            indexed = (
                len(e.prefix)
                if e.prefix is not None and e.health() is not Health.STOPPED
                else 0
            )
            if e.allocator.num_in_use != indexed:
                return (
                    f"[{label}] engine {e.engine_id} leaked "
                    f"{e.allocator.num_in_use} pages ({indexed} indexed)"
                )
            if indexed:
                drift = e.prefix.check(e.allocator)
                if drift is not None:
                    return (
                        f"[{label}] engine {e.engine_id} refcount "
                        f"drift: {drift}"
                    )
            if e.allocator.num_swapped:
                return (
                    f"[{label}] engine {e.engine_id} left "
                    f"{e.allocator.num_swapped} phantom swapped pages"
                )
        return None

    def check(label, reqs, temperature=0.0, top_k=None):
        """Every handle finished token-identical or failed with the
        client's own typed deadline/cancel; returns (n_ok, n_typed) or
        an error string."""
        n_ok = n_typed = 0
        for prompt, mnt, key, h in reqs:
            if not h.done:
                return f"[{label}] request {key} neither finished nor failed"
            if h.error is not None:
                if not isinstance(h.error, RequestError):
                    return (
                        f"[{label}] request {key} failed UNTYPED: "
                        f"{type(h.error).__name__}: {h.error}"
                    )
                if not isinstance(
                    h.error, (DeadlineExceeded, RequestCancelled)
                ):
                    return (
                        f"[{label}] request {key} lost to infrastructure: "
                        f"{h.error!r}"
                    )
                n_typed += 1
            else:
                if h.result() != solo(prompt, key, mnt, temperature, top_k):
                    return (
                        f"[{label}] request {key} diverged from solo "
                        "generate()"
                    )
                n_ok += 1
        return n_ok, n_typed

    # ---------------- Phase 1: role split + live handoff ----------------
    # A prefill-role and a decode-role replica under a long-prompt +
    # chatty mix: the router steers long prompts to the prefill replica,
    # and every router.step() rebalances decode-phase streams onto the
    # decode replica mid-stream — pages shipped, zero recomputed tokens.
    eng_p = make_engine(role="prefill")
    eng_d = make_engine(role="decode")
    router = FleetRouter(
        [eng_p, eng_d], version="v1", max_hops=4, long_prompt_tokens=16,
    )
    n = max(8, N_REQUESTS // 8)
    reqs = []
    for i in range(n):
        if rng.random() < 0.5:
            plen = int(rng.integers(16, 29))  # long: steered to prefill
        else:
            plen = int(rng.integers(3, 9))  # chatty: steered off prefill
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice((4, 8, 12)))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = router.submit(
            prompt, max_new_tokens=mnt, key=30_000 + i, deadline_s=deadline,
        )
        if rng.random() < 0.05:
            h.cancel()
        reqs.append((prompt, mnt, 30_000 + i, h))
    for _, _, _, h in reqs:
        # step() runs the engines AND the prefill→decode rebalance;
        # interleaving it with the pulls ships decode-phase streams off
        # the prefill replica at many different fleet states.  The
        # pulls themselves drive the bound engine (as in fleet_main).
        router.step()
        try:
            h.result()
        except RequestError:
            pass
    res = check("roles", reqs)
    if isinstance(res, str):
        return fail(res)
    err = settle("roles", [eng_p, eng_d], router.step)
    if err is not None:
        return fail(err)
    n_moved = telemetry.counter("fleet.migrations").value
    if n_moved < 1:
        return fail("role-split phase produced no prefill→decode handoff")
    roles = sorted(r["role"] for r in router.stats()["replicas"])
    if roles != ["decode", "prefill"]:
        return fail(f"[roles] fleet roles wrong: {roles}")
    router.close()
    print(
        f"chaos_soak: migration roles OK — {res[0]} token-identical, "
        f"{res[1]} typed deadline/cancel, {n_moved} handoffs"
    )

    # ---------------- Phase 2: drain-by-migration ----------------
    # Scale-in drill: close admission, migrate every live stream out,
    # and the drain completes with the streams finishing on the peer —
    # zero recomputed prefill tokens (no crash-recovery replays).
    eng_a = make_engine(temperature=0.7, top_k=8)
    eng_b = make_engine(temperature=0.7, top_k=8)
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    eng_b.detector.observe_tick(50.0)  # pin routing to A
    reqs = []
    for i in range(3):
        prompt = rng.integers(0, cfg.vocab_size, size=6 + i).astype(np.int32)
        h = router.submit(prompt, max_new_tokens=12, key=31_000 + i)
        reqs.append((prompt, 12, 31_000 + i, h))
        eng_b.detector.observe_tick(50.0)
    for _ in range(MAX_STEPS):
        # Wait until EVERY stream is in its decode phase (admitted and
        # past prefill) so the whole set is migratable at once.
        if (
            not len(eng_a.scheduler)
            and eng_a._n_running()
            and eng_a._n_running() == eng_a._n_decoding()
        ):
            break
        eng_a.step()
    else:
        return fail("[drain] streams never all reached their decode phase")
    rid_a = next(
        rid for rid, rep in router._replicas.items() if rep.engine is eng_a
    )
    router.close_admission(rid_a)
    # A stream can legitimately hit EOS during the warm-up; migrate
    # whatever is still live, and all of it must move.
    n_live = len(list(eng_a.migratable_slots()))
    out = router.migrate_out_streams(rid_a)
    if out["migrated"] != n_live or n_live < 1 or out["fallbacks"] or out["left"]:
        return fail(f"[drain] migrate_out_streams: {out} (live={n_live})")
    for *_, h in reqs:
        try:
            h.result()  # pulls now drive the PEER — the streams moved
        except RequestError:
            pass
    res = check("drain", reqs, temperature=0.7, top_k=8)
    if isinstance(res, str):
        return fail(res)
    if eng_b.stats()["recoveries"]:
        return fail("[drain] peer recomputed a migrated stream (replays>0)")
    eng_a.begin_drain()
    for _ in range(MAX_STEPS):
        if eng_a.health() is Health.STOPPED:
            break
        router.step()
    else:
        return fail("[drain] emptied replica did not reach STOPPED")
    err = settle("drain", [eng_a, eng_b], router.step)
    if err is not None:
        return fail(err)
    router.close()
    print(
        f"chaos_soak: migration drain OK — {out['migrated']} streams "
        f"migrated, {res[0]} finished on the peer, zero recompute"
    )

    # ---------------- Phase 3: verified fallback-to-replay ----------------
    # An injected io fault on the import side: the destination frees its
    # partial page set, the source slot is already gone, and the stream
    # must still complete token-identical via the cold key-pinned replay.
    eng_a = make_engine()
    eng_b = make_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    eng_b.detector.observe_tick(50.0)
    prompt = rng.integers(0, cfg.vocab_size, size=9).astype(np.int32)
    h = router.submit(prompt, max_new_tokens=10, key=32_000)
    for _ in range(MAX_STEPS):
        if eng_a._n_decoding():
            break
        eng_a.step()
    rid_a = next(
        rid for rid, rep in router._replicas.items() if rep.engine is eng_a
    )
    slot = next(iter(eng_a.migratable_slots()))
    before = telemetry.counter("fleet.migration_fallbacks").value
    faults.reset("serve.migrate_in:1:io")
    try:
        if router.migrate_stream(rid_a, slot):
            return fail("[fallback] migration succeeded through an io fault")
    finally:
        faults.reset("")
    if telemetry.counter("fleet.migration_fallbacks").value != before + 1:
        return fail("[fallback] fleet.migration_fallbacks did not advance")
    if eng_b.allocator.num_in_use != (
        len(eng_b.prefix) if eng_b.prefix is not None else 0
    ):
        return fail("[fallback] import fault leaked pages on the destination")
    try:
        h.result()  # the pull catches the retryable preemption → replay
    except RequestError:
        pass
    res = check("fallback", [(prompt, 10, 32_000, h)])
    if isinstance(res, str):
        return fail(res)
    if res[0] != 1:
        return fail("[fallback] stream did not complete after the replay")
    if h.hops < 1:
        return fail("[fallback] stream completed without a replay hop")
    err = settle("fallback", [eng_a, eng_b], router.step)
    if err is not None:
        return fail(err)
    router.close()
    print("chaos_soak: migration fallback OK — io fault on import, "
          "destination clean, stream replayed token-identical")

    # ---------------- Phase 4: kill mid-migration ----------------
    # The source pool dies before the export can run: migrate_stream
    # declines (the export must never strand a stream it cannot move),
    # and closing the dead replica routes the stream through the normal
    # cold-replay failover — migration never replaces that last resort.
    eng_a = make_engine()
    eng_b = make_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    eng_b.detector.observe_tick(50.0)
    prompt = rng.integers(0, cfg.vocab_size, size=7).astype(np.int32)
    h = router.submit(prompt, max_new_tokens=8, key=33_000)
    for _ in range(MAX_STEPS):
        if eng_a._n_decoding():
            break
        eng_a.step()
    rid_a = next(
        rid for rid, rep in router._replicas.items() if rep.engine is eng_a
    )
    slot = next(iter(eng_a.migratable_slots()))
    for leaf in jax.tree.leaves(eng_a._cache):
        leaf.delete()
    if router.migrate_stream(rid_a, slot):
        return fail("[kill] migration claimed success off a dead pool")
    eng_a.close()
    router.poll()
    try:
        h.result()  # close() failed the stream retryably → cold replay
    except RequestError:
        pass
    res = check("kill", [(prompt, 8, 33_000, h)])
    if isinstance(res, str):
        return fail(res)
    if res[0] != 1 or h.hops < 1:
        return fail("[kill] stream did not cold-replay off the dead replica")
    err = settle("kill", [eng_b], router.step)
    if err is not None:
        return fail(err)
    router.close()
    print("chaos_soak: migration kill OK — dead pool declined the export, "
          "stream cold-replayed on the peer")

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters, dumps, events = parse_trace(trace)
    missing = {"serve.migrate_out", "serve.migrate_in"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    if counters.get("fleet.migrations", 0) < 2:
        return fail(
            f"trace shows fleet.migrations="
            f"{counters.get('fleet.migrations', 0)} < 2"
        )
    if counters.get("fleet.migration_fallbacks", 0) < 1:
        return fail("trace shows no fleet.migration_fallbacks")
    if not events.get("req.migration_fallback"):
        return fail("trace has no req.migration_fallback event")
    if counters.get("serve.migrated_pages", 0) < 1:
        return fail("trace shows no serve.migrated_pages")
    if AUDITING:
        if counters.get("audit.checked", 0) < 1:
            return fail(
                "TDX_AUDIT_SAMPLE set but the migration trace shows no "
                "audit.checked"
            )
        if counters.get("audit.divergences", 0) != 0:
            return fail(
                f"audit.divergences = {counters.get('audit.divergences')} "
                "!= 0 in the migration soak"
            )
    print(
        "chaos_soak: migration trace OK — "
        f"migrations={counters.get('fleet.migrations')}, "
        f"fallbacks={counters.get('fleet.migration_fallbacks')}, "
        f"migrated_pages={counters.get('serve.migrated_pages')}, "
        f"audit.checked={counters.get('audit.checked', 0)}"
    )
    return 0


def autoscale_main() -> int:
    """Autoscale chaos (ISSUE 16): flash crowd, diurnal ramp, runaway
    tenant — the autoscaler must recover the SLO burn autonomously,
    with a kill + hot swap + 100% audit riding along, zero dropped
    requests, and scale-in back to min replicas with no flap."""
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.fleet import (
        Autoscaler,
        AutoscaleConfig,
        FleetRouter,
        hot_swap,
    )
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import (
        DeadlineExceeded,
        Engine,
        Health,
        RequestCancelled,
        RequestError,
    )
    from torchdistx_tpu.telemetry import ops as tdx_ops

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    def make_engine():
        # QoS engines (the runaway scenario needs fair queueing), sized
        # for queue pressure so the crowd actually queues.
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            drain_deadline_s=120.0, handle_preemption=False,
            scheduler="qos", tenant_weights={"gold": 4.0, "runaway": 0.5},
        )

    # Tight SLO windows (event-time seconds): the flash crowd's misses
    # must burn, and sustained good traffic must clear the burn, within
    # a CPU soak's wall clock.  Watchdog off: handles drive the engines
    # pull-by-pull, long idle gaps are normal here.
    router = FleetRouter(
        [make_engine()], version="v1", max_hops=4,
        ops_port=0, ops_config=tdx_ops.OpsConfig(
            watchdog=False,
            slo=tdx_ops.SLOConfig(
                slo=0.9, fast_window_s=2.0, slow_window_s=8.0,
                burn_threshold=2.0, min_samples=4,
            ),
        ),
    )
    ops_url = router.ops_plane.server.url
    scaler = Autoscaler(
        router, make_engine, version="v1",
        config=AutoscaleConfig(
            min_replicas=1, max_replicas=3, fast_ticks=2,
            occupancy_high=0.85, occupancy_low=0.3,
            queue_low_per_replica=1.0, slope_window=4, slope_high=3.0,
            slow_ticks=6, scale_out_cooldown=4, scale_in_cooldown=6,
        ),
    )

    n_ok = n_typed = 0
    chaos = {"killed": False, "swapped": False}

    def submit(n, *, key_base, tenant="default", priority=0,
               deadline=None, doomed_frac=0.0):
        out = []
        for i in range(n):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(
                np.int32
            )
            d = deadline
            if doomed_frac and rng.random() < doomed_frac:
                # Deadline-doomed: the deterministic stand-in for "the
                # crowd exceeded capacity" — misses the SLO monitor
                # counts, typed failures the drop gate permits.
                d = 1e-6
            out.append(router.submit(
                prompt, max_new_tokens=int(rng.choice((4, 8, 12))),
                key=key_base + i, deadline_s=d,
                tenant=tenant, priority=priority,
            ))
        return out

    def classify(label, handles):
        nonlocal n_ok, n_typed
        for h in handles:
            if not h.done:
                return f"[{label}] a request neither finished nor failed"
            if h.error is None:
                n_ok += 1
            elif not isinstance(h.error, RequestError):
                return (
                    f"[{label}] request failed UNTYPED: "
                    f"{type(h.error).__name__}: {h.error}"
                )
            elif isinstance(h.error, (DeadlineExceeded, RequestCancelled)):
                n_typed += 1
            else:
                # Lost to infrastructure — the autoscaler/router's job
                # was to absorb the chaos, not to shed it untyped.
                return f"[{label}] request lost to infrastructure: {h.error!r}"
        return None

    def drive(label, handles, *, pulls_per_tick=8, mid=None):
        """Round-robin pull every handle to completion, ticking the
        control loop as the traffic flows; ``mid`` maps pull-fraction →
        callback (the kill / swap chaos hooks)."""
        gens = [(h, h.tokens()) for h in handles]
        n_pulls = 0
        fired = set()
        # Rough pull budget for the mid-point hooks: max_new ≤ 12.
        est_total = max(1, 12 * len(handles))
        for _ in range(MAX_STEPS):
            if not gens:
                return None
            nxt = []
            for h, g in gens:
                try:
                    next(g)
                    nxt.append((h, g))
                except (StopIteration, RequestError):
                    pass
                n_pulls += 1
                if n_pulls % pulls_per_tick == 0:
                    scaler.tick()
                for frac, hook in (mid or {}).items():
                    if frac not in fired and n_pulls >= frac * est_total:
                        fired.add(frac)
                        hook()
            gens = nxt
        return f"[{label}] drive loop exceeded {MAX_STEPS} passes (hang)"

    def kill_one():
        live = [
            rep for rep in router.replicas()
            if rep.engine.health() not in (Health.STOPPED, Health.DRAINING)
        ]
        if len(live) > 1:
            victim = live[-1].engine  # the newest spawn
            for leaf in jax.tree.leaves(victim._cache):
                leaf.delete()
            victim.close()
            chaos["killed"] = True

    def swap_v2():
        hot_swap(router, make_engine, version="v2")
        scaler.version = "v2"  # later spawns join the new version
        chaos["swapped"] = True

    # ---------------- Scenario 1: flash crowd ----------------
    baseline = max(2, min(6, N_REQUESTS // 50))
    crowd = 10 * baseline  # the 10x arrival step
    warm = submit(baseline, key_base=0)
    err = drive("warmup", warm) or classify("warmup", warm)
    if err:
        return fail(err)
    wave = submit(crowd, key_base=1_000, doomed_frac=0.3)
    # Kill at ~30% of the ESTIMATED pulls: the estimate assumes the max
    # token budget while the mean is lower, so a late fraction can land
    # past the end of the drive and never fire.
    err = drive(
        "flash-crowd", wave,
        mid={0.3: kill_one},  # device failure amid the crowd
    ) or classify("flash-crowd", wave)
    if err:
        return fail(err)
    if not chaos["killed"]:
        return fail(
            "kill hook never fired mid-crowd (fleet stayed at 1 replica "
            f"too long; decisions: {list(scaler.decisions)})"
        )
    samples = scrape(ops_url)  # mid-soak: control plane in /metrics
    if pick(samples, "fleet_replicas_target") is None:
        return fail("fleet_replicas_target missing from /metrics")
    if not any(
        pick(samples, "serve_queue_depth", engine=rep.engine.engine_id)
        is not None
        for rep in router.replicas()
    ):
        return fail("per-engine serve_queue_depth family missing from scrape")
    if scaler.scale_outs < 1:
        return fail(
            f"flash crowd did not scale out (decisions: "
            f"{list(scaler.decisions)})"
        )
    # Recovery trickle: sustained good traffic must CLEAR the burn with
    # no human action (bounded wait — event time is wall time here).
    t0 = time.monotonic()
    k = 20_000
    while scaler.recoveries < 1:
        if time.monotonic() - t0 > 120.0:
            return fail("SLO burn did not recover within 120 s of the crowd")
        trickle = submit(3, key_base=k)
        k += 3
        err = drive("recovery", trickle) or classify("recovery", trickle)
        if err:
            return fail(err)
        time.sleep(0.25)
    print(
        f"chaos_soak: autoscale flash-crowd OK — scale_outs="
        f"{scaler.scale_outs}, killed={chaos['killed']}, burn recovered "
        f"in {time.monotonic() - t0:.1f}s"
    )

    # ---------------- Scenario 2: diurnal ramp (+ hot swap) ----------------
    k = 40_000
    for i, load in enumerate((2, 4, 6, 8, 6, 4, 2)):
        ramp = submit(load, key_base=k)
        k += load
        hooks = {0.5: swap_v2} if i == 4 else None  # upgrade on the way down
        err = drive("diurnal", ramp, mid=hooks) or classify("diurnal", ramp)
        if err:
            return fail(err)
    if not chaos["swapped"]:
        return fail("diurnal ramp never hot-swapped")
    versions = {rep.version for rep in router.replicas()}
    if versions != {"v2"}:
        return fail(f"fleet did not converge on v2: {versions}")
    print("chaos_soak: autoscale diurnal OK — hot swap to v2 under load")

    # ---------------- Scenario 3: one-tenant runaway under QoS ----------------
    runaway = submit(16, key_base=60_000, tenant="runaway", priority=0)
    gold = submit(6, key_base=61_000, tenant="gold", priority=1,
                  deadline=30.0)
    err = (drive("runaway", runaway + gold)
           or classify("runaway", runaway + gold))
    if err:
        return fail(err)
    for h in gold:
        if h.error is not None:
            return fail("QoS failed to protect the gold tenant from "
                        f"the runaway: {h.error!r}")
    print("chaos_soak: autoscale runaway OK — gold tenant protected")

    # ---------------- Quiet-down: scale-in back to min ----------------
    t0 = time.monotonic()
    while True:
        scaler.tick()
        router.step()
        live = [
            rep.engine for rep in router.replicas()
            if rep.engine.health() is not Health.STOPPED
        ]
        if (
            len(router.replicas()) == scaler.config.min_replicas
            and not any(
                len(e.scheduler) or e._n_running() or e.audit_backlog()
                for e in live
            )
        ):
            break
        if time.monotonic() - t0 > 180.0:
            return fail(
                f"fleet did not land at min replicas "
                f"({len(router.replicas())} live, decisions: "
                f"{list(scaler.decisions)})"
            )
        time.sleep(0.02)
    if scaler.scale_ins < 1:
        return fail("soak ended without a scale-in")
    if scaler.monitor is not None and any(scaler.monitor.burning().values()):
        return fail(f"still burning at quiesce: {scaler.monitor.burning()}")
    # No flap: every decision was load-driven; a bounded count is the
    # hysteresis working (the unit tests pin the band itself).
    n_decisions = scaler.scale_outs + scaler.scale_ins + scaler.replaces
    if n_decisions > 12:
        return fail(
            f"{n_decisions} scaling decisions — flapping "
            f"({list(scaler.decisions)})"
        )
    # Leak accounting on the survivors (stopped replicas released with
    # their engines).
    for rep in router.replicas():
        eng = rep.engine
        indexed = len(eng.prefix) if eng.prefix is not None else 0
        if eng.allocator.num_in_use != indexed:
            return fail(
                f"replica {rep.rid} leaked {eng.allocator.num_in_use} "
                f"pages ({indexed} indexed)"
            )
    scaler.close()
    router.close()
    try:
        scrape(ops_url)
        return fail("ops plane still up after router.close()")
    except OSError:
        pass
    print(
        f"chaos_soak: autoscale quiesce OK — min replicas, "
        f"{n_decisions} decisions (outs={scaler.scale_outs}, "
        f"ins={scaler.scale_ins}, replaces={scaler.replaces}), "
        f"{n_ok} completed + {n_typed} typed deadline/cancel"
    )

    # ---------------- Trace assertions ----------------
    telemetry.emit_counters()
    spans, counters, dumps, events = parse_trace(trace)
    if counters.get("fleet.scale_outs", 0) < 1:
        return fail("trace shows no fleet.scale_outs")
    if counters.get("fleet.scale_ins", 0) < 1:
        return fail("trace shows no fleet.scale_ins")
    if counters.get("serve.slo_burns", 0) < 1:
        return fail("trace shows no serve.slo_burns from the flash crowd")
    decisions = [
        rec for rec in events.get("fleet.autoscale", ())
        if (rec.get("attrs") or {}).get("decision") not in (None, "hold")
    ]
    if not decisions:
        return fail("trace has no fleet.autoscale decision events")
    if os.environ.get("TDX_FLIGHT_RECORDER") and "slo_burn" not in dumps:
        return fail(f"trace shows no reason=slo_burn dump (dumps: {dumps})")
    if AUDITING:
        if counters.get("audit.checked", 0) < 1:
            return fail("TDX_AUDIT_SAMPLE set but no audit.checked in trace")
        if counters.get("audit.divergences", 0) != 0:
            return fail(
                f"audit.divergences = {counters.get('audit.divergences')} "
                "!= 0 in the autoscale soak"
            )
    missing = {"fleet.swap", "serve.drain", "serve.prefill"} - spans
    if missing:
        return fail(f"trace missing spans {missing}")
    print(
        "chaos_soak: autoscale trace OK — "
        f"scale_outs={counters.get('fleet.scale_outs')}, "
        f"scale_ins={counters.get('fleet.scale_ins')}, "
        f"slo_burns={counters.get('serve.slo_burns')}, "
        f"decisions={len(decisions)}, "
        f"audit.checked={counters.get('audit.checked', 0)}"
    )
    return 0


def multimodel_main() -> int:
    """Model-plane chaos (ISSUE 18): three pool models + the engine's
    own weights interleaved on one page pool under eviction thrash,
    materialize faults, forks, deadlines, cancels, and a second engine
    killed mid-materialize — token identity per model or typed failure,
    zero divergences at 100% audit, zero decode recompiles after
    warmup, zero leaked pages."""
    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.resilience import faults
    from torchdistx_tpu.serving import (
        Engine,
        Health,
        ModelPool,
        RequestError,
    )

    cfg = llama.llama_test()
    rng = np.random.default_rng(SEED)
    TEMP, TOPK = 0.8, 8
    # "Model <seed>": same family/cfg, different weights — identical KV
    # geometry, so every model shares the engine's compiled programs.
    SEEDS = {"default": 0, "m1": 1, "m2": 2, "m3": 3}
    weights = {
        tag: llama.init_params(jax.random.PRNGKey(s), cfg)
        for tag, s in SEEDS.items()
    }

    def make_pool():
        pool = ModelPool(max_resident=2)  # 3 models -> eviction thrash
        for tag in ("m1", "m2", "m3"):
            s = SEEDS[tag]
            pool.register(
                tag, model=llama, cfg=cfg,
                materialize=(
                    lambda s=s: llama.init_params(jax.random.PRNGKey(s),
                                                  cfg)
                ),
            )
        return pool

    def make_engine():
        return Engine(
            weights["default"], model=llama, cfg=cfg, eos_id=EOS,
            num_slots=4, block_size=8, num_blocks=41, max_model_len=64,
            decode_chunk=4, max_queue=8 * N_REQUESTS,
            drain_deadline_s=120.0, handle_preemption=False,
            temperature=TEMP, top_k=TOPK, model_pool=make_pool(),
        )

    solo_cache = {}

    def solo(tag, prompt, key_arr, max_new):
        ck = (tag, prompt.tobytes(), key_arr.tobytes(), max_new)
        if ck not in solo_cache:
            toks = [
                int(t) for t in np.asarray(
                    generate(
                        weights[tag], prompt[None], key_arr,
                        model=llama, cfg=cfg, max_new_tokens=max_new,
                        eos_id=EOS, temperature=TEMP, top_k=TOPK,
                    )
                )[0]
            ]
            if EOS in toks:
                toks = toks[: toks.index(EOS) + 1]
            solo_cache[ck] = toks
        return solo_cache[ck]

    def sibling_key(key, n, i):
        base = jax.random.PRNGKey(key)
        if n == 1:
            return np.asarray(base).astype(np.uint32).reshape(2)
        return np.asarray(
            jax.random.fold_in(base, i)
        ).astype(np.uint32).reshape(2)

    def drive(eng, label):
        for _ in range(MAX_STEPS):
            if not (
                len(eng.scheduler) or eng._n_running()
                or eng.audit_backlog() or eng._materialize_wanted
            ):
                return None
            eng.step()
        return f"[{label}] drive loop exceeded {MAX_STEPS} steps (hang)"

    # ---------------- Phase 1: warmup (compile every program) ----------------
    eng = make_engine()
    warm = []
    for j, tag in enumerate(("default", "m1", "m2", "m3")):
        p = rng.integers(0, cfg.vocab_size, size=6).astype(np.int32)
        h = eng.submit(p, max_new_tokens=4, key=90_000 + j,
                       model=None if tag == "default" else tag, n=2)
        warm.append((tag, p, 4, 90_000 + j, 2, h.siblings))
    err = drive(eng, "warmup")
    if err:
        return fail(err)
    c0 = {
        k: v for k, v in telemetry.snapshot()["counters"].items()
        if k.startswith("compile.count") and "decode" in k
    }

    # ---------------- Phase 2: the interleaved soak ----------------
    # Seeded faults over every serving site, serve.materialize included
    # (a failed materialization retries next tick, skeleton intact).
    specs = []
    for site, hi, kinds in [
        ("serve.admit", N_REQUESTS, ["io", "nan"]),
        ("serve.prefill", N_REQUESTS, ["io", "nan"]),
        ("serve.step", 4 * N_REQUESTS, ["io", "nan"]),
        ("serve.materialize", max(4, N_REQUESTS // 4), ["io", "io", "nan"]),
    ]:
        for step in rng.integers(1, hi, size=6):
            specs.append(f"{site}:{int(step)}:{rng.choice(kinds)}")
    faults.reset(",".join(sorted(set(specs))))

    reqs = []
    tags = ("default", "m1", "m2", "m3")
    for i in range(N_REQUESTS):
        tag = tags[int(rng.integers(0, len(tags)))]
        plen = int(rng.integers(3, 14))
        p = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice((4, 8, 12)))
        n = int(rng.choice((1, 1, 1, 1, 2, 4)))
        deadline = None if rng.random() > 0.05 else 1e-6
        h = eng.submit(
            p, max_new_tokens=mnt, key=i, deadline_s=deadline,
            model=None if tag == "default" else tag, n=n,
        )
        sibs = h.siblings or [h]
        if rng.random() < 0.05:
            sibs[int(rng.integers(0, len(sibs)))].cancel()
        reqs.append((tag, p, mnt, i, n, sibs))
    err = drive(eng, "soak")
    if err:
        return fail(err)
    faults.reset("")

    n_ok = n_typed = 0
    for tag, p, mnt, key, n, sibs in warm + reqs:
        for i, h in enumerate(sibs):
            if not h.done:
                return fail(f"request {key}.{i} neither finished nor failed")
            if h.error is not None:
                if not isinstance(h.error, RequestError):
                    return fail(
                        f"request {key}.{i} ({tag}) failed UNTYPED: "
                        f"{type(h.error).__name__}: {h.error}"
                    )
                n_typed += 1
            else:
                if h.result() != solo(tag, p, sibling_key(key, n, i), mnt):
                    return fail(
                        f"request {key}.{i} ({tag}, n={n}) diverged "
                        "from solo generate() under its model's weights"
                    )
                n_ok += 1
    if n_ok < N_REQUESTS // 2:
        return fail(f"only {n_ok} requests completed — soak too lossy")
    # Zero decode recompiles after warmup: every model shares the
    # engine's compiled chunk (same geometry, static-arg identity).
    c1 = {
        k: v for k, v in telemetry.snapshot()["counters"].items()
        if k.startswith("compile.count") and "decode" in k
    }
    grew = {k: v - c0.get(k, 0) for k, v in c1.items() if v != c0.get(k, 0)}
    if grew:
        return fail(f"steady-state decode recompiled: {grew}")
    if eng.allocator.num_in_use != len(eng.prefix):
        return fail(
            f"soak leaked pages: {eng.allocator.num_in_use} in use vs "
            f"{len(eng.prefix)} indexed"
        )
    drift = eng.prefix.check(eng.allocator)
    if drift is not None:
        return fail(f"soak refcount drift: {drift}")
    if eng.health() is not Health.READY:
        return fail(f"engine health {eng.health()} != READY after soak")
    st = eng.stats()["models"]
    if st["n_registered"] != 3:
        return fail(f"pool lost skeletons: {st}")
    evictions = sum(m["evictions"] for m in st["models"].values())
    if evictions < 1:
        return fail("max_resident=2 over 3 interleaved models never "
                    f"evicted: {st}")
    print(
        f"chaos_soak: multimodel soak OK — {n_ok} token-identical, "
        f"{n_typed} typed failures, {evictions} evictions, "
        f"{st['materialize_retries']} materialize retries, 0 decode "
        f"recompiles (seed={SEED}, n={N_REQUESTS})"
    )

    # ---------------- Phase 3: killed mid-materialize ----------------
    # serve.materialize:1:fatal is the in-process stand-in for a crash
    # inside the weight load: the fault fires INSIDE the materialize
    # span with nothing allocated, the engine dies with queued work,
    # and a replacement re-registers the skeletons and serves the same
    # requests token-identically.
    eng2 = make_engine()
    victims = []
    for j in range(3):
        p = rng.integers(0, cfg.vocab_size, size=6).astype(np.int32)
        victims.append((p, 95_000 + j))
    faults.reset("serve.materialize:1:fatal")
    handles = [
        eng2.submit(p, max_new_tokens=4, key=k, model="m1")
        for p, k in victims
    ]
    died = False
    try:
        for _ in range(MAX_STEPS):
            eng2.step()
            if all(h.done for h in handles):
                break
    except faults.FatalInjectedFault:
        died = True
    faults.reset("")
    if not died:
        return fail("serve.materialize:1:fatal never fired")
    if eng2.model_pool.ready("m1"):
        return fail("killed materialization left weights behind")
    eng2.close()  # queued work fails typed and retryable
    for h in handles:
        if h.error is not None and not isinstance(h.error, RequestError):
            return fail(
                f"kill-mid-materialize failed a request UNTYPED: "
                f"{type(h.error).__name__}"
            )
    eng3 = make_engine()  # the replacement re-registers the skeletons
    replays = [
        eng3.submit(p, max_new_tokens=4, key=k, model="m1")
        for p, k in victims
    ]
    err = drive(eng3, "replacement")
    if err:
        return fail(err)
    for (p, k), h in zip(victims, replays):
        if h.result() != solo("m1", p, sibling_key(k, 1, 0), 4):
            return fail(f"replacement diverged on request {k}")
    if eng3.model_pool.stats()["models"]["m1"]["materializations"] != 1:
        return fail("replacement materialized m1 more than once")
    eng3.drain()
    if eng3.allocator.num_in_use != len(eng3.prefix):
        return fail("replacement engine leaked pages")
    print(
        "chaos_soak: multimodel kill-mid-materialize OK — typed "
        "failures, replacement token-identical"
    )

    # ---------------- Drain + trace assertions ----------------
    eng.close()
    eng3.close()
    telemetry.emit_counters()
    spans, counters, dumps, events = parse_trace(trace)
    if "serve.materialize" not in spans:
        return fail("trace missing the serve.materialize span")
    if counters.get("serve.materializations", 0) < 3:
        return fail("trace shows fewer than 3 serve.materializations")
    if counters.get("serve.model_evictions", 0) < 1:
        return fail("trace shows no serve.model_evictions")
    if counters.get("serve.forks", 0) < 1:
        return fail("trace shows no serve.forks")
    if not events.get("model.materialized"):
        return fail("trace has no model.materialized events")
    if AUDITING:
        if counters.get("audit.checked", 0) < 1:
            return fail("TDX_AUDIT_SAMPLE set but no audit.checked in trace")
        if counters.get("audit.divergences", 0) != 0:
            return fail(
                f"audit.divergences = {counters.get('audit.divergences')} "
                "!= 0 in the multimodel soak"
            )
    print(
        "chaos_soak: multimodel trace OK — "
        f"materializations={counters.get('serve.materializations')}, "
        f"evictions={counters.get('serve.model_evictions')}, "
        f"forks={counters.get('serve.forks')}, "
        f"audit.checked={counters.get('audit.checked', 0)}"
    )
    return 0


def _crashchild_main(phase: str, jdir: str) -> int:
    """One crash-restart child (re-invoked ``chaos_soak.py _crashchild
    <phase> <dir>``).  ``ref`` runs the wave uninterrupted (no journal)
    and reports the oracle; ``crash`` runs it journaled, prints the
    kill-window marker, and keeps stepping until the parent's SIGKILL
    lands; ``resume`` steals the stale lock, finishes every stream at
    100% audit sampling, and reports outcomes + the final journal fold.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torchdistx_tpu.serving import (
        Engine,
        RequestError,
        RequestJournal,
        journal as journal_mod,
    )

    from torchdistx_tpu.models import llama

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(SEED)

    def make_engine(journal=None):
        return Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, num_slots=4,
            block_size=8, num_blocks=33, max_model_len=64, decode_chunk=4,
            max_queue=4 * N_REQUESTS, drain_deadline_s=120.0,
            handle_preemption=False, journal=journal,
        )

    def wave(eng):
        """The seeded mixed wave — IDENTICAL across ref and crash
        children (same SEED drives prompts, budgets, deadlines, and
        cancels), so uid ``i+1`` means the same request in both."""
        budgets = (4, 8, 12)
        handles = []
        for i in range(N_REQUESTS):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(
                np.int32
            )
            mnt = int(rng.choice(budgets))
            deadline = None if rng.random() > 0.05 else 1e-6
            h = eng.submit(
                prompt, max_new_tokens=mnt, key=i, deadline_s=deadline
            )
            if rng.random() < 0.05:
                h.cancel()
            handles.append(h)
        return handles

    def outcome(h):
        if h.error is None:
            return "finished"
        if not isinstance(h.error, RequestError):
            return f"UNTYPED:{type(h.error).__name__}"
        return type(h.error).__name__

    if phase == "ref":
        eng = make_engine()
        handles = wave(eng)
        eng.drain()
        out = {
            str(i + 1): {
                "outcome": outcome(h),
                "digest": h.digest if h.error is None else None,
            }
            for i, h in enumerate(handles)
        }
        eng.close()
        print("RESULT " + json.dumps({"streams": out}), flush=True)
        return 0

    if phase == "crash":
        eng = make_engine(journal=RequestJournal(jdir))
        handles = wave(eng)
        for _ in range(6):  # mid-decode: slots full, streams partial
            eng.step()
        print("CRASH_WINDOW_OPEN", flush=True)
        # Keep serving until the parent's SIGKILL lands — real work in
        # flight, journal group-committing every tick, no cleanup runs.
        for _ in range(MAX_STEPS):
            eng.step()
            time.sleep(0.01)
        print("RESULT " + json.dumps({"error": "SIGKILL never arrived"}),
              flush=True)
        return 7

    if phase == "resume":
        eng = make_engine()  # audit sampling comes from TDX_AUDIT_SAMPLE
        handles = eng.resume_from_journal(RequestJournal(jdir))
        for _ in range(MAX_STEPS):
            if not (
                len(eng.scheduler) or eng._n_running()
                or eng.audit_backlog()
            ):
                break
            eng.step()
        else:
            print("RESULT " + json.dumps(
                {"error": f"resume did not drain in {MAX_STEPS} steps"}
            ), flush=True)
            return 1
        resumed = {
            str(u): {
                "outcome": outcome(h),
                "digest": h.digest if h.error is None else None,
            }
            for u, h in handles.items()
        }
        st = eng.stats()
        indexed = len(eng.prefix) if eng.prefix is not None else 0
        leaked = eng.allocator.num_in_use - indexed
        drift = (
            eng.prefix.check(eng.allocator)
            if eng.prefix is not None else None
        )
        eng.close()
        entries, _ = journal_mod.fold_records(
            journal_mod.read_records(jdir)
        )
        fold = {
            str(u): {
                "retired": e.retired,
                "outcome": e.outcome,
                "digest": e.digest,
            }
            for u, e in entries.items()
        }
        print("RESULT " + json.dumps({
            "resumed": resumed,
            "fold": fold,
            "audit_checked": st.get("audit_checked", 0),
            "audit_divergences": st.get("audit_divergences", 0),
            "resumed_cold": st.get("journal", {}),
            "leaked_pages": leaked,
            "refcount_drift": drift,
        }), flush=True)
        return 0

    print(f"chaos_soak: unknown crash child phase {phase!r}",
          file=sys.stderr)
    return 2


def crashrestart_main() -> int:
    """Crash-restart durability soak (ISSUE 20): real SIGKILL of a
    loaded journaled engine subprocess, restart, resume at 100% audit
    sampling — zero silent loss, digests equal the uninterrupted
    reference, zero audit divergences, zero leaked pages."""
    import signal as _signal
    import subprocess
    import tempfile

    trace = os.environ.get("TDX_TELEMETRY", "")
    if not trace:
        print("chaos_soak: set TDX_TELEMETRY", file=sys.stderr)
        return 2

    jdir = os.path.join(tempfile.mkdtemp(prefix="tdx-crashrestart-"), "j")

    def child_env(phase):
        env = dict(os.environ)
        env.pop("TDX_FAULT", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["TDX_TELEMETRY"] = f"{trace}.{phase}"
        env["TDX_AUDIT_SAMPLE"] = "1.0" if phase == "resume" else "0"
        return env

    def run_child(phase):
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "_crashchild",
             phase, jdir],
            env=child_env(phase), capture_output=True, text=True,
            timeout=1800,
        )

    def result_of(stdout, stderr):
        for line in stdout.splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
        print(f"chaos_soak: no RESULT line\nstdout:\n{stdout}\n"
              f"stderr:\n{stderr}", file=sys.stderr)
        return None

    # ---- Reference: the uninterrupted oracle ----
    proc = run_child("ref")
    if proc.returncode != 0:
        return fail(f"reference child rc={proc.returncode}: "
                    f"{proc.stderr[-2000:]}")
    ref = result_of(proc.stdout, proc.stderr)
    if ref is None:
        return 1
    n_ref_finished = sum(
        1 for s in ref["streams"].values() if s["outcome"] == "finished"
    )
    print(f"chaos_soak: crashrestart ref OK — {N_REQUESTS} streams, "
          f"{n_ref_finished} finished (seed={SEED})")

    # ---- The kill: a REAL SIGKILL on a loaded engine ----
    # stderr goes to a file, not a pipe: an unread pipe fills and would
    # block the child before it ever opens the kill window.
    err_path = jdir + ".crash-stderr"
    with open(err_path, "w") as err_f:
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "_crashchild",
             "crash", jdir],
            env=child_env("crash"), stdout=subprocess.PIPE,
            stderr=err_f, text=True, bufsize=1,
        )
        killed = False
        try:
            for line in child.stdout:
                if line.strip() == "CRASH_WINDOW_OPEN":
                    child.kill()  # SIGKILL: no handlers, no cleanup
                    killed = True
                    break
            child.wait(timeout=120)
        finally:
            if child.poll() is None:
                child.kill()
                child.wait()
    if not killed:
        with open(err_path) as f:
            tail = f.read()[-2000:]
        return fail("crash child never opened the kill window "
                    f"(rc={child.returncode}): {tail}")
    if child.returncode != -_signal.SIGKILL:
        return fail(
            f"crash child rc={child.returncode}, wanted "
            f"-{int(_signal.SIGKILL)} (SIGKILL)"
        )
    print("chaos_soak: crashrestart kill OK — SIGKILL landed mid-decode, "
          "journal unclosed, stale lock left")

    # ---- Restart + resume at 100% audit sampling ----
    proc = run_child("resume")
    if proc.returncode != 0:
        return fail(f"resume child rc={proc.returncode}: "
                    f"{proc.stderr[-2000:]}")
    res = result_of(proc.stdout, proc.stderr)
    if res is None or res.get("error"):
        return fail(f"resume child: {res}")

    # Zero silently-lost requests: every admitted uid is in the final
    # fold, retired, with a typed outcome — never "failed"/untyped.
    all_uids = {str(i + 1) for i in range(N_REQUESTS)}
    fold = res["fold"]
    missing = all_uids - set(fold)
    if missing:
        return fail(f"{len(missing)} admitted streams absent from the "
                    f"journal fold (lost): {sorted(missing)[:8]}")
    unretired = [u for u in all_uids if not fold[u]["retired"]]
    if unretired:
        return fail(f"streams never retired after resume: {unretired[:8]}")
    bad = {
        u: fold[u]["outcome"] for u in all_uids
        if fold[u]["outcome"] not in ("finished", "cancelled", "expired")
    }
    if bad:
        return fail(f"streams retired with non-typed outcomes: {bad}")
    for u, s in res["resumed"].items():
        if s["outcome"].startswith("UNTYPED"):
            return fail(f"resumed stream {u} failed untyped: {s['outcome']}")

    # Digest identity: every stream finished in BOTH runs must carry the
    # uninterrupted reference's exact determinism digest — whether it
    # finished before the kill (fold digest) or after resume.
    n_checked = n_resumed_finished = 0
    for u, r in ref["streams"].items():
        if r["outcome"] != "finished":
            continue
        if fold[u]["outcome"] != "finished":
            return fail(
                f"stream {u} finished uninterrupted but ended "
                f"{fold[u]['outcome']!r} across the crash"
            )
        got = fold[u]["digest"]
        if u in res["resumed"]:
            got = res["resumed"][u]["digest"] or got
            n_resumed_finished += 1
        if got != r["digest"]:
            return fail(f"stream {u} digest diverged across kill -9: "
                        f"{got} != {r['digest']}")
        n_checked += 1
    if n_resumed_finished < 1:
        return fail("the kill window closed after every stream finished "
                    "— nothing was actually resumed")

    # The restarted engine re-executed everything it served at 100%
    # sampling: zero divergences, zero leaks, zero refcount drift.
    if res["audit_checked"] < n_resumed_finished:
        return fail(
            f"audit checked {res['audit_checked']} < "
            f"{n_resumed_finished} resumed streams at 100% sampling"
        )
    if res["audit_divergences"] != 0:
        return fail(
            f"audit.divergences = {res['audit_divergences']} != 0 on "
            "the resumed engine"
        )
    if res["leaked_pages"] != 0:
        return fail(f"resumed engine leaked {res['leaked_pages']} pages")
    if res["refcount_drift"] is not None:
        return fail(f"resumed engine refcount drift: "
                    f"{res['refcount_drift']}")
    jstats = res.get("resumed_cold") or {}
    print(
        "chaos_soak: crashrestart OK — "
        f"{n_checked} digests identical across kill -9 "
        f"({n_resumed_finished} finished post-resume), "
        f"audit checked={res['audit_checked']} divergences=0, "
        f"0 lost, 0 leaked (journal: {jstats.get('segments', '?')} "
        f"segments, fsync={jstats.get('fsync', '?')})"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "_crashchild":
        sys.exit(_crashchild_main(sys.argv[2], sys.argv[3]))
    if len(sys.argv) > 1 and sys.argv[1] == "crashrestart":
        sys.exit(crashrestart_main())
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        sys.exit(fleet_main())
    if len(sys.argv) > 1 and sys.argv[1] == "migration":
        sys.exit(migration_main())
    if len(sys.argv) > 1 and sys.argv[1] == "autoscale":
        sys.exit(autoscale_main())
    if len(sys.argv) > 1 and sys.argv[1] == "multimodel":
        sys.exit(multimodel_main())
    sys.exit(main())
