"""Can cheaper XLA optimization settings cut compile time for RNG programs?"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)

LAYER_SHAPES = (
    [((2048, 2048), P("x", None))] * 4
    + [((5504, 2048), P("x", None))] * 2
    + [((2048, 5504), P(None, "x"))]
)
E = [((32000, 2048), P("x", None), "embed"),
     ((32000, 2048), P("x", None), "lm_head")]
for li in range(24):
    for j, (shp, spec) in enumerate(LAYER_SHAPES):
        E.append((shp, spec, f"l{li}p{j}"))
ords = np.arange(len(E), dtype=np.uint32)


def fold(k, o):
    return jax.random.fold_in(jax.random.fold_in(k, o), 1)


def fa(k, ords):
    out = {}
    for i, (shp, spec, nm) in enumerate(E):
        out[nm] = jax.random.normal(fold(k, ords[i]), shp, dtype=jnp.float32) * 0.02
    return out


osh = {nm: NamedSharding(mesh, spec) for shp, spec, nm in E}

for opts in (
    {"xla_backend_optimization_level": 0},
    {"xla_backend_optimization_level": 1},
    {"xla_cpu_enable_fast_math": False},
):
    try:
        t0 = time.perf_counter()
        c = jax.jit(fa, out_shardings=osh).lower(key, ords).compile(
            compiler_options=opts
        )
        dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        r = c(key, ords)
        jax.block_until_ready(list(r.values()))
        de = time.perf_counter() - t0
        print(f"{opts}: compile {dt:.1f}s exec {de:.1f}s")
    except Exception as ex:
        print(f"{opts}: FAILED {type(ex).__name__}: {str(ex)[:120]}")
