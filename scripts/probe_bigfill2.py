"""Which shapes / how many outputs defeat backward sharding propagation?"""
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
key = jax.random.key(0)


def build(shapes, names):
    ords = np.arange(len(shapes), dtype=np.uint32)
    s1 = np.full(len(shapes), 0.02, dtype=np.float32)

    def fn(k, ords, s1):
        out = {}
        for i, (nm, shp) in enumerate(zip(names, shapes)):
            kk = jax.random.fold_in(jax.random.fold_in(k, ords[i]), 1)
            n = int(np.prod(shp))
            flat = jax.random.normal(kk, (n,), dtype=jnp.float32) * s1[i]
            out[nm] = flat[:n].reshape(shp)
        return out

    osh = {nm: NamedSharding(mesh, P("x", None)) for nm in names}
    return jax.jit(fn, out_shardings=osh).lower(key, ords, s1).compile()


def full_bufs(cfn, shapes):
    txt = cfn.as_text()
    bad = []
    for shp in set(shapes):
        n = int(np.prod(shp))
        if txt.count(f"f32[{n}]") or txt.count(f"f32[{shp[0]},{shp[1]}]"):
            bad.append(shp)
    return bad


# 1: each suspect shape alone
for shp in [(32000, 2048), (5504, 2048), (2048, 5504), (2048, 2048)]:
    c = build([shp], ["a"])
    print(f"solo {shp}: full-size bufs: {full_bufs(c, [shp])}")

# 2: 24 copies of one shape
shapes = [(5504, 2048)] * 24
names = [f"p{i}" for i in range(24)]
c = build(shapes, names)
print("24x (5504,2048): full bufs:", full_bufs(c, shapes))

# 3: mixed 170-ish: 2 embed + 24*7 layer shapes
shapes = [(32000, 2048)] * 2 + (
    [(2048, 2048)] * 4 + [(5504, 2048)] * 2 + [(2048, 5504)]
) * 24
names = [f"p{i}" for i in range(len(shapes))]
c = build(shapes, names)
print(f"{len(shapes)} mixed: full bufs:", full_bufs(c, shapes))
