// Native stack/pytree utilities — the hot-path analog of the reference's
// stack_utils (/root/reference/src/cc/torchdistx/stack_utils.cc:23-58):
// iterate / convert every tensor in a boxed call frame, descending into
// containers.  The Python-side pytree (torch.utils._pytree.tree_map) costs
// ~half of fake-construction time at GPT-2-XL scale (profiled: ~1.0s of
// 1.98s for 1743 recorded ops); this module does the container recursion in
// C and calls back into Python only for actual tensor leaves (typically 0-3
// per op).
//
// Exposed as a CPython extension module `_tdx_stack` (the environment has no
// pybind11; the CPython API is the binding layer, same role as the
// reference's `_C` module).
//
//   register_types(tensor_type, ok_types_tuple)
//   leaves(obj) -> list            flatten tuple/list/dict-values, any depth
//   convert(obj, fn, strict) -> obj'   copy-on-write map of fn over tensor
//                                      leaves; `strict` raises Fallback for
//                                      leaves outside the known-immutable set
//                                      (callers fall back to pytree — the
//                                      immutability validation analog of
//                                      deferred_init.cc:227-253)
//
// Plus the native per-op RECORD core (the deferred_init.cc:102-710 analog's
// hot half; _tape.py remains the executable spec and the fallback):
//
//   OutputRef            — C type for dependency edges (node, index)
//   Recorder             — per-tape C++ graph: writer index, dep/dependent
//                          edges, weak node registry, call-stack traversal,
//                          downgrade-to-Python export
//   record_preserve(args, kwargs, fake_type, slot_key, guard_type)
//                        — the whole argument-preservation walk in C:
//                          fake→OutputRef substitution + dependency
//                          collection, external-tensor guard snapshots,
//                          immutable-domain validation
//
// Exotic containers (namedtuples, torch.return_types struct sequences, dict
// subclasses) raise Fallback; callers keep the pytree path for those.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

// Py_T_* / Py_READONLY are public only since CPython 3.12; earlier
// versions spell them T_* / READONLY in structmember.h.
#if PY_VERSION_HEX < 0x030C0000
#include <structmember.h>
#define Py_T_OBJECT_EX T_OBJECT_EX
#define Py_T_PYSSIZET T_PYSSIZET
#define Py_T_LONGLONG T_LONGLONG
#define Py_READONLY READONLY
#endif

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

PyObject* g_tensor_type = nullptr;  // torch.Tensor
PyObject* g_ok_types = nullptr;     // tuple of immutable leaf types
PyObject* g_fallback = nullptr;     // _tdx_stack.Fallback exception

int collect_leaves(PyObject* obj, PyObject* out_list) {
  if (PyTuple_Check(obj)) {
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (collect_leaves(PyTuple_GET_ITEM(obj, i), out_list) < 0) return -1;
    }
    return 0;
  }
  if (PyList_Check(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (collect_leaves(PyList_GET_ITEM(obj, i), out_list) < 0) return -1;
    }
    return 0;
  }
  if (PyDict_Check(obj)) {
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (collect_leaves(value, out_list) < 0) return -1;
    }
    return 0;
  }
  return PyList_Append(out_list, obj);
}

// Returns a NEW reference, or nullptr with an exception set.  Sets *changed
// when the returned object differs from obj.
PyObject* convert_rec(PyObject* obj, PyObject* fn, int strict, int* changed) {
  if (PyTuple_Check(obj)) {
    if (!PyTuple_CheckExact(obj)) {  // namedtuple / torch.return_types
      PyErr_SetString(g_fallback, "tuple subclass");
      return nullptr;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    PyObject* items = PyList_New(n);
    if (!items) return nullptr;
    int any = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = convert_rec(PyTuple_GET_ITEM(obj, i), fn, strict, &c);
      if (!r) {
        Py_DECREF(items);
        return nullptr;
      }
      any |= c;
      PyList_SET_ITEM(items, i, r);  // steals
    }
    if (!any) {
      Py_DECREF(items);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    PyObject* out = PyList_AsTuple(items);
    Py_DECREF(items);
    return out;
  }
  if (PyList_Check(obj)) {
    if (!PyList_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "list subclass");
      return nullptr;
    }
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return nullptr;
    int any = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = convert_rec(PyList_GET_ITEM(obj, i), fn, strict, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      any |= c;
      PyList_SET_ITEM(out, i, r);  // steals
    }
    if (!any) {
      Py_DECREF(out);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    return out;
  }
  if (PyDict_Check(obj)) {
    if (!PyDict_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "dict subclass");
      return nullptr;
    }
    PyObject* out = PyDict_New();
    if (!out) return nullptr;
    int any = 0;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      int c = 0;
      PyObject* r = convert_rec(value, fn, strict, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      any |= c;
      int rc = PyDict_SetItem(out, key, r);
      Py_DECREF(r);
      if (rc < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    }
    if (!any) {
      Py_DECREF(out);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    return out;
  }

  // Leaf.
  int is_tensor = PyObject_IsInstance(obj, g_tensor_type);
  if (is_tensor < 0) return nullptr;
  if (is_tensor) {
    PyObject* r = PyObject_CallOneArg(fn, obj);
    if (r && r != obj) *changed = 1;
    return r;
  }
  if (strict) {
    // The known-immutable leaf domain (deferred_init.cc:227-253's
    // validation): exact scalar types plus the registered torch value types.
    if (!(obj == Py_None || PyBool_Check(obj) || PyLong_CheckExact(obj) ||
          PyFloat_CheckExact(obj) || PyUnicode_CheckExact(obj) ||
          PyBytes_CheckExact(obj) || PyComplex_CheckExact(obj))) {
      int ok = PyObject_IsInstance(obj, g_ok_types);
      if (ok < 0) return nullptr;
      if (!ok) {
        PyErr_SetString(g_fallback, "leaf outside immutable domain");
        return nullptr;
      }
    }
  }
  Py_INCREF(obj);
  return obj;
}

PyObject* py_register_types(PyObject*, PyObject* args) {
  PyObject *tensor_type, *ok_types;
  if (!PyArg_ParseTuple(args, "OO", &tensor_type, &ok_types)) return nullptr;
  if (!PyTuple_Check(ok_types)) {
    PyErr_SetString(PyExc_TypeError, "ok_types must be a tuple of types");
    return nullptr;
  }
  Py_XDECREF(g_tensor_type);
  Py_XDECREF(g_ok_types);
  Py_INCREF(tensor_type);
  Py_INCREF(ok_types);
  g_tensor_type = tensor_type;
  g_ok_types = ok_types;
  Py_RETURN_NONE;
}

PyObject* py_leaves(PyObject*, PyObject* obj) {
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  if (collect_leaves(obj, out) < 0) {
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

PyObject* py_convert(PyObject*, PyObject* args) {
  PyObject *obj, *fn;
  int strict = 0;
  if (!PyArg_ParseTuple(args, "OO|p", &obj, &fn, &strict)) return nullptr;
  if (!g_tensor_type) {
    PyErr_SetString(PyExc_RuntimeError, "register_types() not called");
    return nullptr;
  }
  int changed = 0;
  return convert_rec(obj, fn, strict, &changed);
}

// ---------------------------------------------------------------------------
// OutputRef: the dependency-edge marker (analog of the reference's
// OpOutputDescriptor, deferred_init.cc:106-154) as a C type.  Holds the
// producing node STRONGLY; participates in GC (node→args→OutputRef→node
// cycles are how tapes die).

typedef struct {
  PyObject_HEAD
  PyObject* node;
  Py_ssize_t index;
} OutputRefObject;

extern PyTypeObject OutputRefType;

PyObject* outputref_new_fast(PyObject* node, Py_ssize_t index) {
  OutputRefObject* self =
      PyObject_GC_New(OutputRefObject, &OutputRefType);
  if (!self) return nullptr;
  Py_INCREF(node);
  self->node = node;
  self->index = index;
  PyObject_GC_Track((PyObject*)self);
  return (PyObject*)self;
}

PyObject* OutputRef_tp_new(PyTypeObject*, PyObject* args, PyObject* kwds) {
  PyObject* node;
  Py_ssize_t index;
  static const char* kwlist[] = {"node", "index", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "On", (char**)kwlist, &node,
                                   &index))
    return nullptr;
  return outputref_new_fast(node, index);
}

void OutputRef_dealloc(OutputRefObject* self) {
  PyObject_GC_UnTrack((PyObject*)self);
  Py_CLEAR(self->node);
  PyObject_GC_Del(self);
}

int OutputRef_traverse(OutputRefObject* self, visitproc visit, void* arg) {
  Py_VISIT(self->node);
  return 0;
}

int OutputRef_clear(OutputRefObject* self) {
  Py_CLEAR(self->node);
  return 0;
}

PyObject* OutputRef_repr(OutputRefObject* self) {
  PyObject* nr = PyObject_GetAttrString(self->node, "op_nr");
  if (!nr) return nullptr;
  PyObject* out = PyUnicode_FromFormat("OutputRef(op_nr=%S, index=%zd)", nr,
                                       self->index);
  Py_DECREF(nr);
  return out;
}

PyMemberDef OutputRef_members[] = {
    {"node", Py_T_OBJECT_EX, offsetof(OutputRefObject, node), 0, nullptr},
    {"index", Py_T_PYSSIZET, offsetof(OutputRefObject, index), 0, nullptr},
    {nullptr, 0, 0, 0, nullptr},
};

PyTypeObject OutputRefType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "_tdx_stack.OutputRef",              /* tp_name */
    sizeof(OutputRefObject),             /* tp_basicsize */
    0,                                   /* tp_itemsize */
    (destructor)OutputRef_dealloc,       /* tp_dealloc */
    0, nullptr, nullptr, nullptr,        /* vectorcall/getattr/setattr/as_async */
    (reprfunc)OutputRef_repr,            /* tp_repr */
    nullptr, nullptr, nullptr,           /* number/sequence/mapping */
    nullptr, nullptr, nullptr,           /* hash/call/str */
    nullptr, nullptr, nullptr,           /* getattro/setattro/as_buffer */
    Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC, /* tp_flags */
    "Dependency edge: (producing node, output index)", /* tp_doc */
    (traverseproc)OutputRef_traverse,    /* tp_traverse */
    (inquiry)OutputRef_clear,            /* tp_clear */
};

// ---------------------------------------------------------------------------
// Recorder: per-tape native graph.  The graph ENGINE is tdx_graph
// (graph.cc — one implementation, shared with the C-ABI/ctypes lane and
// stress-tested under TSan by scripts/tsan_native.sh); this type adds the
// Python glue: a weak op_nr→OpNode registry (call-stack results are always
// strongly reachable from the target via the Python OutputRef edges, so a
// strong registry would pin whole tapes) and the keep-alive `dependents`
// mirroring.  All mutation runs under the GIL — the same serialization
// contract the stress harness models with a mutex.

#include "graph.h"

typedef struct {
  PyObject_HEAD
  tdx_graph* graph;
  std::unordered_map<int64_t, PyObject*>* wrefs;  // op_nr -> weakref(OpNode)
  // Keep-alive appends dropped on OOM in note_op's mutation phase (the
  // graph stays consistent, but a node may later be GC'd while natively
  // referenced — degrading to the documented 'dead toucher' skip).
  // Exposed as `.dropped_appends` so an OOM-degraded tape is diagnosable
  // instead of silent (advisor r4).
  int64_t dropped_appends;
} RecorderObject;

PyObject* Recorder_tp_new(PyTypeObject* type, PyObject*, PyObject*) {
  RecorderObject* self = (RecorderObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->graph = tdx_graph_new();
  self->wrefs = new std::unordered_map<int64_t, PyObject*>();
  self->dropped_appends = 0;
  return (PyObject*)self;
}

void Recorder_dealloc(RecorderObject* self) {
  if (self->wrefs) {
    for (auto& [nr, wref] : *self->wrefs) Py_XDECREF(wref);
    delete self->wrefs;
  }
  if (self->graph) tdx_graph_free(self->graph);
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyObject* deref_or_null(PyObject* wref) {
  PyObject* obj = PyWeakref_GetObject(wref);  // borrowed
  return (obj == Py_None) ? nullptr : obj;
}

PyObject* recorder_deref(RecorderObject* self, int64_t nr) {
  auto it = self->wrefs->find(nr);
  return it == self->wrefs->end() ? nullptr : deref_or_null(it->second);
}

// note_op(op_nr, node, dep_nodes, write_keys) -> bool
// False (no side effects) when a dependency is unknown to this recorder —
// a cross-tape edge; the caller downgrades the tape to the Python path.
//
// Besides the numeric graph, this also appends the new node to each
// earlier writer's PYTHON `dependents` list, exactly like the Python
// note_write: those strong refs are the keep-alive contract (a later
// in-place op on a view stays reachable from the base's producing node)
// AND what cross-tape Python traversals navigate.
PyObject* Recorder_note_op(RecorderObject* self, PyObject* args) {
  long long op_nr;
  PyObject *node, *dep_nodes, *write_keys;
  if (!PyArg_ParseTuple(args, "LOOO", &op_nr, &node, &dep_nodes, &write_keys))
    return nullptr;
  if (!PyList_Check(dep_nodes) || !PyList_Check(write_keys)) {
    PyErr_SetString(PyExc_TypeError, "dep_nodes/write_keys must be lists");
    return nullptr;
  }
  std::vector<int64_t> dep_nrs;
  Py_ssize_t nd = PyList_GET_SIZE(dep_nodes);
  dep_nrs.reserve(nd);
  for (Py_ssize_t i = 0; i < nd; i++) {
    PyObject* nr_obj =
        PyObject_GetAttrString(PyList_GET_ITEM(dep_nodes, i), "op_nr");
    if (!nr_obj) return nullptr;
    long long nr = PyLong_AsLongLong(nr_obj);
    Py_DECREF(nr_obj);
    if (nr == -1 && PyErr_Occurred()) return nullptr;
    if (!tdx_graph_has_node(self->graph, nr))
      Py_RETURN_FALSE;  // cross-tape dependency
    dep_nrs.push_back(nr);
  }
  // Validate/convert EVERYTHING fallible-by-input before the first graph
  // mutation: a mid-loop failure after add_node would leave the node
  // partially recorded, desyncing the native graph from the Python tape
  // (and double-linking dependents on a later downgrade).
  Py_ssize_t nw = PyList_GET_SIZE(write_keys);
  std::vector<uint64_t> wkeys;
  wkeys.reserve(nw);
  for (Py_ssize_t i = 0; i < nw; i++) {
    uint64_t key =
        PyLong_AsUnsignedLongLongMask(PyList_GET_ITEM(write_keys, i));
    if (PyErr_Occurred()) return nullptr;
    wkeys.push_back(key);
  }
  // Still read-only: resolve every prior writer's `dependents` list now
  // (writers_of is unaffected by this op's own note_write — op_nr is
  // skipped below — so pre-computing sees the same writer sets).  After
  // this loop the only fallible step left is PyList_Append on a
  // validated list, i.e. OOM.
  std::vector<PyObject*> deplists;  // borrowed-into-owned, decref'd below
  std::vector<int64_t> prev;
  bool fail = false;
  for (uint64_t key : wkeys) {
    int64_t n = tdx_graph_writers_of(self->graph, key, nullptr, 0);
    prev.resize((size_t)n);
    tdx_graph_writers_of(self->graph, key, prev.data(), n);
    for (int64_t p : prev) {
      if (p == op_nr) continue;
      PyObject* prev_obj = recorder_deref(self, p);
      if (!prev_obj) continue;  // dead toucher: same skip as Python
      PyObject* deplist = PyObject_GetAttrString(prev_obj, "dependents");
      if (!deplist || !PyList_Check(deplist)) {
        if (deplist) {
          Py_DECREF(deplist);
          PyErr_SetString(PyExc_TypeError, "dependents must be a list");
        }
        fail = true;
        break;
      }
      deplists.push_back(deplist);
    }
    if (fail) break;
  }
  if (fail) {
    for (PyObject* dl : deplists) Py_DECREF(dl);
    return nullptr;
  }
  PyObject* wref = PyWeakref_NewRef(node, nullptr);
  if (!wref) {
    for (PyObject* dl : deplists) Py_DECREF(dl);
    return nullptr;
  }
  // Mutation phase — nothing below here returns an error (the appends'
  // only failure mode is OOM, accepted: the graph itself is complete by
  // then, and Python's fallback re-note never runs unless we error).
  tdx_graph_add_node(self->graph, op_nr);
  (*self->wrefs)[op_nr] = wref;
  for (int64_t d : dep_nrs) tdx_graph_add_dep(self->graph, op_nr, d);
  for (uint64_t key : wkeys) tdx_graph_note_write(self->graph, op_nr, key);
  for (PyObject* dl : deplists) {
    if (PyList_Append(dl, node) < 0) {  // OOM only
      PyErr_Clear();
      self->dropped_appends++;
    }
    Py_DECREF(dl);
  }
  Py_RETURN_TRUE;
}

// call_stack(op_nr) -> [OpNode, ...] chronological — tdx_graph's
// buildCallStack traversal mapped back to Python nodes.
PyObject* Recorder_call_stack(RecorderObject* self, PyObject* arg) {
  long long target = PyLong_AsLongLong(arg);
  if (target == -1 && PyErr_Occurred()) return nullptr;
  int64_t cap = tdx_graph_num_nodes(self->graph);
  std::vector<int64_t> buf((size_t)cap);
  int64_t n = tdx_graph_call_stack(self->graph, target, buf.data(), cap);
  if (n < 0) {
    PyErr_Format(PyExc_KeyError, "unknown op_nr %lld", target);
    return nullptr;
  }
  PyObject* out = PyList_New((Py_ssize_t)n);
  if (!out) return nullptr;
  for (int64_t i = 0; i < n; i++) {
    PyObject* obj = recorder_deref(self, buf[(size_t)i]);
    if (!obj) {
      // Unreachable by construction (schedule members are strongly
      // reachable from the target); fail loudly rather than truncate.
      Py_DECREF(out);
      PyErr_Format(PyExc_RuntimeError, "node %lld died",
                   (long long)buf[(size_t)i]);
      return nullptr;
    }
    Py_INCREF(obj);
    PyList_SET_ITEM(out, (Py_ssize_t)i, obj);
  }
  return out;
}

// downgrade() -> {storage_key: [OpNode, ...]}: hand the graph back to the
// Python path (cross-tape dependency appeared).  The Python `dependents`
// lists were maintained all along (note_op), so only membership needs
// clearing and the writer index exporting — future Python note_write calls
// must still see the native-era writers.
PyObject* Recorder_downgrade(RecorderObject* self, PyObject*) {
  for (auto& [nr, wref] : *self->wrefs) {
    PyObject* obj = deref_or_null(wref);
    if (!obj) continue;
    if (PyObject_SetAttrString(obj, "native_graph", Py_None) < 0)
      return nullptr;
  }
  int64_t nk = tdx_graph_writer_keys(self->graph, nullptr, 0);
  std::vector<uint64_t> keys((size_t)nk);
  tdx_graph_writer_keys(self->graph, keys.data(), nk);
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  std::vector<int64_t> nrs;
  for (uint64_t key : keys) {
    int64_t n = tdx_graph_writers_of(self->graph, key, nullptr, 0);
    nrs.resize((size_t)n);
    tdx_graph_writers_of(self->graph, key, nrs.data(), n);
    PyObject* lst = PyList_New(0);
    if (!lst) {
      Py_DECREF(out);
      return nullptr;
    }
    for (int64_t nr : nrs) {
      PyObject* obj = recorder_deref(self, nr);
      if (obj && PyList_Append(lst, obj) < 0) {
        Py_DECREF(lst);
        Py_DECREF(out);
        return nullptr;
      }
    }
    PyObject* key_obj = PyLong_FromUnsignedLongLong(key);
    int rc = key_obj ? PyDict_SetItem(out, key_obj, lst) : -1;
    Py_XDECREF(key_obj);
    Py_DECREF(lst);
    if (rc < 0) {
      Py_DECREF(out);
      return nullptr;
    }
  }
  return out;
}

Py_ssize_t Recorder_len(PyObject* self) {
  return (Py_ssize_t)tdx_graph_num_nodes(((RecorderObject*)self)->graph);
}

PyMethodDef Recorder_methods[] = {
    {"note_op", (PyCFunction)Recorder_note_op, METH_VARARGS,
     "note_op(op_nr, node, dep_nodes, write_keys) -> bool"},
    {"call_stack", (PyCFunction)Recorder_call_stack, METH_O,
     "call_stack(op_nr) -> [OpNode, ...]"},
    {"downgrade", (PyCFunction)Recorder_downgrade, METH_NOARGS,
     "downgrade() -> {storage_key: [OpNode, ...]}"},
    {nullptr, nullptr, 0, nullptr},
};

PySequenceMethods Recorder_as_sequence = {
    Recorder_len,  /* sq_length */
};

PyMemberDef Recorder_members[] = {
    {"dropped_appends", Py_T_LONGLONG,
     offsetof(RecorderObject, dropped_appends), Py_READONLY,
     "keep-alive appends dropped on OOM (nonzero => degraded tape)"},
    {nullptr, 0, 0, 0, nullptr},
};

PyTypeObject RecorderType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "_tdx_stack.Recorder",               /* tp_name */
    sizeof(RecorderObject),              /* tp_basicsize */
    0,                                   /* tp_itemsize */
    (destructor)Recorder_dealloc,        /* tp_dealloc */
    0, nullptr, nullptr, nullptr,        /* vectorcall/getattr/setattr/as_async */
    nullptr,                             /* tp_repr */
    nullptr, &Recorder_as_sequence, nullptr, /* number/sequence/mapping */
    nullptr, nullptr, nullptr,           /* hash/call/str */
    nullptr, nullptr, nullptr,           /* getattro/setattro/as_buffer */
    Py_TPFLAGS_DEFAULT,                  /* tp_flags */
    "Per-tape native op graph (writer index + edges + weak registry)",
    nullptr, nullptr,                    /* tp_traverse/tp_clear */
};

// ---------------------------------------------------------------------------
// record_preserve: the argument-preservation walk (copyStack,
// deferred_init.cc:69-100 + the immutability validation of 227-253) fully
// in C.  Fake tensors become OutputRef edges (their producing nodes
// collected as dependencies), real tensors get version-guard snapshots,
// immutable scalars pass through; anything else raises Fallback and the
// caller retries with the pytree deep-copy path.

struct PreserveCtx {
  PyObject* fake_type;
  PyObject* slot_key;
  PyObject* guard_type;
  PyObject* deps;    // list of producing OpNodes
  PyObject* guards;  // list of ExternalTensorGuard
};

PyObject* preserve_leaf(PyObject* obj, PreserveCtx* ctx, int* changed) {
  int is_fake = PyObject_IsInstance(obj, ctx->fake_type);
  if (is_fake < 0) return nullptr;
  if (is_fake) {
    PyObject* slots = PyObject_GetAttrString(obj, "_slots");
    if (!slots) return nullptr;
    PyObject* rec = PyDict_GetItemWithError(slots, ctx->slot_key);  // borrowed
    Py_DECREF(slots);
    if (!rec) {
      if (!PyErr_Occurred())
        PyErr_SetString(
            PyExc_RuntimeError,
            "Cannot record an operation on a fake tensor that was created "
            "outside of a deferred-init context.");
      return nullptr;
    }
    PyObject* node = PyObject_GetAttrString(rec, "node");
    if (!node) return nullptr;
    PyObject* index = PyObject_GetAttrString(rec, "index");
    if (!index) {
      Py_DECREF(node);
      return nullptr;
    }
    Py_ssize_t idx = PyLong_AsSsize_t(index);
    Py_DECREF(index);
    if (idx == -1 && PyErr_Occurred()) {
      Py_DECREF(node);
      return nullptr;
    }
    int rc = PyList_Append(ctx->deps, node);
    PyObject* oref = rc < 0 ? nullptr : outputref_new_fast(node, idx);
    Py_DECREF(node);
    if (oref) *changed = 1;
    return oref;
  }
  int is_tensor = PyObject_IsInstance(obj, g_tensor_type);
  if (is_tensor < 0) return nullptr;
  if (is_tensor) {
    PyObject* version = PyObject_GetAttrString(obj, "_version");
    if (!version) return nullptr;
    PyObject* guard =
        PyObject_CallFunctionObjArgs(ctx->guard_type, obj, version, nullptr);
    Py_DECREF(version);
    if (!guard) return nullptr;
    int rc = PyList_Append(ctx->guards, guard);
    Py_DECREF(guard);
    if (rc < 0) return nullptr;
    Py_INCREF(obj);
    return obj;
  }
  // The known-immutable leaf domain (deferred_init.cc:227-253).
  if (!(obj == Py_None || PyBool_Check(obj) || PyLong_CheckExact(obj) ||
        PyFloat_CheckExact(obj) || PyUnicode_CheckExact(obj) ||
        PyBytes_CheckExact(obj) || PyComplex_CheckExact(obj))) {
    int ok = PyObject_IsInstance(obj, g_ok_types);
    if (ok < 0) return nullptr;
    if (!ok) {
      PyErr_SetString(g_fallback, "leaf outside immutable domain");
      return nullptr;
    }
  }
  Py_INCREF(obj);
  return obj;
}

PyObject* preserve_rec(PyObject* obj, PreserveCtx* ctx, int* changed) {
  if (PyTuple_Check(obj)) {
    if (!PyTuple_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "tuple subclass");
      return nullptr;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    PyObject* items = PyList_New(n);
    if (!items) return nullptr;
    int any = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = preserve_rec(PyTuple_GET_ITEM(obj, i), ctx, &c);
      if (!r) {
        Py_DECREF(items);
        return nullptr;
      }
      any |= c;
      PyList_SET_ITEM(items, i, r);
    }
    if (!any) {
      Py_DECREF(items);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    PyObject* out = PyList_AsTuple(items);
    Py_DECREF(items);
    return out;
  }
  if (PyList_Check(obj)) {
    if (!PyList_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "list subclass");
      return nullptr;
    }
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = preserve_rec(PyList_GET_ITEM(obj, i), ctx, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      *changed |= c;
      PyList_SET_ITEM(out, i, r);
    }
    *changed = 1;  // fresh list either way (arg stacks are never shared)
    return out;
  }
  if (PyDict_Check(obj)) {
    if (!PyDict_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "dict subclass");
      return nullptr;
    }
    PyObject* out = PyDict_New();
    if (!out) return nullptr;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      int c = 0;
      PyObject* r = preserve_rec(value, ctx, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      *changed |= c;
      int rc = PyDict_SetItem(out, key, r);
      Py_DECREF(r);
      if (rc < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    }
    *changed = 1;
    return out;
  }
  return preserve_leaf(obj, ctx, changed);
}

PyObject* py_record_preserve(PyObject*, PyObject* args) {
  PyObject *in_args, *in_kwargs, *fake_type, *slot_key, *guard_type;
  if (!PyArg_ParseTuple(args, "OOOOO", &in_args, &in_kwargs, &fake_type,
                        &slot_key, &guard_type))
    return nullptr;
  if (!g_tensor_type) {
    PyErr_SetString(PyExc_RuntimeError, "register_types() not called");
    return nullptr;
  }
  PreserveCtx ctx{fake_type, slot_key, guard_type, PyList_New(0),
                  PyList_New(0)};
  if (!ctx.deps || !ctx.guards) {
    Py_XDECREF(ctx.deps);
    Py_XDECREF(ctx.guards);
    return nullptr;
  }
  int changed = 0;
  PyObject* p_args = preserve_rec(in_args, &ctx, &changed);
  PyObject* p_kwargs = p_args ? preserve_rec(in_kwargs, &ctx, &changed) : nullptr;
  if (!p_kwargs) {
    Py_XDECREF(p_args);
    Py_DECREF(ctx.deps);
    Py_DECREF(ctx.guards);
    return nullptr;
  }
  PyObject* out = PyTuple_Pack(4, p_args, p_kwargs, ctx.deps, ctx.guards);
  Py_DECREF(p_args);
  Py_DECREF(p_kwargs);
  Py_DECREF(ctx.deps);
  Py_DECREF(ctx.guards);
  return out;
}

PyMethodDef methods[] = {
    {"register_types", py_register_types, METH_VARARGS,
     "register_types(tensor_type, ok_types_tuple)"},
    {"leaves", py_leaves, METH_O, "leaves(obj) -> list"},
    {"convert", py_convert, METH_VARARGS,
     "convert(obj, fn, strict=False) -> mapped obj"},
    {"record_preserve", py_record_preserve, METH_VARARGS,
     "record_preserve(args, kwargs, fake_type, slot_key, guard_type) -> "
     "(p_args, p_kwargs, dep_nodes, guards)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_tdx_stack",
    "Native stack/pytree utilities (stack_utils.cc analog)", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__tdx_stack(void) {
  OutputRefType.tp_new = OutputRef_tp_new;
  OutputRefType.tp_members = OutputRef_members;
  RecorderType.tp_new = Recorder_tp_new;
  RecorderType.tp_methods = Recorder_methods;
  RecorderType.tp_members = Recorder_members;
  if (PyType_Ready(&OutputRefType) < 0 || PyType_Ready(&RecorderType) < 0)
    return nullptr;
  PyObject* m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  g_fallback = PyErr_NewException("_tdx_stack.Fallback", nullptr, nullptr);
  if (!g_fallback || PyModule_AddObject(m, "Fallback", g_fallback) < 0) {
    Py_XDECREF(g_fallback);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(g_fallback);  // module owns one ref; keep ours for raising
  Py_INCREF(&OutputRefType);
  if (PyModule_AddObject(m, "OutputRef", (PyObject*)&OutputRefType) < 0) {
    Py_DECREF(&OutputRefType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&RecorderType);
  if (PyModule_AddObject(m, "Recorder", (PyObject*)&RecorderType) < 0) {
    Py_DECREF(&RecorderType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
