// Native stack/pytree utilities — the hot-path analog of the reference's
// stack_utils (/root/reference/src/cc/torchdistx/stack_utils.cc:23-58):
// iterate / convert every tensor in a boxed call frame, descending into
// containers.  The Python-side pytree (torch.utils._pytree.tree_map) costs
// ~half of fake-construction time at GPT-2-XL scale (profiled: ~1.0s of
// 1.98s for 1743 recorded ops); this module does the container recursion in
// C and calls back into Python only for actual tensor leaves (typically 0-3
// per op).
//
// Exposed as a CPython extension module `_tdx_stack` (the environment has no
// pybind11; the CPython API is the binding layer, same role as the
// reference's `_C` module).
//
//   register_types(tensor_type, ok_types_tuple)
//   leaves(obj) -> list            flatten tuple/list/dict-values, any depth
//   convert(obj, fn, strict) -> obj'   copy-on-write map of fn over tensor
//                                      leaves; `strict` raises Fallback for
//                                      leaves outside the known-immutable set
//                                      (callers fall back to pytree — the
//                                      immutability validation analog of
//                                      deferred_init.cc:227-253)
//
// Exotic containers (namedtuples, torch.return_types struct sequences, dict
// subclasses) raise Fallback; callers keep the pytree path for those.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

PyObject* g_tensor_type = nullptr;  // torch.Tensor
PyObject* g_ok_types = nullptr;     // tuple of immutable leaf types
PyObject* g_fallback = nullptr;     // _tdx_stack.Fallback exception

int collect_leaves(PyObject* obj, PyObject* out_list) {
  if (PyTuple_Check(obj)) {
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (collect_leaves(PyTuple_GET_ITEM(obj, i), out_list) < 0) return -1;
    }
    return 0;
  }
  if (PyList_Check(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (collect_leaves(PyList_GET_ITEM(obj, i), out_list) < 0) return -1;
    }
    return 0;
  }
  if (PyDict_Check(obj)) {
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (collect_leaves(value, out_list) < 0) return -1;
    }
    return 0;
  }
  return PyList_Append(out_list, obj);
}

// Returns a NEW reference, or nullptr with an exception set.  Sets *changed
// when the returned object differs from obj.
PyObject* convert_rec(PyObject* obj, PyObject* fn, int strict, int* changed) {
  if (PyTuple_Check(obj)) {
    if (!PyTuple_CheckExact(obj)) {  // namedtuple / torch.return_types
      PyErr_SetString(g_fallback, "tuple subclass");
      return nullptr;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    PyObject* items = PyList_New(n);
    if (!items) return nullptr;
    int any = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = convert_rec(PyTuple_GET_ITEM(obj, i), fn, strict, &c);
      if (!r) {
        Py_DECREF(items);
        return nullptr;
      }
      any |= c;
      PyList_SET_ITEM(items, i, r);  // steals
    }
    if (!any) {
      Py_DECREF(items);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    PyObject* out = PyList_AsTuple(items);
    Py_DECREF(items);
    return out;
  }
  if (PyList_Check(obj)) {
    if (!PyList_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "list subclass");
      return nullptr;
    }
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return nullptr;
    int any = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      int c = 0;
      PyObject* r = convert_rec(PyList_GET_ITEM(obj, i), fn, strict, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      any |= c;
      PyList_SET_ITEM(out, i, r);  // steals
    }
    if (!any) {
      Py_DECREF(out);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    return out;
  }
  if (PyDict_Check(obj)) {
    if (!PyDict_CheckExact(obj)) {
      PyErr_SetString(g_fallback, "dict subclass");
      return nullptr;
    }
    PyObject* out = PyDict_New();
    if (!out) return nullptr;
    int any = 0;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      int c = 0;
      PyObject* r = convert_rec(value, fn, strict, &c);
      if (!r) {
        Py_DECREF(out);
        return nullptr;
      }
      any |= c;
      int rc = PyDict_SetItem(out, key, r);
      Py_DECREF(r);
      if (rc < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    }
    if (!any) {
      Py_DECREF(out);
      Py_INCREF(obj);
      return obj;
    }
    *changed = 1;
    return out;
  }

  // Leaf.
  int is_tensor = PyObject_IsInstance(obj, g_tensor_type);
  if (is_tensor < 0) return nullptr;
  if (is_tensor) {
    PyObject* r = PyObject_CallOneArg(fn, obj);
    if (r && r != obj) *changed = 1;
    return r;
  }
  if (strict) {
    // The known-immutable leaf domain (deferred_init.cc:227-253's
    // validation): exact scalar types plus the registered torch value types.
    if (!(obj == Py_None || PyBool_Check(obj) || PyLong_CheckExact(obj) ||
          PyFloat_CheckExact(obj) || PyUnicode_CheckExact(obj) ||
          PyBytes_CheckExact(obj) || PyComplex_CheckExact(obj))) {
      int ok = PyObject_IsInstance(obj, g_ok_types);
      if (ok < 0) return nullptr;
      if (!ok) {
        PyErr_SetString(g_fallback, "leaf outside immutable domain");
        return nullptr;
      }
    }
  }
  Py_INCREF(obj);
  return obj;
}

PyObject* py_register_types(PyObject*, PyObject* args) {
  PyObject *tensor_type, *ok_types;
  if (!PyArg_ParseTuple(args, "OO", &tensor_type, &ok_types)) return nullptr;
  if (!PyTuple_Check(ok_types)) {
    PyErr_SetString(PyExc_TypeError, "ok_types must be a tuple of types");
    return nullptr;
  }
  Py_XDECREF(g_tensor_type);
  Py_XDECREF(g_ok_types);
  Py_INCREF(tensor_type);
  Py_INCREF(ok_types);
  g_tensor_type = tensor_type;
  g_ok_types = ok_types;
  Py_RETURN_NONE;
}

PyObject* py_leaves(PyObject*, PyObject* obj) {
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  if (collect_leaves(obj, out) < 0) {
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

PyObject* py_convert(PyObject*, PyObject* args) {
  PyObject *obj, *fn;
  int strict = 0;
  if (!PyArg_ParseTuple(args, "OO|p", &obj, &fn, &strict)) return nullptr;
  if (!g_tensor_type) {
    PyErr_SetString(PyExc_RuntimeError, "register_types() not called");
    return nullptr;
  }
  int changed = 0;
  return convert_rec(obj, fn, strict, &changed);
}

PyMethodDef methods[] = {
    {"register_types", py_register_types, METH_VARARGS,
     "register_types(tensor_type, ok_types_tuple)"},
    {"leaves", py_leaves, METH_O, "leaves(obj) -> list"},
    {"convert", py_convert, METH_VARARGS,
     "convert(obj, fn, strict=False) -> mapped obj"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_tdx_stack",
    "Native stack/pytree utilities (stack_utils.cc analog)", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__tdx_stack(void) {
  PyObject* m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  g_fallback = PyErr_NewException("_tdx_stack.Fallback", nullptr, nullptr);
  if (!g_fallback || PyModule_AddObject(m, "Fallback", g_fallback) < 0) {
    Py_XDECREF(g_fallback);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(g_fallback);  // module owns one ref; keep ours for raising
  return m;
}
