// See graph.h. Semantics mirror torchdistx_tpu/_tape.py exactly (the Python
// implementation is the executable spec; tests/test_native_tape.py asserts
// both paths produce identical schedules).

#include "graph.h"

#include <algorithm>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
  int64_t op_nr;
  std::vector<int64_t> deps;        // producer op_nrs (argument edges)
  std::vector<int64_t> dependents;  // later writers of aliased storages
};

}  // namespace

struct tdx_graph {
  std::unordered_map<int64_t, Node> nodes;
  // storage key -> op_nrs that wrote it, in record order.
  std::unordered_map<uint64_t, std::vector<int64_t>> writers;
};

extern "C" {

tdx_graph* tdx_graph_new() { return new tdx_graph(); }

void tdx_graph_free(tdx_graph* g) { delete g; }

int tdx_graph_add_node(tdx_graph* g, int64_t op_nr) {
  auto [it, inserted] = g->nodes.try_emplace(op_nr);
  if (!inserted) return -1;
  it->second.op_nr = op_nr;
  return 0;
}

int tdx_graph_add_dep(tdx_graph* g, int64_t op_nr, int64_t producer_op_nr) {
  auto it = g->nodes.find(op_nr);
  if (it == g->nodes.end() || g->nodes.find(producer_op_nr) == g->nodes.end())
    return -1;
  it->second.deps.push_back(producer_op_nr);
  return 0;
}

int tdx_graph_note_write(tdx_graph* g, int64_t op_nr, uint64_t storage_key) {
  return tdx_graph_note_write_prev(g, op_nr, storage_key, nullptr, 0) < 0 ? -1
                                                                          : 0;
}

int64_t tdx_graph_note_write_prev(tdx_graph* g, int64_t op_nr,
                                  uint64_t storage_key, int64_t* out_prev,
                                  int64_t cap) {
  auto it = g->nodes.find(op_nr);
  if (it == g->nodes.end()) return -1;
  auto& entries = g->writers[storage_key];
  int64_t n_prev = 0;
  for (int64_t prev_nr : entries) {
    if (prev_nr == op_nr) continue;
    auto prev = g->nodes.find(prev_nr);
    if (prev != g->nodes.end()) {
      prev->second.dependents.push_back(op_nr);
      if (n_prev < cap) out_prev[n_prev] = prev_nr;
      n_prev++;
    }
  }
  entries.push_back(op_nr);
  return n_prev;
}

int64_t tdx_graph_num_nodes(const tdx_graph* g) {
  return static_cast<int64_t>(g->nodes.size());
}

int tdx_graph_has_node(const tdx_graph* g, int64_t op_nr) {
  return g->nodes.count(op_nr) ? 1 : 0;
}

int64_t tdx_graph_writer_keys(const tdx_graph* g, uint64_t* out, int64_t cap) {
  int64_t n = 0;
  for (const auto& [key, entries] : g->writers) {
    (void)entries;
    if (n < cap) out[n] = key;
    n++;
  }
  return n;
}

int64_t tdx_graph_writers_of(const tdx_graph* g, uint64_t storage_key,
                             int64_t* out, int64_t cap) {
  auto it = g->writers.find(storage_key);
  if (it == g->writers.end()) return 0;
  int64_t n = static_cast<int64_t>(it->second.size());
  for (int64_t i = 0; i < std::min(n, cap); ++i) out[i] = it->second[i];
  return n;
}

int64_t tdx_graph_call_stack(const tdx_graph* g, int64_t target_op_nr,
                             int64_t* out, int64_t cap) {
  auto target = g->nodes.find(target_op_nr);
  if (target == g->nodes.end()) return -1;

  // Horizon: the last in-place op touching the target's storages
  // (getLastInPlaceOpNode, deferred_init.cc:540-578).
  int64_t horizon = target_op_nr;
  for (int64_t d : target->second.dependents) horizon = std::max(horizon, d);

  // Transitive closure over deps + in-horizon dependents
  // (collectCallStack, deferred_init.cc:580-621).
  std::unordered_set<int64_t> seen;
  std::vector<int64_t> work{target_op_nr};
  std::vector<int64_t> result;
  while (!work.empty()) {
    int64_t nr = work.back();
    work.pop_back();
    if (!seen.insert(nr).second) continue;
    result.push_back(nr);
    const Node& node = g->nodes.at(nr);
    for (int64_t d : node.deps) work.push_back(d);
    for (int64_t d : node.dependents)
      if (d <= horizon) work.push_back(d);
  }
  std::sort(result.begin(), result.end());

  int64_t n = static_cast<int64_t>(result.size());
  for (int64_t i = 0; i < std::min(n, cap); ++i) out[i] = result[i];
  return n;
}

}  // extern "C"
