// TSan stress harness for the native graph (scripts/tsan_native.sh).
//
// The reference ships tsan wheels through CI (/root/reference/cmake/
// Helpers.cmake:287-316, .github/workflows/_test_wheel.yaml:49-89).  Here
// the Python interpreter would drown TSan in interpreter-internal reports,
// so the lane drives the C++ core directly under the SAME threading
// contract the bindings provide:
//
//  * graph mutation (add_node/add_dep/note_write) is serialized — in-process
//    that's the GIL; here an explicit mutex plays its role;
//  * call-stack traversals may run CONCURRENTLY from many threads once
//    recording has quiesced (materialize from worker threads), and also
//    interleave with serialized mutations of a DIFFERENT tape's graph.
//
// Any data race visible under this contract is a real bug in tdx_core.

// Asserts carry the graph construction AND the oracles — a Release/-DNDEBUG
// build would silently delete both and "pass" vacuously.
#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <thread>
#include <vector>

#include "graph.h"

namespace {

// Build a chain-with-aliasing tape: node i depends on i-1, every 8th node
// rewrites storage (i % 4) so dependents edges exist.
tdx_graph* build_graph(int n) {
  tdx_graph* g = tdx_graph_new();
  for (int i = 0; i < n; i++) {
    assert(tdx_graph_add_node(g, i) == 0);
    if (i > 0) assert(tdx_graph_add_dep(g, i, i - 1) == 0);
    assert(tdx_graph_note_write(g, i, 0x1000 + (i % 4)) == 0);
  }
  return g;
}

}  // namespace

int main() {
  constexpr int kNodes = 512;
  constexpr int kThreads = 8;
  constexpr int kIters = 200;

  // Phase 1: concurrent read-only traversals over a finished tape.
  tdx_graph* frozen = build_graph(kNodes);
  std::vector<std::thread> readers;
  std::vector<int64_t> sums(kThreads, 0);
  for (int t = 0; t < kThreads; t++) {
    readers.emplace_back([&, t] {
      std::vector<int64_t> buf(kNodes);
      for (int it = 0; it < kIters; it++) {
        int64_t target = (t * 37 + it * 11) % kNodes;
        int64_t n = tdx_graph_call_stack(frozen, target, buf.data(),
                                         (int64_t)buf.size());
        assert(n > 0 && n <= kNodes);
        for (int64_t i = 0; i < n; i++) sums[t] += buf[i];
      }
    });
  }

  // Phase 2 (concurrently): a second tape being recorded under the
  // serialization lock while the readers above traverse the frozen one.
  tdx_graph* live = tdx_graph_new();
  std::mutex gil;
  std::vector<std::thread> writers;
  for (int t = 0; t < 4; t++) {
    writers.emplace_back([&, t] {
      for (int i = 0; i < kNodes / 4; i++) {
        std::lock_guard<std::mutex> lock(gil);
        int64_t nr = (int64_t)t * 1000 + i;
        tdx_graph_add_node(live, nr);
        tdx_graph_note_write(live, nr, 0x2000 + (nr % 8));
      }
    });
  }
  for (auto& th : writers) th.join();
  for (auto& th : readers) th.join();

  // Phase 3: readers over the now-quiesced second tape.
  {
    std::vector<std::thread> post;
    for (int t = 0; t < kThreads; t++) {
      post.emplace_back([&, t] {
        std::vector<int64_t> buf(kNodes);
        for (int it = 0; it < kIters; it++) {
          int64_t n = tdx_graph_call_stack(live, (int64_t)(t % 4) * 1000,
                                           buf.data(), (int64_t)buf.size());
          assert(n > 0);
        }
      });
    }
    for (auto& th : post) th.join();
  }

  int64_t total = 0;
  for (int64_t s : sums) total += s;
  std::printf("graph_stress: OK (checksum %lld)\n", (long long)total);
  tdx_graph_free(frozen);
  tdx_graph_free(live);
  return 0;
}
