// tdx_core: native op-tape graph for torchdistx_tpu.
//
// TPU-native counterpart of the reference's C++ graph machinery
// (/root/reference/src/cc/torchdistx/deferred_init.cc:311-710): chronological
// OpNode graph with dependency edges, a storage->writers alias index
// installing dependent back-edges, and the materialization call-stack builder
// (last-in-place-op horizon search + transitive-closure collection +
// chronological sort, deferred_init.cc:529-621).
//
// The Python layer (torchdistx_tpu/_tape.py) owns op payloads (callables,
// preserved argument stacks); this library owns the *structure* and the
// traversals that dominate materialization scheduling cost on large tapes.
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#pragma once

#include <cstdint>

#if defined(__GNUC__)
#define TDX_API __attribute__((visibility("default")))
#else
#define TDX_API
#endif

extern "C" {

typedef struct tdx_graph tdx_graph;

// Lifecycle -----------------------------------------------------------------
TDX_API tdx_graph* tdx_graph_new();
TDX_API void tdx_graph_free(tdx_graph* g);

// Construction (record time) ------------------------------------------------
// Register a node keyed by its chronological op_nr. Returns 0 on success,
// -1 if the op_nr already exists.
TDX_API int tdx_graph_add_node(tdx_graph* g, int64_t op_nr);

// Add a dependency edge: `op_nr` consumes an output of `producer_op_nr`.
// Returns 0, or -1 if either node is unknown.
TDX_API int tdx_graph_add_dep(tdx_graph* g, int64_t op_nr,
                              int64_t producer_op_nr);

// Note that `op_nr` wrote storage `storage_key`. Installs dependent
// back-edges from every earlier writer of the same storage (the reference's
// dependents_ wiring, deferred_init.cc:397,463-495). Returns 0 or -1.
TDX_API int tdx_graph_note_write(tdx_graph* g, int64_t op_nr,
                                 uint64_t storage_key);

// Like tdx_graph_note_write, additionally writing the op_nrs of the
// PREVIOUS writers/touchers of the storage (the nodes that just received a
// dependent back-edge) into `out_prev` (up to `cap`).  Returns the previous
// writer count, or -1 if the node is unknown.  The Python binding uses this
// to mirror the back-edges into the OpNodes' keep-alive `dependents` lists.
TDX_API int64_t tdx_graph_note_write_prev(tdx_graph* g, int64_t op_nr,
                                          uint64_t storage_key,
                                          int64_t* out_prev, int64_t cap);

// Queries -------------------------------------------------------------------
TDX_API int64_t tdx_graph_num_nodes(const tdx_graph* g);

TDX_API int tdx_graph_has_node(const tdx_graph* g, int64_t op_nr);

// Writer-index export (for downgrading a native tape to the Python path):
// the distinct storage keys, and each key's writer op_nrs in record order.
// Same cap/count convention as tdx_graph_call_stack.
TDX_API int64_t tdx_graph_writer_keys(const tdx_graph* g, uint64_t* out,
                                      int64_t cap);
TDX_API int64_t tdx_graph_writers_of(const tdx_graph* g, uint64_t storage_key,
                                     int64_t* out, int64_t cap);

// Materialization call-stack for `target_op_nr` (deferred_init.cc:529-621):
// horizon = latest dependent writer of the target's storages; closure over
// dependency edges plus dependents within the horizon; chronological order.
// Writes up to `cap` op_nrs into `out`; returns the total count (call with
// cap=0 to size the buffer), or -1 if the target is unknown.
TDX_API int64_t tdx_graph_call_stack(const tdx_graph* g, int64_t target_op_nr,
                                     int64_t* out, int64_t cap);

}  // extern "C"
