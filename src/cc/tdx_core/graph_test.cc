// C++ unit tests for the tdx_graph engine — the tests/cc the reference
// left as a TODO (its root CMakeLists.txt:104-106: "#TODO: Add catch2
// tests"; tests/cc holds only a .gitkeep).  Plain asserts, no framework:
// run by scripts/native_tests.sh and the CI native lanes.
//
// Python-level parity of these semantics is separately asserted against
// the pure-Python executable spec in tests/test_native_tape.py.

#undef NDEBUG
#include <cassert>
#include <cstdio>
#include <vector>

#include "graph.h"

namespace {

std::vector<int64_t> call_stack(tdx_graph* g, int64_t target) {
  int64_t cap = tdx_graph_num_nodes(g);
  std::vector<int64_t> buf((size_t)cap);
  int64_t n = tdx_graph_call_stack(g, target, buf.data(), cap);
  assert(n >= 0);
  buf.resize((size_t)n);
  return buf;
}

void test_dependency_closure() {
  tdx_graph* g = tdx_graph_new();
  for (int64_t i = 0; i < 4; i++) assert(tdx_graph_add_node(g, i) == 0);
  assert(tdx_graph_add_dep(g, 1, 0) == 0);
  assert(tdx_graph_add_dep(g, 2, 1) == 0);
  // 3 independent.
  assert(call_stack(g, 2) == (std::vector<int64_t>{0, 1, 2}));
  assert(call_stack(g, 3) == (std::vector<int64_t>{3}));
  tdx_graph_free(g);
}

void test_inplace_horizon() {
  // t produced by 0; in-place writes at 2 and 5 (each depending on t, as a
  // recorded in-place op references its target through an OutputRef edge).
  // Target 0's horizon is its LAST dependent (5), pulling both writes in.
  tdx_graph* g = tdx_graph_new();
  for (int64_t i = 0; i < 6; i++) assert(tdx_graph_add_node(g, i) == 0);
  assert(tdx_graph_add_dep(g, 2, 0) == 0);
  assert(tdx_graph_add_dep(g, 5, 0) == 0);
  assert(tdx_graph_note_write(g, 0, 0xA) == 0);
  assert(tdx_graph_note_write(g, 2, 0xA) == 0);
  assert(tdx_graph_note_write(g, 5, 0xA) == 0);
  assert(call_stack(g, 0) == (std::vector<int64_t>{0, 2, 5}));
  // Target 2: dep edge pulls 0 in; its own dependent 5 is within horizon.
  assert(call_stack(g, 2) == (std::vector<int64_t>{0, 2, 5}));
  tdx_graph_free(g);
}

void test_horizon_excludes_later_writers_of_other_targets() {
  // A write AFTER the target's last dependent must not join the stack.
  tdx_graph* g = tdx_graph_new();
  for (int64_t i = 0; i < 4; i++) assert(tdx_graph_add_node(g, i) == 0);
  assert(tdx_graph_add_dep(g, 1, 0) == 0);  // 1 reads 0
  assert(tdx_graph_note_write(g, 0, 0xB) == 0);
  assert(tdx_graph_note_write(g, 3, 0xB) == 0);  // later in-place on 0's storage
  // Target 1: horizon is 1 (no dependents of 1); node 3 (nr > 1) excluded.
  assert(call_stack(g, 1) == (std::vector<int64_t>{0, 1}));
  // Target 0: dependent 3 raises the horizon.
  assert(call_stack(g, 0) == (std::vector<int64_t>{0, 3}));
  tdx_graph_free(g);
}

void test_note_write_prev_reports_previous_touchers() {
  tdx_graph* g = tdx_graph_new();
  for (int64_t i = 0; i < 3; i++) assert(tdx_graph_add_node(g, i) == 0);
  int64_t prev[4];
  assert(tdx_graph_note_write_prev(g, 0, 0xC, prev, 4) == 0);
  assert(tdx_graph_note_write_prev(g, 1, 0xC, prev, 4) == 1 && prev[0] == 0);
  int64_t n = tdx_graph_note_write_prev(g, 2, 0xC, prev, 4);
  assert(n == 2 && prev[0] == 0 && prev[1] == 1);
  // cap smaller than count: count still returned, buffer filled to cap.
  assert(tdx_graph_note_write_prev(g, 0, 0xC, prev, 1) == 2);
  tdx_graph_free(g);
}

void test_writer_index_export() {
  tdx_graph* g = tdx_graph_new();
  for (int64_t i = 0; i < 3; i++) assert(tdx_graph_add_node(g, i) == 0);
  assert(tdx_graph_note_write(g, 0, 0xD) == 0);
  assert(tdx_graph_note_write(g, 2, 0xD) == 0);
  assert(tdx_graph_note_write(g, 1, 0xE) == 0);
  uint64_t keys[4];
  assert(tdx_graph_writer_keys(g, keys, 4) == 2);
  int64_t nrs[4];
  assert(tdx_graph_writers_of(g, 0xD, nrs, 4) == 2);
  assert(nrs[0] == 0 && nrs[1] == 2);  // record order
  assert(tdx_graph_writers_of(g, 0xE, nrs, 4) == 1 && nrs[0] == 1);
  assert(tdx_graph_writers_of(g, 0xFF, nrs, 4) == 0);
  tdx_graph_free(g);
}

void test_error_paths() {
  tdx_graph* g = tdx_graph_new();
  assert(tdx_graph_add_node(g, 7) == 0);
  assert(tdx_graph_add_node(g, 7) == -1);  // duplicate
  assert(tdx_graph_add_dep(g, 7, 99) == -1);  // unknown producer
  assert(tdx_graph_note_write(g, 99, 0xF) == -1);  // unknown writer
  int64_t buf[1];
  assert(tdx_graph_call_stack(g, 99, buf, 1) == -1);  // unknown target
  assert(tdx_graph_has_node(g, 7) == 1);
  assert(tdx_graph_has_node(g, 99) == 0);
  tdx_graph_free(g);
}

}  // namespace

int main() {
  test_dependency_closure();
  test_inplace_horizon();
  test_horizon_excludes_later_writers_of_other_targets();
  test_note_write_prev_reports_previous_touchers();
  test_writer_index_export();
  test_error_paths();
  std::printf("graph_test: OK\n");
  return 0;
}
