"""Build the native core into the wheel.

Parity with the reference's packaging story (setup.py +
src/cc/torchdistx/CMakeLists.txt): ``pip install .`` / ``pip wheel .``
produces a wheel whose ``torchdistx_tpu/lib`` contains the compiled native
libraries, so installed environments never need the import-time g++
fallback (which remains for editable/dev checkouts).

Two artifacts (same compile lines as torchdistx_tpu/_native.py):

* ``libtdx_core.so``  — plain C-ABI shared library (op-graph traversals)
* ``_tdx_stack.so``   — CPython extension module (native stack utilities)
"""

import os
import subprocess
import sysconfig

from setuptools import setup
from setuptools.command.build_py import build_py

ROOT = os.path.dirname(os.path.abspath(__file__))
CC_DIR = os.path.join(ROOT, "src", "cc", "tdx_core")


class build_py_with_native(build_py):
    def run(self):
        super().run()
        lib_dir = os.path.join(self.build_lib, "torchdistx_tpu", "lib")
        os.makedirs(lib_dir, exist_ok=True)
        common = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared"]
        subprocess.run(
            common
            + ["-o", os.path.join(lib_dir, "libtdx_core.so"),
               os.path.join(CC_DIR, "graph.cc")],
            check=True,
        )
        include = sysconfig.get_paths()["include"]
        subprocess.run(
            common
            + [f"-I{include}", f"-I{CC_DIR}",
               "-o", os.path.join(lib_dir, "_tdx_stack.so"),
               os.path.join(CC_DIR, "stack.cc"),
               os.path.join(CC_DIR, "graph.cc")],
            check=True,
        )


setup(cmdclass={"build_py": build_py_with_native})
