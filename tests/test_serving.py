"""Serving engine: paged KV cache + continuous batching ≡ solo generate.

The acceptance bar (ISSUE 3): engine output for every request is
token-identical to a solo ``generate()`` call with the same key — greedy
and sampled, under out-of-order admission and mid-stream slot recycling —
and the block allocator never double-assigns or leaks (exhaustion is
backpressure, not a crash).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import gpt2, llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.ops.attention import cached_attention, paged_attention
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    BlockAllocator,
    Engine,
    Health,
    RecoveryFailed,
    blocks_needed,
    init_paged_cache,
    write_prompt,
)

EOS = 5


@pytest.fixture(autouse=True)
def _clean_preemption():
    """Engines consume the process-wide preemption flag (graceful
    drain); keep tests hermetic against leftovers in either direction."""
    preemption.clear()
    yield
    preemption.clear()


@pytest.fixture(scope="module", params=["llama", "gpt2"])
def family(request):
    if request.param == "llama":
        cfg = llama.llama_test()
        model = llama
    else:
        cfg = gpt2.gpt2_test()
        model = gpt2
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    return model, cfg, params


def solo(model, cfg, params, prompt, seed, max_new, *, eos=None,
         temperature=0.0, top_k=None):
    """Reference: solo generate, truncated at first EOS (inclusive) the
    way a finished serving request's token stream is."""
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new,
        temperature=temperature, top_k=top_k, eos_id=eos,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


# Canonical engine geometry shared by most tests below: one decode-chunk
# compile and one prefill bucket per sampling config for the whole module
# (generate/forward_cached compile per static max_new_tokens too, so
# budgets come from a small fixed menu).
# prefix_cache pinned OFF: these suites assert raw page accounting
# (num_in_use == 0 at idle) that predates the cache-on default; the
# cache-on path is covered by the explicit prefix tests and the
# perf-plane lifecycle test.
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    prefix_cache=False,
)


def mixed_requests(rng, cfg, n, budgets=(5, 9, 16)):
    """Out-of-order lengths: prompts and budgets drawn independently
    (budgets from a fixed menu — each distinct budget is a distinct solo
    generate compile)."""
    reqs = []
    for i in range(n):
        plen = int(rng.integers(3, 16))
        mnt = int(rng.choice(budgets))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        reqs.append((prompt, mnt, i))
    return reqs


# ---------------------------------------------------------------------------
# Block allocator


def test_allocator_basics():
    a = BlockAllocator(num_blocks=8, block_size=4)
    assert a.capacity == 7  # page 0 is trash
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.num_in_use == 3
    assert a.alloc(5) is None  # only 4 left: no partial grant
    assert a.num_in_use == 3  # failed alloc took nothing
    a.free(got)
    assert a.num_in_use == 0 and a.num_free == 7


def test_allocator_never_double_assigns():
    a = BlockAllocator(num_blocks=16, block_size=4)
    grants = [a.alloc(3) for _ in range(5)]
    flat = [b for g in grants for b in g]
    assert len(flat) == len(set(flat)) == 15
    assert a.alloc(1) is None  # exhausted → backpressure signal


def test_allocator_double_free_raises():
    a = BlockAllocator(num_blocks=4, block_size=4)
    got = a.alloc(2)
    a.free(got)
    with pytest.raises(RuntimeError, match="not in use"):
        a.free(got)
    with pytest.raises(RuntimeError, match="not in use"):
        a.free([0])  # the trash page is never owned


def test_blocks_needed():
    assert blocks_needed(1, 8) == 1
    assert blocks_needed(8, 8) == 1
    assert blocks_needed(9, 8) == 2


def test_allocator_refcounts_share_free():
    """Prefix-sharing refcounts: share() adds references, free() drops
    one, the page only returns to the free list at zero — and the
    physical accounting (num_in_use, utilization) counts a shared page
    ONCE."""
    a = BlockAllocator(num_blocks=8, block_size=4)
    got = a.alloc(2)
    assert [a.refcount(b) for b in got] == [1, 1]
    a.share(got)  # the index's reference
    a.share([got[0]])  # and a second request's, on one of them
    assert a.refcount(got[0]) == 3 and a.refcount(got[1]) == 2
    # Physical: still 2 pages of HBM, not 5.
    assert a.num_in_use == 2
    assert a.utilization() == pytest.approx(2 / 7)
    a.free(got)  # first owners walk away
    assert a.num_in_use == 2  # pages survive: the index still holds them
    a.free([got[0]])  # second request done
    a.free(got)  # the index lets go of both
    assert a.num_in_use == 0 and a.num_free == 7
    assert a.refcount(got[0]) == 0


def test_allocator_share_and_free_invariants():
    """Stray share (page not in use), double free past zero, and reset
    all behave: raise, raise, forget."""
    a = BlockAllocator(num_blocks=8, block_size=4)
    with pytest.raises(RuntimeError, match="stray share"):
        a.share([3])
    with pytest.raises(RuntimeError, match="stray share"):
        a.share([0])  # the trash page is never shareable
    got = a.alloc(1)
    a.share(got)
    a.free(got)
    a.free(got)
    with pytest.raises(RuntimeError, match="not in use"):
        a.free(got)  # refcount already hit zero: double free
    # A failed multi-page free must not half-apply: validation runs
    # before any reference moves, wherever the bad page sits.
    got2 = a.alloc(1)
    with pytest.raises(RuntimeError, match="not in use"):
        a.free([got2[0], 5])  # page 5 was never allocated
    assert a.refcount(got2[0]) == 1  # untouched by the failed call
    with pytest.raises(RuntimeError, match="not in use"):
        a.free([got2[0], got2[0]])  # two drops of a single reference
    assert a.refcount(got2[0]) == 1
    a.share(got2)
    a.reset()
    assert a.num_in_use == 0 and a.num_free == a.capacity
    assert a.refcount(got2[0]) == 0


# ---------------------------------------------------------------------------
# Paged attention + prompt scatter


def test_paged_attention_matches_cached():
    """Block-table gather + per-slot mask ≡ contiguous cached_attention."""
    key = jax.random.PRNGKey(0)
    b, hq, hkv, d, bs, m = 3, 4, 2, 8, 4, 4
    smax = m * bs
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, 1, hq, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, smax, hkv, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, smax, hkv, d))
    positions = jnp.asarray([5, 11, 2])

    # Lay the same contiguous cache out as pages: slot i's block j is page
    # 1 + i*m + j (page 0 trash, filled with junk to prove masking).
    k_pages = jnp.concatenate(
        [
            jnp.full((1, bs, hkv, d), 7.7, k.dtype),
            k.reshape(b * m, bs, hkv, d),
        ]
    )
    v_pages = jnp.concatenate(
        [
            jnp.full((1, bs, hkv, d), -3.3, v.dtype),
            v.reshape(b * m, bs, hkv, d),
        ]
    )
    tables = 1 + jnp.arange(b * m).reshape(b, m)

    paged = paged_attention(q, k_pages, v_pages, tables, positions)
    for i in range(b):
        ref = cached_attention(
            q[i : i + 1], k[i : i + 1], v[i : i + 1], positions[i]
        )
        np.testing.assert_array_equal(
            np.asarray(paged[i : i + 1]), np.asarray(ref),
            err_msg=f"slot {i}",
        )


def test_write_prompt_scatter_and_trash(family):
    model, cfg, params = family
    bs, nb = 4, 9
    paged = init_paged_cache(model, cfg, nb, bs)
    length, p_pad = 6, 16  # pad tail must land in trash, not real pages
    scratch = model.init_cache(cfg, 1, p_pad)
    tokens = jnp.arange(1, p_pad + 1, dtype=jnp.int32)[None] % cfg.vocab_size
    _, scratch = model.forward_cached(params, tokens, cfg, scratch, 0)
    table = np.zeros((4,), np.int32)
    table[:2] = [3, 7]  # blocks_needed(6, 4) == 2
    paged = write_prompt(paged, scratch, jnp.asarray(table), length,
                         block_size=bs)

    k_pages = np.asarray(paged["k"])
    k_ref = np.asarray(scratch["k"])[:, 0]  # (L, P, H, D)
    np.testing.assert_array_equal(k_pages[:, 3], k_ref[:, 0:4])
    np.testing.assert_array_equal(k_pages[:, 7, :2], k_ref[:, 4:6])
    # Positions >= length went to trash page 0; pages the table never
    # named stayed zero.
    np.testing.assert_array_equal(k_pages[:, 7, 2:], 0 * k_pages[:, 7, 2:])
    for untouched in (1, 2, 4, 5, 6, 8):
        assert not np.any(k_pages[:, untouched])


# ---------------------------------------------------------------------------
# Engine ≡ solo generate


def test_engine_greedy_token_identical(family):
    """2 slots, 6 mixed requests: admission is out-of-order relative to
    completion, every retire recycles a slot mid-stream — and every
    request's tokens equal its solo generate() run."""
    model, cfg, params = family
    rng = np.random.default_rng(0)
    eng = Engine(params, model=model, cfg=cfg, eos_id=EOS, **ENGINE_KW)
    reqs = mixed_requests(rng, cfg, 6)
    handles = [
        eng.submit(p, max_new_tokens=m, key=seed) for p, m, seed in reqs
    ]
    eng.drain()
    for (prompt, mnt, seed), h in zip(reqs, handles):
        assert h.result() == solo(
            model, cfg, params, prompt, seed, mnt, eos=EOS
        ), f"request {seed} (prompt_len={len(prompt)}, max_new={mnt})"
    assert eng.allocator.num_in_use == 0, "blocks leaked after drain"


def test_engine_sampled_token_identical(family):
    model, cfg, params = family
    rng = np.random.default_rng(1)
    eng = Engine(
        params, model=model, cfg=cfg, eos_id=EOS,
        temperature=0.8, top_k=20, **ENGINE_KW,
    )
    reqs = mixed_requests(rng, cfg, 6)
    handles = [
        eng.submit(p, max_new_tokens=m, key=100 + seed)
        for p, m, seed in reqs
    ]
    eng.drain()
    for (prompt, mnt, seed), h in zip(reqs, handles):
        assert h.result() == solo(
            model, cfg, params, prompt, 100 + seed, mnt, eos=EOS,
            temperature=0.8, top_k=20,
        ), f"request {seed}"
    assert eng.allocator.num_in_use == 0


def test_engine_streaming_is_incremental():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    h = eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=12, key=0)
    it = h.tokens()
    first = next(it)
    assert isinstance(first, int)
    assert not h.done, "handle finished before its budget was streamed"
    rest = list(it)
    assert [first] + rest == solo(
        llama, cfg, params, np.arange(1, 9, dtype=np.int32), 0, 12
    )


def test_engine_backpressure_not_crash():
    """A pool sized for ~one request at a time: admission waits, nothing
    crashes, every request completes, nothing leaks."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    before = telemetry.counter("serve.backpressure").value
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=4, block_size=8,
        num_blocks=5, max_model_len=32, decode_chunk=2,
        prefix_cache=False,
    )
    handles = [
        eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8, key=i)
        for i in range(3)
    ]
    eng.drain()
    assert all(len(h.result()) == 8 for h in handles)
    assert telemetry.counter("serve.backpressure").value > before
    assert eng.allocator.num_in_use == 0


def test_engine_rejects_oversized_request():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=1, block_size=8,
        max_model_len=32,
    )
    with pytest.raises(ValueError, match="max_model_len"):
        eng.submit(np.zeros(30, np.int32), max_new_tokens=30)
    eng2 = Engine(
        params, model=llama, cfg=cfg, num_slots=1, block_size=8,
        num_blocks=3, max_model_len=32,
    )
    with pytest.raises(ValueError, match="num_blocks"):
        eng2.submit(np.zeros(20, np.int32), max_new_tokens=10)


def test_engine_fault_nan_skips_and_stays_token_identical():
    """TDX_FAULT serve.step:nan: the poisoned chunk is skipped (counted),
    the engine drains, and — decode being pure — output is unchanged."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    skipped_before = telemetry.counter("serve.skipped_steps").value
    admit_before = telemetry.counter("serve.admit_retries").value
    faults.reset("serve.step:2:nan,serve.admit:2:io")
    try:
        eng = Engine(params, model=llama, cfg=cfg, eos_id=EOS, **ENGINE_KW)
        prompts = [np.arange(1, 7, dtype=np.int32) + i for i in range(3)]
        handles = [
            eng.submit(p, max_new_tokens=9, key=i)
            for i, p in enumerate(prompts)
        ]
        eng.drain()
    finally:
        faults.reset("")
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.result() == solo(llama, cfg, params, p, i, 9, eos=EOS)
    assert telemetry.counter("serve.skipped_steps").value == skipped_before + 1
    assert telemetry.counter("serve.admit_retries").value == admit_before + 1


def test_engine_failed_prefill_frees_reservation_and_retries(monkeypatch):
    """A prefill that raises (compile error, device OOM) must return the
    request's page reservation before anything else happens — otherwise
    a few such failures drive the engine into permanent backpressure —
    and the request goes back to the FIFO head under its recovery
    budget: a persistent failure becomes a typed error, a transient one
    is retried to a token-identical completion."""
    import torchdistx_tpu.serving.engine as eng_mod

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    real = eng_mod._prefill_chunk_last

    def boom(*a, **k):
        raise RuntimeError("injected prefill failure")

    # Persistent failure: every retry frees the reservation, and the
    # budget (max_recoveries=2 → 3 attempts) ends in a typed failure,
    # not a raise out of step() and not a hang.  (A short prompt is one
    # chunk, so _prefill_chunk_last is the whole prefill dispatch.)
    monkeypatch.setattr(eng_mod, "_prefill_chunk_last", boom)
    h = eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8, key=0)
    for _ in range(3):
        eng.step()
        assert eng.allocator.num_in_use == 0, "failed prefill leaked pages"
    assert h.done and isinstance(h.error, RecoveryFailed)
    with pytest.raises(RecoveryFailed):
        h.result()
    assert eng.allocator.num_free == eng.allocator.capacity

    # Transient failure: one boom, then the real prefill — the retried
    # request completes token-identical to solo generate.
    flaky = {"left": 1}

    def boom_once(*a, **k):
        if flaky["left"]:
            flaky["left"] -= 1
            raise RuntimeError("injected prefill failure")
        return real(*a, **k)

    monkeypatch.setattr(eng_mod, "_prefill_chunk_last", boom_once)
    h2 = eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8, key=7)
    eng.drain()
    assert h2.result() == solo(
        llama, cfg, params, np.arange(1, 9, dtype=np.int32), 7, 8
    )
    assert eng.allocator.num_in_use == 0


def test_engine_recovers_lost_donated_cache(monkeypatch):
    """The compiled prefill/decode calls hold the page pool DONATED: a
    failure that consumed the buffers takes every in-flight request's KV
    with it.  The recovery supervisor must rebuild the pool and REPLAY
    the live requests from their committed tokens — the fold_in(key,
    n_gen) sampling schedule makes the continuation token-identical, so
    the device failure is invisible in the token stream."""
    import torchdistx_tpu.serving.engine as eng_mod

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    recoveries_before = telemetry.counter("serve.recoveries").value
    eng = Engine(
        params, model=llama, cfg=cfg, temperature=0.8, top_k=20,
        **ENGINE_KW,
    )
    h1 = eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=16, key=0)
    h2 = eng.submit(np.arange(2, 8, dtype=np.int32), max_new_tokens=9, key=1)
    eng.step()  # both admitted?  (interleave knob admits one per tick)
    eng.step()
    assert not h1.done

    real = eng_mod._decode_chunk

    def consume_and_die(params_, paged, *a, **k):
        for leaf in jax.tree.leaves(paged):
            leaf.delete()  # what a real on-device failure does to a donation
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(eng_mod, "_decode_chunk", consume_and_die)
    eng.step()  # supervised: no raise, pool rebuilt, live slots replayed
    monkeypatch.setattr(eng_mod, "_decode_chunk", real)

    eng.drain()
    assert h1.result() == solo(
        llama, cfg, params, np.arange(1, 9, dtype=np.int32), 0, 16,
        temperature=0.8, top_k=20,
    )
    assert h2.result() == solo(
        llama, cfg, params, np.arange(2, 8, dtype=np.int32), 1, 9,
        temperature=0.8, top_k=20,
    )
    assert telemetry.counter("serve.recoveries").value > recoveries_before
    assert eng.allocator.num_in_use == 0
    assert eng.health() is Health.READY


def test_engine_recovery_budget_exhausts_typed(monkeypatch):
    """A device failure that keeps recurring must not loop forever: each
    recovery event charges the live requests' budgets, and exhaustion is
    a typed RecoveryFailed — engine still servable, nothing leaked."""
    import torchdistx_tpu.serving.engine as eng_mod

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(params, model=llama, cfg=cfg, max_recoveries=1, **ENGINE_KW)
    h = eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8, key=0)
    eng.step()
    assert not h.done

    def die(params_, paged, *a, **k):
        raise RuntimeError("persistent device failure")

    monkeypatch.setattr(eng_mod, "_decode_chunk", die)
    for _ in range(4):
        if h.done:
            break
        eng.step()
    assert h.done and isinstance(h.error, RecoveryFailed)
    assert h.error.retryable
    assert eng.allocator.num_in_use == 0


def test_engine_fault_fatal_propagates():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    faults.reset("serve.step:1:fatal")
    try:
        eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4, key=0)
        with pytest.raises(faults.FatalInjectedFault):
            eng.drain()
    finally:
        faults.reset("")


# ---------------------------------------------------------------------------
# Prefix caching + chunked prefill (ISSUE 7)


def shared_prefix_requests(cfg, sys_len=16, tail_len=5, n=4):
    """n prompts sharing a sys_len-token system prompt, distinct tails."""
    rng = np.random.default_rng(7)
    system = rng.integers(0, cfg.vocab_size, size=sys_len).astype(np.int32)
    return [
        np.concatenate(
            [system, rng.integers(0, cfg.vocab_size, size=tail_len).astype(np.int32)]
        )
        for _ in range(n)
    ]


@pytest.mark.parametrize("sampled", [False, True])
def test_prefix_cache_token_identical(sampled):
    """Requests sharing a system prompt: cache-on output ≡ cache-off
    output ≡ solo generate (greedy AND sampled), the shared pages hit,
    and after every request finishes the only pages still owned are the
    index's own (refcount exactly 1 — zero drift)."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    sample_kw = (
        dict(temperature=0.8, top_k=20) if sampled else {}
    )
    prompts = shared_prefix_requests(cfg)
    results = {}
    for cache_on in (False, True):
        eng = Engine(
            params, model=llama, cfg=cfg, eos_id=EOS,
            **sample_kw, **{**ENGINE_KW, "prefix_cache": cache_on},
        )
        handles = [
            eng.submit(p, max_new_tokens=9, key=200 + i)
            for i, p in enumerate(prompts)
        ]
        eng.drain()
        results[cache_on] = [h.result() for h in handles]
        if cache_on:
            st = eng.stats()
            # 16-token system prompt, 8-token pages: 2 shared pages per
            # follow-up request.
            assert st["prefix_hits"] >= len(prompts) - 1, st
            assert st["prefix_hit_tokens"] >= (len(prompts) - 1) * 16, st
            # Zero refcount drift: every surviving page belongs to the
            # index alone, and releasing the cache releases everything.
            assert eng.prefix.check(eng.allocator) is None
            assert eng.allocator.num_in_use == len(eng.prefix)
            eng.prefix.release(eng.allocator)
        assert eng.allocator.num_in_use == 0
    for i, p in enumerate(prompts):
        ref = solo(
            llama, cfg, params, p, 200 + i, 9, eos=EOS, **sample_kw
        )
        assert results[False][i] == ref, f"cache-off diverged on {i}"
        assert results[True][i] == ref, f"cache-on diverged on {i}"


@pytest.mark.parametrize("sampled", [False, True])
def test_chunked_prefill_token_identical(sampled):
    """A prompt longer than prefill_chunk splits across ticks; chunked
    output ≡ unchunked output ≡ solo generate, greedy and sampled."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=n).astype(np.int32)
        for n in (19, 32, 7)
    ]
    results = {}
    for chunk in (4, 512):  # 4 → up to 8 chunks; 512 → single chunk
        eng = Engine(
            params, model=llama, cfg=cfg, eos_id=EOS, prefill_chunk=chunk,
            min_prefill_bucket=4, **sample_kw, **ENGINE_KW,
        )
        handles = [
            eng.submit(p, max_new_tokens=9, key=300 + i)
            for i, p in enumerate(prompts)
        ]
        eng.drain()
        results[chunk] = [h.result() for h in handles]
        assert eng.allocator.num_in_use == 0
    for i, p in enumerate(prompts):
        ref = solo(llama, cfg, params, p, 300 + i, 9, eos=EOS, **sample_kw)
        assert results[4][i] == ref, f"chunked diverged on prompt {i}"
        assert results[512][i] == ref, f"unchunked diverged on prompt {i}"


def test_chunked_prefill_interleaves_decode():
    """A long prompt admitted mid-load must not freeze the running
    stream: with prefill_chunk=4, every tick of the long prefill still
    runs a decode chunk — the running slot keeps emitting between
    admission and the long prompt's first token."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=2, block_size=8,
        max_model_len=64, decode_chunk=2, prefill_chunk=4,
        min_prefill_bucket=4, prefix_cache=False,
    )
    running = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=40, key=0)
    eng.step()  # running stream admitted and decoding
    emitted_before = len(running._tokens)
    rng = np.random.default_rng(3)
    long = eng.submit(
        rng.integers(0, cfg.vocab_size, size=32).astype(np.int32),
        max_new_tokens=4, key=1,
    )
    progress = []  # running stream's token count at each prefill tick
    while long.ttft_s is None:
        eng.step()
        progress.append(len(running._tokens))
    # The 32-token prompt took several chunked ticks...
    assert len(progress) >= 8, f"expected >= 8 chunk ticks, got {len(progress)}"
    # ...and the running stream advanced on EVERY one of them (2
    # tokens/tick: decode never skipped a beat while the prefill ran).
    assert progress[0] > emitted_before
    assert all(b > a for a, b in zip(progress, progress[1:])), progress
    eng.drain()
    assert running.result() == solo(
        llama, cfg, params, np.arange(1, 7, dtype=np.int32), 0, 40
    )
    assert eng.allocator.num_in_use == 0


def test_cow_divergence_mid_page():
    """Copy-on-write: a block-aligned prompt fully served from cache
    still needs its last token's logits, so the final shared page is
    privatized before the recompute writes mid-page into it.  Two
    sampled streams diverging from the same cached prefix must each
    match their solo run — and the original's cached pages survive
    untouched (a third request still hits them)."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    before = telemetry.counter("serve.cow_copies").value
    eng = Engine(
        params, model=llama, cfg=cfg, eos_id=EOS,
        temperature=0.8, top_k=20,
        **{**ENGINE_KW, "prefix_cache": True},
    )
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, cfg.vocab_size, size=16).astype(np.int32)  # 2 pages exactly
    ha = eng.submit(prompt, max_new_tokens=8, key=400)
    eng.drain()
    # B and C: full-prompt hits on A's pages, then divergent sampling.
    hb = eng.submit(prompt, max_new_tokens=8, key=401)
    hc = eng.submit(prompt, max_new_tokens=8, key=402)
    eng.drain()
    st = eng.stats()
    assert st["prefix_hits"] == 2 and st["cow_copies"] == 2, st
    assert telemetry.counter("serve.cow_copies").value == before + 2
    for h, key in ((ha, 400), (hb, 401), (hc, 402)):
        assert h.result() == solo(
            llama, cfg, params, prompt, key, 8, eos=EOS,
            temperature=0.8, top_k=20,
        ), f"key {key} diverged"
    # The shared pages were never scribbled on: a divergent-tail prompt
    # still matches only the intact first page.
    tail = np.concatenate([prompt[:12], prompt[:9]]).astype(np.int32)
    hd = eng.submit(tail, max_new_tokens=8, key=403)
    eng.drain()
    assert hd.result() == solo(
        llama, cfg, params, tail, 403, 8, eos=EOS,
        temperature=0.8, top_k=20,
    )
    assert eng.stats()["prefix_hits"] == 3  # page 0 hit; divergence mid-page 1 missed
    assert eng.prefix.check(eng.allocator) is None


def test_prefix_eviction_under_pressure():
    """A full index must never stall admission: unreferenced cached
    prefixes evict LRU to make room, so a cache-on engine admits
    everything a cache-off engine would."""
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    # 9 usable pages; each 8-token-prompt request needs 3 (8+16=24/8),
    # so two busy slots leave 3 pages for cached prefixes — the 4th
    # distinct prompt to finish MUST evict someone.
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=2, block_size=8,
        num_blocks=10, max_model_len=64, decode_chunk=4, prefix_cache=True,
    )
    rng = np.random.default_rng(29)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=8).astype(np.int32)
        for _ in range(8)
    ]
    handles = [
        eng.submit(p, max_new_tokens=16, key=500 + i)
        for i, p in enumerate(prompts)
    ]
    eng.drain()
    st = eng.stats()
    # 8 distinct one-page prefixes cached into 9 usable pages alongside
    # live requests (2 slots x 3 pages): the later admissions forced
    # LRU evictions.
    assert st["prefix_evictions"] >= 1, st
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.result() == solo(llama, cfg, params, p, 500 + i, 16)
    assert eng.prefix.check(eng.allocator) is None
    assert eng.allocator.num_in_use == len(eng.prefix)
    eng.close()  # releases the index's pages with the engine
    assert eng.allocator.num_in_use == 0


def test_engine_stats_and_telemetry_spans():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prev = telemetry.configure(collect=True)
    try:
        eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
        for i in range(3):
            eng.submit(
                np.arange(1, 6, dtype=np.int32), max_new_tokens=6, key=i
            )
        eng.drain()
        st = eng.stats()
        assert st["requests"] == 3 and st["running"] == 0
        assert st["decode_tokens_per_s"] > 0
        assert 0 <= st["ttft_p50_s"] <= st["ttft_p95_s"]
        names = {s["name"] for s in telemetry.snapshot()["spans"]}
        assert {"serve.prefill", "serve.step"} <= names
    finally:
        telemetry.configure(**prev)
