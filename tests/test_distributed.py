"""Multi-host runtime: process-group init + hybrid (ICI×DCN) meshes.

Single-host rig: the coordinator rendezvous runs as a real 1-process group
in a subprocess; hybrid meshes assemble over the virtual CPU devices (the
granule-fallback path — real slice_index metadata only exists on TPU pods).
"""

import socket
import subprocess
import sys

import pytest

try:
    import jax
except ImportError:  # torch-only environment
    pytest.skip("jax required", allow_module_level=True)

from torchdistx_tpu.parallel import MeshSpec, make_hybrid_mesh


def test_hybrid_mesh_dcn_major_layout():
    devices = jax.devices()
    assert len(devices) == 8, "test rig expects the 8-device CPU mesh"
    mesh = make_hybrid_mesh(MeshSpec(tp=2), MeshSpec(dp=4), devices=devices)
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}
    # DCN-major: each dp row is one granule (contiguous slice of the flat
    # device list on the virtual rig); tp varies within it.
    arr = mesh.devices
    for i in range(4):
        assert list(arr[i]) == devices[2 * i : 2 * i + 2]


def test_hybrid_mesh_axis_factor_merge():
    devices = jax.devices()
    # fsdp = 2 (dcn) × 2 (ici) = 4; tp = 2 (ici) — one axis split across
    # both networks.
    mesh = make_hybrid_mesh(
        MeshSpec(fsdp=2, tp=2), MeshSpec(fsdp=2), devices=devices
    )
    assert mesh.axis_names == ("fsdp", "tp")
    assert dict(mesh.shape) == {"fsdp": 4, "tp": 2}
    arr = mesh.devices
    # DCN-major within the fsdp axis: the outer half of fsdp indexes the
    # second granule.
    flat_first_granule = {d.id for d in devices[:4]}
    assert {d.id for d in arr[:2].ravel()} == flat_first_granule


def test_hybrid_mesh_trivial_dcn_is_plain_mesh():
    devices = jax.devices()
    mesh = make_hybrid_mesh(
        MeshSpec(fsdp=4, tp=2), MeshSpec(), devices=devices
    )
    assert dict(mesh.shape) == {"fsdp": 4, "tp": 2}


def test_hybrid_mesh_size_mismatch():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_hybrid_mesh(
            MeshSpec(tp=4), MeshSpec(dp=4), devices=jax.devices()
        )


def test_hybrid_mesh_rejects_contradicting_granules(monkeypatch):
    """Real slice metadata that contradicts the dcn spec must raise, not
    silently lay ICI axes across DCN via a contiguous split."""
    from torchdistx_tpu.parallel import distributed as D

    devices = jax.devices()
    monkeypatch.setattr(
        D,
        "_slice_granules",
        lambda devs: [devs[i::4] for i in range(4)],  # 4 granules of 2
    )
    with pytest.raises(ValueError, match="DCN granule"):
        make_hybrid_mesh(MeshSpec(tp=4), MeshSpec(dp=2), devices=devices)


def test_hybrid_mesh_collective_crosses_axes():
    """A psum over the hybrid mesh computes the same result as a dense
    mesh — the layout changes device placement, not semantics."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_hybrid_mesh(
        MeshSpec(tp=2), MeshSpec(dp=4), devices=jax.devices()
    )
    x = jnp.arange(8.0)
    y = jax.device_put(x, NamedSharding(mesh, P("dp")))
    total = jax.jit(lambda v: v.sum())(y)
    assert float(total) == 28.0


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_multiprocess_train_and_slowmo_match_single_process():
    """The real multi-process harness (reference bar: FSDPTest's
    multi-process spawn, tests/python/test_slowmo_fsdp.py): 2 JAX processes
    × 4 virtual CPU devices rendezvous through ``initialize``, build hybrid
    (ICI×DCN) meshes, and run a data-parallel train step plus a SlowMo
    stacked-replica step with gloo cross-process collectives.  Both ranks
    must agree on every replicated scalar, SlowMo replicas must sync
    exactly on the averaging step, and the loss/param digests must match a
    single-process 8-device run of the identical computation."""
    import json
    import os
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = __file__.rsplit("/tests/", 1)[0]
    worker = os.path.join(repo, "tests", "_mp_worker.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), coord],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=repo,
        )
        for r in range(2)
    ]
    # Drain both ranks CONCURRENTLY (a sequential communicate() can
    # deadlock: rank 1 blocks on a full stderr pipe, stalling a collective
    # rank 0 is waiting on) and always reap on the way out.
    try:
        with ThreadPoolExecutor(2) as pool:
            outs = list(
                pool.map(lambda p: p.communicate(timeout=900), procs)
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} rc={p.returncode}\n{out[-2000:]}\n{err[-3000:]}"
        )
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, f"rank {r} produced no RESULT\n{out[-2000:]}"
        results[r] = json.loads(line[-1][len("RESULT "):])

    # Cross-rank agreement on every replicated scalar.
    for key in ("loss", "wq_sum", "slowmo_synced", "slowmo_wq0_sum"):
        assert results[0][key] == results[1][key], (key, results)
    assert results[0]["slowmo_synced"] is True

    # Single-process reference: the IDENTICAL computation (shared
    # run_flows) on the local 8-device mesh — the granule fallback gives
    # the same dp-major layout the 2-process world used.
    from tests._mp_worker import run_flows

    ref = run_flows()
    assert ref["slowmo_synced"] is True
    for key in ("loss", "wq_sum", "slowmo_wq0_sum"):
        np.testing.assert_allclose(
            results[0][key], ref[key], rtol=1e-5,
            err_msg=f"multi-process {key} diverged from single-process",
        )


def test_initialize_single_process_group():
    """Real coordinator rendezvous, 1-process world, in a subprocess (the
    distributed client mutates process-global runtime state)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from torchdistx_tpu.parallel import initialize\n"
        f"info = initialize('127.0.0.1:{port}', num_processes=1, process_id=0)\n"
        "assert info.process_count == 1 and info.process_index == 0, info\n"
        "assert info.local_device_count == info.global_device_count\n"
        "info2 = initialize()  # idempotent\n"
        "assert info2 == info\n"
        "print('INIT-OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=__file__.rsplit("/tests/", 1)[0],
    )
    assert "INIT-OK" in out.stdout, out.stderr[-2000:]
