"""Fill fast path: pooled bucketed draws for trivial init stacks.

The overwhelmingly common init stack is ``factory → (views) → whole-storage
fill`` (every torch.nn default init).  The grouped materializer pools those
across SHAPES into padded power-of-two buckets — one small compiled program
per (dtype, bucket) instead of one subgraph per unique parameter shape —
compiled concurrently with the rest.  Values must be bitwise identical to
the per-op lowering replay (the lowerings draw the same buckets;
threefry fold_in keys are vmap-invariant).
"""

import os

import numpy as np
import pytest
import torch
import torch.nn as nn

import torchdistx_tpu.deferred_init as di
import torchdistx_tpu.materialize as M
from torchdistx_tpu.materialize import (
    materialize_module_jax,
    materialize_tensor_jax,
)


class _ShapeZoo(nn.Module):
    """Many distinct shapes and fill kinds — the fast path's target."""

    def __init__(self):
        super().__init__()
        self.c1 = nn.Conv2d(3, 16, 3)      # kaiming uniform + uniform bias
        self.c2 = nn.Conv2d(16, 8, 1)
        self.bn = nn.BatchNorm2d(16)       # ones / zeros (+ buffers)
        self.ln = nn.LayerNorm(24)
        self.fc = nn.Linear(24, 7)
        self.emb = nn.Embedding(11, 5)     # normal_


def _materialize_both_ways(module_fn, **kw):
    m1 = di.deferred_init(module_fn)
    fast = materialize_module_jax(m1, **kw)
    n_fast = M.last_fill_fastpath_params
    os.environ["TDX_NO_FILL_FASTPATH"] = "1"
    try:
        m2 = di.deferred_init(module_fn)
        slow = materialize_module_jax(m2, **kw)
        assert M.last_fill_fastpath_params == 0
    finally:
        del os.environ["TDX_NO_FILL_FASTPATH"]
    return fast, slow, n_fast


def test_fastpath_engages_and_matches_template_path():
    fast, slow, n_fast = _materialize_both_ways(_ShapeZoo, seed=7)
    assert n_fast == len(fast)  # every param+buffer is a trivial fill
    assert set(fast) == set(slow)
    for k in fast:
        np.testing.assert_array_equal(
            np.asarray(fast[k]), np.asarray(slow[k]), err_msg=k
        )


def test_fastpath_matches_tensor_path():
    m = di.deferred_init(_ShapeZoo)
    out = materialize_module_jax(m, seed=3)
    assert M.last_fill_fastpath_params > 0
    for name in ("c1.weight", "fc.bias", "emb.weight", "bn.weight"):
        fake = dict(m.named_parameters())[name]
        single = materialize_tensor_jax(fake, seed=3)
        np.testing.assert_array_equal(
            np.asarray(out[name]), np.asarray(single), err_msg=name
        )


def test_fastpath_distribution_bounds():
    m = di.deferred_init(nn.Conv2d, 3, 16, 3)
    out = materialize_module_jax(m)
    w = np.asarray(out["weight"])
    fan_in = 3 * 3 * 3
    bound = np.sqrt(6.0 / ((1 + 5) * fan_in))  # kaiming_uniform(a=√5)
    assert np.abs(w).max() <= bound + 1e-6
    assert w.std() > 0.5 * bound / np.sqrt(3)
    # distinct params draw distinct streams
    assert not np.allclose(w.reshape(-1)[:16], np.asarray(out["bias"]))


def test_fastpath_sharded_matches_unsharded():
    from torchdistx_tpu.parallel import MeshSpec, fsdp_plan, make_mesh

    mesh = make_mesh(MeshSpec(fsdp=8))
    m = di.deferred_init(nn.Linear, 64, 32)
    sharded = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=1))
    assert M.last_fill_fastpath_params == 2
    unsharded = materialize_module_jax(m)
    for k in sharded:
        np.testing.assert_array_equal(
            np.asarray(sharded[k]), np.asarray(unsharded[k])
        )
    assert len(sharded["weight"].sharding.device_set) == 8


def test_large_fills_stay_on_template_path():
    # > _FILL_POOL_MAX elements: pooling buys no dedup for large repeated
    # shapes; they must take the exact-shape template path.
    big = M._FILL_POOL_MAX + 1

    class Big(nn.Module):
        def __init__(self):
            super().__init__()
            self.p = nn.Parameter(torch.empty(big).normal_())
            self.small = nn.Linear(4, 4)

    m = di.deferred_init(Big)
    out = materialize_module_jax(m)
    assert M.last_fill_fastpath_params == 2  # linear only
    assert out["p"].shape == (big,)
    # Values still match the tensor path (both via the padded lowering).
    single = materialize_tensor_jax(m.p)
    np.testing.assert_array_equal(np.asarray(out["p"]), np.asarray(single))


def test_bucket_chunking_bitwise_stable():
    # Force multi-chunk draws inside one bin program and check values are
    # unchanged (chunk boundaries must not alter per-row draws).
    class Rows(nn.Module):
        def __init__(self):
            super().__init__()
            for i in range(6):
                self.register_parameter(
                    f"p{i}", nn.Parameter(torch.empty(300).uniform_())
                )

    old = M._FILL_CHUNK_BYTES
    m1 = di.deferred_init(Rows)
    ref = materialize_module_jax(m1, seed=11)
    try:
        M._FILL_CHUNK_BYTES = 512 * 4  # 512 elems f32 → 1 row per chunk
        # The chunk size is a process constant, deliberately outside the
        # exec-cache key — disable the cache so the re-chunked program
        # actually compiles here.
        os.environ["TDX_NO_EXEC_CACHE"] = "1"
        m2 = di.deferred_init(Rows)
        chunked = materialize_module_jax(m2, seed=11)
    finally:
        M._FILL_CHUNK_BYTES = old
        os.environ.pop("TDX_NO_EXEC_CACHE", None)
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(chunked[k]), err_msg=k
        )


def test_exec_cache_hits_across_bin_programs():
    # Second materialization of the same architecture reuses every program
    # (bins + none fused) — counted as one whole-call hit.
    m1 = di.deferred_init(_ShapeZoo)
    materialize_module_jax(m1, seed=0)
    before = M.exec_cache_hits
    m2 = di.deferred_init(_ShapeZoo)
    materialize_module_jax(m2, seed=1)  # seed is traced: same programs
    assert M.exec_cache_hits == before + 1


def test_exec_disk_tier_gated_off_cpu_backend():
    # The serialized-executable disk tier is accelerator-only: CPU
    # executables are machine-feature-bound, and the suite's cache-hit
    # invariants must not leak across runs (same rule as the persistent
    # compilation cache).  This suite runs on the CPU backend.
    assert M._exec_disk_dir() is None
    assert M._exec_disk_path(("any", "key")) is None
    assert M._exec_disk_get(("any", "key")) is None


def test_fill_bucket_monotone_and_padded():
    from torchdistx_tpu.ops.aten_jax import fill_bucket

    prev = 0
    for n in [1, 127, 128, 129, 5000, 65536, 65537, 10**6, 10**8]:
        b = fill_bucket(n)
        assert b >= n and b >= 128
        assert b >= prev
        prev = b
    assert fill_bucket(128) == 128
    # pow2 regime above 64Ki bounds waste at 2×
    assert fill_bucket(65537) <= 65537 * 2
    assert fill_bucket(10**8) <= 2 * 10**8


def test_nonfill_stacks_unaffected():
    # A stack with real compute after the fill must not be claimed.
    class Scaled(nn.Module):
        def __init__(self):
            super().__init__()
            w = torch.empty(8, 8).uniform_()
            w.mul_(2.0)
            self.p = nn.Parameter(w)

    m = di.deferred_init(Scaled)
    out = materialize_module_jax(m)
    assert M.last_fill_fastpath_params == 0
    w = np.asarray(out["p"])
    assert np.abs(w).max() <= 2.0 + 1e-6
    assert w.max() > 1.0  # scaling actually applied
