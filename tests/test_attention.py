"""Attention ops: flash (Pallas, interpreted on CPU) and ring vs reference.

Test rig per SURVEY.md §4: single host, virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import pytest

from torchdistx_tpu.ops.attention import attention, mha_reference
from torchdistx_tpu.ops.pallas.flash_attention import flash_attention
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.parallel.ring_attention import ring_attention


def _qkv(b=2, s=64, hq=4, hkv=2, d=16, dtype=jnp.float32):
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, s, hq, d), dtype=dtype)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, hkv, d), dtype=dtype)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, hkv, d), dtype=dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_mha_no_gqa(self):
        q, k, v = _qkv(hq=4, hkv=4)
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_grads_match_reference(self):
        q, k, v = _qkv(s=32)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        g_ref = jax.grad(
            loss(lambda q, k, v: mha_reference(q, k, v, causal=True)),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_fa = jax.grad(
            loss(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, interpret=True
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_ref, g_fa):
            assert jnp.allclose(a, b, atol=1e-4)

    def test_long_seq_multiple_q_blocks(self):
        # seq > block size → several q-block grid steps.
        q, k, v = _qkv(s=512, d=8)
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_grads_multi_block_gqa(self):
        # Backward kernels across several q/kv blocks (s=512 → multiple
        # grid steps on the streamed axes) with GQA group reduction —
        # exercises the causal diagonal-clamped index maps end to end.
        key = jax.random.PRNGKey(3)
        b, s, hq, hkv, d = 2, 512, 4, 2, 16
        q = jax.random.normal(key, (b, s, hq, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, hkv, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, hkv, d))

        def loss_fa(q, k, v):
            return (flash_attention(q, k, v, causal=True, interpret=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True) ** 2).sum()

        g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ref, g_fa):
            assert jnp.allclose(a, b_, atol=5e-4)

    def test_fused_bwd_matches_two_kernel_path(self):
        """The fused nk==1 backward (training regime) and the streamed
        two-kernel backward (long-context regime) must compute the same
        gradients — only f32 accumulation order differs."""
        from torchdistx_tpu.ops.pallas import flash_attention as fa

        key = jax.random.PRNGKey(7)
        b, s, hq, hkv, d = 2, 256, 4, 2, 32
        q = jax.random.normal(key, (b, s, hq, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, hkv, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, hkv, d))

        def loss(q, k, v):
            return (
                flash_attention(q, k, v, causal=True, interpret=True) ** 2
            ).sum()

        # Spy on the fused kernel entry so the test cannot pass vacuously
        # if the dispatch condition ever drifts.
        fused_calls = []
        orig_fused = fa._fa_backward_fused_nk1

        def spy(*a, **kw):
            fused_calls.append(1)
            return orig_fused(*a, **kw)

        old = fa._BWD_BLOCK_Q, fa._BWD_BLOCK_KV
        fa._fa_backward_fused_nk1 = spy
        try:
            # Defaults: bkv == s_pad, fused single-kernel path.
            g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            assert fused_calls, "defaults no longer take the fused path"
            n_fused = len(fused_calls)
            # Force two kv blocks: the streamed dq + dkv kernel pair.
            fa._BWD_BLOCK_Q, fa._BWD_BLOCK_KV = 128, 128
            jax.clear_caches()
            g_two = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            assert len(fused_calls) == n_fused, (
                "128-block override still took the fused path"
            )
        finally:
            fa._fa_backward_fused_nk1 = orig_fused
            fa._BWD_BLOCK_Q, fa._BWD_BLOCK_KV = old
            jax.clear_caches()
        for a, b_ in zip(g_fused, g_two):
            assert jnp.allclose(a, b_, atol=5e-5)

    def test_fused_bwd_2048_gradients(self):
        """S=2048 takes the fused backward with bkv = s_pad (above the
        1024 default block — the _FUSED_BWD_MAX_KV extension); gradients
        must match the dense reference."""
        from torchdistx_tpu.ops.pallas import flash_attention as fa

        key = jax.random.PRNGKey(9)
        b, s, h, d = 1, 2048, 1, 32
        q = jax.random.normal(key, (b, s, h, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, h, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, d))

        fused_calls = []
        orig = fa._fa_backward_fused_nk1

        def spy(*a, **kw):
            fused_calls.append(1)
            return orig(*a, **kw)

        fa._fa_backward_fused_nk1 = spy
        try:
            def loss_fa(q, k, v):
                return (
                    flash_attention(q, k, v, causal=True, interpret=True)
                    ** 2
                ).sum()

            def loss_ref(q, k, v):
                return (mha_reference(q, k, v, causal=True) ** 2).sum()

            g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
            assert fused_calls, "S=2048 did not take the fused path"
        finally:
            fa._fa_backward_fused_nk1 = orig
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ref, g_fa):
            assert jnp.allclose(a, b_, atol=5e-4)

    def test_fused_bwd_vmem_guard_falls_back_to_streamed(self):
        """When even bq=128 cannot fit the (bq, s_pad) f32 p/ds working
        set under the VMEM cap, the fused backward must hand off to the
        streamed two-kernel path instead of overflowing — with identical
        gradients."""
        from torchdistx_tpu.ops.pallas import flash_attention as fa

        key = jax.random.PRNGKey(11)
        b, s, h, d = 1, 256, 2, 32
        q = jax.random.normal(key, (b, s, h, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, h, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, d))

        def loss(q, k, v):
            return (
                flash_attention(q, k, v, causal=True, interpret=True) ** 2
            ).sum()

        g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        streamed_calls = []
        orig_streamed = fa._fa_backward_streamed

        def spy(*a, **kw):
            streamed_calls.append(kw)
            return orig_streamed(*a, **kw)

        old_cap = fa._FUSED_BWD_VMEM_CAP
        fa._fa_backward_streamed = spy
        try:
            # Cap below the bq=128 working set (128·256·4 bytes): the
            # fused path cannot whittle its way under and must fall back.
            fa._FUSED_BWD_VMEM_CAP = 128 * s * 4 - 1
            jax.clear_caches()
            g_streamed = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            assert streamed_calls, "VMEM guard did not fall back"
            # The handoff must NOT pin the streamed path to the whittled
            # bq=128 — against _block_for-sized kv blocks its own default
            # q block fits the cap and runs far fewer grid iterations.
            assert "bq" not in streamed_calls[0]
            assert streamed_calls[0]["bkv"] == fa._block_for(s)
        finally:
            fa._fa_backward_streamed = orig_streamed
            fa._FUSED_BWD_VMEM_CAP = old_cap
            jax.clear_caches()
        for a, b_ in zip(g_fused, g_streamed):
            assert jnp.allclose(a, b_, atol=5e-5)

    def test_long_context_kv_streaming(self):
        # The long-context regime the kernel exists for: 8 q-blocks ×
        # 8 kv-blocks streamed through the VMEM scratch accumulators.
        # (16k/32k fwd+bwd are exercised on real TPU hardware via the bench
        # and graft entry; the interpreter at that size is impractical.)
        key = jax.random.PRNGKey(0)
        b, s, h, d = 1, 2048, 2, 64
        q = jax.random.normal(key, (b, s, h, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, h, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, d))
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        assert jnp.allclose(ref, out, atol=1e-5)


class TestRingAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh(MeshSpec(dp=2, sp=4))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv()
        ref = mha_reference(q, k, v, causal=causal)
        out = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh=mesh, axis="sp", causal=causal
            )
        )(q, k, v)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_zigzag_matches_reference(self, mesh):
        q, k, v = _qkv()  # s=64 = 2·sp·8
        ref = mha_reference(q, k, v, causal=True)
        out = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh=mesh, axis="sp", causal=True,
                schedule="zigzag",
            )
        )(q, k, v)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_zigzag_grads_match_reference(self, mesh):
        q, k, v = _qkv()
        g_ref = jax.grad(
            lambda q, k, v: (mha_reference(q, k, v, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_z = jax.jit(jax.grad(
            lambda q, k, v: (
                ring_attention(
                    q, k, v, mesh=mesh, axis="sp", causal=True,
                    schedule="zigzag",
                ) ** 2
            ).sum(),
            argnums=(0, 1, 2),
        ))(q, k, v)
        for a, b in zip(g_ref, g_z):
            assert jnp.allclose(a, b, atol=1e-4)

    def test_zigzag_validation(self, mesh):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="causal-only"):
            ring_attention(
                q, k, v, mesh=mesh, axis="sp", causal=False,
                schedule="zigzag",
            )
        q2, k2, v2 = _qkv(s=36)  # not divisible by 2·sp=8
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(
                q2, k2, v2, mesh=mesh, axis="sp", causal=True,
                schedule="zigzag",
            )

    def test_causal_skips_future_blocks(self, mesh):
        """Future K/V ring blocks take a lax.cond identity branch; the
        compiled module retains a real HLO conditional (skipped, not
        select-executed) in forward and backward."""
        q, k, v = _qkv()
        fwd = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh=mesh, axis="sp", causal=True
            )
        )
        assert "conditional" in fwd.lower(q, k, v).compile().as_text()
        bwd = jax.jit(jax.grad(
            lambda q, k, v: (
                ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True)
                ** 2
            ).sum(),
            argnums=(0,),
        ))
        assert "conditional" in bwd.lower(q, k, v).compile().as_text()

    def test_grads_match_reference(self, mesh):
        q, k, v = _qkv(s=32)
        g_ref = jax.grad(
            lambda q, k, v: (mha_reference(q, k, v, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_ring = jax.jit(
            jax.grad(
                lambda q, k, v: (
                    ring_attention(q, k, v, mesh=mesh, axis="sp") ** 2
                ).sum(),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
        for a, b in zip(g_ref, g_ring):
            assert jnp.allclose(a, b, atol=1e-4)

    def test_sp_only_mesh(self):
        mesh = make_mesh(MeshSpec(sp=8))
        q, k, v = _qkv(s=64)
        ref = mha_reference(q, k, v, causal=True)
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, axis="sp")
        )(q, k, v)
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_missing_axis_raises(self, mesh):
        q, k, v = _qkv(s=8)
        with pytest.raises(ValueError, match="no axis"):
            ring_attention(q, k, v, mesh=mesh, axis="nope")


class TestDispatcher:
    def test_auto_cpu_is_jnp(self):
        q, k, v = _qkv(s=16)
        out = attention(q, k, v, causal=True)
        assert jnp.allclose(out, mha_reference(q, k, v, causal=True), atol=1e-5)

    def test_ring_requires_mesh(self):
        q, k, v = _qkv(s=16)
        with pytest.raises(ValueError, match="mesh"):
            attention(q, k, v, impl="ring")


class TestShardedFlash:
    """shard_map-wrapped Pallas kernel under the mesh (VERDICT r2 item 1)."""

    @pytest.mark.parametrize("spec", [
        MeshSpec(dp=2, fsdp=2, tp=2),
        MeshSpec(fsdp=8),
        MeshSpec(tp=2, dp=4),
    ])
    def test_values_match_reference(self, spec):
        from torchdistx_tpu.ops.pallas.flash_attention import (
            flash_attention_sharded,
        )

        mesh = make_mesh(spec)
        q, k, v = _qkv(b=8, s=64, hq=4, hkv=2)
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention_sharded(
            q, k, v, causal=True, mesh=mesh, interpret=True
        )
        assert jnp.allclose(ref, out, atol=1e-5)

    def test_grads_match_reference(self):
        from torchdistx_tpu.ops.pallas.flash_attention import (
            flash_attention_sharded,
        )

        mesh = make_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        q, k, v = _qkv(b=4, s=32, hq=4, hkv=4)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        g_ref = jax.grad(
            loss(lambda q, k, v: mha_reference(q, k, v, causal=True)),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_fa = jax.grad(
            loss(lambda q, k, v: flash_attention_sharded(
                q, k, v, causal=True, mesh=mesh, interpret=True
            )),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_ref, g_fa):
            assert jnp.allclose(a, b, atol=1e-4)

    def test_inside_jit_with_sharded_inputs(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from torchdistx_tpu.ops.pallas.flash_attention import (
            flash_attention_sharded,
        )

        mesh = make_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        q, k, v = _qkv(b=4, s=32, hq=8, hkv=8)
        sh = NamedSharding(mesh, P(("dp", "fsdp"), None, "tp", None))
        q, k, v = (jax.device_put(t, sh) for t in (q, k, v))
        out = jax.jit(
            lambda q, k, v: flash_attention_sharded(
                q, k, v, causal=True, mesh=mesh, interpret=True
            )
        )(q, k, v)
        ref = mha_reference(q, k, v, causal=True)
        assert jnp.allclose(ref, out, atol=1e-5)
        assert out.sharding.is_equivalent_to(sh, 4)

    def test_shardable_predicate(self):
        from torchdistx_tpu.ops.pallas.flash_attention import shardable

        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        assert shardable(mesh, (8, 64, 4, 16), (8, 64, 2, 16))
        # batch 3 not divisible by dp*fsdp=4
        assert not shardable(mesh, (3, 64, 4, 16), (3, 64, 2, 16))
        # kv heads 1 not divisible by tp=2
        assert not shardable(mesh, (8, 64, 4, 16), (8, 64, 1, 16))

    def test_indivisible_raises(self):
        from torchdistx_tpu.ops.pallas.flash_attention import (
            flash_attention_sharded,
        )

        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        q, k, v = _qkv(b=3, s=32)
        with pytest.raises(ValueError, match="not.*divisible|divisible"):
            flash_attention_sharded(q, k, v, mesh=mesh, interpret=True)


class TestAutoSelection:
    def test_auto_under_mesh_on_tpu_picks_pallas(self, monkeypatch):
        from torchdistx_tpu.ops import attention as A

        monkeypatch.setattr(A, "_on_tpu", lambda: True)
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        assert A._select_impl(
            "auto", mesh, None, (8, 64, 4, 16), (8, 64, 2, 16)
        ) == "pallas"
        # indivisible shapes fall back to jnp
        assert A._select_impl(
            "auto", mesh, None, (3, 64, 4, 16), (3, 64, 2, 16)
        ) == "jnp"
        # seq parallelism still wins
        assert A._select_impl(
            "auto", mesh, "sp", (8, 64, 4, 16), (8, 64, 2, 16)
        ) == "ring"
        assert A._select_impl(
            "auto", None, None, (8, 64, 4, 16), (8, 64, 2, 16)
        ) == "pallas"

    def test_pp_forward_pins_jnp(self):
        from torchdistx_tpu.models import llama

        cfg = llama.llama_test()
        mesh = make_mesh(MeshSpec(pp=2, dp=4))
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
        )
        with pytest.raises(ValueError, match="pipeline stage"):
            llama.forward(
                params, tokens, cfg, mesh=mesh, pp_axis="pp",
                n_microbatches=2, attn_impl="pallas",
            )

    def test_auto_under_unknown_axis_names_is_jnp(self, monkeypatch):
        """A mesh with custom axis names ("data"/"model") must fall back to
        jnp — the wrapper only understands dp/fsdp/tp (review r3)."""
        from torchdistx_tpu.ops import attention as A

        monkeypatch.setattr(A, "_on_tpu", lambda: True)
        mesh = make_mesh(axis_names=("data", "model"), shape=(4, 2))
        assert A._select_impl(
            "auto", mesh, None, (8, 64, 4, 16), (8, 64, 2, 16)
        ) == "jnp"

    def test_slowmo_refuses_explicit_pallas(self):
        import optax
        from torchdistx_tpu.models import llama
        from torchdistx_tpu.parallel import train_step as ts
        from torchdistx_tpu.parallel.slowmo import SlowMomentumOptimizer

        cfg = llama.llama_test()
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
        opt = SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1, slowmo_freq=2)
        with pytest.raises(ValueError, match="SlowMo"):
            ts.make_slowmo_train_step(cfg, mesh, opt, attn_impl="pallas")


def test_noncausal_padded_grads_finite():
    """Non-causal + padded seq + very negative logits: padded kv cols'
    p = exp(-lse) must not overflow into NaN dq (review r3)."""
    b, s, h, d = 1, 100, 2, 16
    key = jax.random.PRNGKey(0)
    q = 50.0 * jax.random.normal(key, (b, s, h, d))
    k = -50.0 * q[:, :, :, :]  # strongly negative logits
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, d))
    g = jax.grad(
        lambda q, k, v: flash_attention(
            q, k, v, causal=False, interpret=True
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for arr in g:
        assert bool(jnp.isfinite(arr).all())
