"""Worker for the multi-process distributed test (FSDPTest-spawn analog).

Launched as ``python tests/_mp_worker.py <rank> <coordinator>`` with
``XLA_FLAGS=--xla_force_host_platform_device_count=4``: two of these form a
2-process × 4-device CPU world (multi-controller JAX, gloo collectives).

``run_flows()`` holds the computation itself and is also imported by the
parent test for the single-process reference run — the "identical
computation" contract lives in exactly one place.

Prints one ``RESULT {...}`` JSON line with replicated-scalar outcomes; the
parent asserts cross-rank agreement and equality with the single-process
run.
"""

import json
import sys

if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # default implementation already supports cpu collectives
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])


def run_flows() -> dict:
    """One DP train step ×2 and one SlowMo cycle on hybrid (ICI×DCN)
    meshes; returns replicated-scalar digests only (computed under jit, so
    no process ever needs non-addressable shards on host)."""
    import jax
    import optax

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel import (
        MeshSpec, make_hybrid_mesh, train_step as ts,
    )
    from torchdistx_tpu.parallel.slowmo import SlowMomentumOptimizer

    cfg = llama.llama_test()
    out = {}

    # --- data-parallel train step over the hybrid (dp=DCN) mesh ----------
    mesh = make_hybrid_mesh(MeshSpec(fsdp=4), MeshSpec(dp=2))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
    )
    batch = {
        "tokens": jax.device_put(tokens, ts.batch_sharding(mesh)),
        "targets": jax.device_put(tokens, ts.batch_sharding(mesh)),
    }
    state, m = step_fn(state, batch)
    state, m = step_fn(state, batch)
    out["loss"] = float(m["loss"])
    out["wq_sum"] = float(
        jax.jit(lambda p: p["layers"]["wq"].astype("float32").sum())(
            state.params
        )
    )

    # --- SlowMo stacked-replica step, dp as the (DCN) averaging axis -----
    mesh2 = make_hybrid_mesh(MeshSpec(tp=4), MeshSpec(dp=2))
    opt = SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1, slowmo_freq=2)
    init2, step2 = ts.make_slowmo_train_step(cfg, mesh2, opt)
    st2 = init2(jax.random.PRNGKey(0))
    t2 = jax.random.randint(
        jax.random.PRNGKey(5), (2, 4, 32), 0, cfg.vocab_size
    )
    b2 = {
        "tokens": jax.device_put(t2, ts.slowmo_batch_sharding(mesh2)),
        "targets": jax.device_put(t2, ts.slowmo_batch_sharding(mesh2)),
    }
    st2, _ = step2(st2, b2)  # diverge
    st2, _ = step2(st2, b2)  # averaging step: replicas must sync exactly
    synced, wq0 = jax.jit(
        lambda p: (
            (p["layers"]["wq"][0] == p["layers"]["wq"][1]).all(),
            p["layers"]["wq"][0].astype("float32").sum(),
        )
    )(st2.params)
    out["slowmo_synced"] = bool(synced)
    out["slowmo_wq0_sum"] = float(wq0)
    return out


def main() -> None:
    rank, coord = int(sys.argv[1]), sys.argv[2]

    from torchdistx_tpu.parallel import initialize

    info = initialize(coord, num_processes=2, process_id=rank)
    assert info.process_count == 2, info
    assert info.global_device_count == 8, info
    assert info.local_device_count == 4, info

    out = {"rank": rank, **run_flows()}
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
