"""Perf plane (ISSUE 12): compile observatory, HBM ledger, OOM
forensics, and the bench regression gate.

The acceptance bar: per-program compile counts are exact (the decode
chunk compiles exactly ONCE through a full serving lifecycle —
admission, chunked prefill, decode, slot recycling, preempt/resume); an
injected shape-churn storm trips the detector (latch gauge +
``reason="recompile_storm"`` flight dump + the engine marked
OVERLOADED); an induced pool-exhaustion failure's flight dump carries
the HBM ledger snapshot; and ``scripts/bench_gate.py`` exits nonzero on
a synthetically regressed metric and zero on a round replayed against
itself.
"""

import json
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from torchdistx_tpu import telemetry  # noqa: E402
from torchdistx_tpu.models import llama  # noqa: E402
from torchdistx_tpu.models.generate import generate  # noqa: E402
from torchdistx_tpu.serving import Engine  # noqa: E402
from torchdistx_tpu.serving.blocks import BlockAllocator  # noqa: E402
from torchdistx_tpu.telemetry import perf  # noqa: E402

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts"),
)
import bench_gate  # noqa: E402


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def counter_value(name):
    return telemetry.counters().get(name, 0)


# ---------------------------------------------------------------------------
# Compile observatory


def test_jit_program_counts_compiles_exactly():
    """Cache-size delta detection: one count per distinct shape, zero
    on reuse, recompiles only past the first."""
    f = jax.jit(lambda x: x + 1)
    jp = perf.JitProgram(lambda: f, "tdx_test_prog_a")
    c = "compile.count{program=tdx_test_prog_a}"
    r = "compile.recompiles{program=tdx_test_prog_a}"
    base_c, base_r = counter_value(c), counter_value(r)
    jp.call(None, None, jax.numpy.ones((2,)))
    assert counter_value(c) - base_c == 1
    assert counter_value(r) - base_r == 0
    jp.call(None, None, jax.numpy.ones((2,)))  # warm: no compile
    assert counter_value(c) - base_c == 1
    jp.call(None, None, jax.numpy.ones((3,)))  # new shape: recompile
    assert counter_value(c) - base_c == 2
    assert counter_value(r) - base_r == 1
    hist = telemetry.histograms().get(
        "compile.time_s{program=tdx_test_prog_a}", {}
    )
    assert hist.get("count", 0) >= 2


def test_monkeypatched_stand_in_passes_through():
    """A plain function swapped in for the jitted one (the chaos tests'
    flaky decode) is not instrumented — and not broken."""
    calls = []

    def stand_in(x):
        calls.append(x)
        return x

    jp = perf.JitProgram(lambda: stand_in, "tdx_test_prog_b")
    base = counter_value("compile.count{program=tdx_test_prog_b}")
    assert jp.call(None, None, 7) == 7
    assert calls == [7]
    assert counter_value("compile.count{program=tdx_test_prog_b}") == base


def test_recompile_storm_latches_dumps_and_marks_owner(tmp_path):
    """An injected shape-churn storm: threshold recompiles in-window ⇒
    the latch gauge, a reason="recompile_storm" flight dump, and the
    owner marked OVERLOADED via its stall hook."""
    flight = str(tmp_path / "flight.jsonl")
    prev_cfg = telemetry.configure(flight=flight)
    prev_storm = perf.storm_config(threshold=3, window_s=60.0)

    class Owner:
        engine_id = "storm-test-eng"
        marked = 0

        def _mark_stalled(self):
            self.marked += 1

    owner = Owner()
    try:
        f = jax.jit(lambda x: x * 2)
        jp = perf.JitProgram(lambda: f, "tdx_test_churny")
        for n in range(1, 6):  # every call a fresh shape
            jp.call(owner, None, jax.numpy.ones((n,)))
        assert owner.marked == 1
        assert (
            telemetry.gauges()[
                "serve.recompile_storm{engine=storm-test-eng}"
            ]
            == 1
        )
        records = [json.loads(line) for line in open(flight)]
        headers = [
            rec for rec in records
            if rec.get("type") == "flight_dump"
            and rec.get("reason") == "recompile_storm"
            and rec.get("attrs", {}).get("program") == "tdx_test_churny"
        ]
        assert headers, "no recompile_storm flight dump for the churny program"
        assert headers[0]["attrs"].get("engine") == "storm-test-eng"
    finally:
        perf.storm_config(*prev_storm)
        telemetry.configure(**prev_cfg)


def test_storm_latch_clears_after_quiet_window(tmp_path):
    prev_cfg = telemetry.configure(flight=str(tmp_path / "f.jsonl"))
    # Latch under a window comfortably wider than CPU compile time...
    prev_storm = perf.storm_config(threshold=2, window_s=30.0)

    class Owner:
        engine_id = "quiet-test-eng"

        def _mark_stalled(self):
            pass

    owner = Owner()
    try:
        f = jax.jit(lambda x: x - 1)
        jp = perf.JitProgram(lambda: f, "tdx_test_quiet")
        for n in range(1, 4):
            jp.call(owner, None, jax.numpy.ones((n,)))
        assert (
            telemetry.gauges()[
                "serve.recompile_storm{engine=quiet-test-eng}"
            ]
            == 1
        )
        import time

        # ...then shrink it so a short quiet period counts as a full
        # recompile-free window.
        perf.storm_config(threshold=2, window_s=0.05)
        time.sleep(0.1)  # the window drains
        jp.call(owner, None, jax.numpy.ones((3,)))  # warm call: no compile
        assert (
            telemetry.gauges()[
                "serve.recompile_storm{engine=quiet-test-eng}"
            ]
            == 0
        )
    finally:
        perf.storm_config(*prev_storm)
        telemetry.configure(**prev_cfg)


def test_decode_chunk_compiles_exactly_once_through_lifecycle(family):
    """The steady-state compile invariant, assertable for the first
    time: ONE decode-chunk compile covers admission → chunked prefill →
    decode → slot recycling → priority preemption → resume.  Unique
    engine geometry (num_slots=3, decode_chunk=5) guarantees a fresh
    program, so the expected count is exactly 1 — anything more is the
    shape leak the storm detector exists for.  Runs with the
    prefix-cache default ON (the flipped default earns its tier-1
    coverage here)."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=3,
        block_size=8, max_model_len=64, decode_chunk=5, prefill_chunk=4,
        min_prefill_bucket=4, preempt_mechanism="replay",
        handle_preemption=False,
    )
    assert eng.prefix is not None  # the new default
    name = "compile.count{program=decode_chunk}"
    base = counter_value(name)

    def solo(prompt, seed, max_new):
        out = generate(
            params, jax.numpy.asarray(prompt)[None],
            jax.random.PRNGKey(seed), model=model, cfg=cfg,
            max_new_tokens=max_new,
        )
        return [int(t) for t in np.asarray(out)[0]]

    # Admission + chunked prefill (12 tokens = 3 chunks of 4) + decode.
    p0 = np.arange(1, 13, dtype=np.int32)
    h0 = eng.submit(p0, max_new_tokens=8, key=0, priority=0)
    # Slot recycling: two more requests through the freed slots.
    h1 = eng.submit(np.arange(2, 8, dtype=np.int32), max_new_tokens=6,
                    key=1, priority=0)
    eng.drain()
    # Preempt/resume: fill every slot with low priority, then a
    # high-priority arrival forces a drop-and-replay preemption.
    victims = [
        eng.submit(np.arange(3, 9, dtype=np.int32), max_new_tokens=20,
                   key=10 + i, priority=0)
        for i in range(3)
    ]
    eng.step()
    urgent = eng.submit(np.arange(4, 10, dtype=np.int32),
                        max_new_tokens=6, key=99, priority=5)
    eng.drain()
    assert eng.stats()["preemptions_replay"] >= 1
    # Token identity held throughout...
    assert h0.result() == solo(p0, 0, 8)
    assert h1.result() == solo(np.arange(2, 8, dtype=np.int32), 1, 6)
    assert urgent.result() == solo(np.arange(4, 10, dtype=np.int32), 99, 6)
    for i, v in enumerate(victims):
        assert v.result() == solo(np.arange(3, 9, dtype=np.int32),
                                  10 + i, 20)
    # ...and the decode chunk compiled exactly once for all of it.
    assert counter_value(name) - base == 1, (
        "decode chunk recompiled during steady-state serving"
    )
    assert (
        "compile.recompiles{program=decode_chunk}"
        not in telemetry.counters()
    )
    # Cache-on idle accounting: the allocator owns exactly the index's
    # pages, each at refcount 1.
    assert eng.allocator.num_in_use == len(eng.prefix)
    assert eng.prefix.check(eng.allocator) is None


# ---------------------------------------------------------------------------
# HBM ledger + OOM forensics


def test_ledger_register_sum_unregister_and_exposition():
    perf.ledger.register("tdx_test_comp", 100, owner="a")
    perf.ledger.register("tdx_test_comp", 50, owner="b")
    g = "mem.hbm_bytes{component=tdx_test_comp}"
    assert telemetry.gauges()[g] == 150
    assert perf.ledger.components()["tdx_test_comp"] == 150
    from torchdistx_tpu.telemetry.ops import render_prometheus

    text = render_prometheus()
    assert 'mem_hbm_bytes{component="tdx_test_comp"} 150' in text
    perf.ledger.unregister("tdx_test_comp", owner="a")
    assert telemetry.gauges()[g] == 50
    perf.ledger.unregister("tdx_test_comp", owner="b")
    assert g not in telemetry.gauges()  # pruned: bounded cardinality


def test_ledger_weights_dedupe_across_engines(family):
    """N engines over ONE params pytree are one copy of HBM: weights
    register under the params identity, not per engine."""
    model, cfg, params = family
    eng_a = Engine(params, model=model, cfg=cfg, num_slots=2,
                   block_size=8, max_model_len=64, decode_chunk=4,
                   handle_preemption=False)
    w1 = telemetry.gauges()["mem.hbm_bytes{component=weights}"]
    eng_b = Engine(params, model=model, cfg=cfg, num_slots=2,
                   block_size=8, max_model_len=64, decode_chunk=4,
                   handle_preemption=False)
    assert telemetry.gauges()["mem.hbm_bytes{component=weights}"] == w1
    # Each engine's pool is its own HBM: kv_pool sums.
    pool_total = telemetry.gauges()["mem.hbm_bytes{component=kv_pool}"]
    eng_a.close()
    assert (
        telemetry.gauges()["mem.hbm_bytes{component=kv_pool}"]
        == pool_total - eng_a._pool_nbytes
    )
    eng_b.close()
    # Retirement: a hot-swapped-out version's weights leave the ledger
    # when the LAST engine over that pytree stops — retired versions
    # must not pile up on the component forever.
    fresh = model.init_params(jax.random.PRNGKey(7), cfg)
    before = telemetry.gauges().get("mem.hbm_bytes{component=weights}", 0)
    eng_c = Engine(fresh, model=model, cfg=cfg, num_slots=2,
                   block_size=8, max_model_len=64, decode_chunk=4,
                   handle_preemption=False)
    eng_d = Engine(fresh, model=model, cfg=cfg, num_slots=2,
                   block_size=8, max_model_len=64, decode_chunk=4,
                   handle_preemption=False)
    during = telemetry.gauges()["mem.hbm_bytes{component=weights}"]
    assert during > before  # counted once for both
    eng_c.close()
    assert telemetry.gauges()["mem.hbm_bytes{component=weights}"] == during
    eng_d.close()  # the last engine over `fresh`: its bytes retire
    assert (
        telemetry.gauges().get("mem.hbm_bytes{component=weights}", 0)
        == before
    )


def test_is_oom_classifier():
    assert perf.is_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes"
    ))
    assert perf.is_oom(ValueError("backend ran Out of memory"))
    assert not perf.is_oom(RuntimeError("shape mismatch"))


def test_pool_exhaustion_dump_carries_ledger(tmp_path, family):
    """An induced pool-exhaustion failure's flight dump contains the
    HBM ledger snapshot — the OOM post-mortem names what held the
    memory."""
    model, cfg, params = family
    flight = str(tmp_path / "oom.jsonl")
    prev_cfg = telemetry.configure(flight=flight)
    try:
        eng = Engine(
            params, model=model, cfg=cfg, num_slots=2, block_size=8,
            max_model_len=64, decode_chunk=4, handle_preemption=False,
        )
        # Induce exhaustion: the allocator's map is emptied under the
        # tick (the supervisor-reset race _start_prefill defends
        # against), so the promised reservation cannot be met.
        eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4,
                   key=0)
        real_alloc = eng.allocator.alloc
        eng.allocator.alloc = lambda n: None
        eng.step()  # admission's _start_prefill fails; request requeues
        eng.allocator.alloc = real_alloc
        records = [json.loads(line) for line in open(flight)]
        headers = [
            rec for rec in records
            if rec.get("type") == "flight_dump"
            and rec.get("reason") == "pool_exhausted"
        ]
        assert headers, "no pool_exhausted flight dump"
        attrs = headers[0]["attrs"]
        assert attrs["engine"] == eng.engine_id
        assert "kv_pool" in attrs["ledger"] and "weights" in attrs["ledger"]
        assert attrs["ledger"]["kv_pool"] >= eng._pool_nbytes
        assert "pool_fragmentation" in attrs
        # The engine survived: the request still completes.
        eng.drain()
        eng.close()
    finally:
        telemetry.configure(**prev_cfg)


def test_device_oom_dump_carries_ledger(tmp_path, family):
    """A RESOURCE_EXHAUSTED device failure routes through the same
    forensic dump under reason="device_oom"."""
    model, cfg, params = family
    flight = str(tmp_path / "oom2.jsonl")
    prev_cfg = telemetry.configure(flight=flight)
    try:
        eng = Engine(
            params, model=model, cfg=cfg, num_slots=2, block_size=8,
            max_model_len=64, decode_chunk=4, handle_preemption=False,
        )
        eng._oom_check(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
            "serve.step",
        )
        records = [json.loads(line) for line in open(flight)]
        headers = [
            rec for rec in records
            if rec.get("type") == "flight_dump"
            and rec.get("reason") == "device_oom"
        ]
        assert headers and "kv_pool" in headers[0]["attrs"]["ledger"]
        eng.close()
    finally:
        telemetry.configure(**prev_cfg)


def test_allocator_fragmentation_estimate():
    a = BlockAllocator(num_blocks=9, block_size=4)
    assert a.fragmentation() == 0.0  # all free: one run
    pages = a.alloc(8)
    assert a.fragmentation() == 0.0  # nothing free
    a.free([pages[1], pages[3], pages[5]])  # single-page holes
    assert a.fragmentation() == pytest.approx(1 - 1 / 3)
    a.free([pages[0], pages[2], pages[4], pages[6], pages[7]])
    assert a.fragmentation() == 0.0  # everything free again


# ---------------------------------------------------------------------------
# Bench regression gate


def _history_round(tmp_path, name, xl_s, warm_s, serving=None):
    doc = {
        "metric": "deferred_init_materialize_gpt2xl_bf16_1chip",
        "value": xl_s,
        "details": {
            "gpt2xl_1p6b_bf16": {"ours_s": xl_s, "ours_warm_s": warm_s},
        },
    }
    if serving is not None:
        doc["details"]["serving_llama_350m_continuous"] = serving
    path = tmp_path / name
    path.write_text(json.dumps({"parsed": doc}))
    return str(path)


SERVING_ROW = {
    "sustained_decode_tokens_per_s": 4000.0,
    "ttft_p95_s": 0.5,
    "tpot_p95_s": 0.002,
    "goodput_tokens_per_s": 3500.0,
}


def test_bench_gate_round_replayed_against_itself_passes(tmp_path):
    r = _history_round(tmp_path, "BENCH_r09.json", 1.6, 0.13, SERVING_ROW)
    assert bench_gate.main(["--baseline", r, "--candidate", r]) == 0


def test_bench_gate_real_history_self_replay(tmp_path):
    """BENCH_r05 replayed against the repo's own history: r05 is the
    best round on every recorded metric, so the gate passes."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r05 = os.path.join(repo, "BENCH_r05.json")
    assert bench_gate.main(["--candidate", r05]) == 0


def test_bench_gate_fails_synthetic_regression(tmp_path, capsys):
    base = _history_round(tmp_path, "BENCH_r09.json", 1.6, 0.13, SERVING_ROW)
    bad_serving = dict(SERVING_ROW, sustained_decode_tokens_per_s=2000.0)
    cand = _history_round(
        tmp_path, "candidate.json", 1.6, 0.13, bad_serving
    )
    assert bench_gate.main(["--baseline", base, "--candidate", cand]) == 1
    verdict = json.loads(capsys.readouterr().out)
    row = verdict["metrics"]["serving_sustained_decode_tok_s"]
    assert row["status"] == "regressed" and verdict["pass"] is False


def test_bench_gate_fails_when_tracked_metric_vanishes(tmp_path, capsys):
    base = _history_round(tmp_path, "BENCH_r09.json", 1.6, 0.13, SERVING_ROW)
    cand = _history_round(tmp_path, "candidate.json", 1.6, 0.13, None)
    assert bench_gate.main(["--baseline", base, "--candidate", cand]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert (
        verdict["metrics"]["serving_ttft_p95_s"]["status"]
        == "missing_from_candidate"
    )


def test_bench_gate_tolerance_band(tmp_path):
    base = _history_round(tmp_path, "BENCH_r09.json", 1.0, 0.1, SERVING_ROW)
    slower = dict(SERVING_ROW, ttft_p95_s=0.6)  # +20% < 35% band
    cand = _history_round(tmp_path, "candidate.json", 1.2, 0.12, slower)
    assert bench_gate.main(["--baseline", base, "--candidate", cand]) == 0
    # The same candidate fails a tightened band.
    assert (
        bench_gate.main(
            ["--baseline", base, "--candidate", cand,
             "--tolerance", "0.05"]
        )
        == 1
    )
