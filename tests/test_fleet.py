"""Fleet layer (ISSUE 6): health-aware routing, typed failover, hot swap.

A :class:`~torchdistx_tpu.fleet.FleetRouter` fronting N engines must
route on per-engine health/TTFT, fail retryable typed errors over to
peers token-identically (greedy AND sampled), pin mid-stream failovers
to the weights version that produced the yielded prefix, fail typed —
never silently — when no replica can take a request, and hot-swap to a
deferred-init-materialized standby with zero dropped requests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.fleet import (
    FailoverDiverged,
    FailoverExhausted,
    FleetRouter,
    NoReplicaAvailable,
    hot_swap,
    materialize_standby,
)
from torchdistx_tpu.models import llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    DeadlineExceeded,
    Engine,
    EngineOverloaded,
    Health,
    RequestCancelled,
    RequestError,
)

EOS = 5
# prefix_cache pinned OFF: these suites assert raw page accounting
# (num_in_use == 0 at idle) that predates the cache-on default; the
# cache-on path is covered by the explicit prefix tests and the
# perf-plane lifecycle test.
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False, prefix_cache=False,
)


@pytest.fixture(autouse=True)
def _clean_preemption():
    preemption.clear()
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def solo(model, cfg, params, prompt, seed, max_new, *, eos=None,
         temperature=0.0, top_k=None):
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new, eos_id=eos,
        temperature=temperature, top_k=top_k,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def make_engine(family, **over):
    model, cfg, params = family
    kw = {**ENGINE_KW, **over}
    return Engine(params, model=model, cfg=cfg, **kw)


# ---------------------------------------------------------------------------
# Routing policy


def test_routes_to_least_estimated_ttft(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    # Seed the detectors: A looks slow, B fast — the router must read the
    # PER-ENGINE estimate (the global gauge would be whichever wrote last).
    eng_a.detector.observe_tick(0.5)
    eng_b.detector.observe_tick(0.01)
    h1 = router.submit(prompt_of(4), max_new_tokens=2, key=0)
    assert h1.replica_id == 1
    # Load tiebreak when estimates match: the next request moves off the
    # loaded replica instead of piling onto the lowest replica id.
    eng_a.detector._tick_ewma_s = eng_b.detector._tick_ewma_s = None
    h2 = router.submit(prompt_of(4), max_new_tokens=2, key=1)
    assert h2.replica_id == 0
    for h in (h1, h2):
        assert len(h.result()) == 2
    assert eng_a.allocator.num_in_use == 0
    assert eng_b.allocator.num_in_use == 0


def test_overloaded_avoided_draining_excluded(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    # B overloaded: avoided while A is healthy...
    eng_b._set_health(Health.OVERLOADED)
    assert router._pick().rid == 0
    # ...but the last resort once A stops admitting.
    eng_a.begin_drain()
    assert router._pick().rid == 1
    # DRAINING/STOPPED never route: with B draining too, submission
    # fails TYPED, not silently.
    eng_b._set_health(Health.READY)
    eng_b.begin_drain()
    with pytest.raises(NoReplicaAvailable) as ei:
        router.submit(prompt_of(4), max_new_tokens=2, key=0)
    assert ei.value.retryable
    while eng_a.health() is not Health.STOPPED or (
        eng_b.health() is not Health.STOPPED
    ):
        router.step()
    assert router.replicas() == []  # step() reaped the stopped replicas


def test_replicas_ready_gauge_and_respawn(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    assert telemetry.gauge("fleet.replicas_ready").value == 2
    eng_a.close()
    assert router.poll() == [0]
    assert telemetry.gauge("fleet.replicas_ready").value == 1
    # The fleet heals by respawn: a replacement replica takes traffic.
    rid = router.add_replica(make_engine(family), version="v1")
    assert telemetry.gauge("fleet.replicas_ready").value == 2
    eng_b.begin_drain()
    h = router.submit(prompt_of(4), max_new_tokens=3, key=0)
    assert h.replica_id == rid
    assert len(h.result()) == 3


# ---------------------------------------------------------------------------
# Failover


@pytest.mark.parametrize(
    "temperature,top_k", [(0.0, None), (0.8, 8)], ids=["greedy", "sampled"]
)
def test_midstream_failover_token_identical(family, temperature, top_k):
    """The money path: a stream mid-flight on a replica that dies must
    continue on a peer with not one token lost, duplicated, or changed
    — greedy and sampled — because the replay re-derives the identical
    stream from the pinned key and the verified prefix is skipped."""
    model, cfg, params = family
    eng_a = make_engine(family, temperature=temperature, top_k=top_k,
                        eos_id=EOS)
    eng_b = make_engine(family, temperature=temperature, top_k=top_k,
                        eos_id=EOS)
    router = FleetRouter([eng_a, eng_b], version="v1")
    before = telemetry.counter("fleet.failovers").value
    h = router.submit(prompt_of(6), max_new_tokens=10, key=3)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    eng_a.close()  # the serving replica dies mid-stream
    rest = list(g)  # ...and the same iterator keeps streaming
    expect = solo(model, cfg, params, prompt_of(6), 3, 10, eos=EOS,
                  temperature=temperature, top_k=top_k)
    assert first + rest == expect
    assert h.replica_id == 1 and h.version == "v1" and h.hops == 1
    assert telemetry.counter("fleet.failovers").value == before + 1
    assert eng_a.allocator.num_in_use == 0
    assert eng_b.allocator.num_in_use == 0


def test_failover_replays_prefix_cached_stream(family):
    """A stream admitted THROUGH the prefix cache (its prompt's prefill
    skipped via shared pages) must fail over like any other: the peer —
    whose own cache has never seen the prefix — re-prefills from the
    pinned key and continues token-identically.  Prefix caching is a
    per-engine acceleration; it must never leak into the stream
    contract."""
    model, cfg, params = family
    kw = dict(temperature=0.8, top_k=8, eos_id=EOS, prefix_cache=True)
    eng_a = make_engine(family, **kw)
    eng_b = make_engine(family, **kw)
    router = FleetRouter([eng_a, eng_b], version="v1")
    # Pin least-TTFT routing onto A (B reads slow) so the warmer and the
    # victim land on the SAME replica's prefix index.
    eng_b.detector.observe_tick(5.0)
    # Warm A's prefix index with the shared system prompt.
    warm = router.submit(prompt_of(16), max_new_tokens=2, key=7)
    assert warm.replica_id == 0
    assert len(warm.result()) == 2
    assert eng_a.stats()["prefix_cached_pages"] >= 2
    # The victim stream extends the cached prefix: admission maps shared
    # pages instead of prefilling them.
    victim_prompt = np.concatenate([prompt_of(16), prompt_of(4, base=90)])
    hits_before = eng_a.stats()["prefix_hits"]
    h = router.submit(victim_prompt, max_new_tokens=10, key=8)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    assert eng_a.stats()["prefix_hits"] == hits_before + 1
    eng_a.close()  # the replica (and its whole prefix cache) dies
    rest = list(g)
    assert first + rest == solo(
        model, cfg, params, victim_prompt, 8, 10, eos=EOS,
        temperature=0.8, top_k=8,
    )
    assert h.replica_id == 1 and h.hops == 1
    assert eng_a.allocator.num_in_use == 0
    # B served the replay cold and cached the replayed prompt's pages.
    assert eng_b.stats()["prefix_cached_pages"] >= 2
    eng_b.close()
    assert eng_b.allocator.num_in_use == 0


def test_queued_work_reroutes_on_drain(family):
    """begin_drain() flushes a replica's queue with retryable errors;
    the router re-places that work on a peer — nothing is dropped."""
    model, cfg, params = family
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    handles = [
        router.submit(prompt_of(5, base=i + 1), max_new_tokens=4, key=i)
        for i in range(6)
    ]
    on_a = [h for h in handles if h.replica_id == 0]
    assert on_a  # routing spread work onto A
    eng_a.begin_drain()
    for i, h in enumerate(handles):
        assert h.result() == solo(
            model, cfg, params, prompt_of(5, base=i + 1), i, 4
        )
    assert all(h.replica_id == 1 for h in on_a)  # re-routed, completed
    while eng_a.health() is not Health.STOPPED:
        eng_a.step()
    assert eng_a.allocator.num_in_use == 0
    assert eng_b.allocator.num_in_use == 0


def test_hop_budget_exhausted_fails_typed(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=0)
    before = telemetry.counter("fleet.hops_exhausted").value
    h = router.submit(prompt_of(6), max_new_tokens=8, key=0)
    g = h.tokens()
    next(g)
    eng_a.close()
    with pytest.raises(FailoverExhausted) as ei:
        list(g)
    assert ei.value.retryable
    assert isinstance(ei.value.__cause__, RequestError)
    assert ei.value.__cause__.retryable
    assert h.done and h.error is ei.value
    assert telemetry.counter("fleet.hops_exhausted").value == before + 1
    # A terminally failed handle re-raises, it does not resurrect.
    with pytest.raises(FailoverExhausted):
        h.result()


def test_sole_replica_retried_after_transient_rejection(family):
    """A single-replica fleet must RETRY its replica (with backoff,
    under the hop budget) after a transient rejection — not fail
    NoReplicaAvailable because the one candidate was just excluded."""
    model, cfg, params = family
    eng = make_engine(family)
    router = FleetRouter([eng], version="v1", max_hops=3)
    real_submit = eng.submit
    state = {"n": 0}

    def shed_once(*args, **kwargs):
        state["n"] += 1
        if state["n"] == 1:
            raise EngineOverloaded("transient shed; retry with backoff")
        return real_submit(*args, **kwargs)

    eng.submit = shed_once
    h = router.submit(prompt_of(5), max_new_tokens=4, key=0)
    assert h.hops == 1  # one backoff hop, same replica, success
    assert h.result() == solo(model, cfg, params, prompt_of(5), 0, 4)
    # A persistent rejection still exhausts the budget TYPED.
    eng2 = make_engine(family)
    router2 = FleetRouter([eng2], version="v1", max_hops=2)

    def always_shed(*args, **kwargs):
        raise EngineOverloaded("still overloaded")

    eng2.submit = always_shed
    with pytest.raises(FailoverExhausted):
        router2.submit(prompt_of(5), max_new_tokens=4, key=0)


def test_failover_divergence_fails_typed(family):
    """A replay on a peer whose weights differ (a mislabeled version —
    the parity invariant broken) must fail typed, whether the replay
    MISMATCHES the yielded prefix or ends SHORTER than it — never a
    silent splice or truncation."""
    model, cfg, params = family
    other = llama.init_params(jax.random.PRNGKey(99), cfg)
    eng_a = make_engine(family)
    eng_b = Engine(other, model=model, cfg=cfg, **ENGINE_KW)
    router = FleetRouter([eng_a], version="v1")
    router.add_replica(eng_b, version="v1")  # lies about its weights
    h = router.submit(prompt_of(6), max_new_tokens=8, key=0)
    g = h.tokens()
    consumed = [next(g), next(g)]
    assert consumed == solo(model, cfg, params, prompt_of(6), 0, 8)[:2]
    eng_a.close()
    with pytest.raises(FailoverDiverged) as ei:
        list(g)
    assert not ei.value.retryable
    assert h.done and h.error is ei.value
    eng_b.step()  # the divergence guard cancelled the bad replay...
    assert eng_b.allocator.num_in_use == 0  # ...and its pages came back


def test_midstream_failover_is_version_pinned(family):
    """A stream that already yielded v1 tokens must NOT continue on a
    v2 replica: with every v1 replica gone it fails typed — two model
    versions never interleave within one stream."""
    model, cfg, params = family
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a], version="v1")
    router.add_replica(eng_b, version="v2")
    h = router.submit(prompt_of(6), max_new_tokens=8, key=0)
    assert h.version == "v1"
    g = h.tokens()
    next(g)
    eng_a.close()
    with pytest.raises(NoReplicaAvailable) as ei:
        list(g)
    assert ei.value.retryable
    assert "version" in str(ei.value)
    # A FRESH request (nothing yielded yet) crosses versions freely.
    h2 = router.submit(prompt_of(4), max_new_tokens=3, key=1)
    assert h2.version == "v2"
    assert h2.result() == solo(model, cfg, params, prompt_of(4), 1, 3)


def test_cancelled_request_does_not_fail_over(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    h = router.submit(prompt_of(6), max_new_tokens=20, key=0)
    g = h.tokens()
    next(g)
    assert h.cancel()
    with pytest.raises(RequestCancelled):
        list(g)
    assert h.hops == 0  # the client's own cancel is not an infra failure
    assert not h.cancel()  # post-completion cancel is a reported no-op
    assert eng_a.allocator.num_in_use == 0
    assert eng_b.allocator.num_in_use == 0


def test_fleet_deadline_spans_hops(family):
    """The fleet-level deadline keeps ticking across failovers: a
    re-route cannot grant a request more wall clock than it was given."""
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    h = router.submit(prompt_of(6), max_new_tokens=8, key=0,
                      deadline_s=60.0)
    g = h.tokens()
    next(g)
    h._deadline = 0.0  # force expiry deterministically (no sleeps)
    eng_a.close()
    with pytest.raises(DeadlineExceeded):
        list(g)
    assert isinstance(h.error, DeadlineExceeded)
    assert eng_b.allocator.num_in_use == 0  # never re-submitted


# ---------------------------------------------------------------------------
# Hot swap


def test_hot_swap_zero_drop_under_load(family):
    """A weight upgrade under load: v2 params are deferred-init
    recorded and materialized while v1 serves, admission flips, v1
    drains.  Zero requests dropped; in-flight streams finish on v1;
    queued + fresh work completes on v2; no stream mixes versions."""
    transformers = pytest.importorskip("transformers")
    from torchdistx_tpu.models import convert

    config = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attn_implementation="eager",
    )
    cfg = convert.llama_config_from_hf(config, dtype=jnp.float32, remat=False)
    params_v1 = llama.init_params(jax.random.PRNGKey(7), cfg)
    kw = dict(model=llama, cfg=cfg, **ENGINE_KW)
    eng_v1 = Engine(params_v1, **kw)
    router = FleetRouter([eng_v1], version="v1")

    # In-flight on v1: consume two tokens mid-stream.
    h_live = router.submit(prompt_of(6), max_new_tokens=8, key=0)
    g = h_live.tokens()
    first = [next(g), next(g)]
    # Queued on v1 (slots full before these admit).
    h_queued = [
        router.submit(prompt_of(5, base=i + 2), max_new_tokens=5, key=10 + i)
        for i in range(3)
    ]

    # v2: the paper's standby path — record under deferred_init (zero
    # allocation), materialize to jax.Arrays, convert to the family tree.
    params_v2 = materialize_standby(
        transformers.LlamaForCausalLM, config,
        convert=lambda arrays: convert.llama_params_from_hf(arrays, cfg),
    )
    before_swaps = telemetry.counter("fleet.swaps").value
    prev = telemetry.configure(collect=True)
    try:
        hot_swap(router, lambda: Engine(params_v2, **kw), version="v2")
        span_names = {s["name"] for s in telemetry.snapshot()["spans"]}
        assert "fleet.swap" in span_names
    finally:
        telemetry.configure(**prev)
    assert telemetry.counter("fleet.swaps").value == before_swaps + 1

    # The in-flight stream finished on its ORIGINAL engine: pure v1.
    rest = list(g)
    assert first + rest == solo(llama, cfg, params_v1, prompt_of(6), 0, 8)
    assert h_live.version == "v1" and h_live.hops == 0
    # Queued work was flushed by the drain and re-routed: pure v2.
    for i, h in enumerate(h_queued):
        assert h.result() == solo(
            llama, cfg, params_v2, prompt_of(5, base=i + 2), 10 + i, 5
        )
        assert h.version == "v2" and h.hops >= 1
    # Fresh work lands on v2; v1 is drained, closed, and gone.
    h_new = router.submit(prompt_of(4), max_new_tokens=3, key=99)
    assert h_new.result() == solo(llama, cfg, params_v2, prompt_of(4), 99, 3)
    assert [r.version for r in router.replicas()] == ["v2"]
    assert eng_v1.health() is Health.STOPPED
    assert eng_v1.allocator.num_in_use == 0


# ---------------------------------------------------------------------------
# QoS context propagation (ISSUE 8)


def test_failover_forwards_qos_context(family):
    """A request's QoS context — tenant, priority, deadline — must ride
    EVERY fleet re-submission: a stream preempted on one replica and
    failed over to another keeps its class there.  Pinned two ways: the
    bound engine's queued Request carries the context verbatim, and
    after a failover the peer's QoS engine *acts* on the forwarded
    priority (it preempts its own low-priority stream for the
    newcomer)."""
    model, cfg, params = family

    def qos_engine():
        return make_engine(
            family, scheduler="qos", num_slots=1, decode_chunk=4,
        )

    eng_a, eng_b = qos_engine(), qos_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=3)
    # Occupy both engines' single slot with low-priority local streams.
    a_local = eng_a.submit(prompt_of(6), max_new_tokens=30, key=50,
                           priority=0)
    eng_a.step()
    b_local = eng_b.submit(prompt_of(6, base=2), max_new_tokens=30, key=51,
                           priority=0)
    eng_b.step()
    # Deterministic routing: equal estimates and load -> replica 0 (A).
    eng_a.detector._tick_ewma_s = eng_b.detector._tick_ewma_s = None
    h = router.submit(
        prompt_of(5, base=4), max_new_tokens=6, key=52, deadline_s=60.0,
        tenant="gold", priority=3,
    )
    assert h.replica_id == 0
    queued = eng_a.scheduler.peek()
    assert queued.tenant == "gold" and queued.priority == 3
    assert queued.deadline is not None
    # Kill A: the queued request fails retryably and must re-place on B
    # with its context intact — proven by B's QoS engine PREEMPTING its
    # low-priority stream for the forwarded priority-3 arrival.
    before = telemetry.counter("serve.preemptions_replay").value
    eng_a.close()
    assert h.result() == solo(
        model, cfg, params, prompt_of(5, base=4), 52, 6
    )
    assert h.replica_id == 1 and h.tenant == "gold" and h.priority == 3
    assert telemetry.counter("serve.preemptions_replay").value > before
    # A's local stream died with its engine (typed, retryable)...
    assert isinstance(a_local.error, RequestError) and a_local.error.retryable
    # ...and B's preempted local stream resumed token-identically.
    eng_b.drain()
    assert b_local.result() == solo(
        model, cfg, params, prompt_of(6, base=2), 51, 30
    )
    assert eng_b.allocator.num_in_use == 0
    assert eng_b.allocator.num_swapped == 0


# ---------------------------------------------------------------------------
# Mini fleet chaos (the CI-scale soak lives in scripts/chaos_soak.py)


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): the CI fleet-chaos job covers this scenario
def test_fleet_mini_chaos_kill_and_swap(family):
    """Mixed traffic over 2 engines; one is killed mid-load (device
    failure + close) and a hot-swap retires the other: every request
    completes token-identical to solo generate() on SOME replica or
    fails typed by its own deadline/cancel — infrastructure loss is
    zero — and no replica leaks a page."""
    model, cfg, params = family
    rng = np.random.default_rng(42)
    kw = dict(eos_id=EOS)
    eng_a, eng_b = make_engine(family, **kw), make_engine(family, **kw)
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    before_failovers = telemetry.counter("fleet.failovers").value

    reqs = []
    for i in range(28):
        plen = int(rng.integers(3, 14))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.choice([4, 8, 12]))
        deadline = None if rng.random() > 0.1 else 1e-6
        h = router.submit(prompt, max_new_tokens=mnt, key=i,
                          deadline_s=deadline)
        if rng.random() < 0.1:
            h.cancel()
        reqs.append((prompt, mnt, i, h))

    eng_c = None
    for idx, (prompt, mnt, key, h) in enumerate(reqs):
        if idx == 10:
            # Kill A mid-load: the device fails (pool consumed), the
            # replica is closed — its work must re-route, not vanish.
            for leaf in jax.tree.leaves(eng_a._cache):
                leaf.delete()
            eng_a.close()
            assert router.poll() == [0]
        if idx == 18:
            # Upgrade under the remaining load (same weights: every
            # surviving stream still compares against one solo oracle).
            eng_c = make_engine(family, **kw)
            hot_swap(router, lambda: eng_c, version="v2")
        try:
            toks = h.result()
        except RequestError:
            pass
        assert h.done, f"request {key} neither finished nor failed"
        if h.error is not None:
            assert isinstance(
                h.error, (DeadlineExceeded, RequestCancelled)
            ), f"request {key} lost to infrastructure: {h.error!r}"
        else:
            assert toks == solo(
                model, cfg, params, prompt, key, mnt, eos=EOS
            ), f"request {key} diverged from solo generate()"

    n_ok = sum(h.error is None for *_, h in reqs)
    assert n_ok >= 15, "chaos failed almost everything — soak too aggressive"
    assert telemetry.counter("fleet.failovers").value > before_failovers
    for eng in (eng_a, eng_b, eng_c):
        assert eng.allocator.num_in_use == 0, "pages leaked"
    assert [r.version for r in router.replicas()] == ["v2"]


# ---------------------------------------------------------------------------
# Small-N chaos regressions (ISSUE 12): dead-replica buffers + placement
# retry through momentary unroutable windows


def test_killed_replica_buffer_discarded_not_version_pinned(family):
    """The TDX_CHAOS_REQUESTS=16 fleet failure, pinned: a stream whose
    tokens were BUFFERED (never yielded) on a replica that then died
    must not drain the corpse's buffer at pull time — doing so
    version-pins the stream to a replica set a hot swap may have
    already retired, and the pull dies NoReplicaAvailable.  The
    un-yielded buffer is discarded instead and the stream replays
    wherever the router can place it, token-identical from the pinned
    key."""
    model, cfg, params = family
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    eng_b.detector.observe_tick(5.0)  # pin least-TTFT routing onto A
    h = router.submit(prompt_of(6), max_new_tokens=24, key=0)
    assert h.replica_id == 0
    for _ in range(3):  # tokens buffer on A; the consumer pulls nothing
        eng_a.step()
    assert len(h._inner._tokens) > 0 and not h._inner.done
    # A dies (device failure + close) and a hot swap retires B before
    # the handle is ever pulled: zero v1 capacity remains anywhere.
    for leaf in jax.tree.leaves(eng_a._cache):
        leaf.delete()
    eng_a.close()
    router.poll()
    eng_c = make_engine(family)
    hot_swap(router, lambda: eng_c, version="v2")
    assert [rep.version for rep in router.replicas()] == ["v2"]
    assert h.result() == solo(model, cfg, params, prompt_of(6), 0, 24)
    assert h.error is None and h.hops >= 1
    assert eng_c.allocator.num_in_use == 0


def test_placement_retries_through_momentary_unroutable_window(family):
    """A fleet with no routable replica is routinely a MOMENTARY window
    (every replica draining mid-swap, a kill reaped an instant before
    the respawn registers — constant at tiny N): placement must retry
    with backoff under the hop budget, not fail the request on first
    sight."""
    eng = make_engine(family)
    router = FleetRouter([eng], version="v1", max_hops=4)
    real_pick = router._pick
    calls = {"n": 0}

    def flaky_pick(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] <= 2:  # two sightings of an "empty" fleet
            return None
        return real_pick(*args, **kwargs)

    router._pick = flaky_pick
    model, cfg, params = family
    h = router.submit(prompt_of(4), max_new_tokens=3, key=1)
    assert calls["n"] >= 3
    assert h.result() == solo(model, cfg, params, prompt_of(4), 1, 3)
    assert h.error is None


def test_genuinely_empty_fleet_still_fails_typed():
    """The budget bounds the tolerance: a fleet that STAYS unroutable
    fails NoReplicaAvailable (typed, retryable) once the placement
    retries exhaust — never a hang, never a silent drop."""
    router = FleetRouter([], version="v1", max_hops=2)
    with pytest.raises(NoReplicaAvailable) as ei:
        router.submit(prompt_of(4), max_new_tokens=2, key=0)
    assert ei.value.retryable


# ---------------------------------------------------------------------------
# Model plane over the fleet (ISSUE 18)


def test_killed_replica_forwards_model_and_n(family):
    """A pool-model fork stream (``submit(model=..., n=...)``) whose
    replica is killed mid-stream must re-place with BOTH forwarded —
    the peer materializes the same weights on demand and the parent
    stream (sibling 0, key ``fold_in(base, 0)``) continues
    token-identically.  Pinned two ways: the bound engine's live
    Request carries the model tag, and the fork group exists on the
    replacement replica after failover."""
    from torchdistx_tpu.serving import ModelPool

    model, cfg, params = family

    def pooled_engine():
        pool = ModelPool()
        pool.register(
            "tuna", model=model, cfg=cfg,
            materialize=lambda: llama.init_params(jax.random.PRNGKey(9),
                                                  cfg),
        )
        return make_engine(family, num_slots=4, temperature=0.7, top_k=8,
                           eos_id=EOS, model_pool=pool)

    eng_a, eng_b = pooled_engine(), pooled_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=3)
    h = router.submit(prompt_of(6), max_new_tokens=10, key=3,
                      model="tuna", n=2)
    assert h.replica_id == 0 and h.model == "tuna" and h.n == 2
    g = h.tokens()
    first = [next(g)]
    # The bound engine's request carries the model tag, and the fork
    # group (parent + 1 sibling) landed there.
    live_tags = {r.model_tag for r in eng_a._slot_req if r is not None}
    assert "tuna" in live_tags
    assert eng_a.stats()["forks"] == 1
    eng_a.close()  # the serving replica dies mid-stream
    rest = list(g)
    # Token parity: sibling 0 of an n=2 fork samples under
    # fold_in(base, 0) — on the peer's on-demand-materialized weights.
    p9 = llama.init_params(jax.random.PRNGKey(9), cfg)
    k0 = np.asarray(
        jax.random.fold_in(jax.random.PRNGKey(3), 0)
    ).astype(np.uint32).reshape(2)
    out = generate(
        p9, jnp.asarray(prompt_of(6))[None], k0, model=model, cfg=cfg,
        max_new_tokens=10, eos_id=EOS, temperature=0.7, top_k=8,
    )
    expect = [int(t) for t in np.asarray(out)[0]]
    if EOS in expect:
        expect = expect[: expect.index(EOS) + 1]
    assert first + rest == expect
    assert h.replica_id == 1 and h.hops == 1
    assert eng_b.model_pool.ready("tuna")  # materialized on demand
    assert eng_b.stats()["forks"] == 1  # n rode the re-submission
    eng_b.drain()
    assert eng_a.allocator.num_in_use == 0
    assert eng_b.allocator.num_in_use == 0
