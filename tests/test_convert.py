"""HF → native bridge: logit equivalence against transformers eager models.

These are the strongest correctness oracles for the native model families:
the same weights must produce (near-)identical logits.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from torchdistx_tpu.models import convert, gpt2, llama


def _np_state_dict(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


class TestGPT2:
    @pytest.fixture(scope="class")
    def hf(self):
        from transformers import GPT2Config, GPT2LMHeadModel

        torch.manual_seed(0)
        config = GPT2Config(
            vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4
        )
        model = GPT2LMHeadModel(config).eval()
        return model, config

    def test_logit_equivalence(self, hf):
        model, config = hf
        cfg = convert.gpt2_config_from_hf(
            config, dtype=jnp.float32, remat=False
        )
        params = convert.gpt2_params_from_hf(_np_state_dict(model), cfg)
        tokens = torch.randint(0, 128, (2, 16), generator=torch.Generator().manual_seed(1))
        with torch.no_grad():
            ref = model(tokens).logits.numpy()
        ours = np.asarray(
            gpt2.forward(params, jnp.asarray(tokens.numpy()), cfg, attn_impl="jnp")
        )
        assert np.abs(ref - ours).max() < 2e-3

    def test_from_materialized_arrays(self, hf):
        """deferred_init(HF) → materialize_module_jax → convert → forward."""
        from transformers import GPT2Config, GPT2LMHeadModel

        import torchdistx_tpu.deferred_init as di
        from torchdistx_tpu.materialize import materialize_module_jax

        config = GPT2Config(
            vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4
        )
        fake = di.deferred_init(GPT2LMHeadModel, config)
        arrays = materialize_module_jax(fake)
        cfg = convert.gpt2_config_from_hf(config, dtype=jnp.float32, remat=False)
        params = convert.gpt2_params_from_hf(arrays, cfg)
        logits = gpt2.forward(
            params, jnp.zeros((1, 8), jnp.int32), cfg, attn_impl="jnp"
        )
        assert logits.shape == (1, 8, 128)
        assert bool(jnp.isfinite(logits).all())


class TestLlama:
    @pytest.fixture(scope="class")
    def hf(self):
        from transformers import LlamaConfig, LlamaForCausalLM

        torch.manual_seed(0)
        config = LlamaConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=64,
            attn_implementation="eager",
        )
        model = LlamaForCausalLM(config).eval()
        return model, config

    def test_logit_equivalence(self, hf):
        model, config = hf
        cfg = convert.llama_config_from_hf(
            config, dtype=jnp.float32, remat=False
        )
        params = convert.llama_params_from_hf(_np_state_dict(model), cfg)
        tokens = torch.randint(0, 128, (2, 16), generator=torch.Generator().manual_seed(1))
        with torch.no_grad():
            ref = model(tokens).logits.numpy()
        ours = np.asarray(
            llama.forward(params, jnp.asarray(tokens.numpy()), cfg, attn_impl="jnp")
        )
        assert np.abs(ref - ours).max() < 2e-3

    def test_generate_with_converted_weights(self, hf):
        from torchdistx_tpu.models.generate import generate
        import jax

        model, config = hf
        cfg = convert.llama_config_from_hf(config, dtype=jnp.float32, remat=False)
        params = convert.llama_params_from_hf(_np_state_dict(model), cfg)
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate(
            params, prompt, jax.random.PRNGKey(0), model=llama, cfg=cfg,
            max_new_tokens=4, temperature=0.0,
        )
        # HF greedy reference
        with torch.no_grad():
            hf_out = model.generate(
                torch.zeros((1, 4), dtype=torch.long), max_new_tokens=4,
                do_sample=False,
            )[0, 4:].numpy()
        assert np.array_equal(np.asarray(out)[0], hf_out)
