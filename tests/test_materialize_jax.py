"""JAX materialization tests: the shard-then-materialize path.

Runs on a virtual 8-device CPU mesh (conftest.py) — the analog of the
reference's single-host multi-GPU FSDPTest trick (SURVEY.md §4)."""

import numpy as np
import pytest
import torch
import torch.nn as nn

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu import fake
from torchdistx_tpu.materialize import (
    materialize_module_jax,
    materialize_tensor_jax,
)
from torchdistx_tpu.parallel import (
    MeshSpec,
    combine_plans,
    fsdp_plan,
    fsdp_over,
    make_mesh,
    tp_plan_gpt2,
    tp_plan_llama,
)


def test_materialize_tensor_jax_values():
    with di._deferred_init_context():
        t = torch.zeros(4, 4)
        t.add_(1)
        t.mul_(3)
    arr = materialize_tensor_jax(t)
    np.testing.assert_allclose(np.asarray(arr), np.full((4, 4), 3.0))


def test_materialize_linear_statistics():
    m = di.deferred_init(nn.Linear, 128, 64)
    out = materialize_module_jax(m)
    assert set(out) == {"weight", "bias"}
    w = np.asarray(out["weight"])
    assert w.shape == (64, 128)
    bound = (1 / 128) ** 0.5 * (3**0.5)
    assert np.abs(w).max() <= bound + 1e-6
    assert w.std() > 0.5 * bound / (3**0.5)  # roughly uniform spread


def test_jax_path_view_and_inplace():
    with di._deferred_init_context():
        base = torch.zeros(2, 4)
        row = base[1]
        row.fill_(7)
        base.mul_(2)
    arr = materialize_tensor_jax(base)
    np.testing.assert_allclose(
        np.asarray(arr), [[0.0] * 4, [14.0] * 4]
    )


def test_jax_matches_torch_replay_for_deterministic_ops():
    def build():
        t = torch.arange(12.0).view(3, 4)
        u = (t * 2).t()
        return nn.Parameter(u.contiguous())

    with di._deferred_init_context():
        p = build()
    arr = materialize_tensor_jax(p)
    ref = di.materialize_tensor(p)
    np.testing.assert_allclose(np.asarray(arr), ref.detach().numpy())


def test_sharded_materialization_fsdp():
    mesh = make_mesh(MeshSpec(fsdp=8))
    m = di.deferred_init(nn.Linear, 256, 128)
    out = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan())
    w = out["weight"]
    assert w.shape == (128, 256)
    # Sharded along the largest dim (256 = dim 1) over 8 devices.
    assert len(w.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(128, 32)}
    # Bias is small -> replicated.
    assert out["bias"].sharding.is_fully_replicated


def test_sharded_values_match_unsharded():
    mesh = make_mesh(MeshSpec(fsdp=8))
    m = di.deferred_init(nn.Linear, 64, 32)
    sharded = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=1))
    unsharded = materialize_module_jax(m)
    for k in sharded:
        np.testing.assert_allclose(
            np.asarray(sharded[k]), np.asarray(unsharded[k]), rtol=1e-6
        )


def test_replicate_mesh_args_places_explicitly():
    """VERDICT item 8b: mesh-job argument leaves are handed to compiled
    executables as explicitly mesh-replicated arrays — never as raw host
    numpy relying on Compiled.__call__'s version-dependent tolerance."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from torchdistx_tpu.materialize import _replicate_mesh_args

    mesh = make_mesh(MeshSpec(fsdp=8))
    args = [
        (np.arange(6, dtype=np.uint32), [np.ones((2, 3), np.float32)]),
        (np.float64(2.5), 7),  # non-array leaves pass through untouched
    ]
    placed = _replicate_mesh_args(args, mesh)
    rep = NamedSharding(mesh, PartitionSpec())
    a0, (a1,) = placed[0]
    for arr, src in ((a0, args[0][0]), (a1, args[0][1][0])):
        assert isinstance(arr, jax.Array)
        assert arr.sharding.is_equivalent_to(rep, arr.ndim)
        np.testing.assert_array_equal(np.asarray(arr), src)
    assert placed[1] == args[1]


def test_sharded_mesh_jobs_fed_replicated_inputs():
    """End-to-end: a mesh materialization routes its rest-job args
    through _replicate_mesh_args (values already pinned by
    test_sharded_values_match_unsharded; this pins the placement)."""
    import torchdistx_tpu.materialize as mz

    mesh = make_mesh(MeshSpec(fsdp=8))
    seen = []
    orig = mz._replicate_mesh_args

    def spy(all_args, m):
        out = orig(all_args, m)
        seen.append(out)
        return out

    mz._replicate_mesh_args = spy
    try:
        m = di.deferred_init(nn.Linear, 64, 32)
        materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=1))
    finally:
        mz._replicate_mesh_args = orig
    assert seen, "mesh run never placed its job args explicitly"


def test_tp_plan_gpt2_specs():
    plan = tp_plan_gpt2()
    assert tuple(plan("transformer.h.0.attn.c_attn.weight", (768, 2304))) == (None, "tp")
    assert tuple(plan("transformer.h.0.attn.c_proj.weight", (768, 768))) == ("tp", None)
    assert tuple(plan("transformer.wte.weight", (50257, 768))) == ("tp", None)
    assert tuple(plan("transformer.h.0.ln_1.weight", (768,))) == ()


def test_tp_plan_llama_specs():
    plan = tp_plan_llama()
    assert tuple(plan("model.layers.0.self_attn.q_proj.weight", (4096, 4096))) == ("tp", None)
    assert tuple(plan("model.layers.0.self_attn.o_proj.weight", (4096, 4096))) == (None, "tp")
    assert tuple(plan("model.layers.0.mlp.down_proj.weight", (4096, 11008))) == (None, "tp")


def test_fsdp_over_tp_2d():
    plan = fsdp_over(tp_plan_llama())
    spec = plan("model.layers.0.self_attn.q_proj.weight", (4096, 4096))
    assert tuple(spec) == ("tp", "fsdp")
    spec = plan("model.norm.weight", (4096,))
    assert tuple(spec) == ("fsdp",)


def test_gpt2_block_sharded_tp():
    from transformers.models.gpt2.modeling_gpt2 import GPT2Config, GPT2Block

    cfg = GPT2Config(n_layer=2, n_embd=256, n_head=4)
    mesh = make_mesh(MeshSpec(dp=2, tp=4))
    blk = di.deferred_init(GPT2Block, cfg)
    out = materialize_module_jax(blk, mesh=mesh, plan=tp_plan_gpt2())
    w = out["attn.c_attn.weight"]
    assert w.shape == (256, 768)
    # column-parallel over tp=4: each shard (256, 192), replicated over dp.
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(256, 192)}


def test_dtype_override_bf16():
    import jax.numpy as jnp

    m = di.deferred_init(nn.Linear, 32, 16)
    out = materialize_module_jax(m, dtype=torch.bfloat16)
    assert out["weight"].dtype == jnp.bfloat16


def test_rng_order_independence():
    # JAX path keys by op_nr: materializing params in any order gives the
    # same values (unlike the torch global-stream path).
    m = di.deferred_init(nn.Linear, 16, 8)
    both = materialize_module_jax(m, seed=3)
    w_only = materialize_tensor_jax(m.weight, seed=3)
    np.testing.assert_allclose(
        np.asarray(both["weight"]), np.asarray(w_only), rtol=1e-7
    )


def test_guard_failure_in_jax_path():
    ext = torch.ones(4)
    with di._deferred_init_context():
        t = torch.zeros(4)
        u = t + ext
    ext.add_(1)
    with pytest.raises(RuntimeError, match="mutated after recording"):
        materialize_tensor_jax(u)


def test_jax_cross_tape_module():
    m1 = di.deferred_init(nn.Linear, 4, 4)
    m2 = di.deferred_init(nn.Linear, 4, 4)
    seq = nn.Sequential(m1, m2)
    out = materialize_module_jax(seq)
    assert set(out) == {"0.weight", "0.bias", "1.weight", "1.bias"}
    assert not np.allclose(np.asarray(out["0.weight"]), np.asarray(out["1.weight"]))


class _DeepModel(nn.Module):
    """Repeated-block model: the grouped strategy's target shape (48-layer
    models record 48 structurally identical stacks per parameter kind)."""

    def __init__(self, depth=6, dim=32):
        super().__init__()
        self.emb = nn.Embedding(100, dim)
        self.blocks = nn.ModuleList(
            [nn.Linear(dim, dim) for _ in range(depth)]
        )
        self.norm = nn.LayerNorm(dim)


def test_grouped_matches_fused():
    m = di.deferred_init(_DeepModel)
    fused = materialize_module_jax(m, strategy="fused")
    grouped = materialize_module_jax(m, strategy="grouped")
    assert set(fused) == set(grouped)
    for k in fused:
        np.testing.assert_allclose(
            np.asarray(fused[k]), np.asarray(grouped[k]), rtol=1e-7
        )


def test_grouped_matches_fused_sharded():
    mesh = make_mesh(MeshSpec(fsdp=8))
    m = di.deferred_init(_DeepModel, depth=4, dim=64)
    fused = materialize_module_jax(
        m, mesh=mesh, plan=fsdp_plan(min_size=1), strategy="fused"
    )
    grouped = materialize_module_jax(
        m, mesh=mesh, plan=fsdp_plan(min_size=1), strategy="grouped"
    )
    for k in fused:
        assert fused[k].sharding == grouped[k].sharding, k
        np.testing.assert_allclose(
            np.asarray(fused[k]), np.asarray(grouped[k]), rtol=1e-7
        )


def test_grouped_handles_aliased_params_via_fused_fallback():
    # Params whose stacks share nodes must take the fused path inside the
    # grouped strategy (write-ordering through aliases).
    class M(nn.Module):
        pass

    with di._deferred_init_context():
        t = torch.zeros(4)
        u = t + 1
        t.add_(5)
        mod = M()
        mod.t = nn.Parameter(t)
        mod.u = nn.Parameter(u)
        mod.lin = nn.Linear(4, 4)  # groupable alongside
    out = materialize_module_jax(mod, strategy="grouped")
    np.testing.assert_allclose(np.asarray(out["t"]), np.full((4,), 5.0))
    np.testing.assert_allclose(np.asarray(out["u"]), np.ones(4))
    assert out["lin.weight"].shape == (4, 4)


def test_jax_order_independent_aliasing():
    class M(nn.Module):
        pass

    with di._deferred_init_context():
        t = torch.zeros(4)
        u = t + 1
        t.add_(5)
        mod = M()
        mod.t = nn.Parameter(t)
        mod.u = nn.Parameter(u)
    out = materialize_module_jax(mod)
    np.testing.assert_allclose(np.asarray(out["t"]), np.full((4,), 5.0))
    np.testing.assert_allclose(np.asarray(out["u"]), np.ones(4))


def test_rng_cross_tape_reproducibility():
    """Same architecture recorded in two different tapes materializes to
    identical values (streams key on tape-relative identities, never the
    process-global op counter) — and the second materialization reuses the
    first's compiled executable outright (exec cache)."""
    import torchdistx_tpu.materialize as M

    m1 = di.deferred_init(_DeepModel)
    a1 = materialize_module_jax(m1, seed=5)
    hits_before = M.exec_cache_hits
    m2 = di.deferred_init(_DeepModel)
    a2 = materialize_module_jax(m2, seed=5)
    assert M.exec_cache_hits == hits_before + 1
    assert set(a1) == set(a2)
    for k in a1:
        np.testing.assert_array_equal(np.asarray(a1[k]), np.asarray(a2[k]))
    # Distinct same-signature params still draw distinct streams.
    assert not np.array_equal(
        np.asarray(a1["blocks.0.weight"]), np.asarray(a1["blocks.1.weight"])
    )


def test_exec_cache_seed_sweep_and_dtype():
    import torchdistx_tpu.materialize as M

    m1 = di.deferred_init(nn.Linear, 16, 8)
    m2 = di.deferred_init(nn.Linear, 16, 8)
    m3 = di.deferred_init(nn.Linear, 16, 8)
    a1 = materialize_module_jax(m1, seed=1)
    hits_before = M.exec_cache_hits
    # The base key is a traced input: a seed sweep reuses one executable
    # while still drawing distinct values.
    a2 = materialize_module_jax(m2, seed=2)
    assert M.exec_cache_hits == hits_before + 1
    assert not np.array_equal(np.asarray(a1["weight"]), np.asarray(a2["weight"]))
    a3 = materialize_module_jax(m3, seed=1, dtype=torch.bfloat16)
    assert M.exec_cache_hits == hits_before + 1  # different dtype: no reuse
    assert str(a3["weight"].dtype) == "bfloat16"


def test_mono_fast_path_matches_per_job_path(monkeypatch):
    """The mono executable (whole materialization as one program — the
    cached-cold RPC floor on a tunneled chip) must produce bitwise the
    same values as the per-job path and count as a cache-hit run."""
    import torchdistx_tpu.materialize as M

    monkeypatch.setenv("TDX_PROFILE_MATERIALIZE", "1")
    m1 = di.deferred_init(_DeepModel)
    materialize_module_jax(m1, seed=9)  # compiles jobs + seeds mono (mem)
    hits = M.exec_cache_hits
    m2 = di.deferred_init(_DeepModel)
    a2 = materialize_module_jax(m2, seed=9)  # mono mem-tier hit
    assert M.exec_cache_hits == hits + 1
    # Prove the mono executable actually served the second call.
    assert any(lbl == "mono" for lbl, _, _ in M.last_profile["jobs"]), (
        M.last_profile
    )
    monkeypatch.setenv("TDX_NO_MONO", "1")
    m3 = di.deferred_init(_DeepModel)
    a3 = materialize_module_jax(m3, seed=9)  # per-job path
    assert set(a2) == set(a3)
    for k in a2:
        np.testing.assert_array_equal(np.asarray(a2[k]), np.asarray(a3[k]))


def test_bigfill_classes_2d_plan_match_tensor_path(monkeypatch):
    """Large fills (> FILL_POOL_MAX) on a 2-D tp×fsdp mesh take the
    big-fill class path with mixed dim-0/dim-1 shardings; values must be
    bitwise-equal to the single-device tensor path and actually sharded."""
    from transformers import LlamaConfig, LlamaForCausalLM

    import torchdistx_tpu.materialize as M

    monkeypatch.setenv("TDX_PROFILE_MATERIALIZE", "1")
    config = LlamaConfig(
        # embed/lm_head (4096×512) and the mlp mats (512×2752, sharded on
        # dim 1 by the tp plan) are all > FILL_POOL_MAX → big-fill classes
        # with mixed dim-0/dim-1 specs; q_proj (512²) stays pooled.
        vocab_size=4096, hidden_size=512, intermediate_size=2752,
        num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=8, max_position_embeddings=64,
    )
    model = di.deferred_init(LlamaForCausalLM, config)
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    arrays = materialize_module_jax(
        model, mesh=mesh, plan=combine_plans(tp_plan_llama(), fsdp_plan())
    )
    fakes = dict(model.named_parameters())
    # The class path must have actually served this materialization.
    assert any(
        lbl == "bigfillcls" for lbl, _, _ in M.last_profile["jobs"]
    ), M.last_profile
    embed = arrays["model.embed_tokens.weight"]  # 4096×512 = 2.1M > pool max
    assert not embed.sharding.is_fully_replicated
    for name in (
        "model.embed_tokens.weight",
        "model.layers.0.self_attn.q_proj.weight",
        "model.layers.1.mlp.down_proj.weight",
    ):
        got = np.asarray(arrays[name])
        want = np.asarray(materialize_tensor_jax(fakes[name]))
        np.testing.assert_array_equal(got, want, err_msg=name)


def test_tensor_path_cross_tape_streams_distinct():
    """A call stack spanning two tapes draws distinct streams per tape —
    same-relative-offset RNG ops must not produce identical values."""
    t1 = di.deferred_init(lambda: torch.empty(8).uniform_())

    def second():
        return torch.empty(8).uniform_().add_(t1 * 0)

    t2 = di.deferred_init(second)
    v1 = np.asarray(materialize_tensor_jax(t1, seed=0))
    v2 = np.asarray(materialize_tensor_jax(t2, seed=0))
    assert not np.allclose(v1, v2)


def test_pow_lowering_values():
    """pow.Scalar is the one lowering whose FIRST aten arg is the scalar
    (scalar-base ** tensor-exponent, HF Llama's RoPE inv_freq) — lock the
    argument order against eager torch."""
    with di._deferred_init_context():
        exp = torch.arange(0, 8, 2, dtype=torch.float32) / 8
        t = 2.0 ** -exp                      # aten.pow.Scalar
        u = exp ** 2.0                       # aten.pow.Tensor_Scalar
        w = exp ** torch.full((4,), 3.0)     # aten.pow.Tensor_Tensor
    exp_e = np.arange(0, 8, 2, dtype=np.float32) / 8
    np.testing.assert_allclose(
        np.asarray(materialize_tensor_jax(t)), 2.0 ** -exp_e, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(materialize_tensor_jax(u)), exp_e**2.0, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(materialize_tensor_jax(w)), exp_e**3.0, rtol=1e-6
    )


# -- multi-mutation scatter (VERDICT r2 weak #5) ----------------------------

_TWOMUT_LIB = None


def _twomut_op():
    """A custom op mutating TWO positional args, each aliased by its own
    return — the shape that exposed the old outs[0]-everywhere scatter."""
    global _TWOMUT_LIB
    if _TWOMUT_LIB is None:
        lib = torch.library.Library("tdxtest", "DEF")  # noqa: TOR901
        lib.define(
            "twomut(Tensor(a!) x, Tensor(b!) y) -> (Tensor(a!), Tensor(b!))"
        )

        def impl(x, y):
            x.add_(1.0)
            y.mul_(2.0)
            return x, y

        lib.impl("twomut", impl, "CompositeExplicitAutograd")
        lib.impl("twomut", impl, "Meta")
        from torchdistx_tpu.ops import LOWERINGS

        LOWERINGS["tdxtest.twomut.default"] = (
            lambda ctx, x, y: (x + 1.0, y * 2.0)
        )
        _TWOMUT_LIB = lib
    return torch.ops.tdxtest.twomut


def test_two_mutated_args_each_get_own_result():
    op = _twomut_op()
    with di._deferred_init_context():
        x = torch.zeros(4)
        y = torch.ones(4)
        op(x, y)
    np.testing.assert_allclose(np.asarray(materialize_tensor_jax(x)), 1.0)
    # Old scatter wrote outs[0] (= x+1 = 1.0) here instead of y*2.
    np.testing.assert_allclose(np.asarray(materialize_tensor_jax(y)), 2.0)


def test_out_variant_kwarg_only_mutation():
    """aminmax.out mutates two kwarg-ONLY buffers; each must receive its own
    schema-aliased return through the replay scatter."""
    with di._deferred_init_context():
        src = torch.arange(6.0).view(2, 3)
        mn = torch.zeros(2)
        mx = torch.zeros(2)
        torch.aminmax(src, dim=1, out=(mn, mx))
        mn.add_(0.0)  # force post-mutation read through the buffers
        mx.add_(0.0)
    np.testing.assert_allclose(
        np.asarray(materialize_tensor_jax(mn)), [0.0, 3.0]
    )
    np.testing.assert_allclose(
        np.asarray(materialize_tensor_jax(mx)), [2.0, 5.0]
    )


def test_exec_cache_is_lru():
    """A hit refreshes recency, so hot entries survive eviction (ADVICE r2)."""
    import torchdistx_tpu.materialize as M

    saved = dict(M._EXEC_CACHE)
    M._EXEC_CACHE.clear()
    try:
        M._exec_cache_put("hot", "H")
        for i in range(M._EXEC_CACHE_MAX - 1):
            M._exec_cache_put(f"cold{i}", i)
        assert M._exec_cache_get("hot") == "H"  # refresh: back of the queue
        M._exec_cache_put("new", "N")           # evicts cold0, not hot
        assert "hot" in M._EXEC_CACHE
        assert "cold0" not in M._EXEC_CACHE
    finally:
        M._EXEC_CACHE.clear()
        M._EXEC_CACHE.update(saved)
