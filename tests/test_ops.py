"""Live ops plane (ISSUE 10): metrics exposition, healthz/requests
endpoints, per-tick utilization attribution, SLO burn-rate monitor,
stall watchdog, and bounded labeled-metric cardinality.

The rendering tests double as the exposition-format contract: the
parser here mirrors the one scripts/chaos_soak.py validates scrapes
with, so a drift in the renderer fails both."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import llama
from torchdistx_tpu.serving import Engine, Health
from torchdistx_tpu.serving.blocks import BlockAllocator
from torchdistx_tpu.serving.qos import QoSScheduler
from torchdistx_tpu.serving.scheduler import Request
from torchdistx_tpu.telemetry import _core, ops

ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    prev = telemetry.configure(collect=False, jsonl=None, flight=None)
    telemetry.reset()
    ops.enable_tick_attribution(False)
    yield
    # A plane leaked by a failing test must not hold its port (or its
    # watchdog threads) into the next.
    for plane in list(ops._PLANES.values()):
        plane.close()
    ops.enable_tick_attribution(False)
    telemetry.configure(**prev)
    telemetry.reset()


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def http_get(url, timeout=5.0):
    """(status, body-bytes) — non-2xx returned, not raised."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def parse_exposition(text):
    """Validating Prometheus text-exposition parser (the contract the
    chaos-soak scrape check enforces too).  Returns
    ``{family: {"type": t, "samples": [(name, labels, value)]}}`` and
    asserts histogram coherence: cumulative buckets non-decreasing and
    ``+Inf`` == ``_count``."""
    fams, cur = {}, None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[:2] == ["#", "TYPE"], f"bad comment line: {line!r}"
            assert len(parts) == 4, f"bad TYPE line: {line!r}"
            cur = parts[2]
            assert cur not in fams, f"duplicate TYPE for {cur}"
            fams[cur] = {"type": parts[3], "samples": []}
            continue
        name, _, rest = line.partition("{")
        labels = {}
        if rest:
            lblstr, _, rest = rest.rpartition("}")
            for m in __import__("re").finditer(
                r'([a-zA-Z0-9_:]+)="((?:[^"\\]|\\.)*)"', lblstr
            ):
                labels[m.group(1)] = (
                    m.group(2)
                    .replace("\\n", "\n")
                    .replace('\\"', '"')
                    .replace("\\\\", "\\")
                )
            value = rest.strip()
        else:
            name, _, value = line.partition(" ")
            value = value.strip()
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and fam[: -len(suffix)] in fams:
                fam = name[: -len(suffix)]
        assert fam in fams, f"sample before TYPE: {line!r}"
        fams[fam]["samples"].append((name, labels, float(value)))
    for fam, d in fams.items():
        if d["type"] != "histogram":
            continue
        series = {}
        for name, labels, value in d["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            s = series.setdefault(key, {"buckets": [], "count": None})
            if name.endswith("_bucket"):
                s["buckets"].append((labels["le"], value))
            elif name.endswith("_count"):
                s["count"] = value
        for key, s in series.items():
            counts = [v for _, v in s["buckets"]]
            assert counts == sorted(counts), f"{fam}{key}: buckets not cumulative"
            infs = [v for le, v in s["buckets"] if le == "+Inf"]
            assert infs and infs[0] == s["count"], (
                f"{fam}{key}: +Inf bucket {infs} != count {s['count']}"
            )
    return fams


def sample(fams, name, **labels):
    base = name
    for famname, d in fams.items():
        for sname, slabels, value in d["samples"]:
            if sname == base and all(
                slabels.get(k) == str(v) for k, v in labels.items()
            ):
                return value
    return None


# ---------------------------------------------------------------------------
# Prometheus rendering


def test_prom_counters_gauges_render():
    telemetry.counter("ops_test.hits").add(7)
    telemetry.gauge("ops_test.depth").set(3.5)
    fams = parse_exposition(ops.render_prometheus())
    assert fams["ops_test_hits"]["type"] == "counter"
    assert sample(fams, "ops_test_hits") == 7
    assert fams["ops_test_depth"]["type"] == "gauge"
    assert sample(fams, "ops_test_depth") == 3.5


def test_prom_labeled_names_become_labels():
    telemetry.gauge("ops_test.qd", tenant="alice").set(2)
    telemetry.gauge("ops_test.qd", tenant="bob").set(5)
    fams = parse_exposition(ops.render_prometheus())
    # One TYPE line for the family, one sample per label set.
    assert len(fams["ops_test_qd"]["samples"]) == 2
    assert sample(fams, "ops_test_qd", tenant="alice") == 2
    assert sample(fams, "ops_test_qd", tenant="bob") == 5


def test_prom_state_gauge_for_non_numeric_values():
    telemetry.gauge("ops_test.health", engine="e0").set("ready")
    fams = parse_exposition(ops.render_prometheus())
    name, labels, value = [
        s for s in fams["ops_test_health"]["samples"]
        if s[1].get("engine") == "e0"
    ][0]
    assert labels["state"] == "ready" and value == 1


def test_prom_histogram_inf_bucket_and_sum():
    h = telemetry.histogram("ops_test.lat")
    for v in (1e-5, 0.003, 0.05, 2.0, 1e6):  # under- and overflow too
        h.observe(v)
    fams = parse_exposition(ops.render_prometheus())  # asserts +Inf == count
    assert sample(fams, "ops_test_lat_count") == 5
    assert abs(sample(fams, "ops_test_lat_sum") - (1e-5 + 0.003 + 0.05 + 2.0 + 1e6)) < 1e-6


def test_prom_label_escaping():
    telemetry.gauge("ops_test.esc", tenant='a"b\\c').set(1)
    text = ops.render_prometheus()
    assert 'tenant="a\\"b\\\\c"' in text
    fams = parse_exposition(text)
    assert sample(fams, "ops_test_esc", **{"tenant": 'a"b\\c'}) == 1


def test_prom_free_form_label_value_roundtrip():
    """Label values are request-supplied (tenant ids): structural
    characters (',', '=', '{', '}') must survive the canonical-name
    round trip instead of splitting into phantom labels."""
    nasty = "a,b=c{d}%e"
    telemetry.gauge("ops_test.ff", tenant=nasty).set(3)
    fams = parse_exposition(ops.render_prometheus())
    assert sample(fams, "ops_test_ff", tenant=nasty) == 3
    assert len(fams["ops_test_ff"]["samples"]) == 1
    assert telemetry.remove("ops_test.ff", tenant=nasty)


def test_prom_metric_name_sanitized():
    telemetry.counter("serve.prefix-hits.v2").add(1)
    fams = parse_exposition(ops.render_prometheus())
    assert sample(fams, "serve_prefix_hits_v2") == 1


def test_prom_counter_across_reset():
    """reset() zeroes counters IN PLACE: the same instrument re-renders
    from 0 (a scraper sees an ordinary counter reset), with no stale
    duplicate series left behind."""
    c = telemetry.counter("ops_test.mono")
    c.add(5)
    assert sample(parse_exposition(ops.render_prometheus()), "ops_test_mono") == 5
    telemetry.reset()
    assert sample(parse_exposition(ops.render_prometheus()), "ops_test_mono") == 0
    c.add(2)  # the pre-reset binding still feeds the registered object
    fams = parse_exposition(ops.render_prometheus())
    assert sample(fams, "ops_test_mono") == 2
    assert len(fams["ops_test_mono"]["samples"]) == 1


def test_prom_concurrent_scrape_not_torn():
    """/metrics under concurrent observe/add: every scrape parses and
    every histogram snapshot is internally coherent (+Inf == count)."""
    h = telemetry.histogram("ops_test.torn")
    c = telemetry.counter("ops_test.torn_hits")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            h.observe(10.0 ** ((i % 13) - 6))
            c.add()
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            parse_exposition(ops.render_prometheus())
    finally:
        stop.set()
        for t in threads:
            t.join()
    fams = parse_exposition(ops.render_prometheus())
    assert sample(fams, "ops_test_torn_count") > 0


# ---------------------------------------------------------------------------
# Bounded labeled-metric cardinality (telemetry.remove + QoS prune)


def test_remove_drops_instruments():
    telemetry.counter("ops_test.rm").add(1)
    telemetry.gauge("ops_test.rm_g", tenant="t0").set(1)
    telemetry.histogram("ops_test.rm_h").observe(1.0)
    assert telemetry.remove("ops_test.rm")
    assert telemetry.remove("ops_test.rm_g", tenant="t0")
    assert telemetry.remove("ops_test.rm_h")
    assert not telemetry.remove("ops_test.rm")  # already gone
    text = ops.render_prometheus()
    assert "ops_test_rm" not in text


def _churn_tenants(n, active=8):
    """Push/pop n requests with distinct tenant ids through a
    QoSScheduler, keeping ~``active`` waiting at any moment."""
    alloc = BlockAllocator(64, 8)
    sched = QoSScheduler(4)
    for i in range(n):
        sched.push(
            Request(
                rid=i, prompt=np.zeros(4, np.int32), max_new_tokens=1,
                key=np.zeros(2, np.uint32), handle=None, tenant=f"tenant{i}",
            )
        )
        if i >= active:
            sched.pop_admissible(1, alloc, 8)
    sched.flush()
    return sched


def test_tenant_gauges_pruned_on_idle():
    """Distinct per-tenant ids must not grow the registry: the
    queue-depth gauge family tracks ACTIVE tenants (waiting work), and a
    tenant popping to idle leaves the registry entirely."""
    base = len(_core._state.gauges)
    _churn_tenants(25_000)
    growth = len(_core._state.gauges) - base
    assert growth <= 1, f"registry grew by {growth} gauges"
    assert "tenant24999" not in ops.render_prometheus()


@pytest.mark.slow
def test_million_tenants_bounded():
    base = len(_core._state.gauges)
    _churn_tenants(1_000_000)
    assert len(_core._state.gauges) - base <= 1


# ---------------------------------------------------------------------------
# SLO burn-rate monitor (synthetic event stream, deterministic timestamps)


def _req_event(name, rid, ts, **attrs):
    return {"type": "event", "name": name, "rid": rid, "ts": ts,
            "attrs": attrs}


def _feed_terminal(mon, rid, ts, tenant="acme", ok=True):
    mon._on_record(_req_event("req.submitted", rid, ts, tenant=tenant))
    if ok:
        mon._on_record(_req_event("req.finished", rid, ts + 0.01))
    else:
        mon._on_record(
            _req_event("req.failed", rid, ts + 0.01,
                       error="DeadlineExceeded", retryable=False)
        )


def test_slo_burn_fires_and_recovers():
    fired = []
    cfg = ops.SLOConfig(
        slo=0.9, fast_window_s=10, slow_window_s=50, burn_threshold=2.0,
        min_samples=5, on_burn=lambda tenant, info: fired.append((tenant, info)),
    )
    mon = ops.SLOMonitor(cfg)
    t0 = 1000.0
    for i in range(8):  # all misses: burn = (1.0 / 0.1) = 10 >> 2
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    assert mon.burning() == {"acme": True}
    assert fired and fired[0][0] == "acme"
    assert fired[0][1]["burn_fast"] >= 2.0
    assert telemetry.gauges()["serve.slo_burning{tenant=acme}"] == 1
    # Recovery: the bad window ages out of BOTH windows.
    for i in range(20):
        _feed_terminal(mon, 100 + i, t0 + 60 + i * 0.1, ok=True)
    assert mon.burning() == {"acme": False}
    assert telemetry.gauges()["serve.slo_burning{tenant=acme}"] == 0
    assert len(fired) == 1  # recovery does not re-fire
    assert mon.summary()["acme"]["fast"]["deadline_hit_rate"] == 1.0


def test_slo_single_blip_does_not_fire():
    """The multi-window rule: a fast-window spike alone (slow window
    still healthy) must not alert."""
    mon = ops.SLOMonitor(ops.SLOConfig(
        slo=0.9, fast_window_s=10, slow_window_s=1000, burn_threshold=2.0,
        min_samples=5,
    ))
    t0 = 1000.0
    for i in range(200):  # long healthy history fills the slow window
        _feed_terminal(mon, i, t0 + i, ok=True)
    for i in range(6):  # then a fast-window blip
        _feed_terminal(mon, 1000 + i, t0 + 200 + i * 0.1, ok=False)
    # Never burned: no state transition recorded, no gauge minted.
    assert not mon.burning().get("acme", False)
    assert "serve.slo_burning{tenant=acme}" not in telemetry.gauges()


def test_slo_ttft_target_trigger():
    mon = ops.SLOMonitor(ops.SLOConfig(
        slo=0.5, ttft_target_s=0.2, fast_window_s=10, slow_window_s=50,
        burn_threshold=1e9, min_samples=5,  # burn path unreachable
    ))
    t0 = 1000.0
    for i in range(8):
        mon._on_record(_req_event("req.submitted", i, t0 + i * 0.1,
                                  tenant="acme"))
        mon._on_record(_req_event("req.first_token", i, t0 + i * 0.1,
                                  ttft_s=0.9))
    assert mon.burning() == {"acme": True}


def test_slo_idle_tenant_pruned_from_registry():
    mon = ops.SLOMonitor(ops.SLOConfig(
        slo=0.9, fast_window_s=10, slow_window_s=50, burn_threshold=2.0,
        min_samples=5,
    ))
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, tenant="ghost", ok=False)
    assert "serve.slo_burning{tenant=ghost}" in telemetry.gauges()
    # Far-future activity from another tenant ages ghost out entirely.
    for i in range(ops.SLOMonitor._PRUNE_EVERY):
        _feed_terminal(mon, 1000 + i, t0 + 10_000 + i, tenant="live")
    assert "ghost" not in mon.burning()
    assert "serve.slo_burning{tenant=ghost}" not in telemetry.gauges()


def test_slo_on_burn_may_reenter_monitor():
    """The on_burn callback runs OUTSIDE the monitor's lock: a callback
    reading the monitor's own public API (the natural thing to log)
    must not deadlock the emitting thread."""
    seen = []
    box = {}

    def cb(tenant, info):
        seen.append((tenant, box["mon"].burning(), box["mon"].summary()))

    mon = ops.SLOMonitor(ops.SLOConfig(
        slo=0.9, fast_window_s=10, slow_window_s=50, burn_threshold=2.0,
        min_samples=5, on_burn=cb,
    ))
    box["mon"] = mon
    for i in range(8):
        _feed_terminal(mon, i, 1000.0 + i * 0.1, ok=False)
    assert seen and seen[0][0] == "acme"
    assert seen[0][1] == {"acme": True}
    assert seen[0][2]["acme"]["burning"] is True


def test_slo_monitor_as_listener():
    """Subscribed, the monitor is a recording target: req.* events are
    built for it even with every sink off — and close() unsubscribes,
    restoring the disabled path."""
    assert not telemetry.events_enabled()
    mon = ops.SLOMonitor(ops.SLOConfig(min_samples=1)).subscribe()
    try:
        assert telemetry.events_enabled()
        telemetry.event("req.submitted", rid="r1", tenant="t")
        telemetry.event("req.finished", rid="r1")
        assert mon.summary()["t"]["fast"]["n"] == 1
    finally:
        mon.close()
    assert not telemetry.events_enabled()
    assert "serve.slo_burning{tenant=t}" not in telemetry.gauges()


# ---------------------------------------------------------------------------
# Stall watchdog


class _FakeEngine:
    def __init__(self, eid="fake0"):
        self.engine_id = eid
        self._tick_no = 0
        self._decode_tokens = 0
        self._prefill_no = 0
        self.scheduler = [1]  # one queued request
        self.stalled = 0

    def health(self):
        return Health.READY

    def _n_running(self):
        return 0

    def _mark_stalled(self):
        self.stalled += 1


def _wait_for(pred, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_watchdog_fires_on_wedge_then_clears():
    telemetry.configure(collect=True, flight=True)
    eng = _FakeEngine()
    telemetry.event("req.queued", rid="r0")  # something in the ring to dump
    wd = ops.StallWatchdog(eng, deadline_s=0.08, poll_s=0.01)
    wd.start()
    try:
        assert _wait_for(lambda: wd.stalls == 1)
        assert eng.stalled == 1
        assert telemetry.gauges()["serve.stalled{engine=fake0}"] == 1
        recs = telemetry.snapshot()["spans"]
        dumps = [r for r in recs if r.get("type") == "flight_dump"]
        assert dumps and dumps[0]["reason"] == "stall"
        assert any(r.get("name") == "ops.stall" for r in recs)
        # Progress clears the latch without a second fire.
        eng._tick_no += 1
        assert _wait_for(
            lambda: telemetry.gauges()["serve.stalled{engine=fake0}"] == 0
        )
        assert wd.stalls == 1
    finally:
        wd.stop()
    # The stopped watchdog's gauge leaves the registry (replica churn
    # must not accrete one serve.stalled series per engine ever seen).
    assert "serve.stalled{engine=fake0}" not in telemetry.gauges()


def test_watchdog_quiet_when_idle_or_progressing():
    eng = _FakeEngine("fake1")
    eng.scheduler = []  # idle: nothing pending, stillness is fine
    wd = ops.StallWatchdog(eng, deadline_s=0.05, poll_s=0.01)
    wd.start()
    try:
        time.sleep(0.2)
        assert wd.stalls == 0
        eng.scheduler = [1]  # pending, but now the engine ticks
        for _ in range(20):
            eng._tick_no += 1
            time.sleep(0.01)
        assert wd.stalls == 0
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# The ops endpoint on a live engine


def test_engine_ops_endpoints(family):
    model, cfg, params = family
    telemetry.configure(collect=True, flight=True)
    eng = Engine(
        params, model=model, cfg=cfg,
        ops_port=0, ops_config=ops.OpsConfig(watchdog=False),
        **ENGINE_KW,
    )
    url = eng._ops_plane.server.url
    try:
        code, body = http_get(url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        h = eng.submit(prompt_of(4), max_new_tokens=12, key=0)
        eng.step()  # prefill (+ first decode chunk)
        eng.step()  # decoding, well short of the 12-token budget
        code, body = http_get(url + "/metrics")
        assert code == 200
        fams = parse_exposition(body.decode())
        eid = eng.engine_id
        assert sample(fams, "serve_occupancy", engine=eid) is not None
        assert 0 < sample(fams, "serve_occupancy", engine=eid) <= 1
        assert 0 < sample(fams, "serve_page_util", engine=eid) <= 1
        assert sample(fams, "serve_goodput", engine=eid) > 0  # decoding now
        assert sample(fams, "serve_tick_s_count", engine=eid) == 2
        assert sample(fams, "ops_scrapes") >= 1
        code, body = http_get(url + "/requests")
        assert code == 200
        reqs = json.loads(body)["requests"]
        assert any(r["rid"].endswith("-r0") for r in reqs)
        assert h.result()  # finish cleanly
        code, body = http_get(url + "/404")
        assert code == 404
    finally:
        eng.close()
    # STOPPED tore the plane down: the port refuses (the strongest
    # non-200 /healthz), and no listener/watchdog threads linger.
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(url + "/healthz", timeout=2.0)
    assert not any(
        t.name.startswith(("tdx-ops", "tdx-stall")) and t.is_alive()
        for t in threading.enumerate()
    )


def test_engine_wedge_detected_marked_overloaded(family):
    """The acceptance wedge, in-process: queued work + a tick loop that
    stopped being driven → the watchdog flight-dumps reason=stall and
    marks the engine OVERLOADED; resuming ticks restores READY."""
    model, cfg, params = family
    telemetry.configure(collect=True, flight=True)
    eng = Engine(
        params, model=model, cfg=cfg, ops_port=0,
        ops_config=ops.OpsConfig(stall_deadline_s=0.15, watchdog_poll_s=0.02),
        **ENGINE_KW,
    )
    try:
        # A budget one tick cannot finish: the wedge leaves the slot
        # occupied (pending work), which is what a stall requires.
        h = eng.submit(prompt_of(4), max_new_tokens=32, key=0)
        eng.step()  # prefill + first decode chunk, then the driver wedges
        assert _wait_for(lambda: eng.health() is Health.OVERLOADED)
        dumps = [
            r for r in telemetry.snapshot()["spans"]
            if r.get("type") == "flight_dump"
        ]
        assert dumps and dumps[-1]["reason"] == "stall"
        # >= 1: a compile-slow first tick can trip the (deliberately
        # tight) deadline once before the real wedge does.
        assert telemetry.counters()["serve.stalls"] >= 1
        while not h.done:
            eng.step()
        assert h.result()
        assert eng.health() is Health.READY  # its own tick re-checked
    finally:
        eng.close()


def test_env_ops_port(family, monkeypatch):
    model, cfg, params = family
    monkeypatch.setenv("TDX_OPS_PORT", "0")
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    try:
        assert eng._ops_plane is not None
        code, _ = http_get(eng._ops_plane.server.url + "/healthz")
        assert code == 200
    finally:
        eng.close()
    monkeypatch.delenv("TDX_OPS_PORT")
    eng2 = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    try:
        assert eng2._ops_plane is None
    finally:
        eng2.close()


def test_shared_plane_two_engines(family):
    """Two engines on one port share a plane; /healthz stays 200 (and
    keeps serving) until the LAST engine stops."""
    model, cfg, params = family
    eng1 = Engine(params, model=model, cfg=cfg, ops_port=0,
                  ops_config=ops.OpsConfig(watchdog=False), **ENGINE_KW)
    port = eng1._ops_plane.port
    eng2 = Engine(params, model=model, cfg=cfg, ops_port=port, **ENGINE_KW)
    url = eng1._ops_plane.server.url
    assert eng2._ops_plane is eng1._ops_plane
    code, body = http_get(url + "/healthz")
    assert code == 200 and len(json.loads(body)["engines"]) == 2
    eng1.close()
    code, body = http_get(url + "/healthz")
    payload = json.loads(body)
    assert code == 200 and len(payload["engines"]) == 1
    assert eng1.engine_id not in payload["engines"]
    eng2.close()
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(url + "/healthz", timeout=2.0)


# ---------------------------------------------------------------------------
# Disabled path: no ops plane → zero per-tick overhead


def test_disabled_path_no_tick_work(family, monkeypatch):
    """Without ops_port/TDX_OPS_PORT (and attribution off), a served
    request never calls the attribution path and mints no per-tick
    instruments — record-bomb style."""
    model, cfg, params = family

    def bomb(self, *a, **k):  # pragma: no cover — the point is it never runs
        raise AssertionError("_tick_telemetry ran with the ops plane off")

    monkeypatch.setattr(Engine, "_tick_telemetry", bomb)
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    try:
        assert eng._ops_plane is None
        h = eng.submit(prompt_of(4), max_new_tokens=4, key=0)
        assert h.result()
    finally:
        eng.close()
    assert eng._g_occupancy is None
    eid = eng.engine_id
    gauges = telemetry.gauges()
    for g in ("serve.occupancy", "serve.page_util", "serve.goodput",
              "serve.prefill_budget", "serve.churn"):
        assert f"{g}{{engine={eid}}}" not in gauges
    assert f"serve.tick_s{{engine={eid}}}" not in telemetry.histograms()


def test_tick_attribution_without_server(family):
    """bench's path: enable_tick_attribution() turns the gauges on with
    no HTTP listener."""
    model, cfg, params = family
    prev = ops.enable_tick_attribution(True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        h = eng.submit(prompt_of(4), max_new_tokens=12, key=0)
        eid = eng.engine_id
        occ, goodput, ticks = [], [], 0
        while not h.done:
            eng.step()
            ticks += 1
            gauges = telemetry.gauges()
            occ.append(gauges[f"serve.occupancy{{engine={eid}}}"])
            goodput.append(gauges[f"serve.goodput{{engine={eid}}}"])
        assert 0 < max(occ) <= 1
        assert max(goodput) > 0  # > 0 on every decoding tick
        assert (
            telemetry.histograms()[f"serve.tick_s{{engine={eid}}}"]["count"]
            == ticks
        )
        assert h.result()
        eng.close()
    finally:
        ops.enable_tick_attribution(prev)


# ---------------------------------------------------------------------------
# Fleet wiring


def test_fleet_router_ops_plane(family):
    from torchdistx_tpu.fleet import FleetRouter

    model, cfg, params = family
    engines = [
        Engine(params, model=model, cfg=cfg, **ENGINE_KW) for _ in range(2)
    ]
    router = FleetRouter(
        engines, ops_port=0, ops_config=ops.OpsConfig(watchdog=False)
    )
    url = router.ops_plane.server.url
    try:
        code, body = http_get(url + "/healthz")
        assert code == 200 and len(json.loads(body)["engines"]) == 2
        # A replica dying (closed out-of-band, then reaped) unwatches.
        engines[0].close()
        router.poll()
        code, body = http_get(url + "/healthz")
        assert code == 200 and len(json.loads(body)["engines"]) == 1
        # The retain keeps the plane alive with ZERO engines — a scrape
        # mid-respawn sees 503, not connection-refused.
        engines[1].close()
        router.poll()
        code, body = http_get(url + "/healthz")
        assert code == 503
        assert json.loads(body)["status"] == "unavailable"
        # A respawn rejoins the same plane.
        eng3 = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        router.add_replica(eng3)
        code, _ = http_get(url + "/healthz")
        assert code == 200
    finally:
        router.close()
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(url + "/healthz", timeout=2.0)


# ---------------------------------------------------------------------------
# /requests bounding + /profile (ISSUE 15)


def test_requests_endpoint_bounded_by_limit():
    """Satellite pin: /requests returns the `limit` MOST-RECENT
    timelines (default 256) — a long-lived engine's flight ring can
    never produce an unbounded JSON body."""
    telemetry.configure(collect=True, flight=True, flight_capacity=4096)
    plane = ops.OpsPlane(0, ops.OpsConfig(watchdog=False, monitor=False))
    plane.retain()
    url = plane.server.url
    try:
        for i in range(10):
            rid = f"r{i:03d}"
            telemetry.event(
                "req.submitted", rid=rid, engine="eng0", n_prompt=4
            )
            telemetry.event("req.finished", rid=rid, engine="eng0",
                            n_tokens=1)
        code, body = http_get(url + "/requests?limit=3")
        assert code == 200
        payload = json.loads(body)
        assert payload["n_timelines"] == 10 and payload["limit"] == 3
        rids = [r["rid"] for r in payload["requests"]]
        # The 3 most-recent by last event (events were emitted in rid
        # order, so the highest rids are the newest).
        assert rids == ["r007", "r008", "r009"]
        code, body = http_get(url + "/requests")
        assert code == 200
        payload = json.loads(body)
        assert len(payload["requests"]) == 10  # under the 256 default
        assert payload["limit"] == 256
        code, _ = http_get(url + "/requests?limit=bogus")
        assert code == 400
        # limit=0 / negatives would unbound the body — rejected.
        code, _ = http_get(url + "/requests?limit=0")
        assert code == 400
        code, _ = http_get(url + "/requests?limit=-5")
        assert code == 400
    finally:
        plane.release()


def test_profile_endpoint_fires_and_rate_limits(tmp_path):
    from torchdistx_tpu.telemetry import timeplane

    class Stub(timeplane.ProfilerTrigger):
        def _start_profiler(self, path):
            pass

        def _stop_profiler(self):
            pass

    trig = Stub(str(tmp_path), seconds=0.01, cooldown_s=300.0)
    prev = timeplane.set_trigger(trig)
    plane = ops.OpsPlane(0, ops.OpsConfig(watchdog=False, monitor=False))
    plane.retain()
    url = plane.server.url
    try:
        code, body = http_get(url + "/profile?seconds=0.05")
        assert code == 200
        payload = json.loads(body)
        assert payload["fired"] and os.path.isdir(payload["path"])
        assert payload["seconds"] == 0.05
        # Inside the cooldown: 429, suppressed — never queued.
        code, body = http_get(url + "/profile")
        assert code == 429 and not json.loads(body)["fired"]
        code, _ = http_get(url + "/profile?seconds=-1")
        assert code == 400
        trig.wait(5.0)
        assert len(trig.captures) == 1
    finally:
        plane.release()
        timeplane.set_trigger(prev)


def test_router_routes_around_stalled_engine(family):
    """The watchdog marks a wedged engine OVERLOADED; the router's pick
    must prefer the healthy peer."""
    from torchdistx_tpu.fleet import FleetRouter

    model, cfg, params = family
    e0 = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    e1 = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    router = FleetRouter([e0, e1])
    try:
        e0._mark_stalled()
        assert e0.health() is Health.OVERLOADED
        for _ in range(4):
            assert router._pick().engine is e1
    finally:
        router.close()
