"""End-to-end crash/preemption → resume, in real subprocesses.

The acceptance contract of the resilience subsystem: a ``fit()`` run
killed mid-stream — by a hard crash (``os._exit``, simulating SIGKILL /
power loss) or by a real SIGTERM through the real handler — must resume
from the preemption-point checkpoint and reach the same final step count
and parameters as an uninterrupted run, with no optimizer step executed
twice.  Kills are deterministic via the fault injector
(``TDX_FAULT=step.exec:N:crash|sigterm``), so there are no process games
or timing races.

Marked ``slow``: each case spawns fresh JAX subprocesses.  CI runs these
in the fault-injection lane (.github/workflows/ci.yaml).
"""

import json
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from torchdistx_tpu.resilience import CRASH_EXIT_CODE  # noqa: E402

CHILD = os.path.join(os.path.dirname(__file__), "_resilience_child.py")
N_STEPS = 5

pytestmark = pytest.mark.slow


def _run_child(ckpt_dir, steps_log, *, fault=None, trace=None):
    env = dict(os.environ)
    env.pop("TDX_FAULT", None)
    if fault:
        env["TDX_FAULT"] = fault
    if trace:
        env["TDX_TELEMETRY"] = str(trace)
    return subprocess.run(
        [sys.executable, CHILD, str(ckpt_dir), str(N_STEPS), str(steps_log)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )


def _executed_steps(steps_log):
    if not os.path.exists(steps_log):
        return []
    with open(steps_log) as f:
        return [int(line) for line in f if line.strip()]


def _result(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(
        f"no RESULT line\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )


def _child_module():
    sys.path.insert(0, os.path.dirname(__file__))
    try:
        import _resilience_child
    finally:
        sys.path.pop(0)
    return _resilience_child


@pytest.fixture(scope="module")
def reference_digest():
    """Uninterrupted run, same code path as the children (imported, not
    respawned — the rig lives in _resilience_child.run_training)."""
    child = _child_module()
    state, _ = child.run_training(None, N_STEPS)
    return child.digest(state), int(state.step)


def _assert_resumed_matches(ckpt_dir, steps_log, first_executed,
                            reference_digest, trace=None):
    """Resume (no faults) and check alignment + digest + no-step-twice."""
    from torchdistx_tpu.utils.checkpoint import latest_step

    resume_point = latest_step(ckpt_dir)
    proc = _run_child(ckpt_dir, steps_log, trace=trace)
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _result(proc)
    ref_digest, ref_step = reference_digest

    assert result["final_step"] == ref_step == N_STEPS
    # Same end state as the uninterrupted run (same platform, seeds, and
    # data stream; the tolerance only shields cross-process float-sum
    # noise, it is ~12 orders tighter than one optimizer step's effect).
    assert abs(result["digest"] - ref_digest) <= 1e-9 * max(
        1.0, abs(ref_digest)
    )

    executed = _executed_steps(steps_log)
    # The resumed run continued AFTER the checkpoint — optimizer-step /
    # data-stream alignment — and the union covers every step exactly
    # once: nothing lost, nothing executed twice.
    second_executed = executed[len(first_executed):]
    assert second_executed[0] == resume_point + 1
    assert sorted(executed) == list(range(1, N_STEPS + 1))
    assert len(set(executed)) == len(executed)


def test_crash_resume(tmp_path, reference_digest):
    """Hard kill (os._exit — no finally blocks, no atexit) at step 3."""
    from torchdistx_tpu.utils.checkpoint import latest_step

    ckpt = tmp_path / "ckpt"
    steps_log = tmp_path / "steps.log"
    proc = _run_child(ckpt, steps_log, fault="step.exec:3:crash")
    assert proc.returncode == CRASH_EXIT_CODE
    # Steps 1,2 ran; the sync save at checkpoint_every=2 committed.
    assert _executed_steps(steps_log) == [1, 2]
    assert latest_step(ckpt) == 2

    _assert_resumed_matches(
        ckpt, steps_log, [1, 2], reference_digest
    )


def test_sigterm_resume(tmp_path, reference_digest):
    """A real SIGTERM (os.kill through the installed handler) delivered
    as step 3 is about to run: that step still executes (the boundary
    check for it already passed), then the NEXT boundary notices the
    flag, checkpoints step 3, and fit returns resumably with rc 0."""
    from torchdistx_tpu.utils.checkpoint import latest_step

    ckpt = tmp_path / "ckpt"
    steps_log = tmp_path / "steps.log"
    trace = tmp_path / "trace.jsonl"
    proc = _run_child(
        ckpt, steps_log, fault="step.exec:3:sigterm", trace=trace
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _result(proc)
    assert result["preempted"] is True
    assert result["final_step"] < N_STEPS

    executed = _executed_steps(steps_log)
    saved = latest_step(ckpt)
    # The preemption-point checkpoint is the LAST EXECUTED step — not
    # rounded down to a checkpoint_every multiple.
    assert saved == executed[-1]

    # The preemption is visible in the exported telemetry trace.
    counters = {}
    with open(trace) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "counters":
                counters = rec["values"]
    assert counters.get("train.preemptions", 0) >= 1
    assert counters.get("preempt.signals", 0) >= 1

    _assert_resumed_matches(
        ckpt, steps_log, executed, reference_digest, trace=trace
    )
