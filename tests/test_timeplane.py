"""Time plane (ISSUE 15): tick-phase decomposition, host/device
attribution, trigger-fired profiler capture, Perfetto timeline export.

The acceptance shape: the engine tick decomposes into per-phase
histograms that PRUNE with the engine, the host/device split is a
gauge in [0, 1], a stall (or storm, or manual request) fires exactly
one rate-limited profiler capture with a real artifact directory, and
the merged span/event/tick-phase trace exports to a Perfetto timeline
that validates (tracks, nesting, flows).  Real-``jax.profiler``
capture runs in the slow lane; everything else stubs the profiler
seam.
"""

import os
import sys
import threading
import time

import jax
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import llama
from torchdistx_tpu.serving import Engine, Health
from torchdistx_tpu.telemetry import ops, perf, timeplane

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)
import timeline_export  # noqa: E402

ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False,
)


@pytest.fixture(autouse=True)
def _clean():
    prev = telemetry.configure(collect=False, jsonl=None, flight=None)
    telemetry.reset()  # also resets the timeplane trigger to env-lazy
    ops.enable_tick_attribution(False)
    yield
    for plane in list(ops._PLANES.values()):
        plane.close()
    ops.enable_tick_attribution(False)
    timeplane.set_trigger(None)
    telemetry.configure(**prev)
    telemetry.reset()


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


class StubTrigger(timeplane.ProfilerTrigger):
    """ProfilerTrigger with the jax seam stubbed: captures count and
    create artifact dirs, but no real profiler starts."""

    def __init__(self, tmpdir, **kw):
        kw.setdefault("seconds", 0.01)
        super().__init__(str(tmpdir), **kw)
        self.started = []
        self.stopped = 0

    def _start_profiler(self, path):
        self.started.append(path)

    def _stop_profiler(self):
        self.stopped += 1


# ---------------------------------------------------------------------------
# TickTimer + publish semantics


def test_tick_timer_segments_and_totals():
    t = timeplane.TickTimer()
    t.begin("schedule")
    time.sleep(0.002)
    t.begin("decode_dispatch")
    time.sleep(0.002)
    t.begin("schedule")  # phases re-enter; totals accumulate
    t.end()
    t.end()  # idempotent
    names = [s[0] for s in t.segments]
    assert names == ["schedule", "decode_dispatch", "schedule"]
    totals = t.totals()
    assert totals["schedule"] > 0 and totals["decode_dispatch"] >= 0.002
    # Segments are ordered and contiguous: each starts where the
    # previous ended (offsets relative to the tick start).
    for (_, off1, dur1), (_, off2, _) in zip(t.segments, t.segments[1:]):
        assert off2 == pytest.approx(off1 + dur1, abs=1e-6)


def test_engine_tick_phases_and_host_frac(family):
    model, cfg, params = family
    telemetry.configure(collect=True)
    prev = ops.enable_tick_attribution(True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        h = eng.submit(prompt_of(4), max_new_tokens=12, key=0)
        ticks = 0
        while not h.done:
            eng.step()
            ticks += 1
        assert h.result()
        eid = eng.engine_id
        hists = telemetry.histograms()
        for phase in ("schedule", "prefill_dispatch", "decode_dispatch",
                      "device_wait", "commit"):
            row = hists.get(
                f"serve.tick_phase_s{{engine={eid},phase={phase}}}"
            )
            assert row and row["count"] >= 1, f"phase {phase} never observed"
        # Phases partition the tick: no phase total exceeds the ticks'
        # total wall time.
        tick_sum = hists[f"serve.tick_s{{engine={eid}}}"]["sum"]
        sched = hists[f"serve.tick_phase_s{{engine={eid},phase=schedule}}"]
        assert sched["sum"] <= tick_sum * 1.5  # tail segment may overrun
        frac = telemetry.gauges()[f"serve.host_overhead_frac{{engine={eid}}}"]
        assert 0.0 <= frac <= 1.0
        # One serve.tick event per non-idle tick, carrying the ordered
        # segments the Perfetto exporter lays out.
        tick_events = [
            r for r in telemetry.snapshot()["spans"]
            if r.get("name") == "serve.tick"
        ]
        assert len(tick_events) == ticks
        seg = tick_events[-1]["attrs"]["segments"]
        assert seg and all(len(s) == 3 for s in seg)
        assert tick_events[-1]["attrs"]["dur_s"] >= max(
            s[1] + s[2] for s in seg
        ) - 1e-6
        eng.close()
    finally:
        ops.enable_tick_attribution(prev)


def test_tick_phase_rows_pruned_at_finish_drain(family):
    """Satellite pin: no serve.tick_phase_s row (and no host-overhead
    gauge) survives _finish_drain — drain path AND close path."""
    model, cfg, params = family
    prev = ops.enable_tick_attribution(True)
    try:
        for stop in ("drain", "close"):
            eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
            h = eng.submit(prompt_of(4), max_new_tokens=4, key=0)
            assert h.result()
            eid = eng.engine_id
            assert any(
                k.startswith(f"serve.tick_phase_s{{engine={eid}")
                for k in telemetry.histograms()
            )
            if stop == "drain":
                eng.begin_drain()
                while eng.health() is not Health.STOPPED:
                    eng.step()
            else:
                eng.close()
            assert not any(
                k.startswith(f"serve.tick_phase_s{{engine={eid}")
                for k in telemetry.histograms()
            ), f"tick-phase rows survived {stop}"
            assert (
                f"serve.host_overhead_frac{{engine={eid}}}"
                not in telemetry.gauges()
            )
    finally:
        ops.enable_tick_attribution(prev)


def test_disabled_path_builds_no_timer(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    try:
        h = eng.submit(prompt_of(4), max_new_tokens=4, key=0)
        assert h.result()
        assert eng._tp_state is None and eng._tick_timer is None
        assert not any(
            k.startswith("serve.tick_phase_s") for k in telemetry.histograms()
        )
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Histogram concurrency (satellite): multi-thread observe vs
# bucket_counts() snapshot exactness under the new phase families.


def test_histogram_concurrent_observe_snapshot_exact():
    h = telemetry.histogram(
        "serve.tick_phase_s", engine="hx", phase="decode_dispatch"
    )
    N, T = 2000, 4
    stop = threading.Event()
    snapshots = []

    def observer(seed):
        rng = np.random.default_rng(seed)
        for _ in range(N):
            h.observe(float(rng.uniform(1e-4, 1.0)))

    def scraper():
        while not stop.is_set():
            bounds, cum, total, s = h.bucket_counts()
            snapshots.append((cum[-1], total))

    threads = [threading.Thread(target=observer, args=(i,)) for i in range(T)]
    sc = threading.Thread(target=scraper)
    sc.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    sc.join()
    # Every mid-run snapshot held the Prometheus invariant exactly
    # (+Inf cumulative == count), and the final counts are exact.
    assert snapshots and all(c == t for c, t in snapshots)
    bounds, cum, total, s = h.bucket_counts()
    assert total == N * T and cum[-1] == total
    assert h.count == N * T
    telemetry.remove("serve.tick_phase_s", engine="hx", phase="decode_dispatch")


# ---------------------------------------------------------------------------
# ProfilerTrigger: rate limit, events, artifact paths, wiring


def test_trigger_fires_once_then_suppresses(tmp_path):
    telemetry.configure(collect=True)
    trig = StubTrigger(tmp_path, cooldown_s=300.0)
    path = trig.fire("stall", engine="eng0")
    assert path is not None and os.path.isdir(path)
    assert "stall" in os.path.basename(path)
    # Inside the cooldown: suppressed, never queued.
    assert trig.fire("stall", engine="eng0") is None
    trig.wait(5.0)
    assert trig.fire("slo_burn") is None  # still cooling down
    assert trig.captures == [path] and trig.suppressed == 2
    recs = telemetry.snapshot()["spans"]
    profiles = [r for r in recs if r.get("name") == "ops.profile"]
    suppressed = [
        r for r in recs if r.get("name") == "ops.profile_suppressed"
    ]
    assert len(profiles) == 1 and len(suppressed) == 2
    assert profiles[0]["attrs"]["path"] == path
    assert profiles[0]["attrs"]["reason"] == "stall"
    assert trig.started == [path] and trig.stopped == 1


def test_trigger_refires_after_cooldown(tmp_path):
    trig = StubTrigger(tmp_path, cooldown_s=0.0)
    p1 = trig.fire("a")
    trig.wait(5.0)
    p2 = trig.fire("b")
    trig.wait(5.0)
    assert p1 and p2 and p1 != p2
    assert len(trig.captures) == 2


def test_fire_profile_noop_without_trigger():
    assert timeplane.get_trigger() is None  # env unset in tests
    assert timeplane.fire_profile("stall") is None
    assert telemetry.counters().get("ops.profiles", 0) == 0


def test_default_trigger_is_manual_only():
    """The /profile endpoint's temp-dir default must not arm AUTOMATIC
    capture: fire_profile (the stall/burn/storm/slow-tick funnel)
    skips it; a real (env / set_trigger) trigger is not manual-only."""
    trig = timeplane.get_trigger(create_default=True)
    assert trig is not None and trig.manual_only
    assert timeplane.fire_profile("stall") is None  # automatic: skipped
    assert trig.captures == []
    trig.seconds = 0.01  # stub the seam: no real capture in tier-1
    trig._start_profiler = lambda path: None
    trig._stop_profiler = lambda: None
    assert trig.fire("manual") is not None  # on-demand still works
    trig.wait(5.0)


def test_slow_tick_skips_manual_only_trigger(tmp_path, family):
    """The slow-tick outlier is an AUTOMATIC path: it must not fire the
    /profile endpoint's manual-only default trigger."""
    model, cfg, params = family
    trig = StubTrigger(tmp_path, cooldown_s=0.0)
    trig.manual_only = True
    timeplane.set_trigger(trig)
    prev = ops.enable_tick_attribution(True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        for _ in range(timeplane._SLOW_TICK_MIN_TICKS):
            eng._tick_telemetry(0.001, 0, 1, 0)
        timer = timeplane.TickTimer()
        timer.begin("schedule")
        timer.end()
        timeplane.publish_tick(eng, timer, tick_s=1.0)
        assert trig.captures == []  # outlier detected, capture skipped
        eng.close()
    finally:
        ops.enable_tick_attribution(prev)


def test_failed_capture_dir_does_not_arm_cooldown(tmp_path):
    """A capture whose artifact dir cannot be created must roll the
    cooldown back — the NEXT incident still gets its profile — and say
    so (ops.profile_failed), never silently."""
    telemetry.configure(collect=True)
    blocker = tmp_path / "blocked"
    blocker.write_text("")  # a FILE where the log dir should be
    trig = StubTrigger(blocker / "sub", cooldown_s=300.0)
    assert trig.fire("stall") is None
    recs = telemetry.snapshot()["spans"]
    assert any(r.get("name") == "ops.profile_failed" for r in recs)
    assert not any(r.get("name") == "ops.profile" for r in recs)
    # The cooldown was NOT armed: a working trigger state fires now.
    trig.log_dir = str(tmp_path / "ok")
    path = trig.fire("stall")
    assert path is not None and os.path.isdir(path)
    trig.wait(5.0)


def test_env_seeded_trigger(tmp_path, monkeypatch):
    monkeypatch.setenv("TDX_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("TDX_PROFILE_SECONDS", "0.5")
    monkeypatch.setenv("TDX_PROFILE_COOLDOWN_S", "7")
    telemetry.reset()  # drop the fixture's cached None
    trig = timeplane.get_trigger()
    assert trig is not None
    assert trig.log_dir == str(tmp_path)
    assert trig.seconds == 0.5 and trig.cooldown_s == 7.0


class _FakeEngine:
    def __init__(self, eid="tp0"):
        self.engine_id = eid
        self._tick_no = 0
        self._decode_tokens = 0
        self._prefill_no = 0
        self.scheduler = [1]

    def health(self):
        return Health.READY

    def _n_running(self):
        return 0

    def _mark_stalled(self):
        pass


def test_watchdog_stall_fires_trigger(tmp_path):
    telemetry.configure(collect=True, flight=True)
    trig = StubTrigger(tmp_path, cooldown_s=300.0)
    timeplane.set_trigger(trig)
    eng = _FakeEngine()
    wd = ops.StallWatchdog(eng, deadline_s=0.05, poll_s=0.01)
    wd.start()
    try:
        t0 = time.monotonic()
        while not trig.captures and time.monotonic() - t0 < 5.0:
            time.sleep(0.01)
        assert len(trig.captures) == 1
        assert os.path.isdir(trig.captures[0])
        recs = telemetry.snapshot()["spans"]
        prof = [r for r in recs if r.get("name") == "ops.profile"]
        assert prof and prof[0]["attrs"]["reason"] == "stall"
        assert prof[0].get("engine") == "tp0"
    finally:
        wd.stop()


def test_recompile_storm_fires_trigger(tmp_path):
    telemetry.configure(collect=True, flight=True)
    trig = StubTrigger(tmp_path, cooldown_s=300.0)
    timeplane.set_trigger(trig)
    prev = perf.storm_config(threshold=2, window_s=60.0)
    try:
        owner = _FakeEngine("storm0")
        for _ in range(3):  # first compile + 2 recompiles → storm
            perf.record_compile("prog_x", 0.01, owner=owner, track=True)
        assert len(trig.captures) == 1
        recs = telemetry.snapshot()["spans"]
        prof = [r for r in recs if r.get("name") == "ops.profile"]
        assert prof and prof[0]["attrs"]["reason"] == "recompile_storm"
    finally:
        perf.storm_config(*prev)


def test_slow_tick_outlier_fires_trigger(tmp_path, family):
    """A tick far past the engine's own p50 fires ONE capture (k from
    TDX_SLOW_TICK_K; needs real tick history first)."""
    model, cfg, params = family
    trig = StubTrigger(tmp_path, cooldown_s=300.0)
    timeplane.set_trigger(trig)
    prev = ops.enable_tick_attribution(True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        # Feed the tick histogram a tight baseline past the minimum.
        for _ in range(timeplane._SLOW_TICK_MIN_TICKS):
            eng._tick_telemetry(0.001, 0, 1, 0)
        timer = timeplane.TickTimer()
        timer.begin("schedule")
        timer.end()
        timeplane.publish_tick(eng, timer, tick_s=1.0)  # 1000× the p50
        assert len(trig.captures) == 1
        assert "slow_tick" in os.path.basename(trig.captures[0])
        # A second outlier inside the cooldown is suppressed.
        timeplane.publish_tick(eng, timer, tick_s=1.0)
        assert len(trig.captures) == 1 and trig.suppressed >= 1
        eng.close()
    finally:
        ops.enable_tick_attribution(prev)


@pytest.mark.slow
def test_real_jax_profiler_capture_e2e(tmp_path, family):
    """The real seam: jax.profiler start/stop around live device work —
    the capture window must produce a non-empty artifact directory."""
    model, cfg, params = family
    trig = timeplane.ProfilerTrigger(
        str(tmp_path), seconds=0.5, cooldown_s=0.0
    )
    timeplane.set_trigger(trig)
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    try:
        path = timeplane.fire_profile("manual")
        assert path is not None
        h = eng.submit(prompt_of(4), max_new_tokens=8, key=0)
        assert h.result()  # device work inside the capture window
        trig.wait(30.0)
        assert os.path.isdir(path)
        captured = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(path)
            for f in fs
        ]
        assert captured, "profiler capture produced no artifact files"
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Perfetto export


def _ev(name, ts, rid="r0", hop=0, engine="eng0", **attrs):
    rec = {
        "type": "event", "name": name, "ts": ts, "rid": rid, "hop": hop,
        "engine": engine,
    }
    if attrs:
        rec["attrs"] = attrs
    return rec


def test_perfetto_synthetic_failover_flows():
    recs = [
        _ev("req.submitted", 0.0, engine="fleet"),
        _ev("req.admitted", 1.0, engine="eng0"),
        _ev("req.failed", 2.0, engine="eng0", error="RequestPreempted",
            retryable=True),
        _ev("req.failover_hop", 2.5, engine="eng1", hop=1),
        _ev("req.admitted", 3.0, engine="eng1", hop=1),
        _ev("req.first_token", 3.5, engine="eng1", hop=1, ttft_s=3.5),
        _ev("req.finished", 4.0, engine="eng1", hop=1, n_tokens=8),
        {
            "type": "event", "name": "serve.tick", "engine": "eng1",
            "ts": 3.6, "attrs": {
                "tick": 7, "t0": 3.0, "dur_s": 0.6, "tick_s": 0.59,
                "host_overhead_frac": 0.4,
                "segments": [
                    ["schedule", 0.0, 0.1],
                    ["decode_dispatch", 0.1, 0.2],
                    ["device_wait", 0.3, 0.2],
                    ["commit", 0.5, 0.1],
                ],
            },
        },
        {"type": "span", "name": "serve.step", "ts": 3.05, "dur_s": 0.4,
         "thread": 1, "depth": 0},
        {"type": "flight_dump", "ts": 2.1, "reason": "stall", "n": 3},
    ]
    trace = timeline_export.to_perfetto(recs)
    assert timeline_export.validate(trace, recs) == []
    evs = trace["traceEvents"]
    # The request got a named track and a resolved flow chain across
    # the hop: one start, steps, one finish.
    names = {
        e["args"]["name"] for e in evs
        if e.get("ph") == "M" and e["name"] == "thread_name"
        and e["pid"] == timeline_export.PID_REQUESTS
    }
    assert "r0" in names
    flows = [e for e in evs if e.get("ph") in ("s", "t", "f")]
    assert [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])] == [
        "s", "t", "t", "t", "f"
    ]
    # The tick track carries the phase children inside the tick slice.
    ticks = [e for e in evs if e.get("cat") == "tick"]
    phases = [e for e in evs if e.get("cat") == "phase"]
    assert len(ticks) == 1 and len(phases) == 4
    t0, t1 = ticks[0]["ts"], ticks[0]["ts"] + ticks[0]["dur"]
    for ph in phases:
        assert t0 - 1 <= ph["ts"] and ph["ts"] + ph["dur"] <= t1 + 1
    # The failover gap renders as a failover slice on the request track.
    assert any(
        e.get("ph") == "X" and e.get("name") == "failover" for e in evs
    )


def test_perfetto_validation_catches_broken_flow_and_nesting():
    base = [
        _ev("req.submitted", 0.0),
        _ev("req.first_token", 1.0, ttft_s=1.0),
        _ev("req.finished", 2.0, n_tokens=4),
    ]
    trace = timeline_export.to_perfetto(base)
    assert timeline_export.validate(trace, base) == []
    # Break the flow: drop its finish.
    broken = dict(trace)
    broken["traceEvents"] = [
        e for e in trace["traceEvents"] if e.get("ph") != "f"
    ]
    assert any(
        "unresolved" in p for p in timeline_export.validate(broken, base)
    )
    # A slice escaping its parent is caught.
    bad = dict(trace)
    bad["traceEvents"] = trace["traceEvents"] + [
        {"ph": "X", "pid": 77, "tid": 1, "name": "outer", "cat": "t",
         "ts": 0.0, "dur": 10.0},
        {"ph": "X", "pid": 77, "tid": 1, "name": "escapes", "cat": "t",
         "ts": 5.0, "dur": 10.0},
    ]
    assert any("escapes" in p for p in timeline_export.validate(bad, base))
    # A request id with events but no track is caught.
    assert any(
        "missing a timeline track" in p
        for p in timeline_export.validate(
            trace, base + [_ev("req.submitted", 0.0, rid="ghost")]
        )
    )


def test_perfetto_engine_e2e(family):
    """A live engine run (ops attribution on, collector on) exports to
    a timeline that validates: request tracks, tick track with nested
    phases, flows resolved."""
    model, cfg, params = family
    telemetry.configure(collect=True, max_spans=100_000)
    prev = ops.enable_tick_attribution(True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        handles = [
            eng.submit(prompt_of(4 + i), max_new_tokens=6, key=i)
            for i in range(3)
        ]
        for h in handles:
            assert h.result()
        eng.close()
        records = telemetry.snapshot()["spans"]
        trace = timeline_export.to_perfetto(records)
        assert timeline_export.validate(trace, records) == []
        assert trace["otherData"]["n_requests"] == 3
        assert trace["otherData"]["n_engines"] == 1
    finally:
        ops.enable_tick_attribution(prev)
