"""GPT-2 model family + model-agnostic train step."""

import jax
import jax.numpy as jnp
import optax
import pytest

from torchdistx_tpu.models import gpt2
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return gpt2.gpt2_test()


@pytest.fixture(scope="module")
def params(cfg):
    return gpt2.init_params(jax.random.PRNGKey(0), cfg)


def test_param_sizes():
    # gpt2-small is ~124M excluding the tied head (wte counted once).
    n = gpt2.num_params(gpt2.gpt2_small())
    assert abs(n - 124_439_808) / 124_439_808 < 0.02


def test_forward_and_causality(cfg, params):
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, cfg.vocab_size)
    logits = gpt2.forward(params, tokens, cfg, attn_impl="jnp")
    assert logits.shape == (2, 16, cfg.vocab_size)
    tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % cfg.vocab_size)
    logits_b = gpt2.forward(params, tokens_b, cfg, attn_impl="jnp")
    assert jnp.allclose(logits[0, :-1], logits_b[0, :-1], atol=1e-5)


def test_train_step_gpt2_model(cfg):
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.adamw(1e-2), model=gpt2
    )
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(3):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    # qkv weights sharded per the gpt2 spec
    assert state.params["layers"]["attn_qkv"][
        "weight"
    ].sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")
