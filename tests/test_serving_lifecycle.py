"""Serving request-lifecycle robustness (ISSUE 5).

Deadlines, cancellation, overload shedding, graceful drain, and the
crash-recovery supervisor: every way a request can fail is a typed
:class:`~torchdistx_tpu.serving.RequestError` — never a hang, never a
silently truncated stream — and the engine's health walks the
STARTING→READY→(OVERLOADED)→DRAINING→STOPPED machine with zero leaked
pages at every exit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    DeadlineExceeded,
    Engine,
    EngineDraining,
    EngineOverloaded,
    Health,
    OverloadDetector,
    RequestCancelled,
    RequestError,
    RequestPreempted,
)

EOS = 5
# prefix_cache pinned OFF: these suites assert raw page accounting
# (num_in_use == 0 at idle) that predates the cache-on default; the
# cache-on path is covered by the explicit prefix tests and the
# perf-plane lifecycle test.
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    prefix_cache=False,
)


@pytest.fixture(autouse=True)
def _clean_preemption():
    preemption.clear()
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def solo(model, cfg, params, prompt, seed, max_new, *, eos=None):
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new, eos_id=eos,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


# ---------------------------------------------------------------------------
# Health state machine


def test_health_starting_ready(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    assert eng.health() is Health.STARTING
    assert eng.stats()["health"] == "starting"
    eng.submit(prompt_of(4), max_new_tokens=2, key=0)
    eng.drain()
    assert eng.health() is Health.READY


def test_drain_on_preemption_finishes_inflight(family):
    """preemption.request() (the SIGTERM path's programmatic twin) must
    close admission, fail the waiting queue with a retryable error,
    finish the in-flight requests within the drain deadline, and land
    STOPPED with zero pages owned."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, drain_deadline_s=60.0,
                 **ENGINE_KW)
    running = [
        eng.submit(prompt_of(6, base=i + 1), max_new_tokens=6, key=i)
        for i in range(2)
    ]
    eng.step()
    eng.step()  # both admitted (interleave knob is 1/tick)
    waiting = eng.submit(prompt_of(5), max_new_tokens=4, key=9)
    preemption.request()
    while eng.health() is not Health.STOPPED:
        eng.step()
    # In-flight work finished completely — token-identical, no truncation.
    for i, h in enumerate(running):
        assert h.result() == solo(
            model, cfg, params, prompt_of(6, base=i + 1), i, 6
        )
    # The queued request was failed retryably, not silently dropped.
    assert waiting.done and isinstance(waiting.error, RequestPreempted)
    assert waiting.error.retryable
    with pytest.raises(RequestPreempted):
        waiting.result()
    assert eng.allocator.num_in_use == 0
    # A stopped engine refuses work, typed and retryable.
    with pytest.raises(EngineDraining):
        eng.submit(prompt_of(4), max_new_tokens=2, key=3)
    with pytest.raises(EngineDraining):
        eng.step()


def test_drain_deadline_fails_remainder_retryable(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, drain_deadline_s=0.0,
                 **ENGINE_KW)
    h = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
    eng.step()
    assert not h.done
    preemption.request()
    eng.step()  # drain begins; deadline 0 → the remainder fails now
    assert eng.health() is Health.STOPPED
    assert h.done and isinstance(h.error, RequestPreempted)
    assert h.error.retryable
    assert eng.allocator.num_in_use == 0


def test_drain_emits_span_and_counters(family):
    model, cfg, params = family
    prev = telemetry.configure(collect=True)
    try:
        eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
        eng.submit(prompt_of(4), max_new_tokens=4, key=0)
        eng.step()
        preemption.request()
        while eng.health() is not Health.STOPPED:
            eng.step()
        names = {s["name"] for s in telemetry.snapshot()["spans"]}
        assert "serve.drain" in names
        # STOPPED clears the routing gauges (they are process-global; a
        # dead engine must not leave readings for a router to act on).
        assert telemetry.gauge("serve.health").value is None
    finally:
        telemetry.configure(**prev)


# ---------------------------------------------------------------------------
# Deadlines


def test_deadline_expires_queued_request(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    before = telemetry.counter("serve.expired").value
    # Two requests occupy both slots; the third has an already-tiny
    # deadline and must expire in the queue, typed, pages never taken.
    keep = [
        eng.submit(prompt_of(6, base=i + 1), max_new_tokens=6, key=i)
        for i in range(2)
    ]
    doomed = eng.submit(
        prompt_of(5), max_new_tokens=4, key=9, deadline_s=1e-6
    )
    eng.drain()
    assert doomed.done and isinstance(doomed.error, DeadlineExceeded)
    with pytest.raises(DeadlineExceeded):
        doomed.result()
    for i, h in enumerate(keep):
        assert h.result() == solo(
            model, cfg, params, prompt_of(6, base=i + 1), i, 6
        )
    assert telemetry.counter("serve.expired").value > before
    assert eng.allocator.num_in_use == 0


def test_deadline_expires_running_request_releases_pages(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    h = eng.submit(
        prompt_of(6), max_new_tokens=40, key=0, deadline_s=60.0
    )
    eng.step()  # admitted, pages owned, mid-stream
    assert not h.done and eng.allocator.num_in_use > 0
    # Force the expiry deterministically (wall-clock sleeps are flaky).
    eng._slot_req[0].deadline = 0.0
    eng.step()  # next chunk boundary: expiry observed, pages released
    assert h.done and isinstance(h.error, DeadlineExceeded)
    assert eng.allocator.num_in_use == 0
    assert eng.health() is Health.READY


def test_deadline_validation(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(prompt_of(4), max_new_tokens=2, key=0, deadline_s=0)


# ---------------------------------------------------------------------------
# Cancellation


def test_cancel_queued_and_running(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    before = telemetry.counter("serve.cancelled").value
    run = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
    eng.step()
    assert not run.done
    queued = eng.submit(prompt_of(5), max_new_tokens=4, key=1)
    assert run.cancel() and queued.cancel()
    eng.step()  # next chunk boundary: both leave, pages released
    assert run.done and isinstance(run.error, RequestCancelled)
    assert queued.done and isinstance(queued.error, RequestCancelled)
    with pytest.raises(RequestCancelled):
        run.result()
    assert eng.allocator.num_in_use == 0
    assert telemetry.counter("serve.cancelled").value == before + 2
    # cancel() after completion is a no-op that reports so.
    done = eng.submit(prompt_of(4), max_new_tokens=2, key=2)
    eng.drain()
    assert done.result() == solo(model, cfg, params, prompt_of(4), 2, 2)
    assert not done.cancel()


# ---------------------------------------------------------------------------
# Overload shedding


def test_shed_reject_new(family):
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, max_queue=2, **ENGINE_KW
    )
    before = telemetry.counter("serve.shed").value
    handles = [
        eng.submit(prompt_of(4, base=i + 1), max_new_tokens=4, key=i)
        for i in range(2)
    ]
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit(prompt_of(4), max_new_tokens=4, key=9)
    assert ei.value.retryable
    assert eng.health() is Health.OVERLOADED
    assert telemetry.counter("serve.shed").value == before + 1
    eng.drain()  # pressure drops → READY again, everyone completes
    assert eng.health() is Health.READY
    for i, h in enumerate(handles):
        assert h.result() == solo(
            model, cfg, params, prompt_of(4, base=i + 1), i, 4
        )


def test_shed_drop_oldest(family):
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, max_queue=2,
        shed_policy="drop-oldest", **ENGINE_KW,
    )
    oldest = eng.submit(prompt_of(4, base=1), max_new_tokens=4, key=0)
    second = eng.submit(prompt_of(4, base=2), max_new_tokens=4, key=1)
    newest = eng.submit(prompt_of(4, base=3), max_new_tokens=4, key=2)
    # The queue stayed bounded: the OLDEST was shed, the newest admitted.
    assert oldest.done and isinstance(oldest.error, EngineOverloaded)
    assert oldest.error.retryable
    eng.drain()
    assert second.result() == solo(
        model, cfg, params, prompt_of(4, base=2), 1, 4
    )
    assert newest.result() == solo(
        model, cfg, params, prompt_of(4, base=3), 2, 4
    )
    assert eng.allocator.num_in_use == 0


def test_shed_policy_validation(family):
    model, cfg, params = family
    with pytest.raises(ValueError, match="shed_policy"):
        Engine(params, model=model, cfg=cfg, shed_policy="lru", **ENGINE_KW)


def test_overload_detector_chunked_estimates():
    """Chunked prefill changes the unit of queue drain: a queued long
    prompt costs ceil(suffix_chunks / max_prefills_per_tick) ticks, not
    1 — the detector must weigh chunks, and the engine must feed it
    chunk counts (the old depth-based estimate under-reports TTFT and
    under-sheds)."""
    det = OverloadDetector(max_queue=None, max_ttft_s=1.0)
    det.observe_tick(0.5)
    # Unchunked regime: depth doubles as the chunk count (1 chunk/req).
    assert det.est_ttft_s(3, 1) == pytest.approx(2.0)
    assert not det.overloaded(1, 1)  # 2 requests ahead ≈ 1.0s: at bound
    # Chunked regime: ONE queued 16k prompt behind prefill_chunk=512 is
    # 32 chunks — 16x the unchunked estimate at the same depth.
    assert det.est_ttft_s(32, 1) == pytest.approx(16.5)
    assert det.overloaded(1, 1, queued_chunks=32)  # same depth, now sheds
    assert det.est_ttft_s(32, 8) == pytest.approx(2.5)  # ceil(33/8) ticks

    # Engine wiring: the same prompt costs 1 chunk unchunked and many
    # chunked, and est_ttft_s() reflects it (prompt 33 → suffix 33).
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    for chunk, want_chunks in ((512, 1), (8, 5)):
        eng = Engine(
            params, model=llama, cfg=cfg, max_queue=64,
            prefill_chunk=chunk, num_slots=1, block_size=8,
            max_model_len=64, decode_chunk=4, handle_preemption=False,
            prefix_cache=False,
        )
        blocker = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
        eng.step()  # occupies the only slot: the queue cannot drain
        eng.detector._tick_ewma_s = 0.5  # pin the EWMA for determinism
        eng.submit(prompt_of(33), max_new_tokens=8, key=1)
        assert eng._pending_prefill_chunks() == want_chunks
        assert eng.est_ttft_s() == pytest.approx(
            0.5 * (want_chunks + 1)
        )
        blocker.cancel()
        eng.drain()
        assert eng.allocator.num_in_use == 0

    # The ARRIVAL's own chunks count toward its shed decision: a 33-token
    # prompt behind prefill_chunk=8 is 5 chunks ≈ 2.5s of its own prefill
    # wait — over a 1s bound even on an IDLE engine.
    eng = Engine(
        params, model=llama, cfg=cfg, max_ttft_s=1.0, prefill_chunk=8,
        num_slots=1, block_size=8, max_model_len=64, decode_chunk=4,
        handle_preemption=False,
    )
    eng.detector._tick_ewma_s = 0.5
    with pytest.raises(EngineOverloaded):
        eng.submit(prompt_of(33), max_new_tokens=8, key=0)
    # A one-chunk prompt at the same moment is fine (1 tick * 0.5s).
    h = eng.submit(prompt_of(6), max_new_tokens=2, key=1)
    eng.drain()
    assert len(h.result()) == 2


def test_overload_detector_estimates():
    det = OverloadDetector(max_queue=4, max_ttft_s=1.0)
    assert det.enabled
    assert not det.overloaded(3, 1)
    assert det.overloaded(4, 1)  # queue bound
    det.observe_tick(0.5)
    assert det.est_ttft_s(3, 1) == pytest.approx(2.0)
    assert det.overloaded(3, 1)  # TTFT bound: 4 ticks * 0.5s > 1s
    assert not det.overloaded(0, 1)  # 1 tick * 0.5s <= 1s
    # EWMA converges downward as ticks speed up.
    for _ in range(50):
        det.observe_tick(0.01)
    assert not det.overloaded(3, 1)
    with pytest.raises(ValueError):
        OverloadDetector(max_queue=0)
    with pytest.raises(ValueError):
        OverloadDetector(max_ttft_s=0.0)
    assert not OverloadDetector().enabled


# ---------------------------------------------------------------------------
# Admission validation (livelock fix) + backpressure visibility


def test_submit_rejects_never_admissible_immediately(family):
    """A request that can NEVER fit must raise at submit() — parking it
    at the FIFO head would make tokens() spin step() forever."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=1, block_size=8,
        num_blocks=3, max_model_len=32,
    )
    with pytest.raises(ValueError, match="max_model_len"):
        eng.submit(np.zeros(30, np.int32), max_new_tokens=30)
    with pytest.raises(ValueError, match="num_blocks"):
        eng.submit(np.zeros(20, np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="num_slots"):
        Engine(params, model=model, cfg=cfg, num_slots=0)
    # An admissible request still flows normally afterwards.
    h = eng.submit(prompt_of(4), max_new_tokens=2, key=0)
    eng.drain()
    assert len(h.result()) == 2
    assert eng.allocator.num_in_use == 0


def test_slot_bound_stall_counts_backpressure(family):
    """With every slot busy and work waiting, the stall must be counted
    — the old loop only counted page-bound stalls, so a slot-bound
    engine looked healthily idle in telemetry."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=1, block_size=8,
        max_model_len=64, decode_chunk=4,
    )
    eng.submit(prompt_of(6), max_new_tokens=12, key=0)
    eng.step()  # occupies the only slot
    eng.submit(prompt_of(6), max_new_tokens=4, key=1)
    before = telemetry.counter("serve.backpressure").value
    eng.step()  # queue non-empty, zero free slots → visible stall
    assert telemetry.counter("serve.backpressure").value > before
    eng.drain()
    assert eng.allocator.num_in_use == 0


# ---------------------------------------------------------------------------
# Fault sites: serve.prefill / serve.recover


def test_fault_prefill_io_requeues_token_identical(family):
    model, cfg, params = family
    before = telemetry.counter("serve.prefill_retries").value
    faults.reset("serve.prefill:1:io")
    eng = Engine(params, model=model, cfg=cfg, eos_id=EOS, **ENGINE_KW)
    h = eng.submit(prompt_of(6), max_new_tokens=8, key=0)
    eng.drain()
    assert h.result() == solo(model, cfg, params, prompt_of(6), 0, 8, eos=EOS)
    assert telemetry.counter("serve.prefill_retries").value == before + 1
    assert eng.allocator.num_in_use == 0


def test_fault_recover_io_consumes_budget(monkeypatch, family):
    """serve.recover:io fails one supervisor replay attempt: with a
    budget of max_recoveries=2 the replay retries and completes
    token-identically; the failed attempt is charged."""
    import torchdistx_tpu.serving.engine as eng_mod

    model, cfg, params = family
    faults.reset("serve.recover:1:io")
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    h = eng.submit(prompt_of(6), max_new_tokens=12, key=0)
    eng.step()
    assert not h.done

    real = eng_mod._decode_chunk
    state = {"fail": True}

    def die_once(params_, paged, *a, **k):
        if state["fail"]:
            state["fail"] = False
            for leaf in jax.tree.leaves(paged):
                leaf.delete()
            raise RuntimeError("injected device failure")
        return real(params_, paged, *a, **k)

    monkeypatch.setattr(eng_mod, "_decode_chunk", die_once)
    eng.drain()
    assert h.result() == solo(model, cfg, params, prompt_of(6), 0, 12)
    assert eng.allocator.num_in_use == 0


def test_prefill_failure_keeps_fifo_order(monkeypatch, family):
    """A transiently-failing prefill must requeue its request at the
    FIFO HEAD, ahead of the rest of its admission batch — not behind
    it (the failure must not cost the request its place)."""
    import torchdistx_tpu.serving.engine as eng_mod

    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=2, block_size=8,
        max_model_len=64, decode_chunk=4, max_prefills_per_tick=2,
    )
    real = eng_mod._prefill_chunk_last
    state = {"fail": True}

    def boom_first(*a, **k):
        if state["fail"]:
            state["fail"] = False
            raise RuntimeError("boom")
        return real(*a, **k)

    monkeypatch.setattr(eng_mod, "_prefill_chunk_last", boom_first)
    ha = eng.submit(prompt_of(4, base=1), max_new_tokens=4, key=0)
    hb = eng.submit(prompt_of(4, base=2), max_new_tokens=4, key=1)
    eng.step()  # A's prefill fails: batch [A, B] requeued, A still head
    assert [r.rid for r in eng.scheduler._waiting] == [ha.rid, hb.rid]
    eng.drain()
    assert ha.result() == solo(model, cfg, params, prompt_of(4, base=1), 0, 4)
    assert hb.result() == solo(model, cfg, params, prompt_of(4, base=2), 1, 4)
    assert eng.allocator.num_in_use == 0


def test_stopped_engine_clears_routing_gauges(family):
    """A router (or an operator tailing the trace) load-balances on the
    serve.health / serve.est_ttft_s gauges; they are process-global, so
    an engine reaching STOPPED must CLEAR them — stale readings from a
    dead engine would masquerade as a live replica's."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, max_queue=64, **ENGINE_KW
    )
    eng.submit(prompt_of(4), max_new_tokens=2, key=0)
    eng.step()  # detector enabled → est_ttft gauge written this tick
    assert telemetry.gauge("serve.health").value == "ready"
    assert telemetry.gauge("serve.est_ttft_s").value is not None
    eng.drain()
    eng.close()
    assert eng.health() is Health.STOPPED
    assert telemetry.gauge("serve.health").value is None
    assert telemetry.gauge("serve.est_ttft_s").value is None
    # The graceful-drain exit clears them too, not just close().
    eng2 = Engine(
        params, model=model, cfg=cfg, max_queue=64, **ENGINE_KW
    )
    eng2.submit(prompt_of(4), max_new_tokens=2, key=0)
    eng2.step()
    assert telemetry.gauge("serve.health").value == "ready"
    preemption.request()
    while eng2.health() is not Health.STOPPED:
        eng2.step()
    assert telemetry.gauge("serve.health").value is None
    assert telemetry.gauge("serve.est_ttft_s").value is None


def test_est_ttft_hook_matches_detector(family):
    """Engine.est_ttft_s() is the per-engine router hook behind the
    process-global gauge — it must track the detector's estimate for
    the engine's own queue depth."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, max_queue=64, **ENGINE_KW)
    assert eng.est_ttft_s() == 0.0  # no tick observed yet
    eng.detector.observe_tick(0.5)
    assert eng.est_ttft_s() == pytest.approx(0.5)  # empty queue: 1 tick
    eng.submit(prompt_of(4), max_new_tokens=2, key=0)
    eng.submit(prompt_of(4), max_new_tokens=2, key=1)
    assert eng.est_ttft_s() == pytest.approx(
        eng.detector.est_ttft_s(2, eng.max_prefills_per_tick)
    )
    eng.drain()
    assert eng.allocator.num_in_use == 0


def test_close_is_idempotent_and_post_stopped_rejects_typed(family):
    """Engine.close() twice must not double-fail anything (counters
    unchanged on the second call), and a STOPPED engine must reject
    submit()/step() with the typed, retryable EngineDraining — the
    contract the fleet router's failover relies on."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    running = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
    eng.step()
    queued = eng.submit(prompt_of(5), max_new_tokens=4, key=1)
    before_preempted = telemetry.counter("serve.preempted").value
    eng.close()
    assert eng.health() is Health.STOPPED
    assert running.done and queued.done
    after_first = telemetry.counter("serve.preempted").value
    assert after_first == before_preempted + 2
    eng.close()  # idempotent: nothing re-failed, nothing re-counted
    assert telemetry.counter("serve.preempted").value == after_first
    assert eng.stats()["preempted"] == 2
    with pytest.raises(EngineDraining) as ei:
        eng.submit(prompt_of(4), max_new_tokens=2, key=2)
    assert ei.value.retryable
    with pytest.raises(EngineDraining):
        eng.step()
    assert eng.allocator.num_in_use == 0


def test_begin_drain_without_signal(family):
    """begin_drain() — the fleet hot-swap hook — walks the same path a
    SIGTERM does: queue flushed retryably, in-flight work finishes,
    STOPPED at the end; idempotent while draining."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, drain_deadline_s=60.0,
                 **ENGINE_KW)
    running = eng.submit(prompt_of(6), max_new_tokens=6, key=0)
    eng.step()
    waiting = eng.submit(prompt_of(5), max_new_tokens=4, key=1)
    eng.begin_drain()
    assert eng.health() is Health.DRAINING
    eng.begin_drain()  # no-op: no double flush
    while eng.health() is not Health.STOPPED:
        eng.step()
    assert running.result() == solo(model, cfg, params, prompt_of(6), 0, 6)
    assert waiting.done and isinstance(waiting.error, RequestPreempted)
    assert waiting.error.retryable
    assert eng.allocator.num_in_use == 0


def test_close_fails_outstanding_and_restores(family):
    """close() retires an engine without a drain: outstanding work fails
    with retryable typed errors, pages release, health lands STOPPED —
    and it is idempotent."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    run = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
    eng.step()
    assert not run.done
    queued = eng.submit(prompt_of(5), max_new_tokens=4, key=1)
    eng.close()
    assert eng.health() is Health.STOPPED
    assert isinstance(run.error, RequestPreempted) and run.error.retryable
    assert isinstance(queued.error, EngineDraining) and queued.error.retryable
    assert eng.allocator.num_in_use == 0
    eng.close()  # idempotent
    with pytest.raises(EngineDraining):
        eng.submit(prompt_of(4), max_new_tokens=2, key=2)


# ---------------------------------------------------------------------------
# Seeded mini chaos soak (the CI-scale soak lives in scripts/chaos_soak.py)


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): the CI chaos-soak job covers this scenario
def test_chaos_mini_soak(monkeypatch, family):
    """Randomized faults + lifecycle churn over mixed requests: every
    request completes token-identical to solo generate() or fails with a
    typed RequestError; no hangs, zero leaked pages, engine READY."""
    import torchdistx_tpu.serving.engine as eng_mod

    model, cfg, params = family
    rng = np.random.default_rng(1234)
    specs = []
    for site, lo, hi in [
        ("serve.admit", 2, 12), ("serve.prefill", 2, 12),
        ("serve.step", 2, 20), ("serve.recover", 1, 3),
    ]:
        for step in rng.integers(lo, hi, size=2):
            kind = rng.choice(["io", "nan"]) if site != "serve.recover" else "io"
            specs.append(f"{site}:{int(step)}:{kind}")
    faults.reset(",".join(sorted(set(specs))))

    eng = Engine(
        params, model=model, cfg=cfg, eos_id=EOS, num_slots=2,
        block_size=8, num_blocks=17, max_model_len=64, decode_chunk=4,
        prefix_cache=False,
    )
    real = eng_mod._decode_chunk
    chaos = {"chunks": 0}

    def flaky(params_, paged, *a, **k):
        chaos["chunks"] += 1
        if chaos["chunks"] in (5, 9):  # seeded device failures
            for leaf in jax.tree.leaves(paged):
                leaf.delete()
            raise RuntimeError("chaos device failure")
        return real(params_, paged, *a, **k)

    monkeypatch.setattr(eng_mod, "_decode_chunk", flaky)

    reqs = []
    for i in range(24):
        plen = int(rng.integers(3, 14))
        mnt = int(rng.choice([4, 8, 12]))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        deadline = 30.0 if rng.random() > 0.1 else 1e-6
        h = eng.submit(prompt, max_new_tokens=mnt, key=i, deadline_s=deadline)
        if rng.random() < 0.1:
            h.cancel()
        reqs.append((prompt, mnt, i, h))

    for _ in range(3000):  # bounded drive: a hang fails loudly
        if not (len(eng.scheduler) or eng._n_running()):
            break
        eng.step()
    else:
        pytest.fail("chaos soak did not drain within the step bound")

    n_ok = 0
    for prompt, mnt, key, h in reqs:
        assert h.done, f"request {key} neither finished nor failed"
        if h.error is not None:
            assert isinstance(h.error, RequestError), h.error
        else:
            assert h.result() == solo(
                model, cfg, params, prompt, key, mnt, eos=EOS
            ), f"request {key} diverged from solo generate"
            n_ok += 1
    assert n_ok >= 10, "chaos shed almost everything — soak too aggressive"
    assert eng.allocator.num_in_use == 0, "pages leaked"
    assert eng.health() is Health.READY
