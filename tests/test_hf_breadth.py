"""Architecture breadth: deferred_init → JAX materialization of diverse HF
model families (encoder, encoder-decoder, vision, decoder) with ZERO
torch-fallback params — every recorded init op has a JAX lowering.

The reference's pitch is exactly this generality (any torch module records
under deferred init, docs/src/deferred_init.rst); here the bar is higher:
the whole tape must also lower to the TPU-native replay path.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from torchdistx_tpu.deferred_init import deferred_init  # noqa: E402
from torchdistx_tpu.materialize import materialize_module_jax  # noqa: E402


def _cases():
    from transformers import (
        BertConfig,
        BertModel,
        LlamaConfig,
        LlamaForCausalLM,
        T5Config,
        T5ForConditionalGeneration,
        ViTConfig,
        ViTModel,
    )

    from transformers import (
        GPT2Config,
        GPT2LMHeadModel,
        GPTNeoXConfig,
        GPTNeoXForCausalLM,
        MistralConfig,
        MistralForCausalLM,
    )

    return [
        ("gpt2", lambda: GPT2LMHeadModel(
            GPT2Config(n_layer=2, n_embd=64, n_head=4, vocab_size=256)
        )),
        ("mistral-gqa", lambda: MistralForCausalLM(
            MistralConfig(num_hidden_layers=2, hidden_size=64,
                          num_attention_heads=4, num_key_value_heads=2,
                          intermediate_size=128, vocab_size=256)
        )),
        ("gpt-neox", lambda: GPTNeoXForCausalLM(
            GPTNeoXConfig(num_hidden_layers=2, hidden_size=64,
                          num_attention_heads=4, intermediate_size=128,
                          vocab_size=256)
        )),
        ("bert", lambda: BertModel(
            BertConfig(num_hidden_layers=2, hidden_size=128,
                       num_attention_heads=4, intermediate_size=256)
        )),
        ("t5", lambda: T5ForConditionalGeneration(
            T5Config(num_layers=2, num_decoder_layers=2, d_model=64,
                     num_heads=4, d_ff=128)
        )),
        ("vit", lambda: ViTModel(
            ViTConfig(num_hidden_layers=2, hidden_size=64,
                      num_attention_heads=4, intermediate_size=128,
                      image_size=32, patch_size=8)
        )),
        ("hf-llama", lambda: LlamaForCausalLM(
            LlamaConfig(num_hidden_layers=2, hidden_size=64,
                        num_attention_heads=4, intermediate_size=128,
                        vocab_size=256)
        )),
    ]


# tier-1 re-budget (ISSUE 9): the decoder families (gpt2 / mistral-gqa /
# gpt-neox / hf-llama) exercise every lowering class the serving stack
# depends on and stay in the fast lane; the encoder/encoder-decoder/
# vision breadth (bert / t5 / vit) runs in the slow lane.
_SLOW_FAMILIES = {"bert", "t5", "vit"}


@pytest.mark.parametrize(
    "name,fn",
    [
        pytest.param(
            n, f,
            marks=[pytest.mark.slow] if n in _SLOW_FAMILIES else [],
        )
        for n, f in _cases()
    ],
    ids=[n for n, _ in _cases()],
)
def test_hf_family_materializes_natively(name, fn):
    model = deferred_init(fn)
    # _fallback_torch=False: an unlowerable op raises instead of silently
    # replaying on host — the zero-fallback assertion.
    arrays = materialize_module_jax(model, _fallback_torch=False)
    assert arrays, name
    # parameters + ALL buffers (state_dict would omit non-persistent
    # buffers like BERT's position_ids, which materialize too).  Buffers
    # that are REAL at construction (0-d python-scalar constants like
    # GPT-2's masked_bias — nothing to defer) rightly stay out of the
    # materialized set; every parameter must be fake.
    from torchdistx_tpu.fake import is_fake

    assert all(is_fake(p) for p in model.parameters()), name
    eager = fn()
    n_eager = sum(p.numel() for p in eager.parameters()) + sum(
        b.numel() for b in eager.buffers()
    )
    n_real_bufs = sum(
        b.numel() for _, b in model.named_buffers() if not is_fake(b)
    )
    n_ours = sum(int(np.prod(a.shape)) for a in arrays.values())
    assert n_ours == n_eager - n_real_bufs, (name, n_ours, n_eager)
    for pname, a in arrays.items():
        assert np.isfinite(np.asarray(a)).all(), (name, pname)
