"""Telemetry subsystem: spans, counters, sinks, and the instrumented stack.

The telemetry core is dependency-free (stdlib only) and must behave
identically with and without jax present; the span/counter semantics are
tested torch-free, the stack-instrumentation tests (materialize phase
spans, fill-fastpath counters, ``last_profile`` back-compat) skip in a
JAX-less environment like every other materialize test.
"""

import json
import threading
import time

import pytest

from torchdistx_tpu import telemetry


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Every test starts with no sinks and zeroed registries, and leaves
    the process-wide config the way it found it."""
    prev = telemetry.configure(collect=False, jsonl=None)
    telemetry.reset()
    yield
    telemetry.configure(**prev)
    telemetry.reset()


# ---------------------------------------------------------------------------
# Core span semantics


def test_spans_nest_and_time():
    telemetry.configure(collect=True)
    with telemetry.span("outer", kind="test"):
        time.sleep(0.01)
        with telemetry.span("inner"):
            time.sleep(0.01)
    recs = telemetry.drain()
    by_name = {r["name"]: r for r in recs}
    assert set(by_name) == {"outer", "inner"}
    inner, outer = by_name["inner"], by_name["outer"]
    # Children record before parents (they end first), carry parentage and
    # depth, and cannot outlast the enclosing region.
    assert recs[0]["name"] == "inner"
    assert inner["parent"] == "outer"
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert outer["dur_s"] >= inner["dur_s"] >= 0.01
    assert outer["attrs"] == {"kind": "test"}
    assert "parent" not in outer


def test_manual_span_end_is_idempotent():
    telemetry.configure(collect=True)
    sp = telemetry.start_span("phase")
    d1 = sp.end(n=3)
    d2 = sp.end()
    assert d1 == d2  # second end returns the fixed duration
    recs = telemetry.drain()
    assert len(recs) == 1  # and records exactly once
    assert recs[0]["attrs"] == {"n": 3}


def test_abandoned_span_does_not_corrupt_parentage():
    # An exception that skips an end() must not make later siblings claim
    # the dead span as parent forever.
    telemetry.configure(collect=True)
    telemetry.start_span("abandoned")  # never ended
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    by_name = {r["name"]: r for r in telemetry.drain()}
    assert by_name["inner"]["parent"] == "outer"


def test_cancel_records_nothing():
    telemetry.configure(collect=True)
    sp = telemetry.start_span("maybe")
    sp.cancel()
    assert telemetry.drain() == []


# ---------------------------------------------------------------------------
# Counters / gauges


def test_counters_are_thread_exact():
    c = telemetry.counter("test.threaded")
    n_threads, n_adds = 8, 10_000

    def work():
        for _ in range(n_adds):
            c.add()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert telemetry.counters()["test.threaded"] == n_threads * n_adds


def test_counter_identity_and_gauge_last_value():
    assert telemetry.counter("test.same") is telemetry.counter("test.same")
    telemetry.gauge("test.g").set(1.5)
    telemetry.gauge("test.g").set(2.5)
    assert telemetry.gauges()["test.g"] == 2.5
    assert "test.unset" not in telemetry.gauges()


def test_reset_keeps_bound_counters_live():
    # Instrumented modules bind Counter objects at import; reset() must
    # zero them without severing registry membership.
    c = telemetry.counter("test.bound")
    c.add(5)
    telemetry.reset()
    assert telemetry.counters()["test.bound"] == 0
    c.add(2)
    assert telemetry.counters()["test.bound"] == 2


# ---------------------------------------------------------------------------
# Sinks


def test_disabled_mode_emits_nothing():
    assert not telemetry.enabled()
    with telemetry.span("silent"):
        pass
    sp = telemetry.start_span("silent2")
    sp.end()
    assert sp.duration is not None  # spans still time when disabled
    snap = telemetry.snapshot()
    assert snap["spans"] == []


def test_jsonl_sink_roundtrips(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    with telemetry.span("a.phase", n=2):
        pass
    telemetry.counter("test.jsonl").add(3)
    telemetry.gauge("test.jsonl_g").set(0.5)
    telemetry.emit_counters()
    telemetry.configure(jsonl=None)  # closes the handle

    recs = [json.loads(line) for line in path.read_text().splitlines()]
    spans = [r for r in recs if r["type"] == "span"]
    counters = [r for r in recs if r["type"] == "counters"]
    assert spans and counters
    assert spans[0]["name"] == "a.phase"
    assert spans[0]["attrs"] == {"n": 2}
    assert spans[0]["dur_s"] >= 0
    assert counters[-1]["values"]["test.jsonl"] == 3
    assert counters[-1]["gauges"]["test.jsonl_g"] == 0.5


def test_jsonl_unserializable_attrs_degrade_to_str(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    with telemetry.span("odd", obj=object()):
        pass
    telemetry.configure(jsonl=None)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert recs[0]["name"] == "odd"  # parsed, not crashed


def test_configure_returns_previous_settings():
    prev = telemetry.configure(collect=True)
    assert prev["collect"] is False
    restored = telemetry.configure(**prev)
    assert restored["collect"] is True
    assert not telemetry.enabled()


# ---------------------------------------------------------------------------
# Stack instrumentation (needs jax + torch, like the materialize tests)


def _materialize_zoo(seed=0):
    import torch.nn as nn

    import torchdistx_tpu.deferred_init as di
    from torchdistx_tpu.materialize import materialize_module_jax

    class Zoo(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 8)
            self.fc2 = nn.Linear(8, 4)
            self.ln = nn.LayerNorm(4)

    m = di.deferred_init(Zoo)
    return materialize_module_jax(m, seed=seed)


def test_materialize_emits_phase_spans_and_counters():
    pytest.importorskip("jax")
    telemetry.configure(collect=True)
    c0 = telemetry.counters()
    out = _materialize_zoo()
    assert out
    snap = telemetry.snapshot()
    names = {r["name"] for r in snap["spans"]}
    assert "materialize.module" in names
    assert "materialize.plan" in names
    assert "materialize.execute" in names
    # Phase spans nest under the per-call span.
    by_name = {r["name"]: r for r in snap["spans"]}
    assert by_name["materialize.plan"]["parent"] == "materialize.module"
    c1 = snap["counters"]
    assert c1["materialize.calls"] == c0.get("materialize.calls", 0) + 1
    assert c1["materialize.fill_fastpath_hits"] > c0.get(
        "materialize.fill_fastpath_hits", 0
    )
    assert c1["tape.ops_recorded"] > c0.get("tape.ops_recorded", 0)


def test_exec_cache_hit_counter_tracks_legacy_global():
    pytest.importorskip("jax")
    import torchdistx_tpu.materialize as M

    _materialize_zoo(seed=1)
    legacy0 = M.exec_cache_hits
    t0 = telemetry.counters().get("materialize.exec_cache_hits", 0)
    _materialize_zoo(seed=2)  # seed is traced: same programs, cache hit
    assert M.exec_cache_hits == legacy0 + 1
    assert telemetry.counters()["materialize.exec_cache_hits"] == t0 + 1


def test_last_profile_backcompat_keys(monkeypatch):
    """TDX_PROFILE_MATERIALIZE=1 must reproduce the pre-telemetry
    ``last_profile`` shape even with every telemetry sink disabled."""
    pytest.importorskip("jax")
    import torchdistx_tpu.materialize as M

    monkeypatch.setenv("TDX_PROFILE_MATERIALIZE", "1")
    assert not telemetry.enabled()
    _materialize_zoo(seed=3)
    prof = M.last_profile
    assert {"plan_s", "compile_s", "transfer_s", "exec_s", "jobs"} <= set(
        prof
    )
    for key in ("plan_s", "compile_s", "transfer_s", "exec_s"):
        assert isinstance(prof[key], float) and prof[key] >= 0
    assert prof["jobs"], prof
    for label, dur, rss in prof["jobs"]:
        assert isinstance(label, str)
        assert dur >= 0
        assert rss > 0  # RSS in MB
    # And nothing was collected: the view works without sinks.
    assert telemetry.snapshot()["spans"] == []


def test_counters_survive_concurrent_recording():
    """tape.ops_recorded is exact when several threads record tapes
    concurrently (the counter is shared; tapes are thread-local)."""
    pytest.importorskip("jax")
    import torch.nn as nn

    import torchdistx_tpu.deferred_init as di

    ops = telemetry.counter("tape.ops_recorded")
    base = ops.value
    di.deferred_init(nn.Linear, 8, 8)
    per_module = ops.value - base
    assert per_module > 0

    n_threads, per_thread = 4, 5
    before = ops.value
    errors = []

    def work():
        try:
            for _ in range(per_thread):
                di.deferred_init(nn.Linear, 8, 8)
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append(e)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert ops.value - before == n_threads * per_thread * per_module


# ---------------------------------------------------------------------------
# Histograms (ISSUE 9)


def test_histogram_exact_counts_and_percentiles():
    h = telemetry.Histogram("test.h", bounds=[1.0, 2.0, 4.0, 8.0])
    for v in (0.5, 0.5, 1.5, 3.0, 3.5, 10.0):
        h.observe(v)
    assert h.count == 6
    assert h.sum == pytest.approx(19.0)
    s = h.summary()
    assert s["count"] == 6
    assert s["min"] == 0.5 and s["max"] == 10.0
    # Percentiles interpolate within a bucket and clamp to observed
    # min/max — never outside the data.
    assert s["min"] <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    # p99 of 6 observations lands in the overflow bucket: past the last
    # edge (8.0), clamped by the observed max.
    assert 8.0 <= s["p99"] <= 10.0


def test_histogram_aggregated_observe_and_bounds_validation():
    h = telemetry.Histogram("test.h2", bounds=[0.1, 1.0])
    h.observe(0.05, n=100)  # one aggregated observation per decode chunk
    assert h.count == 100
    assert h.sum == pytest.approx(5.0)
    with pytest.raises(ValueError):
        telemetry.Histogram("bad", bounds=[1.0, 1.0])


def test_histogram_thread_exact():
    h = telemetry.histogram("test.h_threads", bounds=[0.5, 1.5])
    n_threads, n_obs = 8, 5000

    def work():
        for _ in range(n_obs):
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * n_obs
    assert telemetry.histograms()["test.h_threads"]["count"] == h.count


def test_labeled_metrics_scope_per_engine():
    # The SAME (name, labels) resolves to the same instrument; different
    # labels to different ones — N replicas stop clobbering one gauge.
    g0 = telemetry.gauge("test.health", engine="eng0")
    g1 = telemetry.gauge("test.health", engine="eng1")
    assert g0 is not g1
    assert g0 is telemetry.gauge("test.health", engine="eng0")
    g0.set("ready")
    g1.set("stopped")
    vals = telemetry.gauges()
    assert vals["test.health{engine=eng0}"] == "ready"
    assert vals["test.health{engine=eng1}"] == "stopped"
    c = telemetry.counter("test.shed", engine="eng0")
    c.add(2)
    assert telemetry.counters()["test.shed{engine=eng0}"] == 2
    h = telemetry.histogram("test.lat", engine="eng0")
    assert h is telemetry.histogram("test.lat", engine="eng0")


# ---------------------------------------------------------------------------
# Request events + trace context (ISSUE 9)


def test_event_carries_trace_context_and_nests():
    telemetry.configure(collect=True)
    with telemetry.tracing(rid="r1", engine="eng0", hop=0):
        telemetry.event("req.submitted", n_prompt=4)
        with telemetry.tracing(hop=1):  # inner scope inherits + overrides
            telemetry.event("req.failover_hop")
        with telemetry.span("serve.prefill", n=4):
            pass
    telemetry.event("req.other", rid="r2")  # explicit kwargs, no scope
    recs = telemetry.drain()
    by_name = {r["name"]: r for r in recs}
    sub = by_name["req.submitted"]
    assert (sub["rid"], sub["engine"], sub["hop"]) == ("r1", "eng0", 0)
    assert sub["attrs"] == {"n_prompt": 4}
    hop = by_name["req.failover_hop"]
    assert (hop["rid"], hop["engine"], hop["hop"]) == ("r1", "eng0", 1)
    # Spans started inside the scope carry the context too.
    span = by_name["serve.prefill"]
    assert span["type"] == "span" and span["rid"] == "r1"
    assert by_name["req.other"]["rid"] == "r2"
    assert "engine" not in by_name["req.other"]


def test_events_enabled_gates_on_sinks_and_flight():
    assert not telemetry.events_enabled()
    telemetry.configure(collect=True)
    assert telemetry.events_enabled()
    telemetry.configure(collect=False)
    assert not telemetry.events_enabled()
    # The flight ring alone counts: events must reach the ring even
    # with every sink off — that is the recorder's whole point.
    telemetry.configure(flight=True)
    assert telemetry.events_enabled()
    telemetry.configure(flight=None)
    assert not telemetry.events_enabled()


def test_disabled_path_builds_no_records(monkeypatch):
    """The acceptance pin: with no sink and no flight ring, spans,
    events, and histogram observations build NO record dict and call
    no sink — the record funnel itself is booby-trapped."""
    from torchdistx_tpu.telemetry import _core

    assert not telemetry.events_enabled()

    def bomb(rec):  # pragma: no cover — the point is it never runs
        raise AssertionError(f"record built while disabled: {rec}")

    monkeypatch.setattr(_core._state, "record", bomb)
    monkeypatch.setattr(_core._state, "write_jsonl", bomb)
    telemetry.event("req.submitted", rid="r1", n_prompt=4)
    with telemetry.span("serve.step", n=1):
        pass
    sp = telemetry.start_span("serve.prefill")
    sp.end(tokens=3)
    telemetry.histogram("test.disabled").observe(0.1)
    assert telemetry.flight_dump("nothing-recorded") == 0
    assert sp.duration is not None  # spans still time when disabled


# ---------------------------------------------------------------------------
# Flight recorder (ISSUE 9)


def test_flight_recorder_dumps_to_dedicated_file(tmp_path):
    flight = tmp_path / "flight.jsonl"
    telemetry.configure(flight=str(flight), flight_capacity=4)
    assert not telemetry.enabled()  # no span sink — ring only
    for i in range(6):  # overflow: ring keeps the most recent 4
        telemetry.event("req.prefill_chunk", rid=f"r{i}")
    n = telemetry.flight_dump("RecoveryFailed", rid="r5")
    assert n == 4
    recs = [json.loads(line) for line in flight.read_text().splitlines()]
    assert recs[0]["type"] == "flight_dump"
    assert recs[0]["reason"] == "RecoveryFailed"
    assert recs[0]["n"] == 4
    assert recs[0]["attrs"] == {"rid": "r5"}
    assert [r["rid"] for r in recs[1:]] == ["r2", "r3", "r4", "r5"]
    # The ring cleared: back-to-back failures dump disjoint windows.
    assert telemetry.flight_dump("again") == 0


def test_flight_recorder_header_only_into_main_sink(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path), flight=True)
    telemetry.event("req.submitted", rid="r0")
    assert telemetry.flight_dump("forced-fault") == 1
    telemetry.configure(jsonl=None)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    kinds = [r["type"] for r in recs]
    # The event was exported as it happened; the dump adds ONLY the
    # header marker (no duplicate records).
    assert kinds.count("event") == 1
    assert kinds.count("flight_dump") == 1


# ---------------------------------------------------------------------------
# Span-stack depth under concurrency (the tier-1 "span flake" pin)


def test_span_depths_exact_under_concurrent_threads():
    """Depth/parent accounting must stay exact per thread under
    concurrent load: stacks are thread-local and only the owner mutates
    them (the PR 1 collector corrupted depths when threads raced)."""
    telemetry.configure(collect=True, max_spans=100_000)
    n_threads, n_iters = 8, 200
    errors = []

    def work(tid):
        try:
            for i in range(n_iters):
                with telemetry.span(f"outer-{tid}"):
                    with telemetry.span(f"inner-{tid}"):
                        pass
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append(e)

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    recs = telemetry.drain()
    assert len(recs) == n_threads * n_iters * 2
    for r in recs:
        tid = r["name"].split("-")[1]
        if r["name"].startswith("outer"):
            assert r["depth"] == 0, r
            assert "parent" not in r, r
        else:
            assert r["depth"] == 1, r
            assert r["parent"] == f"outer-{tid}", r


def test_span_ended_on_another_thread_leaves_owner_stack_clean():
    """A span started on thread A and ended on thread B (a drain span
    finalized by a reaper) must record once and leave A's nesting stack
    consistent: A's next span is depth 0, not a phantom child."""
    telemetry.configure(collect=True)
    sp = telemetry.start_span("crossing")
    t = threading.Thread(target=sp.end)
    t.start()
    t.join()
    with telemetry.span("after"):
        pass
    by_name = {r["name"]: r for r in telemetry.drain()}
    assert by_name["crossing"]["thread"] != by_name["after"]["thread"] or True
    assert by_name["after"]["depth"] == 0
    assert "parent" not in by_name["after"]


def test_detached_span_never_parents():
    telemetry.configure(collect=True)
    drain_sp = telemetry.start_span("serve.drain", detached=True)
    with telemetry.span("serve.step"):
        pass
    drain_sp.end()
    by_name = {r["name"]: r for r in telemetry.drain()}
    assert "parent" not in by_name["serve.step"]
    assert by_name["serve.step"]["depth"] == 0


# ---------------------------------------------------------------------------
# JSONL schema back-compat (ISSUE 9 acceptance)


def test_jsonl_schema_backward_compatible(tmp_path):
    """Pre-ISSUE-9 consumers parse unchanged: span records keep their
    keys, counters records keep values/gauges, and the histograms key
    appears only once a histogram exists."""
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    with telemetry.span("a.phase", n=1):
        pass
    telemetry.counter("test.c").add()
    telemetry.emit_counters()
    telemetry.histogram("test.h_schema").observe(0.5)
    telemetry.emit_counters()
    telemetry.configure(jsonl=None)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    span = next(r for r in recs if r["type"] == "span")
    assert {"type", "name", "ts", "dur_s", "thread", "depth"} <= set(span)
    counters = [r for r in recs if r["type"] == "counters"]
    assert {"type", "ts", "values", "gauges"} <= set(counters[0])
    # The histograms key is ADDITIVE: absent from the first snapshot
    # (the new histogram didn't exist yet), present once it does.
    assert "test.h_schema" not in counters[0].get("histograms", {})
    assert counters[1]["histograms"]["test.h_schema"]["count"] == 1


def test_flight_dump_without_any_sink_keeps_the_window(tmp_path):
    """Ring-only mode with no main sink has nowhere to persist: the
    dump must NOT destroy the post-mortem window — it returns 0, keeps
    the records, and a later dump (once a sink exists) delivers them."""
    telemetry.configure(flight=True)  # ring only: no JSONL, no collector
    telemetry.event("req.submitted", rid="r0")
    assert telemetry.flight_dump("nowhere-to-go") == 0
    # The window survived; route the recorder to a dedicated file and
    # the SAME records dump.
    flight = tmp_path / "late-flight.jsonl"
    telemetry.configure(flight=str(flight))
    assert telemetry.flight_dump("retry") == 1
    recs = [json.loads(line) for line in flight.read_text().splitlines()]
    assert [r["type"] for r in recs] == ["flight_dump", "event"]
    assert recs[1]["rid"] == "r0"


def test_flight_dump_failed_write_keeps_the_window(tmp_path):
    """An unwritable dedicated flight file must not cost the window:
    the failed dump returns 0 and the records remain for a retry."""
    telemetry.configure(flight=str(tmp_path / "no-such-dir" / "f.jsonl"))
    telemetry.event("req.submitted", rid="r0")
    assert telemetry.flight_dump("disk-vanished") == 0
    flight = tmp_path / "flight.jsonl"
    telemetry.configure(flight=str(flight))
    assert telemetry.flight_dump("retry") == 1
    assert flight.exists()


def test_flight_dump_fsync_fault_is_atomic_and_keeps_window(
    tmp_path, monkeypatch
):
    """Durability pin (ISSUE 20): a first dump whose fsync fails (disk
    full, power path gone) reports 0, keeps the window, and leaves NO
    dedicated flight file behind — tmp + fsync + atomic rename means a
    post-mortem reader never opens a torn or empty forensics file.
    With the fault cleared, the SAME window dumps intact."""
    flight = tmp_path / "flight.jsonl"
    telemetry.configure(flight=str(flight), flight_capacity=8)
    telemetry.event("req.submitted", rid="r1")
    telemetry.event("req.finished", rid="r1")

    def _enospc(fd):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(
        "torchdistx_tpu.telemetry._core.os.fsync", _enospc
    )
    assert telemetry.flight_dump("power-loss") == 0
    assert not flight.exists()
    monkeypatch.undo()
    assert telemetry.flight_dump("retry") == 2
    recs = [json.loads(line) for line in flight.read_text().splitlines()]
    assert recs[0]["type"] == "flight_dump" and recs[0]["n"] == 2
    assert [r["rid"] for r in recs[1:]] == ["r1", "r1"]
    assert not (tmp_path / "flight.jsonl.tmp").exists()


def test_flight_dump_append_fsync_fault_keeps_window(
    tmp_path, monkeypatch
):
    """The append path (file already exists) fsyncs before the ring
    clears: a failed fsync reports 0 and the window survives for the
    retry — at-least-once delivery, never silent loss."""
    flight = tmp_path / "flight.jsonl"
    telemetry.configure(flight=str(flight), flight_capacity=8)
    telemetry.event("req.submitted", rid="a")
    assert telemetry.flight_dump("first") == 1
    telemetry.event("req.finished", rid="b")
    monkeypatch.setattr(
        "torchdistx_tpu.telemetry._core.os.fsync",
        lambda fd: (_ for _ in ()).throw(OSError(5, "I/O error")),
    )
    assert telemetry.flight_dump("io-fault") == 0
    monkeypatch.undo()
    assert telemetry.flight_dump("retry") == 1
    recs = [json.loads(line) for line in flight.read_text().splitlines()]
    assert [r.get("rid") for r in recs if r["type"] == "event"].count(
        "b"
    ) >= 1


def test_flight_dump_backfills_presink_records(tmp_path):
    """A main-sink dump must not assume the whole window was exported
    live: records captured before the sink existed are backfilled after
    the header (exactly once), records the sink already exported are
    not re-written, and the ring clears only then."""
    telemetry.configure(flight=True)  # ring only: no sink yet
    telemetry.event("req.submitted", rid="early")
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    telemetry.event("req.finished", rid="late")  # exported as it happens
    assert telemetry.flight_dump("post-mortem") == 2
    assert telemetry.flight_dump("ring-cleared") == 0
    telemetry.configure(jsonl=None)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r.get("rid", r["type"]) for r in recs] == [
        "late", "flight_dump", "early"
    ]
    header = recs[1]
    assert header["n"] == 2 and header["backfilled"] == 1
