"""Telemetry subsystem: spans, counters, sinks, and the instrumented stack.

The telemetry core is dependency-free (stdlib only) and must behave
identically with and without jax present; the span/counter semantics are
tested torch-free, the stack-instrumentation tests (materialize phase
spans, fill-fastpath counters, ``last_profile`` back-compat) skip in a
JAX-less environment like every other materialize test.
"""

import json
import threading
import time

import pytest

from torchdistx_tpu import telemetry


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Every test starts with no sinks and zeroed registries, and leaves
    the process-wide config the way it found it."""
    prev = telemetry.configure(collect=False, jsonl=None)
    telemetry.reset()
    yield
    telemetry.configure(**prev)
    telemetry.reset()


# ---------------------------------------------------------------------------
# Core span semantics


def test_spans_nest_and_time():
    telemetry.configure(collect=True)
    with telemetry.span("outer", kind="test"):
        time.sleep(0.01)
        with telemetry.span("inner"):
            time.sleep(0.01)
    recs = telemetry.drain()
    by_name = {r["name"]: r for r in recs}
    assert set(by_name) == {"outer", "inner"}
    inner, outer = by_name["inner"], by_name["outer"]
    # Children record before parents (they end first), carry parentage and
    # depth, and cannot outlast the enclosing region.
    assert recs[0]["name"] == "inner"
    assert inner["parent"] == "outer"
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert outer["dur_s"] >= inner["dur_s"] >= 0.01
    assert outer["attrs"] == {"kind": "test"}
    assert "parent" not in outer


def test_manual_span_end_is_idempotent():
    telemetry.configure(collect=True)
    sp = telemetry.start_span("phase")
    d1 = sp.end(n=3)
    d2 = sp.end()
    assert d1 == d2  # second end returns the fixed duration
    recs = telemetry.drain()
    assert len(recs) == 1  # and records exactly once
    assert recs[0]["attrs"] == {"n": 3}


def test_abandoned_span_does_not_corrupt_parentage():
    # An exception that skips an end() must not make later siblings claim
    # the dead span as parent forever.
    telemetry.configure(collect=True)
    telemetry.start_span("abandoned")  # never ended
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    by_name = {r["name"]: r for r in telemetry.drain()}
    assert by_name["inner"]["parent"] == "outer"


def test_cancel_records_nothing():
    telemetry.configure(collect=True)
    sp = telemetry.start_span("maybe")
    sp.cancel()
    assert telemetry.drain() == []


# ---------------------------------------------------------------------------
# Counters / gauges


def test_counters_are_thread_exact():
    c = telemetry.counter("test.threaded")
    n_threads, n_adds = 8, 10_000

    def work():
        for _ in range(n_adds):
            c.add()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert telemetry.counters()["test.threaded"] == n_threads * n_adds


def test_counter_identity_and_gauge_last_value():
    assert telemetry.counter("test.same") is telemetry.counter("test.same")
    telemetry.gauge("test.g").set(1.5)
    telemetry.gauge("test.g").set(2.5)
    assert telemetry.gauges()["test.g"] == 2.5
    assert "test.unset" not in telemetry.gauges()


def test_reset_keeps_bound_counters_live():
    # Instrumented modules bind Counter objects at import; reset() must
    # zero them without severing registry membership.
    c = telemetry.counter("test.bound")
    c.add(5)
    telemetry.reset()
    assert telemetry.counters()["test.bound"] == 0
    c.add(2)
    assert telemetry.counters()["test.bound"] == 2


# ---------------------------------------------------------------------------
# Sinks


def test_disabled_mode_emits_nothing():
    assert not telemetry.enabled()
    with telemetry.span("silent"):
        pass
    sp = telemetry.start_span("silent2")
    sp.end()
    assert sp.duration is not None  # spans still time when disabled
    snap = telemetry.snapshot()
    assert snap["spans"] == []


def test_jsonl_sink_roundtrips(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    with telemetry.span("a.phase", n=2):
        pass
    telemetry.counter("test.jsonl").add(3)
    telemetry.gauge("test.jsonl_g").set(0.5)
    telemetry.emit_counters()
    telemetry.configure(jsonl=None)  # closes the handle

    recs = [json.loads(line) for line in path.read_text().splitlines()]
    spans = [r for r in recs if r["type"] == "span"]
    counters = [r for r in recs if r["type"] == "counters"]
    assert spans and counters
    assert spans[0]["name"] == "a.phase"
    assert spans[0]["attrs"] == {"n": 2}
    assert spans[0]["dur_s"] >= 0
    assert counters[-1]["values"]["test.jsonl"] == 3
    assert counters[-1]["gauges"]["test.jsonl_g"] == 0.5


def test_jsonl_unserializable_attrs_degrade_to_str(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.configure(jsonl=str(path))
    with telemetry.span("odd", obj=object()):
        pass
    telemetry.configure(jsonl=None)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert recs[0]["name"] == "odd"  # parsed, not crashed


def test_configure_returns_previous_settings():
    prev = telemetry.configure(collect=True)
    assert prev["collect"] is False
    restored = telemetry.configure(**prev)
    assert restored["collect"] is True
    assert not telemetry.enabled()


# ---------------------------------------------------------------------------
# Stack instrumentation (needs jax + torch, like the materialize tests)


def _materialize_zoo(seed=0):
    import torch.nn as nn

    import torchdistx_tpu.deferred_init as di
    from torchdistx_tpu.materialize import materialize_module_jax

    class Zoo(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 8)
            self.fc2 = nn.Linear(8, 4)
            self.ln = nn.LayerNorm(4)

    m = di.deferred_init(Zoo)
    return materialize_module_jax(m, seed=seed)


def test_materialize_emits_phase_spans_and_counters():
    pytest.importorskip("jax")
    telemetry.configure(collect=True)
    c0 = telemetry.counters()
    out = _materialize_zoo()
    assert out
    snap = telemetry.snapshot()
    names = {r["name"] for r in snap["spans"]}
    assert "materialize.module" in names
    assert "materialize.plan" in names
    assert "materialize.execute" in names
    # Phase spans nest under the per-call span.
    by_name = {r["name"]: r for r in snap["spans"]}
    assert by_name["materialize.plan"]["parent"] == "materialize.module"
    c1 = snap["counters"]
    assert c1["materialize.calls"] == c0.get("materialize.calls", 0) + 1
    assert c1["materialize.fill_fastpath_hits"] > c0.get(
        "materialize.fill_fastpath_hits", 0
    )
    assert c1["tape.ops_recorded"] > c0.get("tape.ops_recorded", 0)


def test_exec_cache_hit_counter_tracks_legacy_global():
    pytest.importorskip("jax")
    import torchdistx_tpu.materialize as M

    _materialize_zoo(seed=1)
    legacy0 = M.exec_cache_hits
    t0 = telemetry.counters().get("materialize.exec_cache_hits", 0)
    _materialize_zoo(seed=2)  # seed is traced: same programs, cache hit
    assert M.exec_cache_hits == legacy0 + 1
    assert telemetry.counters()["materialize.exec_cache_hits"] == t0 + 1


def test_last_profile_backcompat_keys(monkeypatch):
    """TDX_PROFILE_MATERIALIZE=1 must reproduce the pre-telemetry
    ``last_profile`` shape even with every telemetry sink disabled."""
    pytest.importorskip("jax")
    import torchdistx_tpu.materialize as M

    monkeypatch.setenv("TDX_PROFILE_MATERIALIZE", "1")
    assert not telemetry.enabled()
    _materialize_zoo(seed=3)
    prof = M.last_profile
    assert {"plan_s", "compile_s", "transfer_s", "exec_s", "jobs"} <= set(
        prof
    )
    for key in ("plan_s", "compile_s", "transfer_s", "exec_s"):
        assert isinstance(prof[key], float) and prof[key] >= 0
    assert prof["jobs"], prof
    for label, dur, rss in prof["jobs"]:
        assert isinstance(label, str)
        assert dur >= 0
        assert rss > 0  # RSS in MB
    # And nothing was collected: the view works without sinks.
    assert telemetry.snapshot()["spans"] == []


def test_counters_survive_concurrent_recording():
    """tape.ops_recorded is exact when several threads record tapes
    concurrently (the counter is shared; tapes are thread-local)."""
    pytest.importorskip("jax")
    import torch.nn as nn

    import torchdistx_tpu.deferred_init as di

    ops = telemetry.counter("tape.ops_recorded")
    base = ops.value
    di.deferred_init(nn.Linear, 8, 8)
    per_module = ops.value - base
    assert per_module > 0

    n_threads, per_thread = 4, 5
    before = ops.value
    errors = []

    def work():
        try:
            for _ in range(per_thread):
                di.deferred_init(nn.Linear, 8, 8)
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append(e)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert ops.value - before == n_threads * per_thread * per_module
