"""Flagship model stack: shapes, determinism, sharded init, training."""

import jax
import jax.numpy as jnp
import pytest

from torchdistx_tpu.models import llama
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


class TestParams:
    def test_num_params_matches_tree(self, cfg, params):
        total = sum(leaf.size for leaf in jax.tree.leaves(params))
        assert total == llama.num_params(cfg)

    def test_abstract_matches_concrete(self, cfg, params):
        abstract = llama.abstract_params(cfg)
        assert jax.tree.structure(abstract) == jax.tree.structure(params)
        for a, p in zip(jax.tree.leaves(abstract), jax.tree.leaves(params)):
            assert a.shape == p.shape and a.dtype == p.dtype

    def test_init_deterministic(self, cfg, params):
        again = llama.init_params(jax.random.PRNGKey(0), cfg)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(again)):
            assert jnp.array_equal(a, b)

    def test_specs_cover_params(self, cfg):
        specs = llama.param_specs(cfg)
        abstract = llama.abstract_params(cfg)
        assert jax.tree.structure(
            specs, is_leaf=lambda x: hasattr(x, "_normalized_spec")
            or type(x).__name__ == "PartitionSpec"
        ) == jax.tree.structure(abstract)

    def test_init_sharded_places_shards(self, cfg):
        mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
        sharded = llama.init_sharded(jax.random.PRNGKey(0), cfg, mesh)
        wq = sharded["layers"]["wq"]
        # (L, D, Hq): fsdp over D, tp over Hq.
        assert wq.sharding.spec == jax.sharding.PartitionSpec(
            None, "fsdp", "tp"
        )
        # Values identical to unsharded init (same fold_in keys).
        plain = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert jnp.allclose(
            jnp.asarray(wq), jnp.asarray(plain["layers"]["wq"])
        )

    def test_init_sharded_replicates_indivisible(self, cfg):
        # vocab 256 over tp=3 doesn't divide cleanly on any axis of 3.
        mesh = make_mesh(MeshSpec(tp=3), devices=jax.devices()[:3])
        sharded = llama.init_sharded(jax.random.PRNGKey(0), cfg, mesh)
        assert sharded["layers"]["wq"] is not None  # materialized fine


class TestForward:
    def test_logits_shape_dtype(self, cfg, params):
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = llama.forward(params, tokens, cfg, attn_impl="jnp")
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, cfg, params):
        # Changing a future token must not affect earlier logits.
        key = jax.random.PRNGKey(3)
        tokens = jax.random.randint(key, (1, 16), 0, cfg.vocab_size)
        logits_a = llama.forward(params, tokens, cfg, attn_impl="jnp")
        tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % cfg.vocab_size)
        logits_b = llama.forward(params, tokens_b, cfg, attn_impl="jnp")
        assert jnp.allclose(logits_a[0, :-1], logits_b[0, :-1], atol=1e-5)
        assert not jnp.allclose(logits_a[0, -1], logits_b[0, -1], atol=1e-5)

    def test_pallas_path_matches_jnp(self, cfg, params):
        tokens = jnp.arange(32, dtype=jnp.int32)[None] % cfg.vocab_size
        a = llama.forward(params, tokens, cfg, attn_impl="jnp")
        b = llama.forward(params, tokens, cfg, attn_impl="pallas")
        assert jnp.allclose(a, b, atol=1e-4)

    def test_remat_matches(self, cfg, params):
        import dataclasses

        tokens = jnp.arange(16, dtype=jnp.int32)[None] % cfg.vocab_size
        cfg_r = dataclasses.replace(cfg, remat=True)
        a = llama.forward(params, tokens, cfg, attn_impl="jnp")
        b = llama.forward(params, tokens, cfg_r, attn_impl="jnp")
        assert jnp.allclose(a, b, atol=1e-6)

    def test_loss_finite_and_learnable(self, cfg, params):
        import optax

        tokens = jax.random.randint(
            jax.random.PRNGKey(7), (4, 32), 0, cfg.vocab_size
        )
        loss0 = llama.loss_fn(params, tokens, tokens, cfg, attn_impl="jnp")
        assert jnp.isfinite(loss0)
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        p = params

        @jax.jit
        def step(p, opt_state):
            loss, g = jax.value_and_grad(
                lambda p: llama.loss_fn(p, tokens, tokens, cfg, attn_impl="jnp")
            )(p)
            updates, opt_state = tx.update(g, opt_state, p)
            return optax.apply_updates(p, updates), opt_state, loss

        for _ in range(5):
            p, opt_state, loss = step(p, opt_state)
        assert float(loss) < float(loss0)

    def test_presets_shapes(self):
        for preset, expected in [
            (llama.llama_7b(), 6_738_415_616),
            (llama.llama_70b(), 68_976_648_192),
        ]:
            n = llama.num_params(preset)
            # within 3% of the published sizes
            assert abs(n - expected) / expected < 0.03
