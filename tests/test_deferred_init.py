"""Deferred-init unit tests.

Parity with /root/reference/tests/python/test_deferred_init.py (identity
no-op, materialize-twice identity preservation) plus graph-semantics coverage
the reference lacks upstream (views, in-place ordering, aliasing, external
tensor guards — its hardest code paths, deferred_init.cc:529-666, have no
upstream tests; see SURVEY.md §4)."""

import pytest
import torch
import torch.nn as nn

import torchdistx_tpu.deferred_init as deferred_init
from torchdistx_tpu import fake
from torchdistx_tpu.deferred_init import (
    is_deferred,
    materialize_module,
    materialize_tensor,
)


def test_materialize_real_tensor_is_noop():
    # Reference test_deferred_init.py:16-21.
    t = torch.ones([2, 2])
    assert materialize_tensor(t) is t


def test_materializing_parameter_twice_returns_same_object():
    # Reference test_deferred_init.py:24-39 — identity preservation.
    m = deferred_init.deferred_init(nn.Linear, 5, 3)
    a = materialize_tensor(m.weight)
    b = materialize_tensor(m.weight)
    assert a is b


def test_deferred_linear_matches_eager_statistics():
    torch.manual_seed(0)
    m = deferred_init.deferred_init(nn.Linear, 64, 32)
    assert fake.is_fake(m.weight)
    assert m.weight.shape == (32, 64)
    materialize_module(m)
    assert not fake.is_fake(m.weight)
    assert isinstance(m.weight, nn.Parameter)
    assert m.weight.requires_grad
    # kaiming-uniform bound for Linear(64, 32): bound = 1/sqrt(64) * sqrt(3) ≈ 0.216
    assert m.weight.abs().max().item() <= 0.217
    assert m.weight.std().item() > 0.0


def test_deferred_rng_replay_bitwise():
    # Replay must reproduce the recorded RNG ops under the recorded seed.
    torch.manual_seed(42)
    m1 = deferred_init.deferred_init(nn.Linear, 16, 8)
    torch.manual_seed(42)
    materialize_module(m1)
    torch.manual_seed(42)
    m2 = nn.Linear(16, 8)
    assert torch.equal(m1.weight, m2.weight)
    assert torch.equal(m1.bias, m2.bias)


def test_materialize_module_recursive():
    m = deferred_init.deferred_init(
        lambda: nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    )
    assert fake.is_fake(m[0].weight)
    materialize_module(m)
    for p in m.parameters():
        assert not fake.is_fake(p)
    out = m(torch.randn(3, 4))
    assert out.shape == (3, 2)


def test_module_fn_closure():
    def build():
        net = nn.Sequential(nn.Linear(6, 6), nn.LayerNorm(6))
        return net

    m = deferred_init.deferred_init(build)
    assert fake.is_fake(m[1].weight)
    materialize_module(m)
    assert torch.equal(m[1].weight, torch.ones(6))  # LayerNorm init


def test_inplace_mutation_order_preserved():
    def build():
        t = torch.zeros(4)
        t.add_(1)
        t.mul_(3)
        return nn.Parameter(t)

    with deferred_init._deferred_init_context():
        p = build()
    real = materialize_tensor(p)
    assert torch.equal(real.detach(), torch.full((4,), 3.0))


def test_view_aliasing_mutation():
    # Mutating a view must be visible in the materialized base and vice versa.
    with deferred_init._deferred_init_context():
        base = torch.zeros(2, 4)
        row = base[1]
        row.fill_(7)
        base.mul_(2)
    r_base = materialize_tensor(base)
    r_row = materialize_tensor(row)
    assert torch.equal(r_base, torch.tensor([[0.0] * 4, [14.0] * 4]))
    assert torch.equal(r_row, torch.tensor([14.0] * 4))


def test_mutation_after_target_still_replayed():
    # Materializing `t` must include the later in-place op on its storage —
    # the horizon search (deferred_init.cc:540-578).
    with deferred_init._deferred_init_context():
        t = torch.ones(3)
        view = t.view(3)
        view.add_(5)
    real = materialize_tensor(t)
    assert torch.equal(real, torch.full((3,), 6.0))


def test_external_tensor_version_guard():
    ext = torch.ones(4)
    with deferred_init._deferred_init_context():
        t = torch.zeros(4)
        u = t + ext
    ext.add_(1)  # mutate after recording
    with pytest.raises(RuntimeError, match="mutated after recording"):
        materialize_tensor(u)


def test_terminal_op_forces_materialization():
    # `.item()` needs real data: force-materialize (deferred_init.cc:774-779).
    with deferred_init._deferred_init_context():
        t = torch.full((1,), 3.0)
        val = t.item()
    assert val == 3.0


def test_deferred_on_claimed_tpu_device():
    m = deferred_init.deferred_init(nn.Linear, 8, 4, device_="tpu")
    assert m.weight.device.type == "tpu"
    assert is_deferred(m.weight)
    # torch cannot allocate on the claimed device; override at replay.
    materialize_module(m, device="cpu")
    assert m.weight.device.type == "cpu"
    assert m.weight.shape == (4, 8)


def test_buffers_only():
    m = deferred_init.deferred_init(nn.BatchNorm1d, 10)
    materialize_module(m, buffers_only=True)
    assert not fake.is_fake(m.running_mean)
    assert fake.is_fake(m.weight)
    materialize_module(m)
    assert not fake.is_fake(m.weight)


def test_check_fn_gates_submodules():
    m = deferred_init.deferred_init(
        lambda: nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    )
    first = m[0]
    materialize_module(m, check_fn=lambda mod: mod is not first)
    assert fake.is_fake(m[0].weight)
    assert not fake.is_fake(m[1].weight)


def test_fake_created_outside_deferred_rejected():
    with fake.fake_mode():
        t = torch.ones(3)
    with deferred_init._deferred_init_context():
        with pytest.raises(RuntimeError, match="outside of a deferred-init"):
            t.add_(1)


def test_materialize_inside_context():
    # Materialization may happen while still recording (terminal ops do it).
    with deferred_init._deferred_init_context():
        t = torch.arange(4.0)
        real = materialize_tensor(t)
        assert torch.equal(real, torch.arange(4.0))


def test_large_model_no_allocation_then_materialize():
    def build():
        return nn.Sequential(*[nn.Linear(256, 256) for _ in range(8)])

    m = deferred_init.deferred_init(build)
    n_params = sum(p.numel() for p in m.parameters())
    assert n_params == 8 * (256 * 256 + 256)
    for p in m.parameters():
        assert fake.is_fake(p)
    materialize_module(m)
    y = m(torch.randn(2, 256))
    assert y.shape == (2, 256)


def test_terminal_op_with_claimed_device():
    # Regression: `.item()` inside a deferred context with a claimed
    # unallocatable device must replay on host CPU, not the claimed device.
    with deferred_init._deferred_init_context(device="tpu"):
        t = torch.full((1,), 3.0)
        assert t.item() == 3.0


def test_deferred_fake_cuda_without_cuda():
    # Regression: fake-CUDA deferred init on a CUDA-less host (reference
    # parity: _C/fake.cc:18-36 suppresses lazy CUDA init).
    m = deferred_init.deferred_init(nn.Linear, 4, 2, device_="cuda")
    assert m.weight.device.type == "cuda"
    materialize_module(m)
    assert m.weight.device.type == "cpu"  # replays on host by default


def test_storage_key_reuse_no_false_aliasing():
    # Regression: meta storages are pinned by nodes, so a freed storage
    # address cannot be reused and create false alias edges.
    with deferred_init._deferred_init_context():
        t = torch.zeros(4)
        keep = t + 1
        producer = deferred_init._get_record(keep).node
        del t
        import gc
        gc.collect()
        for _ in range(16):
            other = torch.zeros(4)
            other.add_(5)
        n_deps_before = len(producer.dependents)
    real = materialize_tensor(keep)
    assert torch.equal(real, torch.ones(4))
    assert n_deps_before == 0


def test_real_ops_stay_real_under_default_device():
    # Regression: a mode-level default device must not hijack ops on real
    # tensors onto meta (their data would be silently discarded).
    real = torch.arange(6.0)
    with fake.fake_mode(device="tpu"):
        out = real * 2
    assert not fake.is_fake(out)
    assert torch.equal(out, torch.arange(6.0) * 2)


def test_cross_tape_module_materialization():
    # Regression: op_nr is globally unique, so a module assembled from two
    # deferred_init calls materializes correctly.
    m1 = deferred_init.deferred_init(nn.Linear, 4, 4)
    m2 = deferred_init.deferred_init(nn.Linear, 4, 4)
    seq = nn.Sequential(m1, m2)
    materialize_module(seq)
    assert not fake.is_fake(seq[0].weight)
    assert not fake.is_fake(seq[1].weight)
    # Different tapes must not share values (distinct op numbering).
    assert not torch.equal(seq[0].weight, seq[1].weight)


def test_materialize_module_order_independent_aliasing():
    # Regression: write-after-read through an alias — module traversal order
    # must not leak a later in-place op into an earlier-recorded read.
    class M(nn.Module):
        pass

    with deferred_init._deferred_init_context():
        t = torch.zeros(4)
        u = t + 1          # recorded BEFORE the mutation
        t.add_(5)
        mod = M()
        mod.t = nn.Parameter(t)   # registered first -> materialized first
        mod.u = nn.Parameter(u)
    materialize_module(mod)
    assert torch.equal(mod.t.detach(), torch.full((4,), 5.0))
    assert torch.equal(mod.u.detach(), torch.ones(4))
