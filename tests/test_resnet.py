"""BASELINE config 2: deferred_init(resnet50) → materialize on one chip.

Exercises the conv/BN init tape (kaiming conv, BN ones/zeros) end-to-end
through both replay paths.  VERDICT r1 #5: must assert zero torch-fallback
params on the JAX path.
"""

import numpy as np
import pytest
import torch

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu.fake import FakeTensor
from torchdistx_tpu.models.resnet_torch import resnet50

try:
    import jax  # noqa: F401

    from torchdistx_tpu.materialize import materialize_module_jax

    HAS_JAX = True
except ImportError:
    HAS_JAX = False

needs_jax = pytest.mark.skipif(not HAS_JAX, reason="jax unavailable")


@pytest.fixture(scope="module")
def fake_resnet():
    return di.deferred_init(resnet50)


def test_resnet_constructs_fake(fake_resnet):
    m = fake_resnet
    n_params = sum(p.numel() for p in m.parameters())
    assert 25e6 < n_params < 26e6  # ResNet-50 is ~25.6M params
    assert all(isinstance(p, FakeTensor) for p in m.parameters())
    # All float buffers are fake; num_batches_tracked stays real — the
    # int64 scalar literal is allocated by python before dispatch can see
    # it (tiny, and correct either way).
    for name, b in m.named_buffers():
        if "num_batches_tracked" in name:
            assert not isinstance(b, FakeTensor)
        else:
            assert isinstance(b, FakeTensor), name


@needs_jax
@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_resnet_jax_materialize_no_fallback(fake_resnet):
    # _fallback_torch=False: raises if ANY param would take the torch
    # replay+transfer fallback — the zero-fallback assertion of VERDICT #5.
    out = materialize_module_jax(fake_resnet, _fallback_torch=False)
    fakes = sum(1 for _ in fake_resnet.parameters()) + sum(
        1
        for n, b in fake_resnet.named_buffers()
        if "num_batches_tracked" not in n
    )
    assert len(out) == fakes
    w = np.asarray(out["conv1.weight"])
    assert w.shape == (64, 3, 7, 7)
    # kaiming_uniform(a=sqrt5) on fan_in=3*7*7: bound = sqrt(6/((1+5)*147))
    bound = (6.0 / (6 * 147)) ** 0.5
    assert np.abs(w).max() <= bound + 1e-6
    assert w.std() > 0.3 * bound
    np.testing.assert_allclose(np.asarray(out["bn1.weight"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["bn1.running_var"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["bn1.running_mean"]), 0.0)
    np.testing.assert_allclose(np.asarray(out["layer1.0.bn3.bias"]), 0.0)


def test_resnet_torch_materialize_and_forward():
    m = di.deferred_init(resnet50, num_classes=10)
    di.materialize_module(m)
    m.eval()
    with torch.no_grad():
        y = m(torch.randn(2, 3, 64, 64))
    assert y.shape == (2, 10)
    assert torch.isfinite(y).all()
