"""Trace reconstruction (ISSUE 9): request-scoped timelines end to end.

The acceptance bar: a preempted-then-resumed request and a request
failed over mid-stream each reconstruct into ONE contiguous timeline
under ``scripts/trace_report.py`` — the same ``rid`` on every hop, hop
numbers monotone, zero orphan spans — greedy AND sampled; and a forced
device fault produces a flight-recorder dump inside the trace.
"""

import os
import sys

import jax
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.fleet import FleetRouter
from torchdistx_tpu.models import llama
from torchdistx_tpu.serving import Engine

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)
from trace_report import RequestTimeline, reconstruct  # noqa: E402


@pytest.fixture(autouse=True)
def _collect():
    """Collect events + spans + the flight ring in memory per test."""
    prev = telemetry.configure(
        collect=True, jsonl=None, flight=True, max_spans=100_000
    )
    telemetry.reset()
    yield
    telemetry.configure(**prev)
    telemetry.reset()


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def report():
    return reconstruct(telemetry.snapshot()["spans"])


# ---------------------------------------------------------------------------
# Analyzer unit semantics (synthetic streams — no engine)


def _ev(name, ts, rid="r0", hop=0, engine="eng0", **attrs):
    rec = {
        "type": "event", "name": name, "ts": ts, "rid": rid, "hop": hop,
        "engine": engine,
    }
    if attrs:
        rec["attrs"] = attrs
    return rec


def test_phase_attribution_sums_to_total():
    recs = [
        _ev("req.submitted", 0.0),
        _ev("req.queued", 0.0),
        _ev("req.admitted", 2.0),
        _ev("req.prefill_chunk", 2.0),
        _ev("req.first_token", 3.0, ttft_s=3.0),
        _ev("req.swapped", 5.0),
        _ev("req.resumed", 6.5),
        _ev("req.finished", 9.0, n_tokens=12),
    ]
    rep = reconstruct(recs)
    tl = rep.requests["r0"]
    assert tl.complete and tl.outcome == "finished"
    ph = tl.phases()
    assert ph["queue"] == pytest.approx(2.0)
    assert ph["prefill"] == pytest.approx(1.0)
    assert ph["decode"] == pytest.approx(2.0 + 2.5)  # both decode segments
    assert ph["preempt"] == pytest.approx(1.5)
    assert ph["unaccounted"] == 0.0
    assert ph["total"] == pytest.approx(9.0)
    assert sum(ph[p] for p in
               ("queue", "prefill", "decode", "preempt", "failover",
                "unaccounted")) == pytest.approx(ph["total"])
    assert tl.n_tokens == 12 and tl.ttft_s == 3.0
    assert rep.problems() == []


def test_failover_gap_attributed_and_hops_checked():
    recs = [
        _ev("req.submitted", 0.0, engine="eng0"),
        _ev("req.first_token", 1.0, engine="eng0"),
        _ev("req.failed", 2.0, engine="eng0", error="RequestPreempted",
            retryable=True),
        _ev("req.failover_hop", 2.5, engine="eng1", hop=1),
        _ev("req.submitted", 2.5, engine="eng1", hop=1),
        _ev("req.admitted", 3.0, engine="eng1", hop=1),
        _ev("req.first_token", 3.5, engine="eng1", hop=1),
        _ev("req.finished", 4.0, engine="eng1", hop=1, n_tokens=8),
    ]
    rep = reconstruct(recs)
    tl = rep.requests["r0"]
    assert tl.outcome == "finished"
    assert tl.engines == ["eng0", "eng1"]
    assert tl.hops_monotone
    assert tl.phases()["failover"] == pytest.approx(0.5)
    assert rep.problems() == []
    # Hop order violations are flagged.
    bad = reconstruct(recs[:-1] + [
        _ev("req.finished", 4.0, engine="eng1", hop=0, n_tokens=8)
    ])
    assert any("monotone" in p for p in bad.problems())


def test_incomplete_and_orphans_flagged():
    rep = reconstruct([
        _ev("req.submitted", 0.0),
        _ev("req.admitted", 1.0),
        {"type": "span", "name": "serve.prefill", "ts": 1.0, "dur_s": 0.1,
         "rid": "ghost", "thread": 1, "depth": 0},
    ])
    probs = rep.problems()
    assert any("incomplete" in p for p in probs)
    assert any("orphan" in p for p in probs)
    assert rep.requests["r0"].outcome == "incomplete"


def test_ring_wrap_truncated_excluded_from_strict(tmp_path):
    """ISSUE 15 satellite: a request whose HEAD events were evicted by
    flight-ring wraparound is flagged `truncated` and excluded from
    --strict completeness accounting; a genuinely incomplete
    (submitted, never terminal) timeline still fails — and in a trace
    with NO dump window a headless timeline is still a leak."""
    from trace_report import load_records

    dump = str(tmp_path / "flight.jsonl")
    telemetry.configure(flight=dump, flight_capacity=6, collect=False)
    # An old request emits its head, then enough younger traffic wraps
    # the 6-record ring past it; only its tail survives the dump.
    telemetry.event("req.submitted", rid="old", engine="eng0", n_prompt=4)
    telemetry.event("req.admitted", rid="old", engine="eng0")
    for i in range(4):
        telemetry.event("req.submitted", rid=f"new{i}", engine="eng0")
    telemetry.event("req.first_token", rid="old", engine="eng0", ttft_s=0.1)
    telemetry.event("req.finished", rid="old", engine="eng0", n_tokens=8)
    assert telemetry.flight_dump("test_wrap") == 6
    records = load_records(dump)
    assert not any(
        r.get("name") == "req.submitted" and r.get("rid") == "old"
        for r in records
    ), "ring did not wrap past the head"
    rep = reconstruct(records)
    tl = rep.requests["old"]
    assert tl.truncated and not tl.complete
    assert tl.problems() == []  # excluded from strict accounting
    assert tl.summary()["truncated"] is True
    assert rep.summary()["truncated"] == 1
    assert not any("old" in p for p in rep.problems())
    # The wrapped-in new requests have heads but no terminals: those
    # are genuinely incomplete, not truncated — still flagged.
    assert not rep.requests["new0"].truncated
    assert any("incomplete" in p for p in rep.problems())
    # No dump window in the stream → a headless timeline is a genuine
    # trace-context leak, and strict still catches it.
    leak = reconstruct([
        _ev("req.first_token", 1.0, rid="leak"),
        _ev("req.finished", 2.0, rid="leak", n_tokens=4),
    ])
    assert leak.requests["leak"].truncated
    assert any("no req.submitted" in p for p in leak.problems())


# ---------------------------------------------------------------------------
# Engine integration: preempted-then-resumed is ONE contiguous timeline


def _single_timeline(rep, trace_id):
    """Common contiguity assertions; returns the timeline."""
    assert list(rep.requests), "no timelines reconstructed"
    tl = rep.requests[trace_id]
    assert tl.complete, [e["name"] for e in tl._sorted()]
    assert tl.hops_monotone, tl.hops
    assert not rep.orphan_spans, rep.orphan_spans
    return tl


@pytest.mark.parametrize("sampled", [False, True])
@pytest.mark.parametrize("mechanism", ["replay", "swap"])
def test_preempted_resumed_one_contiguous_timeline(family, sampled, mechanism):
    """A QoS preemption (drop-and-replay via slot pressure, or
    swap-to-host via page pressure) leaves ONE timeline: same rid
    throughout, preempted/swapped → resumed present, zero orphan spans,
    phases accounting for the outage."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    if mechanism == "replay":
        eng = Engine(
            params, model=model, cfg=cfg, scheduler="qos", num_slots=1,
            block_size=8, max_model_len=64, decode_chunk=4,
            handle_preemption=False, **sample_kw,
        )
        victim = eng.submit(prompt_of(6), max_new_tokens=24, key=700,
                            priority=0)
        eng.step()
        urgent = eng.submit(prompt_of(6, base=3), max_new_tokens=8,
                            key=701, priority=5)
    else:
        eng = Engine(
            params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
            block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
            handle_preemption=False, **sample_kw,
        )
        victim = eng.submit(prompt_of(8), max_new_tokens=26, key=800,
                            priority=0)
        eng.step()
        urgent = eng.submit(prompt_of(8, base=2), max_new_tokens=26,
                            key=801, priority=5)
    eng.drain()
    assert victim.error is None and urgent.error is None
    st = eng.stats()
    assert st[f"preemptions_{mechanism}"] >= 1

    rep = report()
    tl = _single_timeline(rep, victim._req.trace_id)
    names = [e["name"] for e in tl._sorted()]
    outage_mark = "req.swapped" if mechanism == "swap" else "req.preempted"
    assert outage_mark in names, names
    assert "req.resumed" in names, names
    assert tl.outcome == "finished"
    assert tl.engines == [eng.engine_id]
    ph = tl.phases()
    assert ph["preempt"] > 0
    assert ph["unaccounted"] == 0.0
    assert rep.problems() == []
    # The urgent request reconstructs cleanly too, untouched by the
    # victim's outage.
    assert rep.requests[urgent._req.trace_id].outcome == "finished"
    # The engine-side outage histogram saw the same preemption.
    assert telemetry.histogram(
        "serve.preempt_outage_s", engine=eng.engine_id
    ).count >= 1


# ---------------------------------------------------------------------------
# Fleet integration: mid-stream failover is ONE contiguous timeline


@pytest.mark.parametrize("sampled", [False, True])
def test_failover_one_contiguous_timeline(family, sampled):
    """A stream cut mid-flight by an engine close re-places on the peer
    under the SAME rid: hop numbers step 0 → 1 monotonically, both
    engines appear in order, the failover gap is attributed, and the
    timeline ends finished."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}

    def make_engine():
        return Engine(
            params, model=model, cfg=cfg, num_slots=2, block_size=8,
            max_model_len=64, decode_chunk=4, handle_preemption=False,
            **sample_kw,
        )

    eng_a, eng_b = make_engine(), make_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=3)
    h = router.submit(prompt_of(6), max_new_tokens=16, key=0)
    first = eng_a if h.replica_id == 0 else eng_b
    second = eng_b if h.replica_id == 0 else eng_a

    toks = []
    it = h.tokens()
    for _ in range(4):
        toks.append(next(it))
    first.close()  # mid-stream: the live request fails retryable
    router.poll()
    toks.extend(it)
    assert h.error is None and len(toks) == 16
    assert h.hops == 1

    rep = report()
    tl = _single_timeline(rep, h.trace_id)
    assert tl.outcome == "finished"
    assert tl.engines == [first.engine_id, second.engine_id]
    assert max(tl.hops) == 1
    names = [e["name"] for e in tl._sorted()]
    assert "req.failover_hop" in names
    # The engine-side retryable failure is inside the timeline, not its
    # end.
    assert "req.failed" in names and names[-1] == "req.finished"
    ph = tl.phases()
    assert ph["failover"] > 0
    assert ph["unaccounted"] == 0.0
    assert rep.problems() == []
    assert telemetry.histogram("fleet.failover_added_s").count >= 1


# ---------------------------------------------------------------------------
# Flight recorder fires on a forced device fault


def test_recovery_dumps_flight_recorder(family):
    """A consumed page pool (forced device fault) triggers the
    supervisor — which must dump the flight ring into the trace — and
    the replayed request still reconstructs complete."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=2, block_size=8,
        max_model_len=64, decode_chunk=4, handle_preemption=False,
    )
    h = eng.submit(prompt_of(6), max_new_tokens=12, key=0)
    it = h.tokens()
    next(it)
    for leaf in jax.tree.leaves(eng._cache):
        leaf.delete()
    toks = [h._tokens[0], *it]
    assert h.error is None and len(toks) == 12

    rep = report()
    assert rep.flight_dumps, "serve.recover did not dump the flight ring"
    assert rep.flight_dumps[0]["reason"] == "serve.recover"
    tl = _single_timeline(rep, h._req.trace_id)
    assert tl.outcome == "finished"
    names = [e["name"] for e in tl._sorted()]
    assert "req.preempted" in names and "req.resumed" in names
    assert rep.problems() == []
    assert eng.stats()["recoveries"] == 1


# ---------------------------------------------------------------------------
# Disabled-path overhead (ISSUE 9 acceptance, engine level)


def test_untraced_requests_mint_nothing(family):
    """With no sink and no flight ring, a served request mints no trace
    id (no string formatting), emits no events, and builds no records —
    while the always-on histograms still accumulate for stats()."""
    from torchdistx_tpu.telemetry import _core

    model, cfg, params = family
    telemetry.configure(collect=False, jsonl=None, flight=None)
    assert not telemetry.events_enabled()
    real_record = _core._state.record
    try:
        def bomb(rec):  # pragma: no cover — the point is it never runs
            raise AssertionError(f"record built while disabled: {rec}")

        _core._state.record = bomb
        eng = Engine(
            params, model=model, cfg=cfg, num_slots=2, block_size=8,
            max_model_len=64, decode_chunk=4, handle_preemption=False,
        )
        h = eng.submit(prompt_of(4), max_new_tokens=6, key=0)
        assert h.result() and h._req.trace_id is None
    finally:
        _core._state.record = real_record
    st = eng.stats()
    assert st["ttft_p50_s"] > 0  # histograms accumulate sink or no sink
