"""Durability plane: crash-consistent request journal + cold-restart resume.

The acceptance bar (ISSUE 20): the WAL is torn-tail tolerant (a
truncated final record is skipped, never misparsed — pinned at EVERY
byte offset); an fsync io failure degrades the journal to async with a
counter and never blocks the tick; two engines offered one journal
resolve to exactly one winner (the loser gets typed ``JournalOwned``,
a stale dead-pid lock is stolen silently); rotation compacts retired
requests away while preserving live streams byte-exactly; and a
``kill -9``'d engine's in-flight streams finish **token-identically**
(and digest-identically) in a restarted process, greedy and sampled —
the subprocess e2e at the bottom is the serving twin of
``test_crash_resume.py``.
"""

import json
import os
import struct
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import llama
from torchdistx_tpu.resilience import CRASH_EXIT_CODE, faults, preemption
from torchdistx_tpu.serving import (
    DeadlineExceeded,
    Engine,
    JournalOwned,
    ModelPool,
    RequestJournal,
)
from torchdistx_tpu.serving import journal as journal_mod
from torchdistx_tpu.serving.journal import (
    fold_records,
    read_records,
    read_segment,
)

CHILD = os.path.join(os.path.dirname(__file__), "_serving_crash_child.py")

ENGINE_KW = dict(
    num_slots=4, block_size=8, num_blocks=41, max_model_len=64,
    decode_chunk=4, max_prefills_per_tick=4, handle_preemption=False,
)


@pytest.fixture(autouse=True)
def _clean():
    preemption.clear()
    faults.reset("")
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def _prompts(cfg, n=3, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, cfg.vocab_size, size=6).astype(np.int32)
        for _ in range(n)
    ]


def _simulate_kill9(eng):
    """In-process stand-in for a hard kill: the engine forgets its
    journal without closing it (no final sync, no retirements), and the
    lock is dropped as a dead process's would effectively be (in-process
    the pid is alive, so a stale-steal can't stand in)."""
    j = eng._journal
    eng._journal = None
    j.release()
    eng.close()


# ---------------------------------------------------------------------------
# WAL mechanics (no engine, no model)


def test_wal_roundtrip_and_fold(tmp_path):
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="always")
    j.claim("eng-a")
    j.write_config(engine="eng-a", decode_chunk=4, model_version="v0")
    j.append({"t": "admit", "u": 1, "prompt": [1, 2], "key": [0, 7],
              "max_new": 8, "model": "default", "version": "v0"})
    j.append({"t": "commit", "u": 1, "toks": [5, 6], "n": 2, "d": "ab"})
    j.append({"t": "admit", "u": 2, "prompt": [3], "key": [0, 1],
              "max_new": 4})
    j.append({"t": "retire", "u": 2, "outcome": "cancelled"})
    assert j.stats()["live"] == 1
    j.close()

    entries, config = fold_records(read_records(d))
    assert config["engine"] == "eng-a"
    e1 = entries[1]
    assert e1.prompt == [1, 2] and e1.key == [0, 7]
    assert e1.tokens == [5, 6] and e1.digest == "ab"
    assert not e1.retired and e1.n_gen == 2
    assert entries[2].retired and entries[2].outcome == "cancelled"

    # A re-claim appends a fresh config record; the LAST one governs
    # (the newest engine's geometry), and uids keep ascending.
    j2 = RequestJournal(d)
    unfinished, _ = j2.recover()
    assert set(unfinished) == {1}
    assert j2.next_uid() == 3
    j2.claim("eng-b")
    j2.write_config(engine="eng-b", decode_chunk=4)
    j2.close()
    _, config = fold_records(read_records(d))
    assert config["engine"] == "eng-b"
    assert RequestJournal(d).peek_config()["engine"] == "eng-b"


def test_torn_tail_at_every_byte_offset(tmp_path):
    """Truncating the segment at ANY byte offset parses cleanly to the
    intact prefix — short header, short payload, and mid-record cuts
    are all 'torn tail', never a misparse, never an exception."""
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="always")
    j.claim("eng")
    recs = [
        {"t": "admit", "u": i, "prompt": [i] * 4, "key": [0, i],
         "max_new": 8}
        for i in range(1, 5)
    ]
    for r in recs:
        j.append(r)
    j.close()
    seg = journal_mod._segments(d)[0]
    with open(seg, "rb") as f:
        data = f.read()
    # Frame boundaries from the on-disk layout itself.
    bounds, off = [0], 0
    while off < len(data):
        (length,) = struct.unpack_from("<I", data, off)
        off += 8 + length
        bounds.append(off)
    assert bounds[-1] == len(data) and len(bounds) == len(recs) + 1

    scratch = str(tmp_path / "trunc.wal")
    for cut in range(len(data) + 1):
        with open(scratch, "wb") as f:
            f.write(data[:cut])
        got, torn = read_segment(scratch)
        n_intact = sum(1 for b in bounds[1:] if b <= cut)
        assert [r["u"] for r in got] == [r["u"] for r in recs[:n_intact]]
        assert torn == (cut not in bounds)


def test_corrupt_byte_stops_reader_cleanly(tmp_path):
    """A flipped byte mid-record fails the CRC: the reader returns the
    intact prefix and flags the segment — it never yields garbage."""
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="always")
    j.claim("eng")
    for i in range(1, 4):
        j.append({"t": "admit", "u": i, "prompt": [i], "key": [0, i],
                  "max_new": 8})
    j.close()
    seg = journal_mod._segments(d)[0]
    with open(seg, "rb") as f:
        data = bytearray(f.read())
    # Flip a payload byte inside the SECOND record.
    (len0,) = struct.unpack_from("<I", data, 0)
    data[8 + len0 + 8 + 2] ^= 0xFF
    with open(seg, "wb") as f:
        f.write(bytes(data))
    got, torn = read_segment(seg)
    assert [r["u"] for r in got] == [1]
    assert torn


def test_fsync_io_fault_degrades_to_async(tmp_path):
    """TDX_FAULT journal.fsync:N:io — the group commit degrades the
    journal to async with a counter; appends keep landing and nothing
    raises into the tick."""
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="tick")
    j.claim("eng")
    degraded = telemetry.counter("journal.fsync_degraded")
    before = degraded.value
    j.append({"t": "admit", "u": 1, "prompt": [1], "key": [0, 0],
              "max_new": 2})
    faults.reset("journal.fsync:1:io")
    j.sync()
    assert j.degraded
    assert degraded.value == before + 1
    assert j.stats()["degraded"]
    # Still appending, still readable, no further fsync attempts.
    j.append({"t": "commit", "u": 1, "toks": [9], "n": 1, "d": "cc"})
    j.sync()
    j.close()
    entries, _ = fold_records(read_records(d))
    assert entries[1].tokens == [9]


def test_double_claim_typed_refusal_and_stale_steal(tmp_path):
    d = str(tmp_path / "j")
    j1 = RequestJournal(d)
    j1.claim("eng-a")
    with pytest.raises(JournalOwned):
        RequestJournal(d).claim("eng-b")
    j1.close()  # releases the lock
    j2 = RequestJournal(d)
    j2.claim("eng-b")
    j2.close()
    # A dead pid's lock is stale: stolen silently, never JournalOwned.
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    with open(os.path.join(d, journal_mod._LOCK_NAME), "w") as f:
        json.dump({"owner": "ghost", "pid": p.pid}, f)
    j3 = RequestJournal(d)
    j3.claim("eng-c")
    j3.close()


def test_rotation_compacts_retired_keeps_live(tmp_path):
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="async", rotate_bytes=4096)
    j.claim("eng")
    j.write_config(engine="eng", decode_chunk=4)
    j.append({"t": "admit", "u": 1, "prompt": [1, 2, 3], "key": [0, 1],
              "max_new": 64})
    j.append({"t": "commit", "u": 1, "toks": [7, 8], "n": 2, "d": "aa"})
    u = 2
    while j.stats()["segments_compacted"] == 0:
        j.append({"t": "admit", "u": u, "prompt": [0] * 30, "key": [0, u],
                  "max_new": 8})
        j.append({"t": "retire", "u": u, "outcome": "finished", "n": 0})
        u += 1
        assert u < 500, "rotation never triggered"
    j.close()
    # One active segment on disk, the config carried over, the live
    # stream checkpointed byte-exactly, the retired churn gone.
    assert len(journal_mod._segments(d)) == 1
    entries, config = fold_records(read_records(d))
    assert config is not None and config["engine"] == "eng"
    live = {uu for uu, e in entries.items() if not e.retired}
    assert live == {1}
    assert entries[1].tokens == [7, 8] and entries[1].digest == "aa"
    assert len(entries) <= 2  # live + at most the post-rotation straggler


# ---------------------------------------------------------------------------
# Engine integration: in-process resume


def test_resume_in_process_token_identical(tmp_path, cfg, params):
    """Crash-sim partway through decode; a fresh engine resumes every
    stream from the journal and finishes token-identically (the
    fold_in(key, n_gen) schedule continues where the commit left off)."""
    ps = _prompts(cfg)
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    ref = [
        eng.submit(p, max_new_tokens=24, key=i).result()
        for i, p in enumerate(ps)
    ]
    eng.close()

    d = str(tmp_path / "j")
    eng1 = Engine(params, model=llama, cfg=cfg,
                  journal=RequestJournal(d), **ENGINE_KW)
    hs = [eng1.submit(p, max_new_tokens=24, key=i)
          for i, p in enumerate(ps)]
    for _ in range(3):
        eng1.step()
    assert all(0 < len(h._tokens) < 24 for h in hs), "crash-sim too late"
    _simulate_kill9(eng1)

    resumed = telemetry.counter("journal.resumed")
    before = resumed.value
    eng2 = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    handles = eng2.resume_from_journal(RequestJournal(d))
    assert sorted(handles) == [1, 2, 3]
    got = [handles[u].result() for u in sorted(handles)]
    assert eng2.stats()["journal"]["live"] == 0
    eng2.close()
    assert got == ref
    assert resumed.value == before + 3


def test_geometry_mismatch_refused_before_claim(tmp_path, cfg, params):
    """A journal recorded at one sampling geometry refuses an engine at
    another (ValueError, lock untouched) — so a fleet recover() can
    skip to a compatible replica."""
    d = str(tmp_path / "j")
    eng1 = Engine(params, model=llama, cfg=cfg,
                  journal=RequestJournal(d), **ENGINE_KW)
    h = eng1.submit(_prompts(cfg)[0], max_new_tokens=24, key=0)
    for _ in range(3):
        eng1.step()
    _simulate_kill9(eng1)

    kw = dict(ENGINE_KW, decode_chunk=8)  # different geometry
    eng2 = Engine(params, model=llama, cfg=cfg, **kw)
    with pytest.raises(ValueError, match="journal"):
        eng2.resume_from_journal(RequestJournal(d))
    eng2.close()
    # The refusal did NOT consume the lock: a matching engine resumes.
    eng3 = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    handles = eng3.resume_from_journal(RequestJournal(d))
    assert sorted(handles) == [1]
    handles[1].result()
    eng3.close()


def test_double_resume_exactly_one_winner(tmp_path, cfg, params):
    """Two live engines offered one journal: the first resume claims
    ownership; the second gets typed JournalOwned, resumes nothing."""
    d = str(tmp_path / "j")
    eng1 = Engine(params, model=llama, cfg=cfg,
                  journal=RequestJournal(d), **ENGINE_KW)
    eng1.submit(_prompts(cfg)[0], max_new_tokens=24, key=0)
    for _ in range(3):
        eng1.step()
    _simulate_kill9(eng1)

    winner = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    handles = winner.resume_from_journal(RequestJournal(d))
    assert sorted(handles) == [1]
    loser = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    with pytest.raises(JournalOwned):
        loser.resume_from_journal(RequestJournal(d))
    loser.close()
    handles[1].result()
    winner.close()


def test_resume_expired_deadline_fails_typed(tmp_path, cfg, params):
    """A journaled stream whose wall-clock deadline passed during the
    outage fails typed DeadlineExceeded at resume — never silently
    generated past its SLO."""
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync="always")
    j.claim("dead-engine")
    j.append({
        "t": "admit", "u": 1,
        "prompt": [int(x) for x in _prompts(cfg)[0]],
        "key": [0, 0], "max_new": 8,
        "deadline": time.time() - 5.0,
    })
    j.close()
    expired = telemetry.counter("journal.resume_expired")
    before = expired.value
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    handles = eng.resume_from_journal(RequestJournal(d))
    with pytest.raises(DeadlineExceeded):
        handles[1].result()
    eng.close()
    assert expired.value == before + 1


def test_resume_rematerializes_evicted_model(tmp_path, cfg, params):
    """Resume of a stream whose model is cold in the restarted pool:
    the model plane re-materializes on demand and the stream still
    finishes token-identically."""
    def seeded():
        return llama.init_params(jax.random.PRNGKey(1), cfg)

    p = _prompts(cfg)[0]
    ref_pool = ModelPool()
    ref_pool.register("tuna", model=llama, cfg=cfg, materialize=seeded)
    eng = Engine(params, model=llama, cfg=cfg, model_pool=ref_pool,
                 **ENGINE_KW)
    ref = eng.submit(p, max_new_tokens=24, key=0, model="tuna").result()
    eng.close()

    d = str(tmp_path / "j")
    pool1 = ModelPool()
    pool1.register("tuna", model=llama, cfg=cfg, materialize=seeded)
    eng1 = Engine(params, model=llama, cfg=cfg, model_pool=pool1,
                  journal=RequestJournal(d), **ENGINE_KW)
    h = eng1.submit(p, max_new_tokens=24, key=0, model="tuna")
    for _ in range(4):
        eng1.step()
    assert 0 < len(h._tokens) < 24, "crash-sim too late"
    _simulate_kill9(eng1)

    pool2 = ModelPool()  # fresh process: tuna registered but COLD
    pool2.register("tuna", model=llama, cfg=cfg, materialize=seeded)
    eng2 = Engine(params, model=llama, cfg=cfg, model_pool=pool2,
                  **ENGINE_KW)
    handles = eng2.resume_from_journal(RequestJournal(d))
    got = handles[1].result()
    eng2.close()
    assert got == ref
    assert pool2.stats()["models"]["tuna"]["materializations"] == 1


# ---------------------------------------------------------------------------
# The kill -9 e2e (subprocesses — the serving twin of test_crash_resume)


def _run_child(mode, jdir, temperature, *, fault=None):
    env = dict(os.environ)
    env.pop("TDX_FAULT", None)
    env["JAX_PLATFORMS"] = "cpu"
    if fault:
        env["TDX_FAULT"] = fault
    return subprocess.run(
        [sys.executable, CHILD, mode, str(jdir), str(temperature)],
        env=env, capture_output=True, text=True, timeout=600,
    )


def _result(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(
        f"no RESULT line\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_kill9_crash_resume_token_identical(tmp_path, temperature):
    """Hard SIGKILL-equivalent (os._exit mid-decode, journal unclosed,
    stale lock left) → a fresh process resumes from the WAL and every
    stream finishes with the exact tokens AND digest of an
    uninterrupted run."""
    jdir = str(tmp_path / "journal")
    ref = _result(_run_child("ref", jdir, temperature))

    proc = _run_child("crash", jdir, temperature,
                      fault="serve.step:4:crash")
    assert proc.returncode == CRASH_EXIT_CODE, proc.stderr[-2000:]

    proc = _run_child("resume", jdir, temperature)
    assert proc.returncode == 0, proc.stderr[-2000:]
    res = _result(proc)

    # Zero silently-lost requests: every admitted stream is accounted
    # for — resumed now, or journaled as finished before the crash.
    all_uids = set(ref["tokens"])
    assert set(res["resumed"]) | set(res["finished"]) >= all_uids
    assert res["resumed"], "crash landed after every stream finished"
    for u, toks in ref["tokens"].items():
        if u in res["resumed"]:
            assert res["resumed"][u] == toks, f"uid {u} diverged"
            assert res["digests"][u] == ref["digests"][u]
        else:
            assert res["finished"][u] == toks, f"uid {u} (pre-crash) lost"
