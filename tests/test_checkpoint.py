"""Checkpoint/resume: orbax round-trips of sharded TrainState."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistx_tpu.models import llama
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.utils.checkpoint import Checkpointer, restore_state, save_state


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


def test_save_restore_roundtrip(cfg, tmp_path):
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.adamw(1e-3))
    state = init_fn(jax.random.PRNGKey(0))
    batch_sh = ts.batch_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        batch_sh,
    )
    state, _ = step_fn(state, {"tokens": tokens, "targets": tokens})

    path = tmp_path / "state"
    save_state(path, state)
    shardings = jax.tree.map(lambda l: l.sharding, state)
    restored = restore_state(path, target=state, shardings=shardings)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        assert np.allclose(np.asarray(a), np.asarray(b))
    # Restored arrays carry the mesh shardings (no host round-trip).
    wq = restored.params["layers"]["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")


def test_manager_resume_continues_training(cfg, tmp_path):
    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    state = init_fn(jax.random.PRNGKey(0))
    batch_sh = ts.batch_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        batch_sh,
    )
    batch = {"tokens": tokens, "targets": tokens}

    ckpt = Checkpointer(tmp_path / "run", max_to_keep=2)
    state, _ = step_fn(state, batch)
    ckpt.save(1, state)
    state, m2 = step_fn(state, batch)
    ckpt.save(2, state)
    assert ckpt.latest_step() == 2

    shardings = jax.tree.map(lambda l: l.sharding, state)
    step, restored = Checkpointer(tmp_path / "run").restore_latest(
        target=state, shardings=shardings
    )
    assert step == 2
    restored = ts.TrainState(*restored) if not isinstance(
        restored, ts.TrainState
    ) else restored
    # Training continues from the restored state.
    restored, m3 = step_fn(restored, batch)
    assert int(jnp.asarray(m3["step"])) == 3
    assert np.isfinite(float(m3["loss"]))
