"""Checkpoint/resume: orbax round-trips of sharded TrainState."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistx_tpu.models import llama
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.utils.checkpoint import Checkpointer, restore_state, save_state


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


def test_save_restore_roundtrip(cfg, tmp_path):
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.adamw(1e-3))
    state = init_fn(jax.random.PRNGKey(0))
    batch_sh = ts.batch_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        batch_sh,
    )
    state, _ = step_fn(state, {"tokens": tokens, "targets": tokens})

    path = tmp_path / "state"
    save_state(path, state)
    shardings = jax.tree.map(lambda l: l.sharding, state)
    restored = restore_state(path, target=state, shardings=shardings)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        assert np.allclose(np.asarray(a), np.asarray(b))
    # Restored arrays carry the mesh shardings (no host round-trip).
    wq = restored.params["layers"]["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")


def test_manager_resume_continues_training(cfg, tmp_path):
    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    state = init_fn(jax.random.PRNGKey(0))
    batch_sh = ts.batch_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        batch_sh,
    )
    batch = {"tokens": tokens, "targets": tokens}

    ckpt = Checkpointer(tmp_path / "run", max_to_keep=2)
    state, _ = step_fn(state, batch)
    ckpt.save(1, state)
    state, m2 = step_fn(state, batch)
    ckpt.save(2, state)
    assert ckpt.latest_step() == 2

    shardings = jax.tree.map(lambda l: l.sharding, state)
    step, restored = Checkpointer(tmp_path / "run").restore_latest(
        target=state, shardings=shardings
    )
    assert step == 2
    restored = ts.TrainState(*restored) if not isinstance(
        restored, ts.TrainState
    ) else restored
    # Training continues from the restored state.
    restored, m3 = step_fn(restored, batch)
    assert int(jnp.asarray(m3["step"])) == 3
    assert np.isfinite(float(m3["loss"]))


def test_restore_onto_different_mesh(cfg, tmp_path):
    """Elastic re-shard: save on an fsdp=2×tp=4 mesh, restore onto
    fsdp=4×tp=2 — shards land on the new mesh directly and training
    continues with identical values."""
    mesh_a = make_mesh(MeshSpec(fsdp=2, tp=4))
    init_fn, step_fn = ts.make_train_step(cfg, mesh_a, optax.sgd(0.1))
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size),
        ts.batch_sharding(mesh_a),
    )
    state, _ = step_fn(state, {"tokens": tokens, "targets": tokens})
    save_state(tmp_path / "ck", state)

    mesh_b = make_mesh(MeshSpec(fsdp=4, tp=2))
    init_b, step_b = ts.make_train_step(cfg, mesh_b, optax.sgd(0.1))
    # eval_shape leaves carry mesh B's shardings (init_b is jitted with
    # out_shardings): the restore target is fully abstract — nothing is
    # materialized on mesh B before the shards arrive.
    ref_b = jax.eval_shape(init_b, jax.random.PRNGKey(0))
    restored = restore_state(
        tmp_path / "ck",
        target=ref_b,
        shardings=jax.tree.map(lambda l: l.sharding, ref_b),
    )
    # Values identical, placement on the new mesh.
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert restored.params["layers"]["wq"].sharding.mesh.shape == {
        "fsdp": 4, "tp": 2,
    }
    tokens_b = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size),
        ts.batch_sharding(mesh_b),
    )
    _, m = step_b(restored, {"tokens": tokens_b, "targets": tokens_b})
    assert jnp.isfinite(m["loss"])


def test_fit_resumes_after_interruption(cfg, tmp_path):
    """fit(): run 3 steps with checkpoint_every=2, 'preempt', rerun — the
    loop resumes from step 2, replays the data stream, and finishes with
    the same final state a straight 5-step run produces."""
    from torchdistx_tpu.parallel.fit import fit

    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    bs = ts.batch_sharding(mesh)

    def batches():
        key = jax.random.PRNGKey(42)
        while True:
            key, sub = jax.random.split(key)
            t = jax.device_put(
                jax.random.randint(sub, (8, 16), 0, cfg.vocab_size), bs
            )
            yield {"tokens": t, "targets": t}

    # Straight run, no checkpoints: the reference trajectory.
    ref_state, _ = fit(
        init_fn, step_fn, batches(), key=jax.random.PRNGKey(0), n_steps=5
    )

    # Interrupted run: 3 steps (checkpoint lands at step 2 and 3)...
    fit(
        init_fn, step_fn, batches(), key=jax.random.PRNGKey(0), n_steps=3,
        checkpoint_dir=str(tmp_path / "run"), checkpoint_every=2,
    )
    # ...then resume to 5.
    state, metrics = fit(
        init_fn, step_fn, batches(), key=jax.random.PRNGKey(0), n_steps=5,
        checkpoint_dir=str(tmp_path / "run"), checkpoint_every=2,
    )
    assert int(state.step) == 5
    for a, b in zip(
        jax.tree.leaves(ref_state.params), jax.tree.leaves(state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        )


def test_async_save_round_trip(cfg, tmp_path):
    """wait=False dispatches the save in the background; after
    wait_until_finished the checkpoint restores identically even though
    the source state was mutated right after dispatch."""
    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    state = init_fn(jax.random.PRNGKey(0))
    snapshot = jax.tree.map(np.asarray, state.params)

    ck = Checkpointer(str(tmp_path / "async"))
    ck.save(1, state, wait=False)
    # Mutate (donate) the live state immediately — step_fn donates its
    # input buffers, the hazard async snapshots must be immune to.
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    state, _ = step_fn(state, {"tokens": tokens, "targets": tokens})
    ck.wait_until_finished()

    step, restored = ck.restore_latest(
        target=jax.eval_shape(init_fn, jax.random.PRNGKey(0)),
    )
    assert step == 1
    for a, b in zip(jax.tree.leaves(snapshot), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
