"""Sharded training steps on the virtual 8-device mesh (SURVEY.md §4 rig).

Covers dp/fsdp/tp composition, sequence-parallel (ring) training, and the
SlowMo stacked-replica step with its closed-form oracle — the analog of the
reference's analytic momentum recomputation (test_slowmo_fsdp.py:243-253).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistx_tpu.models import llama
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.parallel.slowmo import SlowMomentumOptimizer


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


def _batch(cfg, sharding, shape=(8, 32), seed=1):
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(seed), shape, 0, cfg.vocab_size),
        sharding,
    )
    return {"tokens": tokens, "targets": tokens}


class TestTrainStep:
    def test_3d_mesh_loss_decreases(self, cfg):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        init_fn, step_fn = ts.make_train_step(
            cfg, mesh, optax.adamw(1e-2), nonfinite_guard=False
        )
        state = init_fn(jax.random.PRNGKey(0))
        batch = _batch(cfg, ts.batch_sharding(mesh))
        losses = []
        for _ in range(4):
            state, m = step_fn(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(jnp.asarray(state.step)) == 4

    def test_sharding_invariance(self, cfg):
        # Same seed, different mesh layouts → numerically close results.
        results = []
        for spec in (MeshSpec(dp=8), MeshSpec(fsdp=4, tp=2)):
            mesh = make_mesh(spec)
            init_fn, step_fn = ts.make_train_step(
                cfg, mesh, optax.sgd(0.1), nonfinite_guard=False
            )
            state = init_fn(jax.random.PRNGKey(0))
            batch = _batch(cfg, ts.batch_sharding(mesh))
            state, m = step_fn(state, batch)
            results.append(float(m["loss"]))
        assert abs(results[0] - results[1]) < 1e-3

    # tier-1 re-budget (ISSUE 9): heavy, and reproduces the known
    # jaxlib SPMD breakage at HEAD (ROADMAP item 1).
    @pytest.mark.slow
    @pytest.mark.parametrize("ring_impl", ["ring", "ring_zigzag"])
    def test_sequence_parallel_matches_single(self, cfg, ring_impl):
        tokens_shape = (8, 64)
        mesh_sp = make_mesh(MeshSpec(fsdp=2, sp=4))
        init_fn, step_fn = ts.make_train_step(
            cfg, mesh_sp, optax.sgd(0.1), seq_axis="sp", attn_impl=ring_impl,
            nonfinite_guard=False,
        )
        state = init_fn(jax.random.PRNGKey(0))
        batch = _batch(cfg, ts.batch_sharding(mesh_sp), tokens_shape)
        state, m_sp = step_fn(state, batch)

        mesh_1 = make_mesh(MeshSpec(dp=8))
        init_fn, step_fn = ts.make_train_step(
            cfg, mesh_1, optax.sgd(0.1), attn_impl="jnp", nonfinite_guard=False
        )
        state = init_fn(jax.random.PRNGKey(0))
        batch = _batch(cfg, ts.batch_sharding(mesh_1), tokens_shape)
        state, m_1 = step_fn(state, batch)
        assert abs(float(m_sp["loss"]) - float(m_1["loss"])) < 1e-3


class TestOptStatePlacement:
    def test_moments_follow_param_shardings_by_path(self, cfg):
        """wq and wo share a shape but have transposed shardings; the Adam
        moments must follow each param's own sharding (path match, not
        shape match)."""
        mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
        init_fn, _ = ts.make_train_step(cfg, mesh, optax.adamw(1e-3))
        state = init_fn(jax.random.PRNGKey(0))
        P = jax.sharding.PartitionSpec
        adam = state.opt_state[0]  # ScaleByAdamState
        assert adam.mu["layers"]["wq"].sharding.spec == P(None, "fsdp", "tp")
        assert adam.mu["layers"]["wo"].sharding.spec == P(None, "tp", "fsdp")
        assert adam.nu["layers"]["wo"].sharding.spec == P(None, "tp", "fsdp")


class TestSlowMoTrainStep:
    def test_replicas_sync_on_averaging_step(self, cfg):
        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        opt = SlowMomentumOptimizer(
            optax.sgd(0.1), base_lr=0.1, slowmo_freq=2
        )
        init_fn, step_fn = ts.make_slowmo_train_step(cfg, mesh, opt)
        state = init_fn(jax.random.PRNGKey(0))
        bs = ts.slowmo_batch_sharding(mesh)
        batch = _batch(cfg, bs, (2, 4, 32))

        state, _ = step_fn(state, batch)  # step 1: replicas diverge
        wq = np.asarray(state.params["layers"]["wq"])
        # Same data per replica here? No — batch[0] != batch[1] slices, and
        # even with equal data SGD would match; use distinct slices:
        state, _ = step_fn(state, batch)  # step 2: averaging step
        wq = np.asarray(state.params["layers"]["wq"])
        assert np.array_equal(wq[0], wq[1])  # exact sync after averaging

    def test_replicas_diverge_between_averaging(self, cfg):
        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        opt = SlowMomentumOptimizer(
            optax.sgd(0.1), base_lr=0.1, slowmo_freq=100
        )
        init_fn, step_fn = ts.make_slowmo_train_step(cfg, mesh, opt)
        state = init_fn(jax.random.PRNGKey(0))
        bs = ts.slowmo_batch_sharding(mesh)
        # distinct per-replica data
        t = jax.random.randint(
            jax.random.PRNGKey(5), (2, 4, 32), 0, cfg.vocab_size
        )
        batch = {"tokens": jax.device_put(t, bs), "targets": jax.device_put(t, bs)}
        state, _ = step_fn(state, batch)
        wq = np.asarray(state.params["layers"]["wq"])
        assert not np.array_equal(wq[0], wq[1])

    def test_slowmo_math_oracle(self, cfg):
        """Recompute the slow-momentum update analytically (the reference's
        closed-form oracle, test_slowmo_fsdp.py:243-253)."""
        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        base_lr, factor, slr = 0.1, 0.5, 1.0
        opt = SlowMomentumOptimizer(
            optax.sgd(base_lr), base_lr=base_lr, slowmo_freq=1,
            slowmo_factor=factor, slowmo_lr=slr,
        )
        init_fn, step_fn = ts.make_slowmo_train_step(cfg, mesh, opt)
        state = init_fn(jax.random.PRNGKey(0))
        prev0 = np.asarray(state.opt_state.prev["layers"]["wq"])
        bs = ts.slowmo_batch_sharding(mesh)
        batch = _batch(cfg, bs, (2, 4, 32))
        state, _ = step_fn(state, batch)
        # freq=1 → averaging every step.  m1 = factor*0 + (prev - avg)/lr;
        # prev1 = prev - slr*lr*m1; params = prev1 (broadcast).
        wq = np.asarray(state.params["layers"]["wq"])
        prev1 = np.asarray(state.opt_state.prev["layers"]["wq"])
        m1 = np.asarray(state.opt_state.momentum["layers"]["wq"])
        # params equal prev after averaging step
        assert np.allclose(wq[0], prev1, atol=1e-6)
        assert np.allclose(wq[1], prev1, atol=1e-6)
        # prev update identity
        assert np.allclose(prev1, prev0 - slr * base_lr * m1, atol=1e-6)

    def test_state_checkpoint_roundtrip(self, cfg, tmp_path):
        """SlowMo state round-trips through orbax (the reference round-trips
        through torch.save, test_slowmo_fsdp.py:283-300)."""
        import orbax.checkpoint as ocp

        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        opt = SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1, slowmo_freq=2)
        init_fn, step_fn = ts.make_slowmo_train_step(cfg, mesh, opt)
        state = init_fn(jax.random.PRNGKey(0))
        batch = _batch(cfg, ts.slowmo_batch_sharding(mesh), (2, 4, 32))
        state, _ = step_fn(state, batch)

        path = tmp_path / "ckpt"
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, jax.tree.map(np.asarray, state))
        ckptr.wait_until_finished()
        target = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), state
        )
        restored = ckptr.restore(path, target)
        assert jax.tree.structure(restored) == jax.tree.structure(state)
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            assert np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_zigzag_layout_matches_contiguous(cfg):
    """Whole-model zigzag layout: same loss as the contiguous layout (the
    permutation is a relabeling — RoPE uses original positions, targets
    align), with NO per-layer sequence resharding."""
    from torchdistx_tpu.models import llama

    tokens_shape = (8, 64)
    mesh = make_mesh(MeshSpec(fsdp=2, sp=4))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.sgd(0.1), seq_axis="sp", attn_impl="ring_zigzag",
        seq_layout="zigzag", nonfinite_guard=False,
    )
    state = init_fn(jax.random.PRNGKey(0))
    batch = _batch(cfg, ts.batch_sharding(mesh), tokens_shape)
    state, m_z = step_fn(state, batch)

    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.sgd(0.1), seq_axis="sp", attn_impl="ring",
        nonfinite_guard=False,
    )
    state = init_fn(jax.random.PRNGKey(0))
    state, m_c = step_fn(state, batch)
    assert abs(float(m_z["loss"]) - float(m_c["loss"])) < 1e-3


def test_zigzag_with_custom_loss_fn_rejected(cfg):
    """seq_layout cannot be applied to a user loss_fn — must raise, not
    silently train contiguous (ADVICE r2)."""
    mesh = make_mesh(MeshSpec(sp=8))
    with pytest.raises(ValueError, match="custom"):
        ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), seq_axis="sp",
            seq_layout="zigzag", loss_fn=lambda p, t, y: jnp.float32(0),
        )


def test_zigzag_layout_rejects_incompatible_attn_impl(cfg):
    """Explicit attn_impl='jnp' under seq_layout='zigzag' would attend in
    permuted order — must raise, not silently override (ADVICE r2)."""
    mesh = make_mesh(MeshSpec(sp=8))
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size
    )
    with pytest.raises(ValueError, match="incompatible"):
        llama.forward(
            params, tokens, cfg, mesh=mesh, seq_axis="sp",
            seq_layout="zigzag", attn_impl="jnp",
        )
