"""Model plane: many models on one page pool + CoW parallel sampling.

The acceptance bar (ISSUE 18): deferred-init skeletons cost ~zero HBM
until demand; materialize-on-demand streams are token-identical to an
engine built with those weights directly; ledger-driven eviction drops
only idle models' weights and never perturbs a live stream;
``submit(n=4)`` forks share prompt pages copy-on-write (page accounting
far below 4x solo) with each sibling token-identical to a solo submit
under its ``fold_in(base, i)`` key; prefix pages and determinism
digests never cross a model boundary.
"""

import jax
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import gpt2, llama
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import DEFAULT_MODEL, Engine, ModelPool

EOS = 5

# One decode-chunk compile per sampling config for the whole module
# (matches the test_serving menu).  prefix_cache stays ON: the model
# plane namespaces the index, and the leak idiom below accounts for
# cached pages explicitly.
ENGINE_KW = dict(
    num_slots=4, block_size=8, num_blocks=41, max_model_len=64,
    decode_chunk=4, eos_id=EOS,
)


@pytest.fixture(autouse=True)
def _clean():
    preemption.clear()
    faults.reset("")
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def cfg():
    return llama.llama_test()


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def seeded(cfg, seed):
    """A materializer for "model <seed>": same llama family/cfg,
    different weights — the realistic fine-tune-pool shape (identical
    KV geometry, so every model shares the engine's compiled programs).
    """
    return lambda: llama.init_params(jax.random.PRNGKey(seed), cfg)


def prompt(n=8, start=10):
    return np.arange(start, start + n, dtype=np.int32)


def assert_settled(eng):
    """Zero leaked pages: everything still refcounted is prefix cache."""
    cached = len(eng.prefix) if eng.prefix is not None else 0
    assert eng.allocator.num_in_use == cached


# ---------------------------------------------------------------------------
# Skeleton registry


def test_skeleton_registry_is_deferred(cfg, params):
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    pool.register("bass", model=llama, cfg=cfg, materialize=seeded(cfg, 2))
    # Nothing materialized: no weights live, yet the geometry is
    # already inspectable (eval_shape over the skeleton — the
    # torchdistx deferred-init contract).
    assert set(pool.tags()) == {"tuna", "bass"}
    assert not pool.ready("tuna") and not pool.ready("bass")
    assert pool.resident() == []
    g1, g2 = pool.geometry("tuna"), pool.geometry("bass")
    assert not g1["materialized"] and g1["n_params"] > 0
    assert (g1["n_leaves"], g1["n_params"], g1["nbytes"]) == (
        g2["n_leaves"], g2["n_params"], g2["nbytes"]
    )
    st = pool.stats()
    assert st["n_registered"] == 2 and st["n_resident"] == 0


def test_register_rejects_reserved_and_duplicate(cfg):
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    with pytest.raises(ValueError):
        pool.register(DEFAULT_MODEL, model=llama, cfg=cfg,
                      materialize=seeded(cfg, 1))
    with pytest.raises(ValueError):
        pool.register("", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    with pytest.raises(ValueError):
        pool.register("tuna", model=llama, cfg=cfg,
                      materialize=seeded(cfg, 3))


def test_geometry_mismatch_rejected(cfg, params):
    """A skeleton whose paged-KV geometry differs from the engine's
    pool can never serve from it — rejected at bind (constructor) and
    at register-after-bind, not at first dispatch."""
    gcfg = gpt2.gpt2_test()
    bad = ModelPool()
    bad.register("wrong", model=gpt2, cfg=gcfg,
                 materialize=lambda: gpt2.init_params(
                     jax.random.PRNGKey(1), gcfg))
    with pytest.raises(ValueError, match="geometry"):
        Engine(params, model=llama, cfg=cfg, model_pool=bad, **ENGINE_KW)

    pool = ModelPool()
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        with pytest.raises(ValueError, match="geometry"):
            pool.register("wrong", model=gpt2, cfg=gcfg,
                          materialize=lambda: gpt2.init_params(
                              jax.random.PRNGKey(1), gcfg))
        assert "wrong" not in pool
    finally:
        eng.close()


def test_pool_binds_one_engine(cfg, params):
    pool = ModelPool()
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        with pytest.raises(ValueError, match="already bound"):
            Engine(params, model=llama, cfg=cfg, model_pool=pool,
                   **ENGINE_KW)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Materialize-on-demand


def test_materialize_on_demand_token_identity(cfg, params):
    """First ``submit(model=...)`` demand materializes; the stream is
    token-identical to an engine BUILT with those weights; a second
    demand reuses the resident weights (one materialization total)."""
    p1 = llama.init_params(jax.random.PRNGKey(1), cfg)
    ref_eng = Engine(p1, model=llama, cfg=cfg, **ENGINE_KW)
    ref = ref_eng.submit(prompt(), max_new_tokens=8, key=0).result()
    ref_eng.close()

    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        assert not pool.ready("tuna")
        got = eng.submit(prompt(), max_new_tokens=8, key=0,
                         model="tuna").result()
        assert got == ref
        assert pool.ready("tuna")
        again = eng.submit(prompt(), max_new_tokens=8, key=0,
                           model="tuna").result()
        assert again == ref
        assert pool.stats()["models"]["tuna"]["materializations"] == 1
        assert_settled(eng)
    finally:
        eng.close()


def test_unregistered_model_rejected(cfg, params):
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    try:
        with pytest.raises(ValueError):
            eng.submit(prompt(), max_new_tokens=4, key=0, model="ghost")
    finally:
        eng.close()


def test_materialize_fault_retries_next_tick(cfg, params):
    """TDX_FAULT serve.materialize:1:io — the first materialization
    attempt fails typed, the skeleton survives, the next tick's demand
    retries, and the stream completes token-identical."""
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        faults.reset("serve.materialize:1:io")
        p1 = llama.init_params(jax.random.PRNGKey(1), cfg)
        got = eng.submit(prompt(), max_new_tokens=8, key=0,
                         model="tuna").result()
        faults.reset("")
        ref_eng = Engine(p1, model=llama, cfg=cfg, **ENGINE_KW)
        ref = ref_eng.submit(prompt(), max_new_tokens=8, key=0).result()
        ref_eng.close()
        assert got == ref
        assert pool.materialize_retries == 1
        assert pool.stats()["models"]["tuna"]["materializations"] == 1
        assert_settled(eng)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Isolation: digests and prefix pages never cross a model boundary


def test_per_model_digest_isolation(cfg, params):
    """Same prompt, same key, two models: the determinism digests MUST
    differ (model_version folds into every token), even if the token
    ids happened to coincide."""
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    pool.register("bass", model=llama, cfg=cfg, materialize=seeded(cfg, 2))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        digests = {}
        for tag in (None, "tuna", "bass"):
            h = eng.submit(prompt(), max_new_tokens=4, key=0, model=tag)
            h.result()
            digests[tag or "default"] = h._req.digest.hexdigest()
        assert len(set(digests.values())) == 3, digests
    finally:
        eng.close()


def test_cross_model_prefix_no_hit(cfg, params):
    """The prefix index is namespaced by model: the same prompt served
    under two models shares ZERO pages across them, while a same-model
    resubmit still hits."""
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        long = prompt(16)  # two full pages
        eng.submit(long, max_new_tokens=4, key=0).result()
        assert eng.prefix.hits == 0
        # Other model, same tokens: its pages hash under its own
        # namespace — a cross-model hit would serve model A's KV to
        # model B.
        eng.submit(long, max_new_tokens=4, key=0, model="tuna").result()
        assert eng.prefix.hits == 0
        # Same model again: hit.
        eng.submit(long, max_new_tokens=4, key=0, model="tuna").result()
        assert eng.prefix.hits == 1
        eng.submit(long, max_new_tokens=4, key=0).result()
        assert eng.prefix.hits == 2
        assert_settled(eng)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Eviction under HBM pressure


def test_eviction_lru_under_max_resident(cfg, params):
    """max_resident=1: demanding a second model evicts the idle first
    (weights only — its skeleton stays registered), and re-demanding
    the first re-materializes to a token-identical stream."""
    pool = ModelPool(max_resident=1)
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    pool.register("bass", model=llama, cfg=cfg, materialize=seeded(cfg, 2))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        first = eng.submit(prompt(), max_new_tokens=8, key=0,
                           model="tuna").result()
        assert pool.resident() == ["tuna"]
        eng.submit(prompt(), max_new_tokens=8, key=0, model="bass").result()
        assert pool.resident() == ["bass"]
        assert "tuna" in pool and not pool.ready("tuna")
        assert pool.stats()["models"]["tuna"]["evictions"] == 1
        # Re-materialized weights are the same weights: determinism
        # across an evict/rematerialize round trip.
        again = eng.submit(prompt(), max_new_tokens=8, key=0,
                           model="tuna").result()
        assert again == first
        assert_settled(eng)
    finally:
        eng.close()


def test_eviction_never_touches_live_stream(cfg, params):
    """A model with live slots is pinned: pressure from a second model
    materializes OVER budget rather than dropping weights mid-stream,
    and the live stream finishes token-identical to an unpressured run.
    """
    pool = ModelPool(max_resident=1)
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    pool.register("bass", model=llama, cfg=cfg, materialize=seeded(cfg, 2))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        h_live = eng.submit(prompt(), max_new_tokens=24, key=3,
                            model="tuna")
        # Drive until tuna is mid-decode, then demand bass.
        while not h_live._tokens:
            eng.step()
        assert pool.resident() == ["tuna"]
        h2 = eng.submit(prompt(32, start=100), max_new_tokens=8, key=0,
                        model="bass")
        live = h_live.result()
        h2.result()
        # tuna was in use when bass materialized: both resident, zero
        # tuna evictions while it streamed.
        assert pool.stats()["models"]["tuna"]["evictions"] == 0
        assert set(pool.resident()) == {"tuna", "bass"}

        # Reference: unpressured tuna-only engine, same key.
        pool2 = ModelPool()
        pool2.register("tuna", model=llama, cfg=cfg,
                       materialize=seeded(cfg, 1))
        ref_eng = Engine(params, model=llama, cfg=cfg, model_pool=pool2,
                         **ENGINE_KW)
        ref = ref_eng.submit(prompt(), max_new_tokens=24, key=3,
                             model="tuna").result()
        ref_eng.close()
        assert live == ref
        assert_settled(eng)
    finally:
        eng.close()


def test_hbm_budget_drives_eviction(cfg, params):
    """hbm_budget_bytes reads the ledger's REAL per-owner rows: a
    budget that fits one model's weights evicts the cold one when the
    second materializes."""
    one = telemetry.perf.pytree_nbytes(
        llama.init_params(jax.random.PRNGKey(1), cfg)
    )
    pool = ModelPool(hbm_budget_bytes=int(one * 1.5))
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    pool.register("bass", model=llama, cfg=cfg, materialize=seeded(cfg, 2))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        eng.submit(prompt(), max_new_tokens=4, key=0, model="tuna").result()
        eng.submit(prompt(), max_new_tokens=4, key=0, model="bass").result()
        assert pool.resident() == ["bass"]
        assert pool.stats()["models"]["tuna"]["evictions"] == 1
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# CoW parallel sampling (submit n=...)


FORK_KW = dict(
    num_slots=8, block_size=8, num_blocks=81, max_model_len=64,
    decode_chunk=4, eos_id=EOS, temperature=1.0, top_k=40,
)


def fold(seed, i):
    return np.asarray(
        jax.random.fold_in(jax.random.PRNGKey(seed), i)
    ).astype(np.uint32).reshape(2)


def test_fork_siblings_match_solo_folded_keys(cfg, params):
    """Every sibling of ``submit(n=4)`` is token-identical to a solo
    submit under ``fold_in(base, i)`` — the fork is an accounting
    optimization, never a sampling change — and the siblings diverge
    from each other under temperature."""
    eng = Engine(params, model=llama, cfg=cfg, **FORK_KW)
    try:
        h = eng.submit(prompt(32), max_new_tokens=8, key=7, n=4)
        assert h.siblings is not None and len(h.siblings) == 4
        res = [s.result() for s in h.siblings]
        assert len({tuple(r) for r in res}) > 1  # sampled: they diverge
        for i, toks in enumerate(res):
            solo = eng.submit(prompt(32), max_new_tokens=8,
                              key=fold(7, i)).result()
            assert solo == toks, i
        assert eng.stats()["forks"] == 3
        assert_settled(eng)
    finally:
        eng.close()


def test_fork_page_accounting_far_below_4x_solo(cfg, params):
    """n=4 over a 4-page prompt: the group's peak page footprint stays
    far below 4x a solo request's (prompt pages are SHARED via the
    donor; only divergence CoW-copies and generation tails are
    per-sibling)."""
    eng = Engine(params, model=llama, cfg=cfg, prefix_cache=False,
                 **FORK_KW)
    try:
        solo_h = eng.submit(prompt(32), max_new_tokens=8, key=fold(7, 0))
        solo_peak = 0
        while not solo_h.done:
            eng.step()
            solo_peak = max(solo_peak, eng.allocator.num_in_use)
        assert eng.allocator.num_in_use == 0

        h = eng.submit(prompt(32), max_new_tokens=8, key=7, n=4)
        fork_peak = 0
        while not all(s.done for s in h.siblings):
            eng.step()
            fork_peak = max(fork_peak, eng.allocator.num_in_use)
        for s in h.siblings:
            s.result()
        eng.step()  # donor sweep runs in the next tick's reap phase
        assert eng.allocator.num_in_use == 0
        # "Far below": strictly under half of 4x solo (measured: ~6 vs
        # 20 at this geometry), with CoW actually exercised.
        assert fork_peak < 2 * solo_peak, (fork_peak, solo_peak)
        assert eng._n_cow >= 1  # divergence actually copy-on-wrote
    finally:
        eng.close()


def test_fork_cancel_refcounts_settle(cfg, params):
    """Cancelling siblings mid-flight (and finishing the rest) settles
    every refcount: no leaked pages, donor pages freed once the last
    sibling retires."""
    eng = Engine(params, model=llama, cfg=cfg, prefix_cache=False,
                 **FORK_KW)
    try:
        h = eng.submit(prompt(32), max_new_tokens=16, key=9, n=4)
        for _ in range(3):
            eng.step()
        h.siblings[2].cancel()
        h.siblings[3].cancel()
        for s in h.siblings:
            try:
                s.result()
            except Exception:
                pass  # the cancelled pair raises typed RequestCancelled
        for _ in range(3):
            eng.step()  # donor sweep runs in the reap phase
        assert eng.allocator.num_in_use == 0
        assert eng.stats()["cancelled"] >= 2
    finally:
        eng.close()


def test_fork_on_pool_model(cfg, params):
    """model= and n= compose: forks of a pool model sample under its
    weights and its digest namespace."""
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **FORK_KW)
    try:
        h = eng.submit(prompt(32), max_new_tokens=8, key=7, model="tuna",
                       n=3)
        res = [s.result() for s in h.siblings]
        for i, toks in enumerate(res):
            solo = eng.submit(prompt(32), max_new_tokens=8,
                              key=fold(7, i), model="tuna").result()
            assert solo == toks, i
        assert_settled(eng)
    finally:
        eng.close()


def test_submit_rejects_bad_n(cfg, params):
    eng = Engine(params, model=llama, cfg=cfg, **ENGINE_KW)
    try:
        with pytest.raises(ValueError):
            eng.submit(prompt(), max_new_tokens=4, key=0, n=0)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Compile economy: per-model programs via static args share compiles


def test_same_geometry_models_share_decode_compile(cfg, params):
    """Two tags of the same family+cfg share ONE decode compile: the
    jit cache keys on (module, cfg, shapes) — the model tag only labels
    the observatory.  Steady-state decode across both models recompiles
    zero times."""
    pool = ModelPool()
    pool.register("tuna", model=llama, cfg=cfg, materialize=seeded(cfg, 1))
    eng = Engine(params, model=llama, cfg=cfg, model_pool=pool, **ENGINE_KW)
    try:
        eng.submit(prompt(), max_new_tokens=8, key=0).result()  # warm
        c0 = {
            k: v for k, v in telemetry.snapshot()["counters"].items()
            if k.startswith("compile.count")
        }
        eng.submit(prompt(), max_new_tokens=8, key=0,
                   model="tuna").result()
        h1 = eng.submit(prompt(16, start=50), max_new_tokens=8, key=1)
        h2 = eng.submit(prompt(16, start=50), max_new_tokens=8, key=1,
                        model="tuna")
        h1.result(), h2.result()
        c1 = {
            k: v for k, v in telemetry.snapshot()["counters"].items()
            if k.startswith("compile.count")
        }
        grew = {k: v - c0.get(k, 0) for k, v in c1.items()
                if v != c0.get(k, 0)}
        assert not any("decode" in k for k in grew), grew
    finally:
        eng.close()
