"""The dryrun stage runner must isolate failures (round-3 postmortem:
one broken stage aborted the run before later stages executed, blanking
their coverage from the driver artifact)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _run_stages  # noqa: E402


def test_one_failing_stage_does_not_blank_the_rest(capsys):
    ran = []

    def ok(name):
        def fn():
            ran.append(name)
            return None

        return fn

    def boom():
        ran.append("boom")
        raise ValueError("injected")

    def skipped():
        ran.append("skipped")
        return "skipped (reason)"

    failures = _run_stages(
        [("a", ok("a")), ("boom", boom), ("b", ok("b")), ("s", skipped)]
    )
    # Every stage ran despite the injected failure in the second.
    assert ran == ["a", "boom", "b", "skipped"]
    assert [name for name, _ in failures] == ["boom"]
    assert isinstance(failures[0][1], ValueError)
    out = capsys.readouterr().out
    assert "[dryrun] a: PASS" in out
    assert "[dryrun] boom: FAIL (ValueError: injected)" in out
    assert "[dryrun] b: PASS" in out
    assert "[dryrun] s: skipped (reason)" in out


def test_all_green_returns_no_failures():
    assert _run_stages([("a", lambda: None), ("b", lambda: None)]) == []
