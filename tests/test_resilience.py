"""Resilience subsystem: retry policy, fault injection, preemption flag,
non-finite guard, and their wiring through Checkpointer and fit().

The pure parts (retry/faults/preemption) run in the torch-only
environment; guard/fit integration tests need JAX and skip without it.
The JAX-integration classes are marked ``slow`` (the tier-1 lane runs
``-m 'not slow'`` under a tight wall-clock budget) and run in full in
CI's fault-injection lane together with tests/test_crash_resume.py.
"""

import os
import signal

import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.resilience import (
    CRASH_EXIT_CODE,
    InjectedFault,
    NonFiniteError,
    RetriesExhausted,
    RetryPolicy,
    SkipTracker,
    faults,
    parse_faults,
    preemption,
)
from torchdistx_tpu.resilience.retry import DEFAULT_RETRYABLE_NAMES


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Every test starts with an empty fault registry and a clear
    preemption flag, and leaves no handlers behind."""
    faults.reset("")
    preemption.clear()
    yield
    faults.reset(None if os.environ.get("TDX_FAULT") else "")
    preemption.clear()
    preemption.uninstall()


# ---------------------------------------------------------------------------
# RetryPolicy


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        c = telemetry.counter("test.retries")
        before = c.value
        p = RetryPolicy(max_attempts=5, base_delay_s=0.001)
        assert p.call(flaky, counter=c) == "ok"
        assert len(calls) == 3
        assert c.value - before == 2  # two granted retries

    def test_exhausted_raises_with_cause(self):
        p = RetryPolicy(max_attempts=2, base_delay_s=0.001)

        def always():
            raise OSError("persistent")

        with pytest.raises(RetriesExhausted) as ei:
            p.call(always)
        assert isinstance(ei.value.__cause__, OSError)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fatal():
            calls.append(1)
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=5, base_delay_s=0.001).call(fatal)
        assert len(calls) == 1

    def test_retryable_by_name(self):
        class Unavailable(Exception):  # grpc-style transport error
            pass

        p = RetryPolicy(max_attempts=2, base_delay_s=0.001)
        assert p.is_retryable(Unavailable())
        assert not p.is_retryable(KeyError())

    def test_explicit_retryable_attribute_is_authoritative(self):
        """An exception carrying a boolean `retryable` (the serving
        RequestError contract) overrides BOTH the isinstance layer and
        the name layer — the router, checkpoint IO, and data IO all
        classify through this one path."""
        p = RetryPolicy(max_attempts=2, base_delay_s=0.001)

        class TransientThing(Exception):  # not an OSError, unknown name
            retryable = True

        class FatalIO(OSError):  # isinstance says retry; raiser says no
            retryable = False

        assert p.is_retryable(TransientThing())
        assert not p.is_retryable(FatalIO())
        # A non-boolean attribute is ignored — heuristics still apply.
        class WeirdAttr(OSError):
            retryable = "yes"

        assert p.is_retryable(WeirdAttr())

    def test_retryable_attribute_request_error_contract(self):
        """End-to-end with the serving taxonomy: a shed/drain is
        retryable; a serving DeadlineExceeded is NOT, even though its
        NAME collides with grpc's transient DeadlineExceeded status."""
        from torchdistx_tpu.serving import (
            DeadlineExceeded,
            EngineDraining,
            EngineOverloaded,
            RequestCancelled,
        )

        p = RetryPolicy(max_attempts=2, base_delay_s=0.001)
        assert p.is_retryable(EngineOverloaded("shed"))
        assert p.is_retryable(EngineDraining("draining"))
        assert not p.is_retryable(RequestCancelled("client cancel"))
        assert not p.is_retryable(DeadlineExceeded("too late"))
        assert "DeadlineExceeded" in DEFAULT_RETRYABLE_NAMES  # the trap

    def test_retryable_attribute_drives_call(self):
        """call() grants retries on attribute-classified exceptions and
        stops immediately on retryable=False ones."""
        p = RetryPolicy(max_attempts=3, base_delay_s=0.001)

        class Transient(Exception):
            retryable = True

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise Transient("hiccup")
            return "ok"

        assert p.call(flaky) == "ok"
        assert len(calls) == 2

        class Fatal(OSError):
            retryable = False

        fatal_calls = []

        def fatal():
            fatal_calls.append(1)
            raise Fatal("corrupt")

        with pytest.raises(Fatal):
            p.call(fatal)
        assert len(fatal_calls) == 1

    def test_delay_backoff_bounds(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, jitter=0.5)
        for k, cap in [(0, 0.1), (1, 0.2), (2, 0.4), (10, 1.0)]:
            for _ in range(8):
                d = p.delay(k)
                assert cap * 0.5 <= d <= cap

    def test_deadline_bounds_total_time(self):
        p = RetryPolicy(
            max_attempts=100, base_delay_s=10.0, deadline_s=0.01
        )

        def always():
            raise OSError("x")

        # The first retry's sleep would cross the deadline: no 10s nap.
        with pytest.raises(RetriesExhausted):
            p.call(always)

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)


# ---------------------------------------------------------------------------
# Fault injection


class TestFaults:
    def test_parse_grammar(self):
        specs = parse_faults("ckpt.save:2:io, step.exec:3:nan")
        assert [(s.site, s.step, s.kind) for s in specs] == [
            ("ckpt.save", 2, "io"),
            ("step.exec", 3, "nan"),
        ]

    @pytest.mark.parametrize(
        "bad",
        [
            "ckpt.save:2",  # missing kind
            "nowhere:2:io",  # unknown site
            "ckpt.save:2:explode",  # unknown kind
            "ckpt.save:x:io",  # non-int step
            "ckpt.save:0:io",  # steps are 1-based
        ],
    )
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_faults(bad)

    def test_fire_once_then_clean(self):
        faults.reset("data.next:4:io")
        assert faults.fire("data.next", 3) is None  # wrong step
        assert faults.fire("ckpt.save", 4) is None  # wrong site
        with pytest.raises(InjectedFault):
            faults.fire("data.next", 4)
        # Consumed: the retry's second attempt succeeds.
        assert faults.fire("data.next", 4) is None

    def test_nan_kind_is_returned_not_raised(self):
        faults.reset("step.exec:1:nan")
        assert faults.fire("step.exec", 1) == "nan"

    def test_fired_counter(self):
        c = telemetry.counter("faults.fired")
        before = c.value
        faults.reset("data.next:1:nan")
        faults.fire("data.next", 1)
        assert c.value - before == 1

    def test_crash_exit_code_reserved(self):
        # The subprocess e2e (test_crash_resume.py) asserts this code.
        assert CRASH_EXIT_CODE == 13


# ---------------------------------------------------------------------------
# Preemption flag


class TestPreemption:
    def test_request_and_clear(self):
        assert not preemption.requested()
        preemption.request()
        assert preemption.requested()
        preemption.clear()
        assert not preemption.requested()

    def test_real_sigterm_sets_flag(self):
        assert preemption.install()
        assert preemption.installed()
        c = telemetry.counter("preempt.signals")
        before = c.value
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython delivers the handler at a bytecode boundary right after
        # the kill returns in the main thread.
        for _ in range(1000):
            if preemption.requested():
                break
        assert preemption.requested()
        assert c.value - before == 1

    def test_second_signal_escalates_to_previous_handler(self):
        hits = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            assert preemption.install()
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(1000):
                if preemption.requested():
                    break
            assert hits == []  # first signal: flag only
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(1000):
                if hits:
                    break
            assert hits == [signal.SIGTERM]  # second: chained
        finally:
            preemption.uninstall()
            signal.signal(signal.SIGTERM, prev)

    def test_uninstall_restores(self):
        prev = signal.getsignal(signal.SIGTERM)
        preemption.install()
        preemption.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev


# ---------------------------------------------------------------------------
# Non-finite guard (host side)


class TestSkipTracker:
    def test_escalates_after_consecutive(self):
        t = SkipTracker(max_consecutive=3)
        t.observe(True, 1)
        t.observe(True, 2)
        t.observe(False, 3)  # finite step resets the streak
        t.observe(True, 4)
        t.observe(True, 5)
        with pytest.raises(NonFiniteError) as ei:
            t.observe(True, 6)
        assert ei.value.step == 6
        assert ei.value.consecutive == 3
        assert t.total == 5

    def test_disabled_escalation_still_counts(self):
        c = telemetry.counter("train.skipped_steps")
        before = c.value
        t = SkipTracker(max_consecutive=0)
        for s in range(1, 20):
            t.observe(True, s)
        assert c.value - before == 19


# ---------------------------------------------------------------------------
# JAX integration: guard inside make_train_step, resilience through fit()


@pytest.fixture(scope="module")
def train_rig():
    jax = pytest.importorskip("jax")
    optax = pytest.importorskip("optax")
    pytest.importorskip("orbax.checkpoint")
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel import train_step as ts
    from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = llama.llama_test()
    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    bs = ts.batch_sharding(mesh)

    def batches(n=None):
        key = jax.random.PRNGKey(42)
        i = 0
        while n is None or i < n:
            key, sub = jax.random.split(key)
            t = jax.device_put(
                jax.random.randint(sub, (8, 16), 0, cfg.vocab_size), bs
            )
            yield {"tokens": t, "targets": t}
            i += 1

    return cfg, mesh, init_fn, step_fn, batches


@pytest.mark.slow
class TestNonFiniteGuard:
    def test_skip_step_returns_prior_state_bit_identical(self, train_rig):
        import jax
        import numpy as np

        _, _, init_fn, step_fn, batches = train_rig
        batch = next(batches(1))
        state, m1 = step_fn(init_fn(jax.random.PRNGKey(0)), batch)
        assert not bool(m1["nonfinite"])
        assert int(m1["step"]) == 1
        snap = jax.tree.map(np.asarray, state)
        state2, m2 = step_fn(state, {**batch, "_tdx_nan": True})
        assert bool(m2["nonfinite"])
        assert not np.isfinite(float(m2["loss"]))
        for a, b in zip(
            jax.tree.leaves(snap),
            jax.tree.leaves(jax.tree.map(np.asarray, state2)),
        ):
            np.testing.assert_array_equal(a, b)
        # Training continues cleanly after the skip.
        state3, m3 = step_fn(state2, batch)
        assert int(m3["step"]) == 2
        assert not bool(m3["nonfinite"])

    def test_guard_composes_with_fsdp_tp_sharding(self, train_rig):
        """The finiteness check must all-reduce across sharded axes and
        the skip-select must respect per-leaf shardings (wq/wo carry
        transposed fsdp×tp specs) — the composition the dp-only tests
        above cannot see."""
        import jax
        import numpy as np
        import optax

        from torchdistx_tpu.parallel import train_step as ts
        from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh

        cfg, _, _, _, _ = train_rig
        mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
        init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.adamw(1e-3))
        state = init_fn(jax.random.PRNGKey(0))
        t = jax.device_put(
            jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
            ts.batch_sharding(mesh),
        )
        batch = {"tokens": t, "targets": t}
        state, m1 = step_fn(state, batch)
        assert not bool(m1["nonfinite"])
        snap = jax.tree.map(np.asarray, state)
        state, m2 = step_fn(state, {**batch, "_tdx_nan": True})
        assert bool(m2["nonfinite"])
        for a, b in zip(
            jax.tree.leaves(snap),
            jax.tree.leaves(jax.tree.map(np.asarray, state)),
        ):
            np.testing.assert_array_equal(a, b)
        # Output placement survives the select.
        wq = state.params["layers"]["wq"]
        assert wq.sharding.spec == jax.sharding.PartitionSpec(
            None, "fsdp", "tp"
        )

    def test_guard_off_keeps_legacy_metrics(self, train_rig):
        import jax
        import optax

        cfg, mesh, _, _, batches = train_rig
        from torchdistx_tpu.parallel import train_step as ts

        init_u, step_u = ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), nonfinite_guard=False
        )
        _, m = step_u(init_u(jax.random.PRNGKey(0)), next(batches(1)))
        assert "nonfinite" not in m


@pytest.mark.slow
class TestFitResilience:
    def test_ckpt_save_fault_is_retried(self, train_rig, tmp_path):
        import jax

        from torchdistx_tpu.parallel.fit import fit
        from torchdistx_tpu.utils.checkpoint import latest_step

        _, _, init_fn, step_fn, batches = train_rig
        c = telemetry.counter("ckpt.retries")
        before = c.value
        faults.reset("ckpt.save:2:io")
        fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=3, checkpoint_dir=str(tmp_path / "run"),
            checkpoint_every=2,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
        )
        assert c.value - before >= 1
        assert latest_step(tmp_path / "run") == 3

    def test_ckpt_fault_without_retry_is_fatal(self, train_rig, tmp_path):
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig
        faults.reset("ckpt.save:2:io")
        with pytest.raises(InjectedFault):
            fit(
                init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
                n_steps=3, checkpoint_dir=str(tmp_path / "run"),
                checkpoint_every=2, retry=None,
            )

    def test_data_fault_is_retried(self, train_rig):
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig
        c = telemetry.counter("data.retries")
        before = c.value
        faults.reset("data.next:2:io")
        state, _ = fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=3,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
        )
        assert c.value - before >= 1
        assert int(state.step) == 3

    def test_final_step_saved_when_batches_exhaust(
        self, train_rig, tmp_path
    ):
        import jax

        from torchdistx_tpu.parallel.fit import fit
        from torchdistx_tpu.utils.checkpoint import latest_step

        _, _, init_fn, step_fn, batches = train_rig
        # 3 batches, n_steps=10, checkpoint_every=100: without the
        # final-save path the run would leave NO checkpoint at all.
        fit(
            init_fn, step_fn, batches(3), key=jax.random.PRNGKey(0),
            n_steps=10, checkpoint_dir=str(tmp_path / "run"),
            checkpoint_every=100,
        )
        assert latest_step(tmp_path / "run") == 3

    def test_nonfinite_step_skipped_and_counted(self, train_rig):
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig
        c = telemetry.counter("train.skipped_steps")
        before = c.value
        faults.reset("step.exec:2:nan")
        state, _ = fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=4,
        )
        assert c.value - before == 1
        # 4 batches consumed, 3 optimizer steps applied (one skipped).
        assert int(state.step) == 3

    def test_nonfinite_escalation_raises(self, train_rig):
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig
        faults.reset("step.exec:1:nan,step.exec:2:nan,step.exec:3:nan")
        with pytest.raises(NonFiniteError):
            fit(
                init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
                n_steps=6, max_consecutive_nonfinite=3,
            )

    def test_preemption_saves_current_step_and_resumes(
        self, train_rig, tmp_path
    ):
        import jax
        import numpy as np

        from torchdistx_tpu.parallel.fit import fit
        from torchdistx_tpu.utils.checkpoint import latest_step

        _, _, init_fn, step_fn, batches = train_rig
        c = telemetry.counter("train.preemptions")
        before = c.value

        def preempt_at_2(step, metrics):
            if step == 2:
                preemption.request()

        fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=10, checkpoint_dir=str(tmp_path / "run"),
            checkpoint_every=100, on_metrics=preempt_at_2,
        )
        # Stopped at the boundary after step 2 and saved THAT step, far
        # from any checkpoint_every multiple.
        assert latest_step(tmp_path / "run") == 2
        assert c.value - before == 1
        # fit() acted on the request and cleared it: the next fit() in
        # this process resumes instead of instantly re-preempting.
        assert not preemption.requested()

        resumed, _ = fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=5, checkpoint_dir=str(tmp_path / "run"),
            checkpoint_every=100,
        )
        ref, _ = fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=5, handle_preemption=False,
        )
        assert int(resumed.step) == 5
        for a, b in zip(
            jax.tree.leaves(ref.params), jax.tree.leaves(resumed.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
            )

    def test_preemption_before_any_step_is_resumable_noop(
        self, train_rig, tmp_path
    ):
        import jax

        from torchdistx_tpu.parallel.fit import fit
        from torchdistx_tpu.utils.checkpoint import latest_step

        _, _, init_fn, step_fn, batches = train_rig
        preemption.request()
        state, metrics = fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=5, checkpoint_dir=str(tmp_path / "run"),
        )
        assert metrics is None  # no step ran
        assert latest_step(tmp_path / "run") is None  # nothing to save

    def test_fit_restores_signal_handlers_on_exit(self, train_rig):
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        fit(
            init_fn, step_fn, batches(), key=jax.random.PRNGKey(0),
            n_steps=1,
        )
        # fit() must not permanently swallow the user's Ctrl-C.
        assert signal.getsignal(signal.SIGTERM) is prev_term
        assert signal.getsignal(signal.SIGINT) is prev_int

    def test_transient_error_from_generator_fails_loudly(self, train_rig):
        """A transient error raised INSIDE a generator closes it; the
        retry's follow-up next() then reports StopIteration.  That must
        surface as the original loud failure, never as silent clean
        'data exhausted' truncation of the run."""
        import jax

        from torchdistx_tpu.parallel.fit import fit

        _, _, init_fn, step_fn, batches = train_rig

        def flaky_batches():
            inner = batches()
            yield next(inner)
            raise OSError("transient read error inside the generator")

        with pytest.raises(RetriesExhausted) as ei:
            fit(
                init_fn, step_fn, flaky_batches(),
                key=jax.random.PRNGKey(0), n_steps=5,
                retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
            )
        assert isinstance(ei.value.__cause__, OSError)


class TestPureReads:
    def test_latest_step_does_not_create_directory(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from torchdistx_tpu.utils.checkpoint import latest_step

        missing = tmp_path / "never-checkpointed"
        assert latest_step(missing) is None
        assert not missing.exists()


class TestAnyFlag:
    def test_single_process_is_local(self):
        pytest.importorskip("jax")
        from torchdistx_tpu.parallel.distributed import any_flag

        assert any_flag(True) is True
        assert any_flag(False) is False


class TestCompileCacheErrorCounter:
    def test_setup_failure_is_counted(self, monkeypatch):
        jax = pytest.importorskip("jax")
        from torchdistx_tpu.utils import compilation_cache as cc

        c = telemetry.counter("compile_cache.errors")
        before = c.value
        monkeypatch.setattr(cc, "_done", False)
        monkeypatch.delenv("TDX_NO_COMPILATION_CACHE", raising=False)
        # Force the accelerator path then fail the mkdir: the swallowed
        # error must surface in the counter.
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(
            cc.os, "makedirs",
            lambda *a, **k: (_ for _ in ()).throw(OSError("read-only")),
        )
        try:
            cc.ensure_compilation_cache()
        finally:
            cc._done = True  # leave the module in its settled state
        assert c.value - before == 1
