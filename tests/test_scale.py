"""North-star scale validation on a single host (BASELINE configs 4-5).

A Llama-7B-shaped HF model is constructed under deferred init — the full
architecture must be inspectable with near-zero memory, the tape must be
fully JAX-lowerable (so sharded materialization would run without torch
fallbacks), and the whole thing must stay within tight host-RSS bounds.
Actual materialization is executed at a scaled-down size; the 7B/70B
materialization itself needs real pod HBM.
"""

import resource

import pytest
import torch

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu import _tape
from torchdistx_tpu.deferred_init import _get_record
from torchdistx_tpu.fake import is_fake


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


@pytest.fixture(scope="module")
def llama7b_fake():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig()  # defaults = 7B shapes
    rss0 = _rss_mb()
    model = di.deferred_init(LlamaForCausalLM, config)
    return model, config, _rss_mb() - rss0


def test_7b_constructs_with_bounded_rss(llama7b_fake):
    model, config, growth = llama7b_fake
    # 7B params in fp32 would be ~27 GB; the fake build must stay in the
    # tens of MBs (tape + meta shadows only).
    assert growth < 500, f"RSS grew {growth:.0f} MB during fake construction"
    n_params = sum(p.numel() for p in model.parameters())
    assert n_params > 6.5e9
    assert all(is_fake(p) for p in model.parameters())


def test_7b_architecture_inspectable(llama7b_fake):
    model, config, _ = llama7b_fake
    # The shard-then-materialize flow needs full shape/dtype visibility.
    shapes = {n: tuple(p.shape) for n, p in model.named_parameters()}
    assert shapes["model.embed_tokens.weight"] == (32000, 4096)
    assert shapes["model.layers.31.mlp.down_proj.weight"] == (4096, 11008)


def test_7b_tape_fully_jax_lowerable(llama7b_fake):
    """Every non-view node in every param's call stack must have a JAX
    lowering — i.e. sharded materialization runs with zero torch fallback
    and zero CUDA calls (the north-star requirement)."""
    from torchdistx_tpu.materialize import _is_view_node, _packet_name
    from torchdistx_tpu.ops.aten_jax import LOWERINGS

    model, config, _ = llama7b_fake
    missing = set()
    for _, p in model.named_parameters():
        node = _get_record(p).node
        for n in _tape.build_call_stack(node):
            if _is_view_node(n):
                continue
            name = _packet_name(n.op.func)
            if name not in LOWERINGS:
                missing.add(name)
    assert not missing, f"ops without JAX lowering: {sorted(missing)}"


def test_7b_native_graph_schedules(llama7b_fake):
    """The C++ core must schedule the 7B tape (hundreds of nodes) quickly
    and consistently with chronological order."""
    model, config, _ = llama7b_fake
    total = 0
    for _, p in model.named_parameters():
        stack = _tape.build_call_stack(_get_record(p).node)
        nrs = [n.op_nr for n in stack]
        assert nrs == sorted(nrs)
        total += len(stack)
    assert total > 0


def test_scaled_down_materialization_is_exact():
    """Execute the same flow at small scale and check real values: sharded
    JAX materialization of an HF Llama must match torch replay statistics
    (RNG differs by design, structure/zeros must match exactly)."""
    import jax
    import numpy as np
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchdistx_tpu.materialize import materialize_module_jax
    from torchdistx_tpu.parallel import MeshSpec, make_mesh
    from torchdistx_tpu.parallel.sharding import combine_plans, fsdp_plan, tp_plan_llama

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    model = di.deferred_init(LlamaForCausalLM, config)
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    arrays = materialize_module_jax(
        model, mesh=mesh, plan=combine_plans(tp_plan_llama(), fsdp_plan())
    )
    # Norm weights init to ones exactly; projections are random but bounded.
    norm = np.asarray(arrays["model.norm.weight"])
    assert np.array_equal(norm, np.ones_like(norm))
    q = np.asarray(arrays["model.layers.0.self_attn.q_proj.weight"])
    assert q.std() < 1.0 and q.std() > 0.001
    # Every param plus the deferred rotary inv_freq buffer materializes.
    n_expected = len(list(model.named_parameters())) + sum(
        1 for _, b in model.named_buffers() if is_fake(b)
    )
    assert len(arrays) == n_expected
