"""North-star scale validation on a single host (BASELINE configs 4-5).

A Llama-7B-shaped HF model is constructed under deferred init — the full
architecture must be inspectable with near-zero memory, the tape must be
fully JAX-lowerable (so sharded materialization would run without torch
fallbacks), and the whole thing must stay within tight host-RSS bounds.
Actual materialization is executed at a scaled-down size; the 7B/70B
materialization itself needs real pod HBM.
"""

import resource

import pytest
import torch

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu import _tape
from torchdistx_tpu.deferred_init import _get_record
from torchdistx_tpu.fake import is_fake


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def _rss_now_mb() -> float:
    """Current VmRSS (not the lifetime peak)."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024
    return 0.0


@pytest.fixture(scope="module")
def llama7b_fake():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig()  # defaults = 7B shapes
    rss0 = _rss_mb()
    model = di.deferred_init(LlamaForCausalLM, config)
    return model, config, _rss_mb() - rss0


def test_7b_constructs_with_bounded_rss(llama7b_fake):
    model, config, growth = llama7b_fake
    # 7B params in fp32 would be ~27 GB; the fake build must stay in the
    # tens of MBs (tape + meta shadows only).
    assert growth < 500, f"RSS grew {growth:.0f} MB during fake construction"
    n_params = sum(p.numel() for p in model.parameters())
    assert n_params > 6.5e9
    assert all(is_fake(p) for p in model.parameters())


def test_7b_architecture_inspectable(llama7b_fake):
    model, config, _ = llama7b_fake
    # The shard-then-materialize flow needs full shape/dtype visibility.
    shapes = {n: tuple(p.shape) for n, p in model.named_parameters()}
    assert shapes["model.embed_tokens.weight"] == (32000, 4096)
    assert shapes["model.layers.31.mlp.down_proj.weight"] == (4096, 11008)


def test_7b_tape_fully_jax_lowerable(llama7b_fake):
    """Every non-view node in every param's call stack must have a JAX
    lowering — i.e. sharded materialization runs with zero torch fallback
    and zero CUDA calls (the north-star requirement)."""
    from torchdistx_tpu.materialize import _is_view_node, _packet_name
    from torchdistx_tpu.ops.aten_jax import LOWERINGS

    model, config, _ = llama7b_fake
    missing = set()
    for _, p in model.named_parameters():
        node = _get_record(p).node
        for n in _tape.build_call_stack(node):
            if _is_view_node(n):
                continue
            name = _packet_name(n.op.func)
            if name not in LOWERINGS:
                missing.add(name)
    assert not missing, f"ops without JAX lowering: {sorted(missing)}"


def test_7b_native_graph_schedules(llama7b_fake):
    """The C++ core must schedule the 7B tape (hundreds of nodes) quickly
    and consistently with chronological order."""
    model, config, _ = llama7b_fake
    total = 0
    for _, p in model.named_parameters():
        stack = _tape.build_call_stack(_get_record(p).node)
        nrs = [n.op_nr for n in stack]
        assert nrs == sorted(nrs)
        total += len(stack)
    assert total > 0


def test_scaled_down_materialization_is_exact():
    """Execute the same flow at small scale and check real values: sharded
    JAX materialization of an HF Llama must match torch replay statistics
    (RNG differs by design, structure/zeros must match exactly)."""
    import jax
    import numpy as np
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchdistx_tpu.materialize import materialize_module_jax
    from torchdistx_tpu.parallel import MeshSpec, make_mesh
    from torchdistx_tpu.parallel.sharding import combine_plans, fsdp_plan, tp_plan_llama

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    model = di.deferred_init(LlamaForCausalLM, config)
    mesh = make_mesh(MeshSpec(fsdp=2, tp=4))
    arrays = materialize_module_jax(
        model, mesh=mesh, plan=combine_plans(tp_plan_llama(), fsdp_plan())
    )
    # Norm weights init to ones exactly; projections are random but bounded.
    norm = np.asarray(arrays["model.norm.weight"])
    assert np.array_equal(norm, np.ones_like(norm))
    q = np.asarray(arrays["model.layers.0.self_attn.q_proj.weight"])
    assert q.std() < 1.0 and q.std() > 0.001
    # Every param plus the deferred rotary inv_freq buffer materializes.
    n_expected = len(list(model.named_parameters())) + sum(
        1 for _, b in model.named_buffers() if is_fake(b)
    )
    assert len(arrays) == n_expected


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_1b_tape_path_sharded_materialize_rss_wall_and_equality():
    """Tape-path twin of the native proof below (VERDICT r4 item 1, the
    north-star configuration: BASELINE configs 4-5 are deferred-init *HF*
    models, shard-then-materialize): a ~1.35B-param HF Llama built under
    deferred init materializes SHARDED over the 8-device mesh through
    ``materialize_module_jax`` — the torch-tape path — with

    * process RSS growth inside the BASELINE <16 GB per-host bound (the
      virtual mesh holds every device's buffers in one process, a strict
      over-approximation of any real host's share),
    * wall-clock < 45 s (round 4 measured 91 s / 23 GB; the big-fill
      class programs now generate every shard on its owning device:
      28 s / 5.5 GB on the same box), and
    * values BITWISE-equal to the single-device tensor path
      (materialize_tensor_jax replays the same per-node key schedule, so
      module/mesh and tensor/single-chip materializations must agree
      exactly — the multi-host determinism guarantee on the tape path).
    """
    import time

    import jax
    import numpy as np
    from transformers import LlamaConfig, LlamaForCausalLM

    from torchdistx_tpu.materialize import (
        materialize_module_jax, materialize_tensor_jax,
    )
    from torchdistx_tpu.parallel import MeshSpec, make_mesh
    from torchdistx_tpu.parallel.sharding import fsdp_plan

    config = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=24, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=2048,
    )
    model = di.deferred_init(LlamaForCausalLM, config)
    n_params = sum(p.numel() for p in model.parameters())
    assert n_params > 1.3e9, f"config too small: {n_params/1e9:.2f}B"
    mesh = make_mesh(MeshSpec(fsdp=8))

    rss0 = _rss_now_mb()
    t0 = time.perf_counter()
    arrays = materialize_module_jax(model, mesh=mesh, plan=fsdp_plan())
    jax.block_until_ready(list(arrays.values()))
    wall = time.perf_counter() - t0
    growth_mb = _rss_now_mb() - rss0

    assert growth_mb < 16 * 1024, f"RSS grew {growth_mb/1024:.1f} GB"
    assert wall < 45, f"tape-path materialize took {wall:.0f}s"

    embed = arrays["model.embed_tokens.weight"]
    assert len(embed.sharding.device_set) == 8
    assert not embed.sharding.is_fully_replicated

    # Bitwise value check against the single-device tensor path, covering
    # every generation class: a singleton big fill (embed), a dim-0- and a
    # dim-1-sharded multi-instance big fill (q_proj / down_proj, distinct
    # layers), a pooled small fill (norm), and zero-fill-free sanity on a
    # mid-stack layer.
    fakes = dict(model.named_parameters())
    for name in (
        "model.embed_tokens.weight",
        "model.layers.0.self_attn.q_proj.weight",
        "model.layers.3.mlp.down_proj.weight",
        "model.layers.7.input_layernorm.weight",
        "lm_head.weight",
    ):
        got = np.asarray(arrays[name])
        want = np.asarray(materialize_tensor_jax(fakes[name]))
        assert got.shape == want.shape
        assert np.array_equal(got, want), f"value mismatch at {name}"
        del got, want


@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_1b_sharded_init_rss_and_shard_equality():
    """Scaled pod-shape proof (BASELINE configs 4-5, north star): a
    ~1.35B-param Llama initializes SHARDED over the 8-device mesh —
    shard-then-materialize, every shard generated into its owning
    device — with peak process RSS inside the BASELINE <16 GB per-host
    bound, and shard values BITWISE-identical to the unsharded init
    (threefry keys are sharding/topology-invariant — the multi-host
    determinism guarantee, checked here at real scale).

    On this virtual CPU mesh every "device" buffer lives in one process,
    so process peak RSS is a strict over-approximation of any real
    host's share.  (The torch-tape path, materialize_module_jax, has its
    own 1.35B twin above — big-fill class programs generate every shard
    on its owning device, 5.5 GB peak growth / 28 s measured.)"""
    import jax
    import numpy as np

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=2048, n_layers=24, n_heads=16,
        n_kv_heads=16, ffn_dim=5504, max_seq_len=2048,
    )
    mesh = make_mesh(MeshSpec(fsdp=8))
    # Growth measured as a CURRENT-VmRSS delta around the init, not
    # ru_maxrss: the lifetime peak would include whatever earlier tests
    # in this process allocated, failing (or passing) spuriously.
    rss0 = _rss_now_mb()
    params = llama.init_sharded(jax.random.PRNGKey(0), cfg, mesh)
    jax.block_until_ready(jax.tree.leaves(params))
    growth_mb = _rss_now_mb() - rss0
    n_params = sum(int(p.size) for p in jax.tree.leaves(params))
    assert n_params > 1.0e9, f"config too small: {n_params/1e9:.2f}B"
    assert growth_mb < 16 * 1024, f"RSS grew {growth_mb/1024:.1f} GB"

    embed = params["embed"]["weight"]
    assert len(embed.sharding.device_set) == 8
    assert not embed.sharding.is_fully_replicated

    ref = llama.init_params(jax.random.PRNGKey(0), cfg)
    for path in (
        ("embed", "weight"),
        ("layers", "w_down"),
        ("norm", "weight"),
        ("lm_head", "weight"),
    ):
        a = params
        b = ref
        for k in path:
            a, b = a[k], b[k]
        a = np.asarray(a).astype(np.float32)
        b = np.asarray(b).astype(np.float32)
        # Near-bitwise: threefry draws are sharding-invariant; the CPU
        # backend's oneDNN fastmath rounds the ×std+cast differently for
        # a handful of boundary elements (≤1 bf16 ulp; bitwise on TPU).
        mismatch = np.count_nonzero(a != b)
        assert mismatch / a.size < 1e-5, (
            f"{'/'.join(path)}: {mismatch}/{a.size} shard mismatches"
        )
        np.testing.assert_allclose(
            a, b, rtol=0, atol=5e-4,
            err_msg=f"shard mismatch at {'/'.join(path)}",
        )
