"""QoS subsystem (ISSUE 8): SLO-aware multi-tenant scheduling with
priority preemption and KV swap-to-host.

The acceptance bar: preempted-and-resumed streams are token-identical
to solo ``generate()`` via BOTH mechanisms (swap-in and
drop-and-replay), greedy and sampled, prefix cache on and off; the
weighted-fair-queueing starvation bound is provable and pinned; a
high-priority arrival preempts low-priority decode within one tick;
and ``scheduler="fifo"`` (the default) leaves every existing behavior
byte-identical — which the unchanged test_serving*.py suites pin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.models import llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    BlockAllocator,
    Engine,
    EngineOverloaded,
    QoSScheduler,
    RequestCancelled,
    RequestPreempted,
)
from torchdistx_tpu.serving.scheduler import Request, RequestHandle

EOS = 5
# prefix_cache pinned OFF: these suites assert raw page accounting
# (num_in_use == 0 at idle) that predates the cache-on default; the
# cache-on path is covered by the explicit prefix tests and the
# perf-plane lifecycle test.
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    prefix_cache=False,
)


@pytest.fixture(autouse=True)
def _clean():
    preemption.clear()
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def solo(model, cfg, params, prompt, seed, max_new, *, eos=None,
         temperature=0.0, top_k=None):
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new, eos_id=eos,
        temperature=temperature, top_k=top_k,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def req_of(rid, *, tenant="default", priority=0, n_chunks=1, deadline=None):
    """A bare waiting Request for scheduler-level tests."""
    return Request(
        rid, np.zeros(4, np.int32), 4, np.zeros(2, np.uint32),
        RequestHandle(None, rid), deadline=deadline, n_chunks=n_chunks,
        tenant=tenant, priority=priority,
    )


def pop_order(sched, n, *, num_blocks=4096, block_size=8):
    """Drain ``n`` pops one at a time; returns the request ids."""
    alloc = BlockAllocator(num_blocks, block_size)
    out = []
    for _ in range(n):
        got = sched.pop_admissible(1, alloc, block_size)
        assert len(got) == 1, "scheduler stalled with work waiting"
        alloc.free(got[0].blocks) if got[0].blocks else None
        alloc.reset()  # pages are irrelevant to these ordering tests
        out.append(got[0].rid)
    return out


# ---------------------------------------------------------------------------
# QoSScheduler: ordering, fairness, starvation bounds (host-side only)


def test_wfq_starvation_bound_weight_8_vs_1():
    """The provable bound: under a sustained weight-8 backlog, a
    weight-1 tenant's consecutive one-chunk requests are separated by
    at most weight_ratio (8) competing chunks — it always progresses."""
    sched = QoSScheduler(tenant_weights={"whale": 8.0, "minnow": 1.0})
    for i in range(30):
        sched.push(req_of(i, tenant="whale"))
    sched.push(req_of(100, tenant="minnow"))
    sched.push(req_of(101, tenant="minnow"))
    order = pop_order(sched, 32)
    first, second = order.index(100), order.index(101)
    # Between the minnow's two admissions: at most 8 whale requests
    # (the weight ratio), so gap <= 9 positions.
    assert second - first <= 9, order
    # And the whale got the bulk of the early service: weights mean
    # shares, not strict alternation.
    assert sum(r < 30 for r in order[:second]) >= second - 2, order


def test_wfq_idle_tenant_banks_no_credit():
    """A tenant that slept while another was served does not return
    with a huge vtime deficit and lock the queue: its virtual time is
    clamped up to the clock on re-arrival."""
    sched = QoSScheduler()
    for i in range(10):
        sched.push(req_of(i, tenant="busy"))
    assert pop_order(sched, 10) == list(range(10))
    # 'busy' served 10 chunks while 'sleeper' was idle.  Now both push.
    for i in range(4):
        sched.push(req_of(20 + i, tenant="busy"))
        sched.push(req_of(30 + i, tenant="sleeper"))
    order = pop_order(sched, 8)
    # Fair interleave from here on — the sleeper gets no 10-chunk
    # catch-up binge (no more than 2 consecutive sleeper pops).
    assert order[:2] != [30, 31] or order[2] == 20, order
    assert sum(r >= 30 for r in order[:4]) == 2, order


def test_vclock_scoped_per_class():
    """Service in one class must not move another class's virtual
    clock: a fresh high-class tenant's pop (virtual time 0) may not
    regress the clock a busy lower class's newcomers clamp to — that
    would hand them a head start over the class's backlogged
    incumbents, breaking the w/W bound."""
    sched = QoSScheduler()
    for i in range(10):
        sched.push(req_of(i, tenant="a", priority=0))
    assert pop_order(sched, 4) == [0, 1, 2, 3]  # a's class-0 vt climbs
    # A fresh tenant pops in class 1 at virtual time 0, while class 0
    # stays backlogged.
    sched.push(req_of(20, tenant="c", priority=1))
    assert pop_order(sched, 1) == [20]
    # Tenant b joins class 0: it clamps to CLASS 0's clock (a's
    # neighborhood), not the class-1 pop's — fair interleave, no
    # b-monopoly burning up from 0.
    for i in range(4):
        sched.push(req_of(30 + i, tenant="b", priority=0))
    order = pop_order(sched, 8)
    assert sum(r >= 30 for r in order[:4]) == 2, order


def test_tenant_state_pruned_when_idle():
    """Scheduler state must track WAITING work, not tenants ever seen:
    free-form per-user tenant ids on a long-lived engine would
    otherwise grow the vt map, counters, gauge iteration, and empty
    heaps without bound.  A class that empties resets its virtual time
    wholesale (the classic busy-period rule)."""
    sched = QoSScheduler()
    for i in range(6):
        sched.push(req_of(i, tenant=f"user-{i}", priority=i % 2))
    assert len(pop_order(sched, 6)) == 6
    assert sched._tenant_n == {}
    assert sched._tenant_gauges == {}
    assert sched._vt == {} and sched._vclock == {}
    assert sched._queues == {}


def test_priority_classes_strict_and_edf_within():
    """Higher classes drain first regardless of tenant vtime; within a
    (class, tenant) queue, earliest deadline first, deadline-less
    requests after, ties by submission order."""
    sched = QoSScheduler()
    sched.push(req_of(0, priority=0))
    sched.push(req_of(1, priority=1, deadline=500.0))
    sched.push(req_of(2, priority=1))  # no deadline: after the dated ones
    sched.push(req_of(3, priority=1, deadline=100.0))
    sched.push(req_of(4, priority=2))
    assert pop_order(sched, 5) == [4, 3, 1, 2, 0]


def test_requeue_returns_head_of_line_without_recharge():
    """A transiently-failed admission batch requeues ahead of the QoS
    order (transactional retry) and is not charged a second fare."""
    sched = QoSScheduler(tenant_weights={"a": 1.0, "b": 1.0})
    sched.push(req_of(0, tenant="a", n_chunks=4))
    sched.push(req_of(1, tenant="b"))
    alloc = BlockAllocator(4096, 8)
    got = sched.pop_admissible(1, alloc, 8)
    assert [r.rid for r in got] == [0]
    vt_after_pop = dict(sched._vt)
    sched.requeue(got)
    assert sched.peek().rid == 0  # head of line again, ahead of b
    sched.pop_admissible(1, alloc, 8)
    assert sched._vt == vt_after_pop  # no second fare for tenant a


def test_shed_hooks_oldest_and_lowest():
    sched = QoSScheduler()
    sched.push(req_of(0, priority=1))
    sched.push(req_of(1, priority=0))
    sched.push(req_of(2, priority=0))
    # by-priority victim: lowest class, youngest first...
    victim = sched.shed_lowest(below_priority=1)
    assert victim.rid == 2
    # ...and None when nothing sits strictly below the arrival's class.
    assert sched.shed_lowest(below_priority=0) is None
    # drop-oldest compatibility: globally oldest by submission.
    assert sched.shed_oldest().rid == 0
    assert sched.shed_oldest().rid == 1
    assert sched.shed_oldest() is None
    assert len(sched) == 0


def test_purge_and_flush_cover_all_queues():
    sched = QoSScheduler()
    r_ok = req_of(0, priority=1)
    r_cancel = req_of(1, priority=0)
    r_expired = req_of(2, priority=2, deadline=-1.0)
    for r in (r_ok, r_cancel, r_expired):
        sched.push(r)
    r_cancel.handle._cancel_requested = True
    expired, cancelled = sched.purge(now=0.0)
    assert [r.rid for r in expired] == [2]
    assert [r.rid for r in cancelled] == [1]
    assert len(sched) == 1 and sched.peek() is r_ok
    assert sched.pending_prefill_chunks() == 1
    assert [r.rid for r in sched.flush()] == [0]
    assert len(sched) == 0


# ---------------------------------------------------------------------------
# Engine + QoS: token parity, preemption via both mechanisms


@pytest.mark.parametrize("sampled", [False, True])
def test_engine_qos_token_identical_plain(family, sampled):
    """QoS-scheduled traffic with mixed tenants/priorities but no
    pressure: every stream equals its solo generate() run."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    eng = Engine(
        params, model=model, cfg=cfg, eos_id=EOS, scheduler="qos",
        tenant_weights={"gold": 4.0}, **sample_kw, **ENGINE_KW,
    )
    reqs = [
        (prompt_of(5 + i, base=i + 1), 6 + (i % 2) * 3, i) for i in range(5)
    ]
    handles = [
        eng.submit(
            p, max_new_tokens=m, key=600 + seed,
            tenant=("gold" if seed % 2 else "free"), priority=seed % 3,
        )
        for p, m, seed in reqs
    ]
    eng.drain()
    for (p, m, seed), h in zip(reqs, handles):
        assert h.result() == solo(
            model, cfg, params, p, 600 + seed, m, eos=EOS, **sample_kw
        ), f"request {seed}"
    assert eng.allocator.num_in_use == 0
    assert eng.allocator.num_swapped == 0


@pytest.mark.parametrize("sampled", [False, True])
@pytest.mark.parametrize("cache_on", [False, True])
def test_preempt_drop_and_replay_token_identical(family, sampled, cache_on):
    """Slot pressure: a high-priority arrival drop-and-replay-preempts
    the low-priority decoding stream within one tick; the victim
    resumes by re-prefilling prompt + generated-so-far and both streams
    equal solo generate() — greedy and sampled, cache on and off."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=1,
        block_size=8, max_model_len=64, decode_chunk=4,
        prefix_cache=cache_on, **sample_kw,
    )
    victim = eng.submit(
        prompt_of(6), max_new_tokens=24, key=700, priority=0
    )
    eng.step()
    assert not victim.done and len(victim._tokens) > 0
    urgent = eng.submit(
        prompt_of(6, base=3), max_new_tokens=8, key=701, priority=5
    )
    before = telemetry.counter("serve.preemptions_replay").value
    eng.step()  # ONE tick: victim out, urgent prefilled into the slot
    assert telemetry.counter("serve.preemptions_replay").value == before + 1
    assert urgent.ttft_s is not None, "high-pri arrival waited out the victim"
    assert eng._slot_req[0] is None or eng._slot_req[0].rid == urgent.rid
    eng.drain()
    assert urgent.result() == solo(
        model, cfg, params, prompt_of(6, base=3), 701, 8, **sample_kw
    )
    assert victim.result() == solo(
        model, cfg, params, prompt_of(6), 700, 24, **sample_kw
    ), "drop-and-replay resume diverged"
    assert eng.stats()["preemptions_replay"] >= 1
    assert eng.allocator.num_in_use == (
        len(eng.prefix) if cache_on else 0
    )
    assert eng.allocator.num_swapped == 0


@pytest.mark.parametrize("sampled", [False, True])
@pytest.mark.parametrize("cache_on", [False, True])
def test_preempt_swap_to_host_token_identical(family, sampled, cache_on):
    """Page pressure with a free slot: the low-priority stream's pages
    swap to host (slot parks out of the decode batch), the
    high-priority request runs, and the victim swaps back in once
    pressure drops — token-identical throughout.  With the prefix cache
    on, both prompts are identical, so the swap covers shared
    (refcounted) pages and the resume covers re-privatized ones."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    # 8 usable pages; each request reserves 5 (8 prompt + 26 out = 34
    # tokens / 8) — two can never both hold pages.
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        prefix_cache=cache_on, **sample_kw,
    )
    prompt_a = prompt_of(8)
    prompt_b = prompt_of(8) if cache_on else prompt_of(8, base=2)
    victim = eng.submit(prompt_a, max_new_tokens=26, key=800, priority=0)
    eng.step()
    assert not victim.done
    urgent = eng.submit(prompt_b, max_new_tokens=26, key=801, priority=5)
    before = telemetry.counter("serve.preemptions_swap").value
    eng.step()  # ONE tick: victim swapped out, urgent admitted
    assert telemetry.counter("serve.preemptions_swap").value == before + 1
    assert eng.allocator.num_swapped > 0
    assert eng.stats()["swapped_pages"] > 0
    if cache_on:
        # The victim's index-shared prompt page stays MAPPED on the
        # refs it keeps (swapping a shared page would free nothing and
        # duplicate it at swap-in): only the 4 private pages of its
        # 5-page reservation are host-resident.
        assert eng.allocator.num_swapped == 4
    eng.drain()
    assert urgent.result() == solo(
        model, cfg, params, prompt_b, 801, 26, **sample_kw
    )
    assert victim.result() == solo(
        model, cfg, params, prompt_a, 800, 26, **sample_kw
    ), "swap-in resume diverged"
    st = eng.stats()
    assert st["preemptions_swap"] >= 1 and st["swapped_pages"] == 0
    assert eng.allocator.num_swapped == 0
    assert eng.allocator.num_in_use == (
        len(eng.prefix) if cache_on else 0
    )
    if cache_on:
        assert eng.prefix.check(eng.allocator) is None


def test_preempt_mechanism_replay_under_page_pressure(family):
    """preempt_mechanism='replay' serves page pressure with
    drop-and-replay instead of swap — same token identity."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        preempt_mechanism="replay", prefix_cache=False,
    )
    victim = eng.submit(prompt_of(8), max_new_tokens=26, key=810, priority=0)
    eng.step()
    urgent = eng.submit(
        prompt_of(8, base=2), max_new_tokens=26, key=811, priority=5
    )
    eng.drain()
    assert urgent.result() == solo(
        model, cfg, params, prompt_of(8, base=2), 811, 26
    )
    assert victim.result() == solo(model, cfg, params, prompt_of(8), 810, 26)
    st = eng.stats()
    assert st["preemptions_replay"] >= 1 and st["preemptions_swap"] == 0
    assert eng.allocator.num_in_use == 0


def test_swap_fault_falls_back_to_drop_and_replay(family):
    """TDX_FAULT serve.swap:io fails the host gather mid-preemption:
    device state is untouched (the gather is read-only) and the
    preemption falls back to drop-and-replay — still token-identical,
    counted as a replay, not a swap."""
    model, cfg, params = family
    faults.reset("serve.swap:1:io")
    fired_before = telemetry.counter("faults.fired").value
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        prefix_cache=False,
    )
    victim = eng.submit(prompt_of(8), max_new_tokens=26, key=820, priority=0)
    eng.step()
    urgent = eng.submit(
        prompt_of(8, base=2), max_new_tokens=26, key=821, priority=5
    )
    eng.drain()
    assert telemetry.counter("faults.fired").value == fired_before + 1
    st = eng.stats()
    assert st["preemptions_swap"] == 0 and st["preemptions_replay"] >= 1
    assert victim.result() == solo(model, cfg, params, prompt_of(8), 820, 26)
    assert urgent.result() == solo(
        model, cfg, params, prompt_of(8, base=2), 821, 26
    )
    assert eng.allocator.num_in_use == 0 and eng.allocator.num_swapped == 0


@pytest.mark.parametrize("sampled", [False, True])
def test_burst_tenant_does_not_starve_weighted_peer(family, sampled):
    """A weight-1 burst tenant flooding the queue cannot make a
    weight-8 steady tenant wait out the whole burst: fair queueing
    admits the steady request after at most a couple of burst ones."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos",
        tenant_weights={"steady": 8.0, "burst": 1.0},
        **sample_kw, **ENGINE_KW,
    )
    burst = [
        eng.submit(
            prompt_of(5, base=i + 1), max_new_tokens=12, key=900 + i,
            tenant="burst",
        )
        for i in range(6)
    ]
    steady = eng.submit(
        prompt_of(5, base=9), max_new_tokens=12, key=950, tenant="steady"
    )
    ticks = 0
    while steady.ttft_s is None:
        eng.step()
        ticks += 1
        assert ticks < 200, "steady tenant starved"
    # At most the 2 burst requests that grabbed the slots first (plus
    # one more finishing) beat the steady tenant to a first token.
    assert sum(h.ttft_s is not None for h in burst) <= 3
    eng.drain()
    assert steady.result() == solo(
        model, cfg, params, prompt_of(5, base=9), 950, 12, **sample_kw
    )
    for i, h in enumerate(burst):
        assert h.result() == solo(
            model, cfg, params, prompt_of(5, base=i + 1), 900 + i, 12,
            **sample_kw,
        )
    assert eng.allocator.num_in_use == 0


def test_cache_aware_admission_cost(family):
    """A prefix-cache hit shrinks a request's fair-queueing cost and
    TTFT weight to its SUFFIX chunks: the second identical prompt
    weighs 1 chunk, not its full length, and the WFQ fare charged to
    its tenant shrinks accordingly."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos",
        prefill_chunk=4, min_prefill_bucket=4,
        **{**ENGINE_KW, "prefix_cache": True},
    )
    prompt = prompt_of(16)  # 2 full pages; 4 chunks of 4 uncached
    h1 = eng.submit(prompt, max_new_tokens=4, key=990, tenant="a")
    assert eng.scheduler.peek().n_chunks == 4
    eng.drain()
    assert eng.stats()["prefix_cached_pages"] == 2
    # Same prompt again: probe() sees the cached pages, the suffix is
    # the single recomputed last token -> 1 chunk.
    h2 = eng.submit(prompt, max_new_tokens=4, key=991, tenant="a")
    assert eng.scheduler.peek().n_chunks == 1
    # A second tenant keeps the class busy across h2's admission, so
    # its WFQ charge is observable (an emptied class resets its
    # virtual time wholesale).
    h3 = eng.submit(prompt_of(4, base=9), max_new_tokens=4, key=992,
                    tenant="b")
    eng.step()  # admits h2 (tenant a pops first on the vt tie)
    assert eng.scheduler._vt[(0, "a")] == pytest.approx(1.0), (
        "WFQ charged the cached request more than its suffix"
    )
    eng.drain()
    assert h1.result() == solo(model, cfg, params, prompt, 990, 4)
    assert h2.result() == solo(model, cfg, params, prompt, 991, 4)
    assert h3.result() == solo(model, cfg, params, prompt_of(4, base=9), 992, 4)
    eng.prefix.release(eng.allocator)
    assert eng.allocator.num_in_use == 0


def test_preempt_requeue_cost_is_cache_aware(family):
    """A drop-and-replay victim's requeue fare must weigh only the
    suffix its re-prefill will actually dispatch: the index still
    holds its prompt pages, so re-admission maps them again and the
    replay is generated-so-far only — not prompt + generated."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=1,
        block_size=8, max_model_len=64, decode_chunk=4, prefill_chunk=4,
        min_prefill_bucket=4, prefix_cache=True,
    )
    victim = eng.submit(prompt_of(8), max_new_tokens=16, key=860, priority=0)
    while victim.ttft_s is None:
        eng.step()
    eng.step()  # one decode chunk: 4 more committed tokens
    urgent = eng.submit(prompt_of(8, base=2), max_new_tokens=4, key=861,
                        priority=5)
    eng.step()  # slot pressure: victim drop-and-replay preempted
    assert eng.stats()["preemptions_replay"] >= 1
    queued = eng.scheduler.peek()
    assert queued is not None and queued.rid == 0
    # The prompt's full page (8 tokens) is still indexed, so the fare
    # weighs only the generated-so-far suffix the re-prefill will
    # actually dispatch — not the whole prompt + generated sequence.
    replay_len = queued.replay_len()
    assert replay_len > 8  # tokens were committed before the preempt
    suffix_chunks = -(-(replay_len - 8) // 4)
    full_chunks = -(-replay_len // 4)
    assert suffix_chunks < full_chunks
    assert queued.n_chunks == suffix_chunks, (
        "requeue fare ignored the prefix cache"
    )
    eng.drain()
    assert urgent.result() == solo(
        model, cfg, params, prompt_of(8, base=2), 861, 4
    )
    assert victim.result() == solo(model, cfg, params, prompt_of(8), 860, 16)
    eng.prefix.release(eng.allocator)
    assert eng.allocator.num_in_use == 0


def test_shed_by_priority_policy(family):
    """shed_policy='by-priority': the overload victim is the lowest
    class, youngest first — and an arrival that is itself the lowest
    class is the one rejected."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", max_queue=2,
        shed_policy="by-priority", num_slots=1, block_size=8,
        max_model_len=64, decode_chunk=4,
    )
    blocker = eng.submit(prompt_of(6), max_new_tokens=30, key=0, priority=9)
    eng.step()  # occupies the only slot: the queue backs up
    low_old = eng.submit(prompt_of(4, base=1), max_new_tokens=4, key=1,
                         priority=0)
    low_young = eng.submit(prompt_of(4, base=2), max_new_tokens=4, key=2,
                           priority=0)
    # Queue full (2).  A higher-class arrival sheds the YOUNGEST of the
    # LOWEST class — not the oldest request.
    high = eng.submit(prompt_of(4, base=3), max_new_tokens=4, key=3,
                      priority=1)
    assert low_young.done and isinstance(low_young.error, EngineOverloaded)
    assert not low_old.done
    # An arrival that is itself the lowest class is the one shed.
    with pytest.raises(EngineOverloaded):
        eng.submit(prompt_of(4, base=4), max_new_tokens=4, key=4, priority=0)
    blocker.cancel()
    eng.drain()
    assert high.result() == solo(model, cfg, params, prompt_of(4, base=3), 3, 4)
    assert low_old.result() == solo(
        model, cfg, params, prompt_of(4, base=1), 1, 4
    )
    assert eng.allocator.num_in_use == 0


def test_shed_by_priority_empty_queue_admits(family):
    """An overloaded engine whose WAITING queue is empty (pressure is
    all in-flight work) must not reject a high-priority arrival under
    shed_policy='by-priority': with no waiting class to compare
    against, the arrival is admitted — and preemption, not shedding,
    resolves the pressure."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos",
        shed_policy="by-priority", max_ttft_s=1e-9, num_slots=1,
        block_size=8, max_model_len=64, decode_chunk=4,
    )
    low = eng.submit(prompt_of(6), max_new_tokens=30, key=0, priority=0)
    eng.step()
    eng.step()  # ticks recorded: est_ttft_s now trips max_ttft_s
    assert eng.est_ttft_s() > 1e-9 and not len(eng.scheduler)
    shed_before = eng.stats()["shed"]
    high = eng.submit(prompt_of(4, base=3), max_new_tokens=4, key=1,
                      priority=5)
    assert eng.stats()["shed"] == shed_before  # nothing waiting was shed
    eng.drain()
    assert high.result() == solo(model, cfg, params, prompt_of(4, base=3), 1, 4)
    assert low.result() == solo(model, cfg, params, prompt_of(6), 0, 30)
    assert eng.allocator.num_in_use == 0


def test_swapped_slot_cancel_settles_accounts(family):
    """Cancelling a swapped-out stream discards its host buffer and
    settles the allocator's swap account — no leaked pages, no phantom
    swapped count."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        prefix_cache=False,
    )
    victim = eng.submit(prompt_of(8), max_new_tokens=26, key=830, priority=0)
    eng.step()
    urgent = eng.submit(
        prompt_of(8, base=2), max_new_tokens=26, key=831, priority=5
    )
    eng.step()
    assert eng.allocator.num_swapped > 0
    victim.cancel()
    eng.step()  # next chunk boundary: the swapped victim leaves
    assert victim.done and isinstance(victim.error, RequestCancelled)
    assert eng.allocator.num_swapped == 0
    eng.drain()
    assert urgent.result() == solo(
        model, cfg, params, prompt_of(8, base=2), 831, 26
    )
    assert eng.allocator.num_in_use == 0


def test_preempted_resumable_flag(family):
    """RequestPreempted.resumable: True for a request that never
    yielded a token (a plain resubmit resumes it losslessly), False
    for a mid-stream cut (a lossless resume needs a key-pinned
    replay)."""
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, drain_deadline_s=0.0,
                 **ENGINE_KW)
    running = eng.submit(prompt_of(6), max_new_tokens=30, key=0)
    eng.step()
    assert len(running._tokens) > 0
    waiting = eng.submit(prompt_of(5), max_new_tokens=4, key=1)
    preemption.request()
    eng.step()  # drain begins; deadline 0 cuts the running stream now
    assert isinstance(waiting.error, RequestPreempted)
    assert waiting.error.resumable  # flushed before prefill: resubmit = resume
    assert isinstance(running.error, RequestPreempted)
    assert not running.error.resumable  # mid-stream: needs a pinned replay
    assert eng.allocator.num_in_use == 0


def test_qos_knob_validation(family):
    model, cfg, params = family
    with pytest.raises(ValueError, match="scheduler"):
        Engine(params, model=model, cfg=cfg, scheduler="lifo", **ENGINE_KW)
    with pytest.raises(ValueError, match="tenant_weights"):
        Engine(params, model=model, cfg=cfg, tenant_weights={"a": 2.0},
               **ENGINE_KW)
    with pytest.raises(ValueError, match="by-priority"):
        Engine(params, model=model, cfg=cfg, shed_policy="by-priority",
               **ENGINE_KW)
    with pytest.raises(ValueError, match="preempt_mechanism"):
        Engine(params, model=model, cfg=cfg, preempt_mechanism="dropall",
               **ENGINE_KW)
    with pytest.raises(ValueError, match="weights must be > 0"):
        QoSScheduler(tenant_weights={"a": 0.0})
    eng = Engine(params, model=model, cfg=cfg, scheduler="qos", **ENGINE_KW)
    with pytest.raises(ValueError, match="tenant"):
        eng.submit(prompt_of(4), max_new_tokens=2, key=0, tenant="")
