"""Native (C++) tape core vs the pure-Python graph: identical schedules.

The Python implementation is the executable spec; the native core
(src/cc/tdx_core) must produce the same materialization call stacks.
"""

import os
import subprocess
import sys

import pytest
import torch

from torchdistx_tpu import _native, _tape
from torchdistx_tpu.deferred_init import (
    deferred_init,
    materialize_module,
    materialize_tensor,
    _get_record,
)

_FORCED_OFF = bool(os.environ.get("TDX_DISABLE_NATIVE"))


@pytest.mark.skipif(_FORCED_OFF, reason="native explicitly disabled via env")
def test_native_builds_and_loads():
    assert _native.native_available(), (
        "native core should build on demand (g++ is in this image)"
    )


@pytest.mark.skipif(_FORCED_OFF, reason="native explicitly disabled via env")
def test_stack_ops_available():
    assert _native.stack_ops() is not None, (
        "_tdx_stack extension should build on demand"
    )


def test_stack_leaves_matches_pytree():
    import torch.utils._pytree as pytree

    s = _native.stack_ops()
    if s is None:
        pytest.skip("native stack unavailable")
    t = torch.ones(2)
    cases = [
        (1, 2, 3),
        (t, [1, t], {"a": t, "b": (None, 2.0)}),
        {"x": [t, {"y": (t,)}]},
        t,
        [],
        ((), [], {}),
    ]
    for obj in cases:
        assert s.leaves(obj) == pytree.tree_leaves(obj), obj


def test_stack_convert_matches_pytree_map():
    import torch.utils._pytree as pytree

    s = _native.stack_ops()
    if s is None:
        pytest.skip("native stack unavailable")
    t = torch.ones(2)
    fn = lambda x: x * 2  # noqa: E731
    obj = (t, [1, t], {"a": t, "b": (None, 2.0)}, "str")
    got = s.convert(obj, fn)
    want = pytree.tree_map(
        lambda a: fn(a) if isinstance(a, torch.Tensor) else a, obj
    )
    assert pytree.tree_structure(got) == pytree.tree_structure(want)
    for g, w in zip(pytree.tree_leaves(got), pytree.tree_leaves(want)):
        if isinstance(g, torch.Tensor):
            assert torch.equal(g, w)
        else:
            assert g == w
    # Copy-on-write: no tensor change -> same object back.
    scalars = (1, [2, 3], {"k": "v"})
    assert s.convert(scalars, fn) is scalars


def test_stack_convert_fallback_signals():
    import collections

    s = _native.stack_ops()
    if s is None:
        pytest.skip("native stack unavailable")
    Point = collections.namedtuple("Point", "x y")
    with pytest.raises(s.Fallback):
        s.convert((Point(1, 2),), lambda x: x)
    # strict mode rejects leaves outside the immutable domain
    with pytest.raises(s.Fallback):
        s.convert((object(),), lambda x: x, True)
    # ...but accepts the torch value types
    ok = (torch.float32, torch.device("cpu"), 1, 2.0, None, "s")
    assert s.convert(ok, lambda x: x, True) is ok


@pytest.mark.skipif(_FORCED_OFF, reason="native explicitly disabled via env")
def test_low_level_graph_roundtrip():
    class Node:  # weak-referenceable registry payload
        def __init__(self, nr):
            self.nr = nr

    g = _native.NativeGraph()
    payloads = [Node(nr) for nr in (10, 11, 12, 13)]
    for p in payloads:
        g.add_node(p.nr, p)
    g.add_dep(11, 10)
    g.add_dep(12, 11)
    g.note_write(10, 0xABC)
    g.note_write(13, 0xABC)  # later in-place write on the same storage
    assert len(g) == 4
    # target 11: deps {10}, horizon from target's dependents only (none for
    # 11; 10's dependent 13 is pulled in via 10 within horizon? no — horizon
    # is computed from the *target*).
    assert g.call_stack(11) == [10, 11]
    # target 10: dependent 13 raises the horizon and joins the stack.
    assert g.call_stack(10) == [10, 13]
    with pytest.raises(KeyError):
        g.call_stack(999)


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(8, 16)
        self.fc2 = torch.nn.Linear(16, 4)
        self.register_buffer("scale", torch.ones(4) * 3)

    def forward(self, x):
        return self.fc2(torch.relu(self.fc1(x))) * self.scale


def _schedules(module):
    out = {}
    for name, p in list(module.named_parameters()) + list(
        module.named_buffers()
    ):
        rec = _get_record(p)
        out[name] = [n.op_nr for n in _tape.build_call_stack(rec.node)]
    return out


@pytest.mark.skipif(_FORCED_OFF, reason="native explicitly disabled via env")
def test_schedules_match_python_fallback():
    m_native = deferred_init(Net)
    native_used = any(
        _get_record(p).node.native_graph is not None
        for p in m_native.parameters()
    )
    assert native_used, "native graph should be active for this tape"
    sched_native = _schedules(m_native)

    # Same model recorded with the native core disabled → same schedules
    # relative to each tape's op_nr base.
    code = """
import os
os.environ["TDX_DISABLE_NATIVE"] = "1"
import torch
from torchdistx_tpu import _tape
from torchdistx_tpu.deferred_init import deferred_init, _get_record
import json

class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(8, 16)
        self.fc2 = torch.nn.Linear(16, 4)
        self.register_buffer("scale", torch.ones(4) * 3)

m = deferred_init(Net)
assert all(
    _get_record(p).node.native_graph is None for p in m.parameters()
)
out = {}
base = None
for name, t in list(m.named_parameters()) + list(m.named_buffers()):
    rec = _get_record(t)
    nrs = [n.op_nr for n in _tape.build_call_stack(rec.node)]
    if base is None:
        base = min(nrs)
    out[name] = nrs
print(json.dumps({"base": base, "sched": out}))
"""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, check=True
    )
    import json

    py = json.loads(proc.stdout.strip().splitlines()[-1])
    base_native = min(min(v) for v in sched_native.values())
    rel_native = {
        k: [nr - base_native for nr in v] for k, v in sched_native.items()
    }
    rel_py = {
        k: [nr - py["base"] for nr in v] for k, v in py["sched"].items()
    }
    assert rel_native == rel_py


def test_materialize_through_native_path():
    m = deferred_init(Net)
    materialize_module(m)
    assert torch.equal(m.scale, torch.ones(4) * 3)
    x = torch.randn(2, 8)
    y = m(x)
    assert y.shape == (2, 4)


def test_identity_preserved_through_native_path():
    m = deferred_init(Net)
    a = materialize_tensor(m.fc1.weight)
    b = materialize_tensor(m.fc1.weight)
    assert a is b
    assert isinstance(a, torch.nn.Parameter)


def test_inplace_horizon_through_native_path():
    def build():
        t = torch.ones(4)
        u = t[:2]  # view
        u.add_(1.0)  # in-place on the view, later than t's producer
        return t, u

    t, u = deferred_init(build)
    real_t = materialize_tensor(t)
    # The in-place write through the view must be visible in t.
    assert torch.equal(real_t, torch.tensor([2.0, 2.0, 1.0, 1.0]))


def test_inplace_horizon_with_dropped_view():
    """The in-place op's node must stay alive (keep-alive contract) even
    when the view tensor object is dropped before materialization."""
    import gc

    def build():
        t = torch.ones(4)
        u = t[:2]
        u.add_(1.0)
        del u
        return t

    t = deferred_init(build)
    gc.collect()
    assert torch.equal(
        materialize_tensor(t), torch.tensor([2.0, 2.0, 1.0, 1.0])
    )


@pytest.mark.skipif(_FORCED_OFF, reason="native explicitly disabled via env")
def test_native_outputref_type():
    s = _native.stack_ops()
    assert _tape.OutputRef is s.OutputRef

    class N:
        op_nr = 7

    r = s.OutputRef(N(), 2)
    assert r.index == 2 and r.node.op_nr == 7
    assert repr(r) == "OutputRef(op_nr=7, index=2)"


def test_cross_tape_sees_native_inplace_writes():
    """A cross-tape read AFTER an in-place write recorded natively in the
    producer's tape must replay that write (the Python traversal navigates
    the dependents lists the native recorder maintains)."""

    def first():
        t = torch.zeros(4)
        t.add_(5.0)
        return nn.Parameter(t)

    import torch.nn as nn

    p1 = deferred_init(first)
    p2 = deferred_init(lambda: nn.Parameter(p1 * 1.0))
    rec = _get_record(p2)
    assert rec.node.native_graph is None  # cross-tape: downgraded
    assert torch.equal(materialize_tensor(p2), torch.full((4,), 5.0))


def test_concurrent_materialize_across_threads():
    """Tapes are recorded thread-locally but materialization may happen from
    other threads (the reference's graphs cross threads the same way); the
    native call-stack traversal must be safe under concurrent readers.
    The C++-level race coverage is scripts/tsan_native.sh."""
    import concurrent.futures

    import torch.nn as nn

    modules = [deferred_init(Net) for _ in range(4)]

    def materialize_one(m):
        materialize_module(m)
        return float(m.fc1.weight.sum())

    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        sums = list(pool.map(materialize_one, modules))
    assert all(s == s for s in sums)  # finite, no crash
    for m in modules:
        assert isinstance(m.fc1.weight, nn.Parameter)
        assert m.fc1.weight.device.type == "cpu"


def test_post_downgrade_writer_linking():
    """After a tape downgrades (cross-tape dep), later in-place ops in the
    SAME tape must still link against native-era writers — the recorder
    exports its writer index into the Python tape."""
    import torch.nn as nn

    ext = deferred_init(lambda: nn.Parameter(torch.ones(4)))

    def build():
        a = torch.zeros(4)         # recorded natively
        b = a + ext                # cross-tape dep -> tape downgrades
        a.add_(3.0)                # python-path write on a native-era storage
        return nn.Parameter(a), b

    a, b = deferred_init(build)
    assert _get_record(a).node.native_graph is None
    # b first: it read a BEFORE the in-place write, and the per-node replay
    # caches mutate in place (chronological materialization order, same as
    # the reference's cached outputs).
    assert torch.equal(materialize_tensor(b), torch.ones(4))
    assert torch.equal(materialize_tensor(a), torch.full((4,), 3.0))
