"""Serving crash child for the journal kill -9 e2e (test_journal.py).

Three modes, one per subprocess (``argv = mode journal_dir temperature``):

* ``ref``    — uninterrupted run, no journal: the deterministic ground
  truth (tokens + digests per request, keyed by the uid the journaled
  run will assign in the same submit order).
* ``crash``  — the same requests against a journaled engine with
  ``TDX_FAULT=serve.step:N:crash`` armed in the environment: the
  process dies ``os._exit(CRASH_EXIT_CODE)`` mid-decode — no finally
  blocks, no atexit, journal unclosed, owner lock left behind.
* ``resume`` — a fresh process: build a bare engine, steal the dead
  pid's stale lock via ``resume_from_journal``, finish every resumed
  stream, and report tokens/digests plus the journal's folded view of
  streams that had already finished before the crash.

Results print as one ``RESULT {json}`` line (the test_crash_resume
protocol).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REQ = 4
MAX_NEW = 24


def _build(temperature, journal=None):
    import jax

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import Engine

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=4, block_size=8,
        num_blocks=41, max_model_len=64, decode_chunk=4,
        max_prefills_per_tick=4, handle_preemption=False,
        temperature=temperature, top_k=8 if temperature else None,
        journal=journal,
    )
    return eng, cfg


def _prompts(cfg):
    rng = np.random.default_rng(11)
    return [
        rng.integers(0, cfg.vocab_size, size=6).astype(np.int32)
        for _ in range(N_REQ)
    ]


def main() -> int:
    mode, jdir, temperature = sys.argv[1], sys.argv[2], float(sys.argv[3])
    from torchdistx_tpu.serving import RequestJournal

    if mode == "ref":
        eng, cfg = _build(temperature)
        toks, digs = {}, {}
        for i, p in enumerate(_prompts(cfg)):
            h = eng.submit(p, max_new_tokens=MAX_NEW, key=i)
            # uid i+1: the journaled run admits in the same order.
            toks[str(i + 1)] = h.result()
            digs[str(i + 1)] = h.digest
        eng.close()
        print("RESULT " + json.dumps({"tokens": toks, "digests": digs}))
        return 0

    if mode == "crash":
        eng, cfg = _build(temperature, journal=RequestJournal(jdir))
        hs = [
            eng.submit(p, max_new_tokens=MAX_NEW, key=i)
            for i, p in enumerate(_prompts(cfg))
        ]
        for h in hs:  # drives the engine until the crash fault fires
            h.result()
        print("RESULT " + json.dumps({"error": "crash fault never fired"}))
        return 1

    if mode == "resume":
        from torchdistx_tpu.serving import journal as journal_mod

        eng, cfg = _build(temperature)
        handles = eng.resume_from_journal(RequestJournal(jdir))
        toks = {str(u): h.result() for u, h in sorted(handles.items())}
        digs = {str(u): h.digest for u, h in sorted(handles.items())}
        stats = eng.stats()
        eng.close()
        entries, _ = journal_mod.fold_records(journal_mod.read_records(jdir))
        finished = {
            str(u): e.tokens
            for u, e in entries.items()
            if e.retired and e.outcome == "finished"
        }
        print("RESULT " + json.dumps({
            "resumed": toks,
            "digests": digs,
            "finished": finished,
            "journal": stats.get("journal"),
        }))
        return 0

    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    sys.exit(main())
