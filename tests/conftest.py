"""Test configuration.

Distributed tests run on a virtual multi-device CPU mesh — the JAX analog of
the reference's multi-process FSDPTest harness (see SURVEY.md §4).

The environment's sitecustomize pins ``JAX_PLATFORMS=axon`` (the tunneled
real TPU); tests must run on virtual CPU devices, so the platform is forced
back to cpu via ``jax.config`` (env vars alone are overwritten by the
sitecustomize hook).

JAX itself is optional: the torch-only surface (fake tensors, deferred init,
torch materialization) must stay testable in a JAX-less environment, so the
import is guarded and JAX-dependent test modules skip via their own imports.
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    + os.environ.get("XLA_FLAGS", "")
)

try:
    import jax
except ImportError:
    jax = None

if jax is not None:
    jax.config.update("jax_platforms", "cpu")
else:
    # torch-only environment: skip collection of JAX-dependent modules so
    # the torch-surface tests (fake, deferred init, native tape) still run.
    collect_ignore = [
        "test_attention.py",
        "test_checkpoint.py",
        "test_gpt2.py",
        "test_materialize_jax.py",
        "test_models.py",
        "test_sharding_plans.py",
        "test_slowmo.py",
        "test_trace_report.py",
        "test_train_step.py",
    ]
