"""Test configuration.

Distributed tests run on a virtual multi-device CPU mesh — the JAX analog of
the reference's multi-process FSDPTest harness (see SURVEY.md §4).

The environment's sitecustomize pins ``JAX_PLATFORMS=axon`` (the tunneled
real TPU); tests must run on virtual CPU devices, so the platform is forced
back to cpu via ``jax.config`` (env vars alone are overwritten by the
sitecustomize hook).
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    + os.environ.get("XLA_FLAGS", "")
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
