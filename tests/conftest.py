"""Test configuration.

Distributed tests run on a virtual multi-device CPU mesh — the JAX analog of
the reference's multi-process FSDPTest harness (see SURVEY.md §4): set the
platform flags BEFORE jax is imported anywhere.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
