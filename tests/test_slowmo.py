"""SlowMo tests — analytic oracles like the reference's
(/root/reference/tests/python/test_slowmo_fsdp.py: rank-distinct gradients via
singleton subgroups, manual averager oracle, closed-form momentum check,
checkpoint round-trip, ctor validation).  Here "rank-distinct" replicas are
the stacked dp axis on a virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from torchdistx_tpu.parallel import MeshSpec, make_mesh
from torchdistx_tpu.parallel.slowmo import (
    SlowMomentumOptimizer,
    load_slowmo_state_dict,
    slowmo_grad_sync,
    slowmo_state_dict,
)

DP = 4


def _stacked_params():
    return {
        "w": jnp.tile(jnp.arange(6.0).reshape(1, 2, 3), (DP, 1, 1)),
        "b": jnp.ones((DP, 3)),
    }


def _distinct_grads():
    # Each replica gets a different gradient (the reference's singleton-
    # subgroup trick, test_slowmo_fsdp.py:119-131).
    return {
        "w": jnp.stack([jnp.full((2, 3), float(r + 1)) for r in range(DP)]),
        "b": jnp.stack([jnp.full((3,), 0.1 * (r + 1)) for r in range(DP)]),
    }


def test_replicas_diverge_then_average():
    lr = 0.1
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=3, slowmo_factor=0.0,
        slowmo_lr=1.0,
    )
    params = _stacked_params()
    state = opt.init(params)
    grads = _distinct_grads()
    for step in range(1, 4):
        params, state = opt.update(grads, state, params)
        replicas = np.asarray(params["w"])
        if step < 3:
            assert not np.allclose(replicas[0], replicas[1])
        else:
            for r in range(1, DP):
                np.testing.assert_allclose(replicas[0], replicas[r])


def test_momentum_math_closed_form():
    # Analytic oracle (slowmo_optimizer.py:206-227 math; reference test
    # recomputes it the same way, test_slowmo_fsdp.py:243-253).
    lr, freq, alpha, slr = 0.1, 2, 0.5, 0.7
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=freq, slowmo_factor=alpha,
        slowmo_lr=slr,
    )
    params = _stacked_params()
    p0 = np.asarray(params["w"][0])  # initial (same on all replicas)
    state = opt.init(params)
    grads = _distinct_grads()
    g = np.asarray(grads["w"])

    # two steps of local SGD then averaging:
    local = np.asarray(params["w"]) - 2 * lr * g
    avg = local.mean(axis=0)
    m = 0.0 * alpha + (p0 - avg) / lr
    prev = p0 - slr * lr * m
    params, state = opt.update(grads, state, params)
    params, state = opt.update(grads, state, params)
    for r in range(DP):
        np.testing.assert_allclose(
            np.asarray(params["w"][r]), prev, rtol=1e-5
        )
    np.testing.assert_allclose(np.asarray(state.momentum["w"]), m, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(state.prev["w"]), prev, rtol=1e-5)


def test_momentum_accumulates_across_cycles():
    lr, freq, alpha = 0.1, 1, 0.5
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=freq, slowmo_factor=alpha,
        slowmo_lr=1.0,
    )
    params = _stacked_params()
    state = opt.init(params)
    grads = _distinct_grads()
    params, state = opt.update(grads, state, params)
    m1 = np.asarray(state.momentum["w"])
    params, state = opt.update(grads, state, params)
    m2 = np.asarray(state.momentum["w"])
    # m2 = alpha*m1 + (prev1 - avg2)/lr, with nonzero m1 -> not equal.
    assert not np.allclose(m1, m2)
    assert np.abs(m2).max() > 0


def test_under_jit_on_mesh():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(dp=4, tp=2))
    lr = 0.05
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=2, slowmo_factor=0.3,
        slowmo_lr=1.0,
    )
    params = _stacked_params()
    state = opt.init(params)
    grads = _distinct_grads()

    shard = NamedSharding(mesh, P("dp"))
    params_sharded = jax.tree.map(lambda p: jax.device_put(p, shard), params)
    grads_sharded = jax.tree.map(lambda g: jax.device_put(g, shard), grads)

    step = jax.jit(opt.update)
    p1, s1 = step(grads_sharded, state, params_sharded)
    p2, s2 = step(grads_sharded, s1, p1)
    # Oracle: same math unjitted/unsharded.
    q1, t1 = opt.update(grads, state, params)
    q2, t2 = opt.update(grads, t1, q1)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(q2["w"]), rtol=1e-6)
    # Sharding preserved across the step.
    assert p2["w"].sharding.spec == shard.spec


def test_works_with_adam():
    lr = 0.01
    opt = SlowMomentumOptimizer(
        optax.adam(lr), base_lr=lr, slowmo_freq=2, slowmo_factor=0.5,
        slowmo_lr=1.0,
    )
    params = _stacked_params()
    state = opt.init(params)
    grads = _distinct_grads()
    for _ in range(4):
        params, state = opt.update(grads, state, params)
    assert np.isfinite(np.asarray(params["w"])).all()
    r = np.asarray(params["w"])
    for k in range(1, DP):
        np.testing.assert_allclose(r[0], r[k], rtol=1e-6)


def test_training_converges():
    # End-to-end: fit y = x @ w on dp-sharded batches; loss must drop.
    key = jax.random.PRNGKey(0)
    true_w = jax.random.normal(key, (8, 1))
    x = jax.random.normal(jax.random.fold_in(key, 1), (DP, 64, 8))
    y = x @ true_w

    params = {"w": jnp.zeros((DP, 8, 1))}
    lr = 0.1
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=4, slowmo_factor=0.5,
        slowmo_lr=1.0,
    )
    state = opt.init(params)

    def replica_loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    @jax.jit
    def train_step(params, state, x, y):
        loss, grads = jax.vmap(jax.value_and_grad(replica_loss))(
            params["w"], x, y
        )
        params, state = opt.update({"w": grads}, state, params)
        return params, state, loss.mean()

    losses = []
    for _ in range(60):
        params, state, loss = train_step(params, state, x, y)
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0]


def test_ctor_validation():
    # Reference test_slowmo_fsdp.py:326-364.
    with pytest.raises(ValueError, match="slowmo_freq"):
        SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1, slowmo_freq=0)
    with pytest.raises(ValueError, match="slowmo_factor"):
        SlowMomentumOptimizer(
            optax.sgd(0.1), base_lr=0.1, slowmo_factor=-1.0
        )
    with pytest.raises(ValueError, match="slowmo_lr"):
        SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1, slowmo_lr=-0.1)
    with pytest.raises(ValueError, match="base_lr"):
        SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.0)


def test_state_dict_roundtrip():
    # Reference test_slowmo_fsdp.py:255-324.
    lr = 0.1
    opt = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=3, slowmo_factor=0.5,
        slowmo_lr=2.0,
    )
    params = _stacked_params()
    state = opt.init(params)
    grads = _distinct_grads()
    for _ in range(3):
        params, state = opt.update(grads, state, params)
    d = slowmo_state_dict(opt, state)
    assert d["slowmo_freq"] == 3 and d["step"] == 3

    opt2 = SlowMomentumOptimizer(
        optax.sgd(lr), base_lr=lr, slowmo_freq=99
    )
    state2 = load_slowmo_state_dict(opt2, d)
    assert opt2.slowmo_freq == 3 and opt2.slowmo_lr == 2.0
    p_a, s_a = opt.update(grads, state, params)
    p_b, s_b = opt2.update(grads, state2, params)
    np.testing.assert_allclose(
        np.asarray(p_a["w"]), np.asarray(p_b["w"]), rtol=1e-7
    )


def test_state_dict_missing_key():
    opt = SlowMomentumOptimizer(optax.sgd(0.1), base_lr=0.1)
    d = slowmo_state_dict(opt, opt.init(_stacked_params()))
    del d["base_lr"]
    with pytest.raises(ValueError, match="base_lr"):
        load_slowmo_state_dict(opt, d)


def test_grad_sync_hook():
    # slowmo_comm parity: pmean over an explicit intra axis in shard_map.
    from jax.sharding import PartitionSpec as P
    try:  # jax >= 0.7 promoted the export; 0.4.x has only the module
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = make_mesh(MeshSpec(dp=2, tp=4))
    g = jnp.arange(8.0).reshape(2, 4)

    def f(g):
        return slowmo_grad_sync(g, axis_name="tp")

    out = shard_map(
        f, mesh=mesh, in_specs=P("dp", "tp"), out_specs=P("dp", "tp")
    )(g)
    expected = np.tile(g.mean(axis=1, keepdims=True), (1, 4))
    np.testing.assert_allclose(np.asarray(out), expected)

    out2 = shard_map(
        lambda g: slowmo_grad_sync(g, axis_name="tp", enabled=False),
        mesh=mesh, in_specs=P("dp", "tp"), out_specs=P("dp", "tp"),
    )(g)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(g))
